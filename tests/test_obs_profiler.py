"""Profiling plane + flight recorder (tpuflow/obs/profiler.py, flight.py).

Covers: thread-name component attribution and the busy/idle leaf-frame
split, include= scoping, the bounded-stack overflow path, snapshot
merge/diff regression verdicts, JSONL spill + load, the alert-triggered
and supervisor-crash capture paths, rate limiting, retention, bundle
schema validation, and the TPUFLOW_OBS_PROFILE_* / TPUFLOW_OBS_FLIGHT_*
knob validation (malformed values must fail loud, naming the variable).
"""

import json
import threading
import time

import pytest

from tpuflow.obs.alerts import AlertEngine
from tpuflow.obs.flight import (
    FlightRecorder,
    flight_from_env,
    list_bundles,
    load_bundle,
    validate_bundle,
)
from tpuflow.obs.history import MetricsHistory
from tpuflow.obs.metrics import Registry
from tpuflow.obs.profiler import (
    SamplingProfiler,
    component_for,
    diff_snapshots,
    load_snapshot,
    merge_snapshots,
    profiler_from_env,
    render_folded,
    render_profile,
    top_component,
    validate_snapshot,
)


class _Workload:
    """One CPU-burning thread + one Event-parked thread, with tpuflow
    lane/prep names so samples attribute to batcher/serving."""

    def __init__(self, busy_name="tpuflow-lane-t", idle_name="tpuflow-prep-t"):
        self.stop = threading.Event()

        def burn():
            x = 0
            while not self.stop.is_set():
                x += sum(range(128))

        self.busy = threading.Thread(target=burn, name=busy_name, daemon=True)
        self.idle = threading.Thread(
            target=self.stop.wait, name=idle_name, daemon=True
        )
        self.busy.start()
        self.idle.start()

    def close(self):
        self.stop.set()
        self.busy.join(timeout=5)
        self.idle.join(timeout=5)


@pytest.fixture
def workload():
    w = _Workload()
    yield w
    w.close()


def _sample_n(profiler, n=25):
    for _ in range(n):
        profiler.sample()
        time.sleep(0.002)


class TestSamplingProfiler:
    def test_component_attribution_table(self):
        assert component_for("tpuflow-lane-8/f32") == "batcher"
        assert component_for("tpuflow-microbatch") == "batcher"
        assert component_for("tpuflow-prep_0") == "serving"
        assert component_for("tpuflow-serve-async") == "serving"
        assert component_for("tpuflow-serve-autoscale") == "autoscaler"
        assert component_for("tpuflow-runtime-probe") == "supervisor"
        assert component_for("tpuflow-runtime-online") == "online"
        assert component_for("tpuflow-elastic-w3") == "gang"
        assert component_for("tpuflow-jobs") == "jobs"
        assert component_for("MainThread") == "main"
        assert component_for("Thread-7") == "other"

    def test_busy_idle_split_and_top_component(self, workload):
        p = SamplingProfiler(0.01, include=("tpuflow-lane", "tpuflow-prep"))
        _sample_n(p)
        snap = p.snapshot()
        assert validate_snapshot(snap) == []
        comps = snap["components"]
        # The burner is busy wall-clock; the Event-parked thread's leaf
        # frame is threading.wait — sampled, but idle.
        assert comps["batcher"]["busy"] > 0
        assert comps["serving"]["samples"] > 0
        assert comps["serving"]["busy"] == 0
        assert top_component(snap) == "batcher"
        assert comps["batcher"]["share"] == 1.0

    def test_include_scopes_threads(self, workload):
        p = SamplingProfiler(0.01, include=("tpuflow-prep",))
        p.sample()
        snap = p.snapshot()
        assert set(snap["components"]) == {"serving"}

    def test_self_metrics(self, workload):
        reg = Registry()
        p = SamplingProfiler(0.01, registry=reg,
                             include=("tpuflow-lane", "tpuflow-prep"))
        _sample_n(p, 10)
        families = {f.name: f for f in reg.collect()}
        samples = families["tpuflow_obs_profiler_samples_total"].collect()
        assert samples and samples[0][2] == 20.0  # 10 ticks x 2 threads
        overhead = families["tpuflow_obs_profiler_overhead_seconds_total"]
        assert overhead.collect()[0][2] > 0.0
        assert families["tpuflow_obs_profiler_stacks"].collect()[0][2] >= 1.0

    def test_bounded_stacks_overflow(self):
        p = SamplingProfiler(0.01, max_stacks=3)
        with p._lock:
            for i in range(10):
                p._ingest_locked("batcher", f"mod:f{i}", False, 1)
        snap = p.snapshot()
        assert snap["dropped_stacks"] == 7
        stacks = {r["stack"]: r["count"] for r in snap["stacks"]}
        assert stacks["<overflow>"] == 7
        # Bound holds (+1 overflow bucket); component totals are exact.
        assert len(snap["stacks"]) == 4
        assert snap["components"]["batcher"]["samples"] == 10

    def test_sampler_thread_start_stop(self, workload):
        p = SamplingProfiler(0.005, include=("tpuflow-lane",))
        p.start()
        deadline = time.monotonic() + 5.0
        while p.snapshot()["ticks"] < 5 and time.monotonic() < deadline:
            time.sleep(0.01)
        p.stop()
        snap = p.snapshot()
        assert snap["ticks"] >= 5
        # The sampler never samples itself.
        assert all("tpuflow-obs-profiler" not in r["stack"]
                   for r in snap["stacks"])

    def test_render_profile_and_folded(self, workload):
        p = SamplingProfiler(0.01, include=("tpuflow-lane", "tpuflow-prep"))
        _sample_n(p, 10)
        snap = p.snapshot()
        text = render_profile(snap, top=5)
        assert "batcher" in text and "busy-share" in text
        assert "burn" in text  # top busy frame names the burner
        folded = render_folded(snap)
        line = folded.splitlines()[0]
        assert line.startswith(("batcher;", "serving;"))
        assert line.rsplit(" ", 1)[1].isdigit()

    def test_constructor_validation(self):
        with pytest.raises(ValueError, match="interval_s"):
            SamplingProfiler(0.0)
        with pytest.raises(ValueError, match="max_stacks"):
            SamplingProfiler(0.01, max_stacks=0)


def _snap(components, stacks=(), **over):
    total_busy = sum(b for _, b in components.values()) or 1
    doc = {
        "schema": "tpuflow.obs.profile/v1",
        "started_unix": 1.0, "captured_unix": 2.0, "interval_s": 0.05,
        "ticks": 10, "thread_samples": 20, "dropped_stacks": 0,
        "overhead_s": 0.001,
        "components": {
            name: {"samples": s, "busy": b, "share": round(b / total_busy, 6)}
            for name, (s, b) in components.items()
        },
        "stacks": [
            {"component": c, "stack": st, "count": n, "idle": idle}
            for c, st, n, idle in stacks
        ],
    }
    doc.update(over)
    return doc


class TestMergeDiff:
    def test_merge_sums_components_and_stacks(self):
        a = _snap({"batcher": (10, 8)}, [("batcher", "m:f", 8, False)])
        b = _snap({"batcher": (4, 2), "serving": (6, 1)},
                  [("batcher", "m:f", 2, False), ("serving", "m:g", 1, False)])
        m = merge_snapshots(a, b)
        assert validate_snapshot(m) == []
        assert m["components"]["batcher"] == {
            "samples": 14, "busy": 10, "share": round(10 / 11, 6),
        }
        assert {(r["stack"], r["count"]) for r in m["stacks"]} == {
            ("m:f", 10), ("m:g", 1),
        }
        assert m["ticks"] == 20

    def test_diff_regression_verdict_deterministic(self):
        base = _snap({"batcher": (10, 2), "serving": (10, 8)})
        new = _snap({"batcher": (10, 8), "serving": (10, 2)})
        verdict = diff_snapshots(base, new, threshold=0.05)
        assert verdict["verdict"] == "regression"
        assert verdict["regressions"] == ["batcher"]
        assert verdict["base_top"] == "serving"
        assert verdict["new_top"] == "batcher"
        row = verdict["components"][0]
        assert row["component"] == "batcher"
        assert row["delta"] == 0.6
        # Same inputs, same verdict — byte-for-byte.
        assert diff_snapshots(base, new, threshold=0.05) == verdict

    def test_diff_ok_within_threshold(self):
        base = _snap({"batcher": (10, 5), "serving": (10, 5)})
        new = _snap({"batcher": (10, 52), "serving": (10, 48)})
        verdict = diff_snapshots(base, new, threshold=0.05)
        assert verdict["verdict"] == "ok"
        assert verdict["regressions"] == []

    def test_diff_rejects_invalid_snapshot(self):
        with pytest.raises(ValueError, match="base"):
            diff_snapshots({"schema": "nope"}, _snap({"batcher": (1, 1)}))

    def test_load_snapshot_json_and_spill(self, tmp_path):
        doc = _snap({"batcher": (3, 3)})
        path = tmp_path / "snap.json"
        path.write_text(json.dumps(doc))
        assert load_snapshot(str(path))["components"] == doc["components"]
        # A spill holds cumulative snapshots; the LAST one wins.
        spill = tmp_path / "spill.jsonl"
        older = _snap({"batcher": (1, 1)})
        with spill.open("w") as fh:
            fh.write(json.dumps({"event": "profile_snapshot", "snapshot": older}) + "\n")
            fh.write("{torn json\n")
            fh.write(json.dumps({"event": "profile_snapshot", "snapshot": doc}) + "\n")
        assert load_snapshot(str(spill))["thread_samples"] == 20
        empty = tmp_path / "none.jsonl"
        empty.write_text(json.dumps({"event": "history_sample"}) + "\n")
        with pytest.raises(ValueError, match="no profile_snapshot"):
            load_snapshot(str(empty))

    def test_spill_written_on_stop(self, tmp_path, workload):
        spill = tmp_path / "prof.jsonl"
        p = SamplingProfiler(
            0.01, include=("tpuflow-lane",), spill_path=str(spill),
        )
        p.start()
        time.sleep(0.05)
        p.stop()
        snap = load_snapshot(str(spill))
        assert validate_snapshot(snap) == []
        assert snap["ticks"] >= 1


class TestFlightRecorder:
    def _wired(self, tmp_path, clock=None):
        reg = Registry()
        counter = reg.counter("requests_total", "requests")
        counter.inc(5)
        hist = MetricsHistory(reg)
        prof = SamplingProfiler(0.01)
        prof.sample()
        rec = FlightRecorder(
            str(tmp_path / "flight"),
            history=hist, profiler=prof, registry=reg,
            min_interval_s=30.0, max_bundles=2,
            clock=clock or time.monotonic,
        )
        return rec, hist, reg

    def test_capture_bundle_schema_valid(self, tmp_path, workload):
        rec, _, _ = self._wired(tmp_path)
        name = rec.capture("manual", reason="unit test", force=True)
        assert name is not None and name.endswith("-manual.json")
        doc = rec.load(name)
        assert validate_bundle(doc) == []
        assert doc["trigger"] == "manual"
        assert doc["reason"] == "unit test"
        thread_names = {t["name"] for t in doc["threads"]}
        assert "tpuflow-lane-t" in thread_names
        assert doc["profile"]["schema"] == "tpuflow.obs.profile/v1"
        assert "python" in doc["env"] and "knobs" in doc["env"]
        assert "tpuflow_requests_total" in doc["registry"]

    def test_alert_transition_triggers_capture(self, tmp_path):
        rec, hist, reg = self._wired(tmp_path)
        engine = AlertEngine(hist, [{
            "name": "too_many", "metric": "requests_total",
            "query": "latest", "op": ">", "threshold": 1.0, "for_s": 0.0,
        }], registry=reg)
        rec.attach(engine)
        hist.sample()
        engine.evaluate()
        names = rec.list_bundles()
        assert len(names) == 1
        doc = rec.load(names[0])
        assert doc["trigger"] == "alert"
        assert doc["rule"] == "too_many"
        assert "too_many" in doc["reason"]
        # The rule-relevant history window rides along.
        series = doc["history"]["series"]["requests_total"]
        assert series["points"]
        # Alerts state shows the rule firing.
        states = {r["name"]: r["state"] for r in doc["alerts"]["rules"]}
        assert states["too_many"] == "firing"

    def test_rate_limit_and_force(self, tmp_path):
        t = [0.0]
        rec, _, reg = self._wired(tmp_path, clock=lambda: t[0])
        assert rec.capture("manual") is not None
        assert rec.capture("manual") is None  # inside min_interval_s
        assert rec.capture("crash", force=True) is not None
        t[0] = 31.0
        assert rec.capture("manual") is not None
        families = {f.name: f for f in reg.collect()}
        suppressed = families["tpuflow_obs_flight_suppressed_total"]
        assert suppressed.collect()[0][2] == 1.0
        bundles = families["tpuflow_obs_flight_bundles_total"].collect()
        assert {(lbl["trigger"], v) for _, lbl, v in bundles} == {
            ("manual", 2.0), ("crash", 1.0),
        }

    def test_retention_keeps_newest(self, tmp_path):
        t = [0.0]
        rec, _, _ = self._wired(tmp_path, clock=lambda: t[0])
        kept = []
        for i in range(4):
            t[0] = i * 60.0
            kept.append(rec.capture("manual"))
        names = rec.list_bundles()
        assert names == sorted(kept[-2:])
        root = str(tmp_path / "flight")
        assert list_bundles(root) == names
        assert validate_bundle(load_bundle(root, names[-1])) == []

    def test_validate_bundle_problems(self):
        assert validate_bundle("x") == ["bundle is not an object"]
        problems = validate_bundle({"schema": "wrong"})
        assert any("schema" in p for p in problems)
        assert any("threads" in p for p in problems)
        assert any("trigger" in p for p in problems)

    def test_supervisor_failed_service_captures_crash_bundle(self, tmp_path):
        from tpuflow.runtime.services import thread_service
        from tpuflow.runtime.supervisor import RuntimeSupervisor

        def _die(stop_event):
            raise RuntimeError("synthetic death")

        rec = FlightRecorder(str(tmp_path / "flight"), min_interval_s=0.0)
        sup = RuntimeSupervisor(
            [thread_service("doomed", _die, grace=1.0)],
            registry=Registry(), probe_interval=0.02, flight=rec,
        )
        sup.start()
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            if sup.healthz()["services"]["doomed"]["state"] == "failed":
                break
            time.sleep(0.02)
        sup.shutdown()
        names = rec.list_bundles()
        assert len(names) >= 1
        doc = rec.load(names[0])
        assert validate_bundle(doc) == []
        assert doc["trigger"] == "crash"
        assert "doomed" in doc["reason"]


class TestEnvKnobs:
    def test_profiler_from_env_off_by_default(self, monkeypatch):
        monkeypatch.delenv("TPUFLOW_OBS_PROFILE", raising=False)
        assert profiler_from_env() is None

    def test_profiler_from_env_on(self, monkeypatch, tmp_path):
        monkeypatch.setenv("TPUFLOW_OBS_PROFILE", "1")
        monkeypatch.setenv("TPUFLOW_OBS_PROFILE_INTERVAL_S", "0.02")
        monkeypatch.setenv("TPUFLOW_OBS_PROFILE_MAX_STACKS", "64")
        monkeypatch.setenv(
            "TPUFLOW_OBS_PROFILE_SPILL", str(tmp_path / "p.jsonl")
        )
        p = profiler_from_env(include=("tpuflow-lane",))
        assert p is not None
        assert p.interval_s == 0.02
        assert p.max_stacks == 64
        assert p.include == ("tpuflow-lane",)
        p.stop()

    def test_flight_from_env_off_by_default(self, monkeypatch):
        monkeypatch.delenv("TPUFLOW_OBS_FLIGHT", raising=False)
        assert flight_from_env() is None

    def test_flight_from_env_requires_dir(self, monkeypatch):
        monkeypatch.setenv("TPUFLOW_OBS_FLIGHT", "1")
        monkeypatch.delenv("TPUFLOW_OBS_FLIGHT_DIR", raising=False)
        with pytest.raises(ValueError, match="TPUFLOW_OBS_FLIGHT_DIR"):
            flight_from_env()

    def test_flight_from_env_on(self, monkeypatch, tmp_path):
        monkeypatch.setenv("TPUFLOW_OBS_FLIGHT", "1")
        monkeypatch.setenv("TPUFLOW_OBS_FLIGHT_DIR", str(tmp_path / "f"))
        monkeypatch.setenv("TPUFLOW_OBS_FLIGHT_MIN_INTERVAL_S", "5")
        monkeypatch.setenv("TPUFLOW_OBS_FLIGHT_MAX_BUNDLES", "3")
        rec = flight_from_env()
        assert rec is not None
        assert rec.min_interval_s == 5.0
        assert rec.max_bundles == 3

    @pytest.mark.parametrize("var,value", [
        ("TPUFLOW_OBS_PROFILE", "ture"),
        ("TPUFLOW_OBS_PROFILE_INTERVAL_S", "fast"),
        ("TPUFLOW_OBS_PROFILE_INTERVAL_S", "-1"),
        ("TPUFLOW_OBS_PROFILE_INTERVAL_S", "inf"),
        ("TPUFLOW_OBS_PROFILE_MAX_STACKS", "many"),
        ("TPUFLOW_OBS_PROFILE_MAX_STACKS", "0"),
        ("TPUFLOW_OBS_PROFILE_SPILL_EVERY_S", "often"),
    ])
    def test_malformed_profiler_knobs_name_the_variable(
        self, monkeypatch, var, value,
    ):
        monkeypatch.setenv("TPUFLOW_OBS_PROFILE", "1")
        monkeypatch.setenv(var, value)
        with pytest.raises(ValueError, match=var):
            profiler_from_env()

    @pytest.mark.parametrize("var,value", [
        ("TPUFLOW_OBS_FLIGHT", "maybe"),
        ("TPUFLOW_OBS_FLIGHT_MIN_INTERVAL_S", "soon"),
        ("TPUFLOW_OBS_FLIGHT_MIN_INTERVAL_S", "-2"),
        ("TPUFLOW_OBS_FLIGHT_MAX_BUNDLES", "lots"),
        ("TPUFLOW_OBS_FLIGHT_MAX_BUNDLES", "0"),
    ])
    def test_malformed_flight_knobs_name_the_variable(
        self, monkeypatch, tmp_path, var, value,
    ):
        monkeypatch.setenv("TPUFLOW_OBS_FLIGHT", "1")
        monkeypatch.setenv("TPUFLOW_OBS_FLIGHT_DIR", str(tmp_path))
        monkeypatch.setenv(var, value)
        with pytest.raises(ValueError, match=var):
            flight_from_env()

    def test_serve_alert_for_s_malformed(self, monkeypatch):
        from tpuflow.utils.env import env_num

        monkeypatch.setenv("TPUFLOW_SERVE_ALERT_FOR_S", "later")
        with pytest.raises(ValueError, match="TPUFLOW_SERVE_ALERT_FOR_S"):
            env_num("TPUFLOW_SERVE_ALERT_FOR_S", 15.0, float)
