"""Pipeline- and expert-parallel building blocks vs dense references.

Both are beyond-parity axes (SURVEY.md §2 lists PP/EP out of scope) kept
expressible with the same shard_map vocabulary; these tests pin their
exact equivalence to unsharded computation on the 8-virtual-device mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpuflow.parallel import make_mesh, moe_forward, pipeline_forward

MODEL_AXIS = "model"


def _stage_fn(params, x):
    w, b = params
    return jnp.tanh(x @ w + b)


class TestPipelineParallel:
    @pytest.mark.parametrize("n_micro", [1, 4, 7])
    def test_matches_sequential_stages(self, n_micro):
        n_stages = 4
        mesh = make_mesh(n_data=2, n_model=n_stages)
        rng = np.random.default_rng(0)
        F, B = 6, 3
        ws = jnp.asarray(rng.standard_normal((n_stages, F, F)) * 0.3, jnp.float32)
        bs = jnp.asarray(rng.standard_normal((n_stages, F)) * 0.1, jnp.float32)
        xs = jnp.asarray(
            rng.standard_normal((n_micro, B, F)), jnp.float32
        )

        got = pipeline_forward(mesh, _stage_fn, (ws, bs), xs)

        want = xs
        for s in range(n_stages):
            want = jax.vmap(lambda m: _stage_fn((ws[s], bs[s]), m))(want)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), atol=1e-5
        )

    def test_two_stage_full_mesh(self):
        mesh = make_mesh(n_data=4, n_model=2)
        rng = np.random.default_rng(1)
        ws = jnp.asarray(rng.standard_normal((2, 5, 5)) * 0.3, jnp.float32)
        bs = jnp.zeros((2, 5), jnp.float32)
        xs = jnp.asarray(rng.standard_normal((3, 2, 5)), jnp.float32)
        got = pipeline_forward(mesh, _stage_fn, (ws, bs), xs)
        want = xs
        for s in range(2):
            want = jax.vmap(lambda m: _stage_fn((ws[s], bs[s]), m))(want)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)


def _expert_fn(params, x):
    w1, w2 = params
    return jax.nn.relu(x @ w1) @ w2


class TestExpertParallel:
    def test_matches_dense_top1_moe(self):
        n_experts = 4
        mesh = make_mesh(n_data=2, n_model=n_experts)
        rng = np.random.default_rng(2)
        F, H, N = 6, 8, 10
        w1 = jnp.asarray(
            rng.standard_normal((n_experts, F, H)) * 0.3, jnp.float32
        )
        w2 = jnp.asarray(
            rng.standard_normal((n_experts, H, F)) * 0.3, jnp.float32
        )
        gate_w = jnp.asarray(rng.standard_normal((F, n_experts)), jnp.float32)
        x = jnp.asarray(rng.standard_normal((N, F)), jnp.float32)

        got = moe_forward(mesh, _expert_fn, (w1, w2), gate_w, x)

        logits = np.asarray(x @ gate_w)
        probs = np.asarray(jax.nn.softmax(jnp.asarray(logits), axis=-1))
        choice = logits.argmax(axis=-1)
        want = np.zeros((N, F), np.float32)
        for i in range(N):
            e = choice[i]
            out = np.asarray(_expert_fn((w1[e], w2[e]), x[i : i + 1]))[0]
            want[i] = probs[i, e] * out
        np.testing.assert_allclose(np.asarray(got), want, atol=1e-5)

    def test_gate_width_mismatch_rejected(self):
        mesh = make_mesh(n_data=2, n_model=4)
        with pytest.raises(ValueError, match="experts"):
            moe_forward(
                mesh,
                _expert_fn,
                (jnp.zeros((4, 3, 3)), jnp.zeros((4, 3, 3))),
                jnp.zeros((3, 5)),  # 5 gate outputs != 4 experts
                jnp.zeros((2, 3)),
            )

    def test_every_token_routed_exactly_once(self):
        """Identity experts: the combine must return gate_weight * x for
        every token (no drops, no double counting)."""
        n_experts = 8
        mesh = make_mesh(n_data=1, n_model=n_experts)
        rng = np.random.default_rng(3)
        F, N = 4, 64
        eye = jnp.broadcast_to(jnp.eye(F), (n_experts, F, F))
        gate_w = jnp.asarray(rng.standard_normal((F, n_experts)), jnp.float32)
        x = jnp.asarray(rng.standard_normal((N, F)), jnp.float32)
        got = moe_forward(
            mesh, lambda p, t: t @ p[0] @ p[1], (eye, eye), gate_w, x
        )
        probs = jax.nn.softmax(x @ gate_w, axis=-1)
        w = jnp.max(probs, axis=-1)  # top-1 weight per token
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(x * w[:, None]), atol=1e-5
        )
