"""Pipeline- and expert-parallel building blocks vs dense references.

Both are beyond-parity axes (SURVEY.md §2 lists PP/EP out of scope) kept
expressible with the same shard_map vocabulary; these tests pin their
exact equivalence to unsharded computation on the 8-virtual-device mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpuflow.parallel import (
    make_mesh,
    moe_forward,
    pipeline_forward,
    set_mesh,
)

MODEL_AXIS = "model"


def _stage_fn(params, x):
    w, b = params
    return jnp.tanh(x @ w + b)


def _sequential_ref(ws, bs, xs):
    """All stages applied in order on one device — the PP parity oracle."""
    out = xs
    for s in range(ws.shape[0]):
        out = jax.vmap(lambda m: _stage_fn((ws[s], bs[s]), m))(out)
    return out


class TestPipelineParallel:
    @pytest.mark.parametrize("n_micro", [1, 4, 7])
    def test_matches_sequential_stages(self, n_micro):
        n_stages = 4
        mesh = make_mesh(n_data=2, n_model=n_stages)
        rng = np.random.default_rng(0)
        F, B = 6, 3
        ws = jnp.asarray(rng.standard_normal((n_stages, F, F)) * 0.3, jnp.float32)
        bs = jnp.asarray(rng.standard_normal((n_stages, F)) * 0.1, jnp.float32)
        xs = jnp.asarray(
            rng.standard_normal((n_micro, B, F)), jnp.float32
        )

        got = pipeline_forward(mesh, _stage_fn, (ws, bs), xs)

        want = _sequential_ref(ws, bs, xs)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), atol=1e-5
        )

    def test_two_stage_full_mesh(self):
        mesh = make_mesh(n_data=4, n_model=2)
        rng = np.random.default_rng(1)
        ws = jnp.asarray(rng.standard_normal((2, 5, 5)) * 0.3, jnp.float32)
        bs = jnp.zeros((2, 5), jnp.float32)
        xs = jnp.asarray(rng.standard_normal((3, 2, 5)), jnp.float32)
        got = pipeline_forward(mesh, _stage_fn, (ws, bs), xs)
        want = xs
        for s in range(2):
            want = jax.vmap(lambda m: _stage_fn((ws[s], bs[s]), m))(want)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)


def _expert_fn(params, x):
    w1, w2 = params
    return jax.nn.relu(x @ w1) @ w2


class TestExpertParallel:
    def test_matches_dense_top1_moe(self):
        n_experts = 4
        mesh = make_mesh(n_data=2, n_model=n_experts)
        rng = np.random.default_rng(2)
        F, H, N = 6, 8, 10
        w1 = jnp.asarray(
            rng.standard_normal((n_experts, F, H)) * 0.3, jnp.float32
        )
        w2 = jnp.asarray(
            rng.standard_normal((n_experts, H, F)) * 0.3, jnp.float32
        )
        gate_w = jnp.asarray(rng.standard_normal((F, n_experts)), jnp.float32)
        x = jnp.asarray(rng.standard_normal((N, F)), jnp.float32)

        got = moe_forward(mesh, _expert_fn, (w1, w2), gate_w, x)

        logits = np.asarray(x @ gate_w)
        probs = np.asarray(jax.nn.softmax(jnp.asarray(logits), axis=-1))
        choice = logits.argmax(axis=-1)
        want = np.zeros((N, F), np.float32)
        for i in range(N):
            e = choice[i]
            out = np.asarray(_expert_fn((w1[e], w2[e]), x[i : i + 1]))[0]
            want[i] = probs[i, e] * out
        np.testing.assert_allclose(np.asarray(got), want, atol=1e-5)

    def test_gate_width_mismatch_rejected(self):
        mesh = make_mesh(n_data=2, n_model=4)
        with pytest.raises(ValueError, match="experts"):
            moe_forward(
                mesh,
                _expert_fn,
                (jnp.zeros((4, 3, 3)), jnp.zeros((4, 3, 3))),
                jnp.zeros((3, 5)),  # 5 gate outputs != 4 experts
                jnp.zeros((2, 3)),
            )

    def test_every_token_routed_exactly_once(self):
        """Identity experts: the combine must return gate_weight * x for
        every token (no drops, no double counting)."""
        n_experts = 8
        mesh = make_mesh(n_data=1, n_model=n_experts)
        rng = np.random.default_rng(3)
        F, N = 4, 64
        eye = jnp.broadcast_to(jnp.eye(F), (n_experts, F, F))
        gate_w = jnp.asarray(rng.standard_normal((F, n_experts)), jnp.float32)
        x = jnp.asarray(rng.standard_normal((N, F)), jnp.float32)
        got = moe_forward(
            mesh, lambda p, t: t @ p[0] @ p[1], (eye, eye), gate_w, x
        )
        probs = jax.nn.softmax(x @ gate_w, axis=-1)
        w = jnp.max(probs, axis=-1)  # top-1 weight per token
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(x * w[:, None]), atol=1e-5
        )


class TestPipelineGradients:
    def test_pipeline_grads_match_sequential(self):
        """PP is training-capable: grads through the fori_loop schedule +
        ppermute ring + psum broadcast match sequential-stage grads."""
        n_stages = 2
        mesh = make_mesh(n_data=4, n_model=n_stages)
        rng = np.random.default_rng(5)
        F, B, M = 6, 3, 4
        ws = jnp.asarray(rng.standard_normal((n_stages, F, F)) * 0.3, jnp.float32)
        bs = jnp.asarray(rng.standard_normal((n_stages, F)) * 0.1, jnp.float32)
        xs = jnp.asarray(rng.standard_normal((M, B, F)), jnp.float32)

        def loss_pp(params):
            return jnp.sum(
                jnp.square(pipeline_forward(mesh, _stage_fn, params, xs))
            )

        def loss_ref(params):
            return jnp.sum(jnp.square(_sequential_ref(*params, xs)))

        with set_mesh(mesh):
            g = jax.grad(loss_pp)((ws, bs))
        gr = jax.grad(loss_ref)((ws, bs))
        for a, e, name in zip(g, gr, ["dws", "dbs"]):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(e), atol=1e-4, err_msg=name
            )


def _mat_expert_fn(p, t):
    return jnp.tanh(t @ p)


class TestMoEGradients:
    def test_moe_grads_match_dense(self):
        """EP is training-capable: grads flow to the chosen experts AND
        the router (through the softmax gate weight), matching a dense
        replication of the same top-1 math."""
        n_experts = 2
        mesh = make_mesh(n_data=4, n_model=n_experts)
        rng = np.random.default_rng(7)
        F, N = 6, 10
        ps = jnp.asarray(rng.standard_normal((n_experts, F, F)) * 0.4, jnp.float32)
        gate = jnp.asarray(rng.standard_normal((F, n_experts)), jnp.float32)
        x = jnp.asarray(rng.standard_normal((N, F)), jnp.float32)

        def loss_ep(a):
            ps, gate, x = a
            return jnp.sum(jnp.square(moe_forward(mesh, _mat_expert_fn, ps, gate, x)))

        def loss_ref(a):
            ps, gate, x = a
            logits = x @ gate
            probs = jax.nn.softmax(logits, axis=-1)
            choice = jnp.argmax(logits, axis=-1)
            weight = jnp.take_along_axis(probs, choice[:, None], axis=1)[:, 0]
            out = sum(
                (choice == e).astype(x.dtype)[:, None]
                * _mat_expert_fn(ps[e], x)
                * weight[:, None]
                for e in range(n_experts)
            )
            return jnp.sum(jnp.square(out))

        with set_mesh(mesh):
            g = jax.grad(loss_ep)((ps, gate, x))
        gr = jax.grad(loss_ref)((ps, gate, x))
        for a, e, name in zip(g, gr, ["dps", "dgate", "dx"]):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(e), atol=1e-4, err_msg=name
            )
