"""Tests for evaluation metrics."""

import jax.numpy as jnp
import pytest

from tpuflow.core import mae_vs_baseline, r2_score, rmse


def test_rmse():
    assert float(rmse(jnp.array([0.0, 0.0]), jnp.array([3.0, 4.0]))) == pytest.approx(
        (12.5) ** 0.5
    )


def test_r2_perfect_and_mean():
    y = jnp.array([1.0, 2.0, 3.0, 4.0])
    assert float(r2_score(y, y)) == pytest.approx(1.0)
    assert float(r2_score(y, jnp.full_like(y, jnp.mean(y)))) == pytest.approx(0.0)


def test_mae_vs_baseline_ratio():
    y = jnp.array([10.0, 20.0])
    pred = jnp.array([11.0, 21.0])  # MAE 1
    base = jnp.array([12.0, 22.0])  # MAE 2
    out = mae_vs_baseline(y, pred, base)
    assert float(out["mae"]) == pytest.approx(1.0)
    assert float(out["baseline_mae"]) == pytest.approx(2.0)
    assert float(out["mae_ratio"]) == pytest.approx(0.5)
