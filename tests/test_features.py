"""Tests for the feature pipeline — especially the fixed fit-once semantics
(reference bug C6: per-split re-fit, cnn.py:89-91)."""

import numpy as np
import pytest

from tpuflow.data import FeaturePipeline, Schema

SCHEMA = Schema.from_cli(
    "pressure,completion,flow", "float,string,float", "flow"
)


def _cols(pressure, completion, flow):
    return {
        "pressure": np.asarray(pressure, dtype=np.float32),
        "completion": np.asarray(completion),
        "flow": np.asarray(flow, dtype=np.float32),
    }


def test_one_hot_assembly_order_and_width():
    train = _cols([1.0, 2.0, 3.0], ["a", "b", "a"], [10.0, 20.0, 30.0])
    pipe = FeaturePipeline(SCHEMA, standardize=False).fit(train)
    # vocab ordered by freq desc: a (2), b (1)
    assert pipe.vocabs["completion"] == ["a", "b"]
    assert pipe.feature_dim == 3  # 2 one-hot + 1 continuous
    x = pipe.transform(train)
    np.testing.assert_array_equal(
        x, [[1, 0, 1.0], [0, 1, 2.0], [1, 0, 3.0]]
    )


def test_fit_once_consistent_across_splits():
    """Same category must map to the same index in every split."""
    train = _cols([1, 2, 3], ["a", "b", "a"], [1, 2, 3])
    val = _cols([4], ["b"], [4])
    pipe = FeaturePipeline(SCHEMA, standardize=False).fit(train)
    xv = pipe.transform(val)
    np.testing.assert_array_equal(xv[0, :2], [0, 1])  # 'b' -> index 1 always


def test_unknown_category_all_zeros():
    train = _cols([1, 2], ["a", "b"], [1, 2])
    pipe = FeaturePipeline(SCHEMA, standardize=False).fit(train)
    x = pipe.transform(_cols([5], ["NEVER_SEEN"], [5]))
    np.testing.assert_array_equal(x[0, :2], [0, 0])


def test_standardization_train_stats_only():
    train = _cols([0.0, 2.0], ["a", "a"], [1, 2])
    test = _cols([4.0], ["a"], [3])
    pipe = FeaturePipeline(SCHEMA, standardize=True).fit(train)
    xt = pipe.transform(test)
    # continuous col: mean 1, std 1 -> (4-1)/1 = 3
    assert xt[0, -1] == pytest.approx(3.0)


def test_continuous_target_passthrough_and_categorical_target_indexing():
    train = _cols([1, 2], ["a", "b"], [5.5, 6.5])
    pipe = FeaturePipeline(
        SCHEMA, standardize=False, standardize_target=False
    ).fit(train)
    np.testing.assert_allclose(pipe.transform_target(train), [5.5, 6.5])

    cat_schema = Schema.from_cli("x,lbl", "float,string", "lbl")
    cols = {
        "x": np.asarray([1.0, 2.0, 3.0], dtype=np.float32),
        "lbl": np.asarray(["hi", "lo", "hi"]),
    }
    p2 = FeaturePipeline(cat_schema, standardize=False).fit(cols)
    np.testing.assert_array_equal(p2.transform_target(cols), [0, 1, 0])


def test_target_standardization_and_inverse():
    """Raw flow targets are O(10^3); scaled targets keep clip=6 meaningful."""
    train = _cols([1, 2, 3], ["a", "a", "b"], [1000.0, 2000.0, 3000.0])
    pipe = FeaturePipeline(SCHEMA, standardize=False).fit(train)
    y = pipe.transform_target(train)
    assert abs(y.mean()) < 1e-5 and y.std() == pytest.approx(1.0, rel=1e-4)
    np.testing.assert_allclose(
        pipe.inverse_target(y), [1000.0, 2000.0, 3000.0], rtol=1e-5
    )


def test_transform_before_fit_raises():
    with pytest.raises(RuntimeError):
        FeaturePipeline(SCHEMA).transform(_cols([1], ["a"], [1]))
