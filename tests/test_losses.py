"""Golden-value tests for losses — especially mae_clip parity with the
reference's Theano clip semantics (reference cnn.py:29-32, CLIP_VALUE=6)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpuflow.core import huber, mae, mae_clip, mse


def test_mae_clip_golden():
    """errors [1, 5, 10, 0] -> clipped [1, 5, 6, 0] -> mean 3.0."""
    y_true = jnp.array([0.0, 0.0, 0.0, 0.0])
    y_pred = jnp.array([1.0, -5.0, 10.0, 0.0])
    assert float(mae_clip(y_true, y_pred)) == pytest.approx(3.0)


def test_mae_clip_below_threshold_equals_mae():
    y_true = jnp.array([1.0, 2.0, 3.0])
    y_pred = jnp.array([1.5, 1.0, 3.2])
    assert float(mae_clip(y_true, y_pred)) == pytest.approx(float(mae(y_true, y_pred)))


def test_mae_clip_saturates():
    """All-outlier batch: loss caps at exactly CLIP_VALUE."""
    y_true = jnp.zeros(8)
    y_pred = jnp.full(8, 1e6)
    assert float(mae_clip(y_true, y_pred)) == pytest.approx(6.0)


def test_mae_clip_custom_clip():
    y_true, y_pred = jnp.zeros(2), jnp.array([1.0, 9.0])
    assert float(mae_clip(y_true, y_pred, clip_value=2.0)) == pytest.approx(1.5)


def test_mae_clip_gradient_zero_in_saturated_region():
    """Outliers beyond the clip contribute zero gradient — the mechanism that
    makes the loss outlier-resistant."""
    g = jax.grad(lambda p: mae_clip(jnp.zeros(1), p))(jnp.array([100.0]))
    assert float(g[0]) == pytest.approx(0.0)
    g2 = jax.grad(lambda p: mae_clip(jnp.zeros(1), p))(jnp.array([3.0]))
    assert float(g2[0]) == pytest.approx(1.0)


def test_mse_and_huber():
    y_true = jnp.array([0.0, 0.0])
    y_pred = jnp.array([1.0, 3.0])
    assert float(mse(y_true, y_pred)) == pytest.approx(5.0)
    # huber(delta=1): 0.5*1 for err=1; 0.5 + 1*(3-1) = 2.5 for err=3 -> mean 1.5
    assert float(huber(y_true, y_pred)) == pytest.approx(1.5)


def test_losses_jittable():
    f = jax.jit(mae_clip)
    x = jnp.ones(16)
    np.testing.assert_allclose(float(f(x, x)), 0.0)


def test_pallas_loss_selectable_from_train_config():
    """loss="mae_clip_pallas" runs the fused kernel end to end through
    train() (registry entry is lazy to avoid the core<->kernels cycle)."""
    import numpy as np

    from tpuflow.api import TrainJobConfig, train

    report = train(
        TrainJobConfig(
            model="static_mlp",
            loss="mae_clip_pallas",
            # One epoch over a small set: the interpret-mode Pallas loss
            # executes eagerly per dispatch on CPU, so runtime scales
            # with step count — the wiring is what's under test, and the
            # kernel's numerics have their own golden tests.
            max_epochs=1,
            batch_size=32,
            verbose=False,
            n_devices=1,
            synthetic_wells=2,
            synthetic_steps=48,
        )
    )
    assert np.isfinite(report.test_loss)
