"""Shared-runtime supervisor + chaos schedule + the day-in-the-life
mini soak (tpuflow/runtime/, docs/architecture.md).

The supervisor drills use synthetic ServiceSpecs (dict handles, scripted
liveness) so lifecycle behavior — dependency order, restart policy,
crash-loop classification, healthz rollup — is asserted without real
workloads; the mini soak at the bottom is the real thing: gang + daemon
+ online loop + Poisson traffic under a seeded fault storm, graded by
one SLO report card.
"""

from __future__ import annotations

import json
import os
import threading
import time
import urllib.error
import urllib.request

import pytest

from tpuflow.obs import Registry
from tpuflow.resilience import (
    FaultInjected,
    armed,
    clear_faults,
    fault_point,
)
from tpuflow.runtime import (
    ChaosPhase,
    ChaosSchedule,
    RuntimeSupervisor,
    ServiceSpec,
    mini_soak_spec,
    process_service,
    run_soak,
    thread_service,
)
from tpuflow.runtime.supervisor import _topo_order


@pytest.fixture(autouse=True)
def _clean_faults(monkeypatch):
    monkeypatch.delenv("TPUFLOW_FAULTS", raising=False)
    monkeypatch.delenv("TPUFLOW_FAULTS_CURSOR", raising=False)
    clear_faults()
    yield
    clear_faults()


def _wait_for(cond, timeout: float = 8.0) -> bool:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.01)
    return False


def _noop_spec(name: str, depends_on=(), **kw) -> ServiceSpec:
    return ServiceSpec(
        name=name, start=lambda: object(), stop=lambda h, g: "stopped",
        liveness=lambda h: ("ok", ""), depends_on=depends_on, **kw,
    )


def _box_service(name: str, *, probe=None, depends_on=(), **kw):
    """A scripted service: the box records starts/stops, ``probe(box)``
    scripts the liveness answer."""
    box = {
        "starts": 0, "stops": [],
        "probe": probe or (lambda b: ("ok", "")),
    }

    def _start():
        box["starts"] += 1
        return box

    def _stop(handle, grace):
        box["stops"].append(grace)
        return "stopped"

    def _liveness(handle):
        return box["probe"](box)

    return box, ServiceSpec(
        name=name, start=_start, stop=_stop, liveness=_liveness,
        depends_on=depends_on, **kw,
    )


class TestTopoOrder:
    def test_declaration_order_without_deps(self):
        specs = [_noop_spec(n) for n in ("c", "a", "b")]
        assert _topo_order(specs) == ["c", "a", "b"]

    def test_dependencies_start_first(self):
        specs = [
            _noop_spec("serving", depends_on=("gang",)),
            _noop_spec("traffic", depends_on=("serving",)),
            _noop_spec("gang"),
        ]
        assert _topo_order(specs) == ["gang", "serving", "traffic"]

    def test_cycle_rejected(self):
        specs = [
            _noop_spec("a", depends_on=("b",)),
            _noop_spec("b", depends_on=("a",)),
        ]
        with pytest.raises(ValueError, match="cycle"):
            _topo_order(specs)

    def test_unknown_dependency_rejected(self):
        with pytest.raises(ValueError, match="unknown service"):
            _topo_order([_noop_spec("a", depends_on=("ghost",))])

    def test_self_dependency_rejected(self):
        with pytest.raises(ValueError, match="depends on itself"):
            _topo_order([_noop_spec("a", depends_on=("a",))])

    def test_duplicate_names_rejected(self):
        with pytest.raises(ValueError, match="duplicate service names"):
            _topo_order([_noop_spec("a"), _noop_spec("a")])


class TestSpecValidation:
    def test_negative_grace_rejected(self):
        with pytest.raises(ValueError, match="grace"):
            _noop_spec("a", grace=-1.0)

    def test_negative_restart_budget_rejected(self):
        with pytest.raises(ValueError, match="max_restarts"):
            _noop_spec("a", max_restarts=-1)

    def test_zero_crash_loop_threshold_rejected(self):
        with pytest.raises(ValueError, match="crash_loop_threshold"):
            _noop_spec("a", crash_loop_threshold=0)

    def test_empty_name_rejected(self):
        with pytest.raises(ValueError, match="name"):
            _noop_spec("")


class TestSupervisorLifecycle:
    def test_shutdown_reverses_startup_order(self):
        boxes = {}
        specs = []
        for name, deps in (
            ("gang", ()), ("serving", ("gang",)), ("traffic", ("serving",)),
        ):
            box, spec = _box_service(name, depends_on=deps)
            boxes[name] = box
            specs.append(spec)
        sup = RuntimeSupervisor(specs, registry=Registry())
        sup.start()
        snap = sup.shutdown()
        services = snap["services"]
        # Reverse dependency order: the dependent stops FIRST.
        assert services["traffic"]["stop_index"] == 0
        assert services["serving"]["stop_index"] == 1
        assert services["gang"]["stop_index"] == 2
        assert all(s["state"] == "stopped" for s in services.values())
        assert all(s["killed_by"] == "stopped" for s in services.values())
        assert all(b["stops"] for b in boxes.values())

    def test_start_failure_unwinds_started_prefix(self):
        first, spec_a = _box_service("a")

        def _boom():
            raise RuntimeError("no port")

        spec_b = ServiceSpec(
            name="b", start=_boom, stop=lambda h, g: None,
            liveness=lambda h: ("ok", ""), depends_on=("a",),
        )
        sup = RuntimeSupervisor([spec_a, spec_b], registry=Registry())
        with pytest.raises(RuntimeError, match="no port"):
            sup.start()
        # The already-started prefix was stopped on the way out.
        assert first["stops"], "service a leaked through the failed start"

    def test_finished_service_detected_and_result_kept(self):
        svc = thread_service("worker", lambda stop: 42, grace=2.0)
        sup = RuntimeSupervisor(
            [svc], registry=Registry(), probe_interval=0.02,
        )
        sup.start()
        try:
            assert _wait_for(
                lambda: sup.healthz()["services"]["worker"]["state"]
                == "finished"
            )
            # FINISHED is terminal-but-healthy.
            assert sup.healthz()["status"] == "ok"
            assert sup.service_handle("worker").result == 42
            assert sup.wait(timeout=2.0)
        finally:
            sup.shutdown()

    def test_dead_service_restarts_under_budget(self):
        # Scripted: the first incarnation reads dead, later ones ok.
        def _probe(box):
            return ("dead", "first life ends") if box["starts"] == 1 \
                else ("ok", "")

        box, spec = _box_service(
            "flappy", probe=_probe, max_restarts=2, min_uptime=0.0,
            backoff_base=0.001, backoff_max=0.002,
        )
        registry = Registry()
        sup = RuntimeSupervisor(
            [spec], registry=registry, probe_interval=0.02,
        )
        sup.start()
        try:
            assert _wait_for(lambda: box["starts"] == 2)
            assert _wait_for(
                lambda: sup.healthz()["services"]["flappy"]["state"]
                == "running"
            )
            snap = sup.healthz()["services"]["flappy"]
            assert snap["restarts"] == 1
            assert snap["failures"] and "first life ends" in \
                snap["failures"][0]["detail"]
            counter = registry.counter(
                "runtime_service_restarts_total",
                "runtime-supervised service restarts by service",
            )
            assert counter.value(service="flappy") == 1.0
        finally:
            sup.shutdown()

    def test_crash_loop_classified_and_failed_with_budget_left(self):
        box, spec = _box_service(
            "looper", probe=lambda b: ("dead", "boom"),
            max_restarts=10, min_uptime=60.0, crash_loop_threshold=2,
            backoff_base=0.001, backoff_max=0.002,
        )
        sup = RuntimeSupervisor(
            [spec], registry=Registry(), probe_interval=0.02,
        )
        sup.start()
        try:
            assert _wait_for(
                lambda: sup.healthz()["services"]["looper"]["state"]
                == "failed"
            )
            snap = sup.healthz()["services"]["looper"]
            # Classified after 2 fast deaths, NOT after 11 attempts.
            assert "crash loop" in snap["detail"]
            assert snap["restarts"] < 10
            assert sup.healthz()["status"] == "failed"
        finally:
            sup.shutdown()

    def test_restart_budget_exhausted_fails(self):
        box, spec = _box_service(
            "mortal", probe=lambda b: ("dead", "gone"),
            max_restarts=0, min_uptime=0.0,
        )
        sup = RuntimeSupervisor(
            [spec], registry=Registry(), probe_interval=0.02,
        )
        sup.start()
        try:
            assert _wait_for(
                lambda: sup.healthz()["services"]["mortal"]["state"]
                == "failed"
            )
            assert "restart budget exhausted" in \
                sup.healthz()["services"]["mortal"]["detail"]
        finally:
            sup.shutdown()

    def test_runtime_services_gauge_tracks_states(self):
        registry = Registry()
        _, spec_a = _box_service("a")
        _, spec_b = _box_service("b")
        sup = RuntimeSupervisor([spec_a, spec_b], registry=registry)
        gauge = registry.gauge(
            "runtime_services",
            "runtime-supervised services by lifecycle state",
        )
        # Before start: everything pending, and every state has a
        # sample (zeros, not missing series).
        assert gauge.value(state="pending") == 2.0
        assert gauge.value(state="running") == 0.0
        sup.start()
        try:
            assert gauge.value(state="running") == 2.0
            assert gauge.value(state="pending") == 0.0
        finally:
            sup.shutdown()
        assert gauge.value(state="stopped") == 2.0
        assert gauge.value(state="running") == 0.0

    def test_healthz_http_endpoint_rolls_up(self):
        _, good = _box_service("good")
        sup = RuntimeSupervisor(
            [good], registry=Registry(), probe_interval=0.02,
        )
        sup.start()
        try:
            port = sup.serve_healthz()
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=5
            ) as resp:
                assert resp.status == 200
                doc = json.loads(resp.read().decode())
            assert doc["status"] == "ok"
            assert doc["services"]["good"]["state"] == "running"
        finally:
            sup.shutdown()

    def test_healthz_http_503_once_a_service_failed(self):
        _, bad = _box_service(
            "bad", probe=lambda b: ("dead", "gone"), max_restarts=0,
            min_uptime=0.0,
        )
        sup = RuntimeSupervisor(
            [bad], registry=Registry(), probe_interval=0.02,
        )
        sup.start()
        try:
            port = sup.serve_healthz()
            assert _wait_for(
                lambda: sup.healthz()["status"] == "failed"
            )
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/healthz", timeout=5
                )
            assert e.value.code == 503
        finally:
            sup.shutdown()


class TestChaosSchedule:
    def test_phase_needs_exactly_one_trigger(self):
        with pytest.raises(ValueError, match="exactly one trigger"):
            ChaosPhase(name="p", faults=("stream.read,nth=1",))
        with pytest.raises(ValueError, match="exactly one trigger"):
            ChaosPhase(
                name="p", faults=("stream.read,nth=1",),
                at_s=1.0, on_event="shift",
            )

    def test_phase_validation(self):
        with pytest.raises(ValueError, match="no faults"):
            ChaosPhase(name="p", faults=(), at_s=1.0)
        with pytest.raises(ValueError, match="duration_s"):
            ChaosPhase(
                name="p", faults=("stream.read,nth=1",), at_s=1.0,
                duration_s=0.0,
            )
        with pytest.raises(ValueError, match="duplicate"):
            ChaosSchedule([
                ChaosPhase(name="p", faults=("stream.read,nth=1",), at_s=1.0),
                ChaosPhase(name="p", faults=("csv.read,nth=1",), at_s=2.0),
            ], registry=Registry())

    def test_typoed_entry_fails_at_construction(self):
        with pytest.raises(ValueError, match="unknown fault site"):
            ChaosSchedule([
                {"name": "p", "at_s": 1.0, "faults": ["no.such.site,nth=1"]},
            ], registry=Registry())

    def test_event_arms_matching_phase_exactly_once(self):
        sched = ChaosSchedule([
            {"name": "drift", "on_event": "regime_shift",
             "faults": ["stream.read,nth=1"]},
            {"name": "later", "at_s": 9999.0,
             "faults": ["csv.read,nth=1"]},
        ], registry=Registry())
        assert sched.fire_event("no_such_event") == []
        assert sched.fire_event("regime_shift") == ["drift"]
        assert [s.site for s in armed()] == ["stream.read"]
        # Idempotent: one arming per phase, ever.
        assert sched.fire_event("regime_shift") == []
        summary = sched.stop()
        assert armed() == []
        assert [t["action"] for t in summary["trail"]] == \
            ["armed", "disarmed"]

    def test_at_s_phase_arms_then_duration_disarms(self):
        registry = Registry()
        sched = ChaosSchedule([
            {"name": "storm", "at_s": 0.03, "duration_s": 0.1,
             "faults": ["stream.read,p=0.5"]},
        ], seed=3, registry=registry, tick=0.01)
        sched.start()
        try:
            assert _wait_for(lambda: len(armed()) == 1)
            assert _wait_for(lambda: len(armed()) == 0)
        finally:
            summary = sched.stop()
        assert [t["action"] for t in summary["trail"]] == \
            ["armed", "disarmed"]
        assert summary["trail"][1]["why"] == "duration elapsed"
        counter = registry.counter(
            "runtime_chaos_phases_total",
            "chaos-schedule phase transitions by phase and action",
        )
        assert counter.value(phase="storm", action="armed") == 1.0
        assert counter.value(phase="storm", action="disarmed") == 1.0

    def test_schedule_seed_derives_entry_seeds_pinned_wins(self):
        def _specs(seed):
            sched = ChaosSchedule([
                {"name": "p", "on_event": "go",
                 "faults": ["stream.read,p=0.5",
                            "stream.read,p=0.5,seed=123"]},
            ], seed=seed, registry=Registry())
            sched.fire_event("go")
            specs = list(armed())
            sched.stop()
            clear_faults()
            return specs

        a = _specs(9)
        b = _specs(9)
        c = _specs(10)
        # Derived seed: deterministic per (schedule seed, phase, entry).
        assert a[0].seed == b[0].seed != 0
        assert a[0].seed != c[0].seed
        # A pinned seed= in the entry text is never overridden.
        assert a[1].seed == b[1].seed == c[1].seed == 123

    def test_seeded_storm_replays_identically(self):
        def _series():
            sched = ChaosSchedule([
                {"name": "p", "on_event": "go",
                 "faults": ["stream.read,p=0.4"]},
            ], seed=7, registry=Registry())
            sched.fire_event("go")
            out = []
            for i in range(30):
                try:
                    fault_point("stream.read")
                except FaultInjected:
                    out.append(i)
            sched.stop()
            clear_faults()
            return out

        first = _series()
        assert first, "p=0.4 over 30 hits fired nothing — seed bug"
        assert _series() == first


class TestMiniSoak:
    """ISSUE 16 acceptance: the tier-1 day-in-the-life mini soak — 2
    gang workers, 1 correlated storm phase, open-loop Poisson traffic,
    a regime shift with drift-detect → warm retrain → hot swap — must
    survive with dropped == 0 and a computed time-to-adapt, and its
    report card must conform to the committed schema."""

    def test_mini_soak_survives_seeded_storm(self, tmp_path):
        result = run_soak(mini_soak_spec(str(tmp_path / "soak")))
        assert result["ok"], {
            k: result[k] for k in ("ok", "dropped", "card_error")
        }
        assert result["dropped"] == 0
        assert result["card_error"] is None
        # The adapt lifecycle was COMPUTED, not absent: drift detected,
        # retrained, swapped, with a measured time-to-adapt.
        assert result["time_to_adapt_s"] is not None
        assert result["time_to_adapt_s"] > 0
        card = result["card"]
        from tpuflow.obs.slo import validate_report_card

        validate_report_card(card)  # the committed schema contract
        src = card["source"]
        # The storm armed, fired, and was disarmed.
        trail = src["chaos"]["trail"]
        assert [t["action"] for t in trail] == ["armed", "disarmed"]
        assert trail[1]["fired"] >= 1
        # Every request answered; nothing dropped, nothing 500'd.
        assert src["traffic"]["sent"] > 0
        assert set(src["traffic"]["by_status"]) == {"200"}
        # The online loop adapted under load.
        assert src["online"]["retrains"] >= 1
        assert src["online"]["swaps"] >= 1
        # Dependency-aware shutdown: traffic stopped before serving,
        # serving DRAINED before the gang was touched.
        services = src["services"]
        assert services["serving"]["killed_by"] == "drained"
        assert services["traffic"]["stop_index"] \
            < services["serving"]["stop_index"] \
            < services["gang"]["stop_index"]
        # The autoscaler rode as the sixth managed service: its control
        # loop ticked against the live daemon's history and stopped
        # before the serving drain it depends on.
        assert "autoscale" in services
        assert services["autoscale"]["stop_index"] \
            < services["serving"]["stop_index"]
        auto = src["autoscale"]
        assert auto["schema"] == "tpuflow.serve_autoscale/v1"
        assert auto["ticks"] >= 1
        # The hard floors held for the whole soak.
        assert auto["replicas"] >= auto["floors"]["min_replicas"]
        assert auto["max_inflight"] >= auto["floors"]["min_inflight"]
        report_path = os.path.join(result["root"], "soak_report.json")
        assert os.path.exists(report_path)
        assert json.load(open(report_path))["ok"] is True

    def test_mini_soak_latency_storm_emits_flight_bundle(
        self, tmp_path, monkeypatch
    ):
        """ISSUE 20 acceptance: with the profiling plane and flight
        recorder armed, the seeded latency storm breaches the p99
        alert and the firing transition captures at least one
        schema-valid bundle whose profiler snapshot names the storm's
        component — the micro-batcher lanes, where the serve.execute
        delay faults sleep — as the top wall-clock consumer."""
        from tpuflow.obs.flight import list_bundles, load_bundle, \
            validate_bundle
        from tpuflow.obs.profiler import top_component

        flight_dir = str(tmp_path / "flight")
        monkeypatch.setenv("TPUFLOW_OBS_PROFILE", "1")
        monkeypatch.setenv("TPUFLOW_OBS_PROFILE_INTERVAL_S", "0.01")
        monkeypatch.setenv("TPUFLOW_OBS_FLIGHT", "1")
        monkeypatch.setenv("TPUFLOW_OBS_FLIGHT_DIR", flight_dir)
        # Make the storm's 20 ms injected delays an SLO breach: p99
        # target far below them, short confirmation window, and
        # history ticks fast enough to see the breach while it lasts.
        monkeypatch.setenv("TPUFLOW_SERVE_SLO_P99_MS", "5")
        monkeypatch.setenv("TPUFLOW_SERVE_ALERT_FOR_S", "1")
        monkeypatch.setenv("TPUFLOW_OBS_HISTORY_INTERVAL_S", "0.25")

        spec = mini_soak_spec(str(tmp_path / "soak"))
        # Harden the seeded latency storm: the stock 20 ms delays fire
        # once per coalesced dispatch and lose the wall-clock race to
        # per-request prep work; 50 ms at p=0.9 makes the batcher lanes
        # the unambiguous top consumer the profiler must name.
        spec["chaos"]["phases"][0]["faults"][2] = \
            "serve.execute,p=0.9,mode=delay,delay=0.05"
        result = run_soak(spec)
        # The observability plane rides along without harming the
        # soak's own acceptance.
        assert result["ok"], {
            k: result[k] for k in ("ok", "dropped", "card_error")
        }
        names = list_bundles(flight_dir)
        assert names, "latency storm produced no flight bundle"
        docs = [load_bundle(flight_dir, n) for n in names]
        for doc in docs:
            assert validate_bundle(doc) == []
        alert_docs = [d for d in docs if d["trigger"] == "alert"]
        assert alert_docs, "no bundle was captured by an alert firing"
        # The black box names the culprit: the profiler snapshot inside
        # at least one alert bundle ranks the batcher lanes (where the
        # injected delays slept) as the top wall-clock consumer.
        tops = {
            top_component(d["profile"])
            for d in alert_docs if d.get("profile")
        }
        assert "batcher" in tops, tops
        # Every alert bundle carries the evidence chain: the firing
        # rule, the rule-relevant history window, and live threads.
        for doc in alert_docs:
            assert doc["rule"]
            assert doc["history"]["series"]
            assert any(
                t["component"] == "batcher" for t in doc["threads"]
            )


@pytest.mark.slow
class TestFullSoak:
    def test_full_soak_within_wall_budget(self, tmp_path):
        spec = mini_soak_spec(str(tmp_path / "soak"))
        # More workers need more wells: each worker trains its shard,
        # and a shard must still fill at least one batch.
        spec["gang"].update({
            "workers": 3, "epochs": 4,
            "synthetic_wells": 3, "synthetic_steps": 128,
        })
        spec["traffic"].update({"max_requests": 200, "rate_rps": 50.0})
        spec["online"].update({"shifted_windows": 8})
        # A second storm phase keyed to the scenario, not the clock:
        # flaky drift scoring exactly while drift is being detected.
        spec["chaos"]["phases"].append({
            "name": "drift-flake", "on_event": "regime_shift",
            "duration_s": 6.0,
            "faults": ["online.drift,p=0.2,mode=delay,delay=0.05"],
        })
        budget_s = 300.0
        t0 = time.monotonic()
        result = run_soak(spec)
        wall = time.monotonic() - t0
        assert result["ok"], {
            k: result[k] for k in ("ok", "dropped", "card_error")
        }
        assert result["dropped"] == 0
        assert wall < budget_s, (
            f"full soak blew its wall-clock budget: {wall:.1f}s "
            f">= {budget_s}s"
        )
        trail = result["card"]["source"]["chaos"]["trail"]
        armed_phases = {
            t["phase"] for t in trail if t["action"] == "armed"
        }
        # BOTH phases opened: the clocked storm and the one triggered
        # by the regime shift actually happening.
        assert armed_phases == {"storm", "drift-flake"}
