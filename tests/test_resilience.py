"""Resilience subsystem: fault registry, retry policy, wired sites,
degraded serving (docs/resilience.md).

Failure here is an INPUT: every drill arms a deterministic fault spec
and asserts the system's contracted response — absorbed, contained, or
degraded — then that the drill is reproducible (same spec, same firing).
"""

from __future__ import annotations

import json
import os
import re
import shutil
import time

import numpy as np
import pytest

from tpuflow.resilience import (
    SITES,
    FaultInjected,
    FaultSpec,
    RetryPolicy,
    TransientFault,
    arm,
    armed,
    clear_faults,
    fault_point,
    fired_log,
    parse_fault_spec,
    retry_call,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_registry(monkeypatch):
    """Every test starts and ends with nothing armed, fast retries, and
    no env faults leaking between tests."""
    monkeypatch.delenv("TPUFLOW_FAULTS", raising=False)
    monkeypatch.setenv("TPUFLOW_RETRY_BASE", "0.001")
    monkeypatch.setenv("TPUFLOW_RETRY_MAX", "0.002")
    clear_faults()
    yield
    clear_faults()


class TestSpecGrammar:
    def test_parse_full_entry(self):
        s = parse_fault_spec("checkpoint.save,at=3,mode=exit,code=43")
        assert s.site == "checkpoint.save"
        assert s.at == 3 and s.mode == "exit" and s.code == 43

    def test_parse_probabilistic(self):
        s = parse_fault_spec("stream.read,p=0.25,seed=7")
        assert s.p == 0.25 and s.seed == 7

    def test_unknown_site_rejected(self):
        with pytest.raises(ValueError, match="unknown fault site"):
            parse_fault_spec("checkpoint.svae,nth=1")

    def test_unknown_option_rejected(self):
        with pytest.raises(ValueError, match="unknown fault spec option"):
            parse_fault_spec("csv.read,nht=1")

    def test_never_firing_spec_rejected(self):
        with pytest.raises(ValueError, match="never fires"):
            parse_fault_spec("csv.read")

    def test_bad_mode_rejected(self):
        with pytest.raises(ValueError, match="raise|exit|hang"):
            parse_fault_spec("csv.read,nth=1,mode=explode")

    def test_at_on_indexless_site_rejected(self):
        # csv.read's fault_point passes no index: an at= spec there
        # could never fire — a drill that silently never fires fakes a
        # pass, so arming it must fail loudly.
        with pytest.raises(ValueError, match="passes no index"):
            parse_fault_spec("csv.read,at=3")

    def test_parse_delay_mode(self):
        s = parse_fault_spec(
            "elastic.transport.send,p=1,mode=delay,delay=0.25"
        )
        assert s.mode == "delay" and s.delay == 0.25

    def test_negative_delay_rejected(self):
        with pytest.raises(ValueError, match="delay"):
            parse_fault_spec("csv.read,nth=1,mode=delay,delay=-1")

    def test_delay_mode_survives_the_site(self):
        # The straggler knob: the site is SLOWED, not killed — the call
        # returns normally and the firing is logged.
        from tpuflow.resilience.faults import fired_log

        arm(parse_fault_spec("csv.read,nth=1,mode=delay,delay=0.0"))
        fault_point("csv.read")  # fires: sleeps 0s, then continues
        assert any(
            rec["site"] == "csv.read" for rec in fired_log()
        )
        fault_point("csv.read")  # one-shot: disarmed


class TestRegistry:
    def test_nth_is_one_shot_by_count(self):
        arm(parse_fault_spec("csv.read,nth=2"))
        fault_point("csv.read")  # hit 1: no fire
        with pytest.raises(FaultInjected):
            fault_point("csv.read")  # hit 2: fires
        fault_point("csv.read")  # disarmed: never double-fires
        assert armed() == []
        assert len(fired_log()) == 1

    def test_at_matches_index_one_shot(self):
        arm(parse_fault_spec("train.epoch_start,at=3"))
        fault_point("train.epoch_start", index=1)
        fault_point("train.epoch_start", index=2)
        with pytest.raises(FaultInjected, match="index=3"):
            fault_point("train.epoch_start", index=3)
        fault_point("train.epoch_start", index=3)  # one-shot
        assert armed() == []

    def test_probabilistic_is_seed_deterministic(self):
        def firing_pattern(seed):
            clear_faults()
            arm(FaultSpec(site="stream.read", p=0.5, seed=seed))
            pattern = []
            for _ in range(20):
                try:
                    fault_point("stream.read")
                    pattern.append(0)
                except FaultInjected:
                    pattern.append(1)
            return pattern

        a, b = firing_pattern(7), firing_pattern(7)
        assert a == b  # the same drill replays identically
        assert firing_pattern(8) != a  # and the seed is actually used
        assert sum(a) > 0  # p=0.5 over 20 calls: fires

    def test_transient_flag_selects_retryable_type(self):
        arm(parse_fault_spec("csv.read,nth=1,transient=1"))
        with pytest.raises(TransientFault):
            fault_point("csv.read")

    def test_env_arming_and_resync(self, monkeypatch):
        monkeypatch.setenv("TPUFLOW_FAULTS", "csv.read,nth=1")
        with pytest.raises(FaultInjected):
            fault_point("csv.read")
        # Changing the env re-arms without any install call.
        monkeypatch.setenv("TPUFLOW_FAULTS", "stream.read,nth=1")
        fault_point("csv.read")  # old env spec gone
        with pytest.raises(FaultInjected):
            fault_point("stream.read")

    def test_env_typo_arms_nothing_and_keeps_failing_loud(
        self, monkeypatch
    ):
        # A typo ANYWHERE in TPUFLOW_FAULTS arms NOTHING (parse-all-
        # before-arm) and raises at every fault_point until fixed —
        # never a partial drill that fakes a pass.
        monkeypatch.setenv(
            "TPUFLOW_FAULTS", "checkpoint.save,nth=1;typo.site,nth=1"
        )
        with pytest.raises(ValueError, match="unknown fault site"):
            fault_point("checkpoint.save", index=1)
        assert armed() == []
        with pytest.raises(ValueError, match="unknown fault site"):
            fault_point("csv.read")  # still loud, any site, any call

    def test_clear_then_same_env_value_rearms(self, monkeypatch):
        monkeypatch.setenv("TPUFLOW_FAULTS", "csv.read,nth=1")
        with pytest.raises(FaultInjected):
            fault_point("csv.read")
        clear_faults()
        # Byte-identical env value after a clear must still arm (the
        # cache is reset by clear_faults, not just the spec list).
        monkeypatch.setenv("TPUFLOW_FAULTS", "csv.read,nth=1")
        with pytest.raises(FaultInjected):
            fault_point("csv.read")

    def test_unregistered_site_fails_loudly(self):
        with pytest.raises(RuntimeError, match="not in the SITES catalog"):
            fault_point("no.such.site")


class TestRetryPolicy:
    def _policy(self, **kw):
        kw.setdefault("base_delay", 0.001)
        kw.setdefault("max_delay", 0.002)
        kw.setdefault("deadline", 5.0)
        return RetryPolicy(**kw)

    def test_absorbs_transient_then_succeeds(self):
        calls = []

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise TransientFault("flaky", "csv.read")
            return "ok"

        assert retry_call(self._policy(), flaky) == "ok"
        assert len(calls) == 3

    def test_oserror_is_transient_by_default(self):
        calls = []

        def flaky():
            calls.append(1)
            if len(calls) == 1:
                raise OSError("mount blip")
            return 42

        assert retry_call(self._policy(), flaky) == 42

    def test_deterministic_failure_not_retried(self):
        calls = []

        def broken():
            calls.append(1)
            raise ValueError("malformed row")

        with pytest.raises(ValueError):
            retry_call(self._policy(), broken)
        assert len(calls) == 1  # retrying a parse bug is pure latency

    def test_deterministic_oserrors_not_retried(self):
        # A typo'd path replays identically: FileNotFoundError (and
        # kin) must not be treated as the transient OSError class.
        for exc in (FileNotFoundError, PermissionError, IsADirectoryError):
            calls = []

            def broken(exc=exc):
                calls.append(1)
                raise exc("/no/such/path")

            with pytest.raises(exc):
                retry_call(self._policy(), broken)
            assert len(calls) == 1

    def test_attempts_exhausted_raises_last_with_count(self):
        def always():
            raise OSError("down")

        with pytest.raises(OSError) as e:
            retry_call(self._policy(max_attempts=3), always)
        assert e.value.retry_attempts == 3

    def test_deadline_bounds_total_wait(self):
        slept = []

        def always():
            raise OSError("down")

        with pytest.raises(OSError) as e:
            retry_call(
                self._policy(
                    max_attempts=100, base_delay=10.0, max_delay=10.0,
                    deadline=0.5, sleep=slept.append,
                ),
                always,
            )
        # First retry's 10s delay already blows the 0.5s deadline.
        assert slept == [] and e.value.retry_attempts == 1

    def test_backoff_grows_exponentially_with_seeded_jitter(self):
        slept = []

        def always():
            raise OSError("down")

        pol = self._policy(
            max_attempts=4, base_delay=0.1, max_delay=10.0,
            multiplier=2.0, jitter=0.0, sleep=slept.append, seed=0,
        )
        with pytest.raises(OSError):
            retry_call(pol, always)
        assert slept == pytest.approx([0.1, 0.2, 0.4])


class TestRetryEnvValidation:
    """Satellite: the ``TPUFLOW_RETRY_*`` knobs are validated at read
    time — a typo'd or negative value raises a ValueError naming the
    env var and the expected form (the TPUFLOW_FAULTS precedent),
    instead of a bare float() traceback or a silent clamp."""

    _VARS = (
        "TPUFLOW_RETRY_ATTEMPTS", "TPUFLOW_RETRY_BASE",
        "TPUFLOW_RETRY_MAX", "TPUFLOW_RETRY_DEADLINE",
    )

    def test_defaults_when_unset_or_empty(self, monkeypatch):
        from tpuflow.resilience.retry import io_policy

        for var in self._VARS:
            monkeypatch.delenv(var, raising=False)
        policy = io_policy()
        assert policy.max_attempts == 4 and policy.deadline == 30.0
        monkeypatch.setenv("TPUFLOW_RETRY_BASE", "")
        assert io_policy().base_delay == 0.05

    def test_valid_overrides_apply(self, monkeypatch):
        from tpuflow.resilience.retry import io_policy

        monkeypatch.setenv("TPUFLOW_RETRY_ATTEMPTS", "7")
        monkeypatch.setenv("TPUFLOW_RETRY_BASE", "0.5")
        policy = io_policy()
        assert policy.max_attempts == 7 and policy.base_delay == 0.5

    @pytest.mark.parametrize("var", _VARS)
    def test_non_numeric_names_the_var_and_form(self, monkeypatch, var):
        from tpuflow.resilience.retry import io_policy

        monkeypatch.setenv(var, "fast")
        with pytest.raises(ValueError, match=var) as e:
            io_policy()
        assert "expected" in str(e.value)

    def test_negative_rejected(self, monkeypatch):
        from tpuflow.resilience.retry import io_policy

        monkeypatch.setenv("TPUFLOW_RETRY_MAX", "-1")
        with pytest.raises(ValueError, match="TPUFLOW_RETRY_MAX"):
            io_policy()

    def test_nan_and_inf_rejected(self, monkeypatch):
        # 'nan' survives a < comparison and 'inf' would sleep forever —
        # both must fail the validation, not the eventual time.sleep.
        from tpuflow.resilience.retry import io_policy

        monkeypatch.setenv("TPUFLOW_RETRY_BASE", "nan")
        with pytest.raises(ValueError, match="TPUFLOW_RETRY_BASE"):
            io_policy()
        monkeypatch.setenv("TPUFLOW_RETRY_BASE", "0.05")
        monkeypatch.setenv("TPUFLOW_RETRY_DEADLINE", "inf")
        with pytest.raises(ValueError, match="TPUFLOW_RETRY_DEADLINE"):
            io_policy()

    def test_zero_or_fractional_attempts_rejected(self, monkeypatch):
        from tpuflow.resilience.retry import io_policy

        monkeypatch.setenv("TPUFLOW_RETRY_ATTEMPTS", "0")
        with pytest.raises(
            ValueError, match="TPUFLOW_RETRY_ATTEMPTS"
        ) as e:
            io_policy()
        assert "integer attempt count >= 1" in str(e.value)
        monkeypatch.setenv("TPUFLOW_RETRY_ATTEMPTS", "2.5")
        with pytest.raises(ValueError, match="TPUFLOW_RETRY_ATTEMPTS"):
            io_policy()


@pytest.mark.faultdrill
class TestWiredSites:
    """One injected fault per registry site, against the real code."""

    def test_checkpoint_save_transient_absorbed(self, tmp_path):
        from tpuflow.train.checkpoint import BestCheckpointer

        arm(parse_fault_spec("checkpoint.save,nth=1,transient=1"))
        ckpt = BestCheckpointer(str(tmp_path), "m", async_save=False)
        try:
            assert ckpt.maybe_save(1, {"w": np.ones(3)}, 0.5)  # retried
            assert ckpt.best_step == 1
        finally:
            ckpt.close()
        assert fired_log()[0]["site"] == "checkpoint.save"

    def test_checkpoint_restore_fatal_fault_propagates(self, tmp_path):
        from tpuflow.train.checkpoint import BestCheckpointer

        ckpt = BestCheckpointer(str(tmp_path), "m", async_save=False)
        try:
            ckpt.maybe_save(1, {"w": np.ones(3)}, 0.5)
            arm(parse_fault_spec("checkpoint.restore,nth=1"))
            with pytest.raises(FaultInjected):
                ckpt.restore_best()
            # One-shot: the next restore (the operator's retry) works.
            assert ckpt.restore_best()["w"].shape == (3,)
        finally:
            ckpt.close()

    def test_csv_read_transient_absorbed(self, tmp_path):
        from tpuflow.data.csv_io import read_csv
        from tpuflow.data.schema import Schema

        p = tmp_path / "d.csv"
        p.write_text("1.0,2.0\n3.0,4.0\n")
        schema = Schema.from_cli("a,b", "float,float", "b")
        arm(parse_fault_spec("csv.read,nth=1,transient=1"))
        out = read_csv(str(p), schema)
        assert out["a"].tolist() == [1.0, 3.0]
        assert fired_log()[0]["site"] == "csv.read"

    def test_stream_read_transient_absorbed_mid_stream(self, tmp_path):
        from tpuflow.data.schema import Schema
        from tpuflow.data.stream import stream_csv_columns

        p = tmp_path / "d.csv"
        p.write_text("".join(f"{i}.0,{i}.5\n" for i in range(10)))
        schema = Schema.from_cli("a,b", "float,float", "b")
        # Fault on the SECOND chunk: absorbed without losing chunk 1.
        arm(parse_fault_spec("stream.read,nth=2,transient=1"))
        chunks = list(stream_csv_columns(str(p), schema, chunk_rows=4))
        assert [len(c["a"]) for c in chunks] == [4, 4, 2]
        total = np.concatenate([c["a"] for c in chunks])
        assert total.tolist() == [float(i) for i in range(10)]

    def test_serve_execute_fault_fails_job_not_service(self, tmp_path):
        from tpuflow.serve import JobRunner

        arm(parse_fault_spec("serve.execute,nth=1"))
        runner = JobRunner()
        tiny = {
            "model": "static_mlp", "model_kwargs": {"hidden": [4]},
            "epochs": 1, "batchSize": 32, "n_devices": 1,
            "synthetic_wells": 2, "synthetic_steps": 64,
        }
        job = runner.submit(tiny)
        deadline = time.time() + 120
        while time.time() < deadline:
            rec = runner.get(job["job_id"])
            if rec["status"] in ("done", "failed"):
                break
            time.sleep(0.05)
        assert rec["status"] == "failed"
        assert "FaultInjected" in rec["error"]
        # Containment: the worker survived; the next job runs clean.
        job2 = runner.submit(tiny)
        deadline = time.time() + 120
        while time.time() < deadline:
            rec2 = runner.get(job2["job_id"])
            if rec2["status"] in ("done", "failed"):
                break
            time.sleep(0.05)
        assert rec2["status"] == "done"


class TestCatalogSelfCheck:
    """Docs and code cannot drift: the SITES catalog, the installed
    fault_point() calls, and the docs/resilience.md table must all name
    the same sites."""

    def test_every_installed_hook_is_catalogued(self):
        found = set()
        pkg = os.path.join(REPO, "tpuflow")
        for root, _, files in os.walk(pkg):
            for name in files:
                if not name.endswith(".py"):
                    continue
                with open(os.path.join(root, name), encoding="utf-8") as f:
                    found |= set(
                        re.findall(r'fault_point\(\s*"([a-z_.]+)"', f.read())
                    )
        assert found == set(SITES), (
            "fault_point() call sites and the SITES catalog disagree — "
            "update tpuflow/resilience/faults.py"
        )

    def test_docs_catalog_matches_sites(self):
        doc = os.path.join(REPO, "docs", "resilience.md")
        with open(doc, encoding="utf-8") as f:
            text = f.read()
        # The doc may name other identifiers; the CATALOG section is
        # delimited so the drift check is exact, and site names are the
        # only fully-backticked dotted lowercase tokens inside it.
        section = re.search(
            r"<!-- fault-site-catalog -->(.*?)<!-- /fault-site-catalog -->",
            text,
            re.S,
        )
        assert section, "docs/resilience.md lost its fault-site-catalog markers"
        documented = set(
            re.findall(r"`([a-z_]+(?:\.[a-z_]+)+)`", section.group(1))
        )
        assert documented == set(SITES), (
            "docs/resilience.md fault-site catalog and faults.SITES "
            f"disagree: doc-only={documented - set(SITES)}, "
            f"code-only={set(SITES) - documented}"
        )


NAMES = "pressure,choke,glr,temperature,water_cut,completion,flow"
TYPES = "float,float,float,float,float,string,float"


def _train_artifact(tmp_path):
    from tpuflow.api import TrainJobConfig, train

    return train(
        TrainJobConfig(
            model="static_mlp",
            model_kwargs={"hidden": [8]},
            max_epochs=2,
            batch_size=32,
            seed=0,
            verbose=False,
            n_devices=1,
            storage_path=str(tmp_path),
            synthetic_wells=2,
            synthetic_steps=96,
        )
    )


@pytest.mark.faultdrill
class TestDegradedServing:
    """Acceptance drill: corrupt checkpoint -> Gilbert fallback with
    degraded:true -> /healthz shows it -> retrain recovers."""

    def _corrupt_checkpoint(self, tmp_path):
        # Weights gone, sidecar intact: the partial-corruption case.
        shutil.rmtree(tmp_path / "models" / "static_mlp")

    def test_fallback_serves_gilbert_with_flag(self, tmp_path):
        from tpuflow.core.gilbert import gilbert_flow
        from tpuflow.data.synthetic import generate_wells, wells_to_table
        from tpuflow.serve import PredictService

        _train_artifact(tmp_path)
        self._corrupt_checkpoint(tmp_path)
        svc = PredictService()
        table = wells_to_table(generate_wells(1, 16, seed=3))
        out = svc.predict({
            "storagePath": str(tmp_path), "model": "static_mlp",
            "columns": {k: v.tolist() for k, v in table.items()
                        if k != "completion"},
        })
        assert out["degraded"] is True
        assert out["fallback"] == "gilbert"
        assert out["count"] == 16
        expect = np.asarray(gilbert_flow(
            table["pressure"], table["choke"], table["glr"]
        ))
        np.testing.assert_allclose(out["predictions"], expect, rtol=1e-5)
        # Surfaced for operators, not just per-response.
        deg = svc.degraded()
        assert len(deg) == 1 and deg[0]["model"] == "static_mlp"
        assert svc.metrics()["degraded_requests"] == 1
        assert svc.metrics()["fallback_loads"] == 1

    def test_degraded_csv_uses_sidecar_schema(self, tmp_path):
        from tpuflow.data.synthetic import (
            generate_wells, wells_to_table, write_csv,
        )
        from tpuflow.serve import PredictService

        _train_artifact(tmp_path)
        self._corrupt_checkpoint(tmp_path)
        table = wells_to_table(generate_wells(1, 8, seed=4))
        csv = str(tmp_path / "serve.csv")
        write_csv(csv, table, NAMES.split(","))
        svc = PredictService()
        out = svc.predict({
            "storagePath": str(tmp_path), "model": "static_mlp",
            "data": csv,
        })
        assert out["degraded"] is True and out["count"] == 8

    def test_retrain_recovers_from_degraded(self, tmp_path):
        from tpuflow.data.synthetic import generate_wells, wells_to_table
        from tpuflow.serve import PredictService

        _train_artifact(tmp_path)
        self._corrupt_checkpoint(tmp_path)
        svc = PredictService()
        table = wells_to_table(generate_wells(1, 8, seed=5))
        # The FULL column set: the degraded path needs only the physical
        # three, but the recovered (real) predictor needs every trained
        # feature, categoricals included.
        cols = {k: v.tolist() for k, v in table.items()}
        spec = {
            "storagePath": str(tmp_path), "model": "static_mlp",
            "columns": cols,
        }
        assert svc.predict(spec)["degraded"] is True
        # The job-runner's artifact-change callback is invalidate():
        # a retrain rewrites the weights and evicts the fallback.
        _train_artifact(tmp_path)
        svc.invalidate(str(tmp_path), "static_mlp")
        out = svc.predict(spec)
        assert "degraded" not in out
        assert svc.degraded() == []

    def test_degraded_ttl_reprobes_real_artifact(self, tmp_path):
        """A fallback cached during a TRANSIENT outage must expire: once
        the TTL passes, the next request re-probes and loads the real
        model — degradation heals without any retrain."""
        from tpuflow.data.synthetic import generate_wells, wells_to_table
        from tpuflow.serve import PredictService

        _train_artifact(tmp_path)
        ckpt_dir = tmp_path / "models" / "static_mlp"
        hidden = tmp_path / "hidden_static_mlp"
        # Simulate "storage briefly unreachable": move the checkpoint
        # away, degrade, move it back, wait out the TTL.
        ckpt_dir.rename(hidden)
        svc = PredictService(degraded_retry_seconds=0.2)
        table = wells_to_table(generate_wells(1, 8, seed=6))
        spec = {
            "storagePath": str(tmp_path), "model": "static_mlp",
            "columns": {k: v.tolist() for k, v in table.items()},
        }
        assert svc.predict(spec)["degraded"] is True
        hidden.rename(ckpt_dir)  # the outage ends
        assert svc.predict(spec)["degraded"] is True  # TTL not up: cached
        time.sleep(0.25)
        out = svc.predict(spec)  # TTL expired: re-probe finds the model
        assert "degraded" not in out
        assert svc.degraded() == []

    def test_never_existing_artifact_still_fails_loudly(self, tmp_path):
        from tpuflow.serve import PredictService

        svc = PredictService()
        with pytest.raises(FileNotFoundError):
            svc.predict({
                "storagePath": str(tmp_path), "model": "typo_model",
                "columns": {"pressure": [1.0], "choke": [32.0],
                            "glr": [1.0]},
            })
        assert svc.degraded() == []

    def test_fallback_disabled_propagates(self, tmp_path):
        from tpuflow.serve import PredictService

        _train_artifact(tmp_path)
        self._corrupt_checkpoint(tmp_path)
        svc = PredictService(gilbert_fallback=False)
        with pytest.raises(Exception):
            svc.predict({
                "storagePath": str(tmp_path), "model": "static_mlp",
                "columns": {"pressure": [1.0], "choke": [32.0],
                            "glr": [1.0]},
            })


@pytest.mark.faultdrill
class TestPrecedence:
    """ISSUE 16 satellite: the documented precedence contract between
    in-process specs (arm() / TrainJobConfig.faults) and TPUFLOW_FAULTS
    at the SAME site — the in-process spec is evaluated first at every
    hit, and when it fires the env spec's counters do not advance on
    that call (tpuflow/resilience/faults.py module docstring)."""

    def test_inprocess_spec_beats_env_on_the_same_call(self, monkeypatch):
        # Both would fire on call 1. The env spec raises the TRANSIENT
        # subtype, so which exception arrives identifies the winner.
        monkeypatch.setenv("TPUFLOW_FAULTS", "csv.read,nth=1,transient=1")
        arm(parse_fault_spec("csv.read,nth=1"))
        with pytest.raises(FaultInjected) as e:
            fault_point("csv.read")
        assert not isinstance(e.value, TransientFault)
        # The env spec's hit counter did NOT advance on the call the
        # in-process spec consumed.
        (env_spec,) = [s for s in armed() if s.transient]
        assert env_spec.hits == 0 and env_spec.fired == 0

    def test_env_counters_advance_once_nothing_inprocess_fires(
        self, monkeypatch
    ):
        monkeypatch.setenv("TPUFLOW_FAULTS", "csv.read,nth=2,transient=1")
        arm(parse_fault_spec("csv.read,nth=5"))
        fault_point("csv.read")  # neither fires; BOTH counters advance
        with pytest.raises(TransientFault):
            fault_point("csv.read")  # env nth=2 reached


@pytest.mark.faultdrill
class TestFaultCursor:
    """ISSUE 16 satellite: TPUFLOW_FAULTS_CURSOR persists env-spec
    firing state across process restarts, so a seeded storm RESUMES
    instead of replaying from hit zero. ``clear_faults()`` + unchanged
    env simulates the restart (it resets the registry and the env
    cache exactly as a fresh process would see them)."""

    _SITES = ("stream.read", "checkpoint.save", "serve.execute")

    def test_one_shot_stays_consumed_across_restart(
        self, tmp_path, monkeypatch
    ):
        monkeypatch.setenv("TPUFLOW_FAULTS", "stream.read,nth=2")
        monkeypatch.setenv(
            "TPUFLOW_FAULTS_CURSOR", str(tmp_path / "cursor.json")
        )
        fault_point("stream.read")
        with pytest.raises(FaultInjected):
            fault_point("stream.read")
        clear_faults()  # simulated restart; env unchanged
        for _ in range(5):
            fault_point("stream.read")  # consumed: never re-fires

    def _storm(self, hits: int, restart_at: int | None) -> list:
        """Replay a 3-fault schedule over ``hits`` rounds of all three
        sites; optionally simulate a process restart before round
        ``restart_at``. Returns the firing series."""
        series = []
        for i in range(1, hits + 1):
            if restart_at is not None and i == restart_at:
                clear_faults()
            for site in self._SITES:
                index = i if site == "checkpoint.save" else None
                try:
                    fault_point(site, index=index)
                except FaultInjected:
                    series.append((i, site))
        return series

    def test_restarted_storm_replays_identically(
        self, tmp_path, monkeypatch
    ):
        """The ISSUE 16 regression drill: replay a 3-fault schedule
        twice — once uninterrupted, once with a mid-storm restart — and
        diff the firing series AND the faults_injected_total counter
        deltas. With the cursor they must be identical."""
        from tpuflow.obs import default_registry

        env = ("stream.read,nth=2;checkpoint.save,p=0.5,seed=7;"
               "serve.execute,nth=4")
        monkeypatch.setenv("TPUFLOW_FAULTS", env)
        counter = default_registry().counter(
            "faults_injected_total",
            "armed fault-injection firings by site",
        )

        def _deltas(fn):
            before = {s: counter.value(site=s) for s in self._SITES}
            series = fn()
            return series, {
                s: counter.value(site=s) - before[s] for s in self._SITES
            }

        monkeypatch.setenv(
            "TPUFLOW_FAULTS_CURSOR", str(tmp_path / "a.json")
        )
        series_a, deltas_a = _deltas(lambda: self._storm(12, None))
        assert series_a, "the seeded storm fired nothing"
        clear_faults()
        monkeypatch.setenv(
            "TPUFLOW_FAULTS_CURSOR", str(tmp_path / "b.json")
        )
        series_b, deltas_b = _deltas(lambda: self._storm(12, restart_at=6))
        assert series_b == series_a
        assert deltas_b == deltas_a
        # The one-shots fired exactly once across the restart.
        assert sum(1 for _, s in series_b if s == "stream.read") == 1
        assert sum(1 for _, s in series_b if s == "serve.execute") == 1

    def test_without_cursor_a_restart_replays_from_hit_zero(
        self, monkeypatch
    ):
        # The contrast case (and the crash-loop drills' dependency):
        # no cursor means the one-shot re-fires after the restart.
        monkeypatch.setenv("TPUFLOW_FAULTS", "stream.read,nth=1")
        with pytest.raises(FaultInjected):
            fault_point("stream.read")
        clear_faults()
        with pytest.raises(FaultInjected):
            fault_point("stream.read")

    def test_unresolved_auto_sentinel_means_no_persistence(
        self, monkeypatch
    ):
        # 'auto' is resolved ONLY by train/supervisor.py; reaching a
        # fault_point unresolved degrades to no persistence — and never
        # creates a file literally named 'auto'.
        monkeypatch.setenv("TPUFLOW_FAULTS", "stream.read,nth=1")
        monkeypatch.setenv("TPUFLOW_FAULTS_CURSOR", "auto")
        with pytest.raises(FaultInjected):
            fault_point("stream.read")
        clear_faults()
        with pytest.raises(FaultInjected):
            fault_point("stream.read")  # nothing persisted: re-fires
        assert not os.path.exists("auto")

    def test_stale_cursor_for_other_env_value_is_ignored(
        self, tmp_path, monkeypatch
    ):
        path = tmp_path / "cursor.json"
        path.write_text(json.dumps({
            "version": 1, "env": "some.other,storm",
            "state": {"0:stream.read,nth=1,mode=raise":
                      {"hits": 1, "fired": 1}},
        }))
        monkeypatch.setenv("TPUFLOW_FAULTS", "stream.read,nth=1")
        monkeypatch.setenv("TPUFLOW_FAULTS_CURSOR", str(path))
        # A cursor written for a DIFFERENT storm must not pre-consume
        # this one.
        with pytest.raises(FaultInjected):
            fault_point("stream.read")

    def test_corrupt_cursor_fails_loudly(self, tmp_path, monkeypatch):
        path = tmp_path / "cursor.json"
        path.write_text("not json{")
        monkeypatch.setenv("TPUFLOW_FAULTS", "stream.read,nth=1")
        monkeypatch.setenv("TPUFLOW_FAULTS_CURSOR", str(path))
        with pytest.raises(ValueError, match="TPUFLOW_FAULTS_CURSOR"):
            fault_point("stream.read")
