"""Out-of-core windowed ingest: well-hash splits, chunk-carry windowing,
and end-to-end streaming sequence training."""

import numpy as np
import pytest

from tpuflow.data.schema import Schema
from tpuflow.data.stream_windows import (
    _WellWindower,
    fit_window_normalizer,
    iter_windows,
    materialize_window_split,
    stream_window_batches,
    well_split,
)
from tpuflow.data.synthetic import generate_wells
from tpuflow.data.windows import teacher_forcing_pairs

NAMES = "well,pressure,choke,glr,temperature,water_cut,flow"
TYPES = "string,float,float,float,float,float,float"
SCHEMA = Schema.from_cli(NAMES, TYPES, "flow")
FEATURES = ("pressure", "choke", "glr", "temperature", "water_cut")


def _write_multiwell_csv(tmp_path, n_wells=12, steps=60, interleave=False):
    """Headerless CSV of n_wells logs; optionally row-interleaved so wells
    span chunks non-contiguously (time order preserved per well)."""
    wells = generate_wells(n_wells, steps, seed=0)
    rows = []
    for w_i, w in enumerate(wells):
        for t in range(steps):
            rows.append(
                (f"well{w_i:02d}", w.pressure[t], w.choke[t], w.glr[t],
                 w.temperature[t], w.water_cut[t], w.flow[t])
            )
    if interleave:  # round-robin across wells, per-well time order kept
        rows = [
            rows[w * steps + t]
            for t in range(steps)
            for w in range(n_wells)
        ]
    path = str(tmp_path / "mw.csv")
    with open(path, "w") as f:
        for r in rows:
            f.write(",".join(str(v) for v in r) + "\n")
    return path, wells


class TestWellSplit:
    def test_deterministic_and_covers_all_splits(self):
        a = [well_split(f"w{i}", seed=0) for i in range(300)]
        b = [well_split(f"w{i}", seed=0) for i in range(300)]
        assert a == b
        fracs = [a.count(k) / len(a) for k in range(3)]
        assert abs(fracs[0] - 0.64) < 0.1
        assert abs(fracs[1] - 0.16) < 0.08
        assert abs(fracs[2] - 0.20) < 0.08

    def test_seed_changes_assignment(self):
        a = [well_split(f"w{i}", seed=0) for i in range(100)]
        b = [well_split(f"w{i}", seed=1) for i in range(100)]
        assert a != b


class TestWellWindower:
    @pytest.mark.parametrize("stride", [1, 2, 3])
    @pytest.mark.parametrize("chunk", [3, 7, 100])
    def test_chunked_feed_matches_whole_series(self, stride, chunk):
        rng = np.random.default_rng(0)
        T, F, window = 41, 2, 5
        series = rng.standard_normal((T, F)).astype(np.float32)
        target = rng.standard_normal(T).astype(np.float32)
        want_x, want_y = teacher_forcing_pairs(series, target, window, stride)

        w = _WellWindower(window, stride)
        xs, ys = [], []
        for s in range(0, T, chunk):
            out = w.feed("w", series[s : s + chunk], target[s : s + chunk])
            if out is not None:
                xs.append(out[0])
                ys.append(out[1])
        got_x = np.concatenate(xs) if xs else np.zeros((0, window, F))
        got_y = np.concatenate(ys) if ys else np.zeros((0, window))
        np.testing.assert_allclose(got_x, want_x, rtol=1e-6)
        np.testing.assert_allclose(got_y, want_y, rtol=1e-6)

    @pytest.mark.parametrize("stride", [1, 2])
    def test_extract_backends_agree(self, stride, monkeypatch):
        """The C++ extractor and the stride-trick NumPy fallback produce
        byte-identical windows through the shared engine the windower
        delegates to (tpuflow.data.windows.teacher_forcing_pairs)."""
        from tpuflow import _native
        from tpuflow.data import windows as windows_mod

        if not _native.native_available():
            pytest.skip("native library not built: only one backend to test")
        rng = np.random.default_rng(3)
        s = rng.standard_normal((40, 3)).astype(np.float32)
        t = rng.standard_normal(40).astype(np.float32)
        a = windows_mod.teacher_forcing_pairs(s, t, 6, stride)
        monkeypatch.setattr(
            windows_mod, "_native_windows", lambda *args: None
        )
        b = windows_mod.teacher_forcing_pairs(s, t, 6, stride)
        n = len(range(0, len(s) - 6 + 1, stride))
        assert np.array_equal(a[0], b[0]) and np.array_equal(a[1], b[1])
        assert a[0].shape == (n, 6, 3) and a[1].shape == (n, 6)


class TestIterWindows:
    @pytest.mark.parametrize("interleave", [False, True])
    @pytest.mark.parametrize("chunk_rows", [37, 10_000])
    def test_union_of_splits_is_all_windows(self, tmp_path, interleave, chunk_rows):
        path, wells = _write_multiwell_csv(tmp_path, interleave=interleave)
        window = 8
        got = {
            w: sum(
                x.shape[0]
                for x, _ in iter_windows(
                    path, SCHEMA, "well", FEATURES, w, 0, window,
                    chunk_rows=chunk_rows,
                )
            )
            for w in ("train", "val", "test")
        }
        per_well = 60 - window + 1
        assert sum(got.values()) == len(wells) * per_well
        # Every well's window count is a multiple of per_well: a well never
        # splits its windows across train/val/test.
        assert all(v % per_well == 0 for v in got.values())

    def test_chunk_size_invariance(self, tmp_path):
        path, _ = _write_multiwell_csv(tmp_path)
        a = np.concatenate(
            [x for x, _ in iter_windows(path, SCHEMA, "well", FEATURES,
                                        "train", 0, 8, chunk_rows=53)]
        )
        b = np.concatenate(
            [x for x, _ in iter_windows(path, SCHEMA, "well", FEATURES,
                                        "train", 0, 8, chunk_rows=9999)]
        )
        np.testing.assert_allclose(a, b, rtol=1e-6)


class TestStreamingBatchesAndEval:
    def test_fixed_batch_shapes_and_normalization(self, tmp_path):
        path, _ = _write_multiwell_csv(tmp_path)
        norm = fit_window_normalizer(
            path, SCHEMA, "well", seed=0, window=8, sample_rows=2000
        )
        bs = list(
            stream_window_batches(
                path, SCHEMA, "well", norm, batch_size=16, seed=0, window=8,
                chunk_rows=100, shuffle_buffer=32,
            )
        )
        assert bs and all(x.shape == (16, 8, len(FEATURES)) for x, _ in bs)
        assert all(y.shape == (16, 8) for _, y in bs)
        # Standardized: overall magnitudes are O(1).
        allx = np.concatenate([x for x, _ in bs])
        assert abs(float(allx.mean())) < 1.0

    def test_materialize_caps_and_returns_raw(self, tmp_path):
        path, _ = _write_multiwell_csv(tmp_path)
        norm = fit_window_normalizer(
            path, SCHEMA, "well", seed=0, window=8, sample_rows=2000
        )
        xn, yn, xr, yr = materialize_window_split(
            path, SCHEMA, "well", norm, "test", seed=0, window=8,
            max_windows=20,
        )
        assert len(xn) == len(yn) == len(xr) == len(yr) <= 20
        np.testing.assert_allclose(norm.normalize(xr), xn, rtol=1e-6)


class TestStreamingSequenceTrain:
    def test_streaming_lstm_end_to_end(self, tmp_path):
        from tpuflow.api import TrainJobConfig, train

        path, _ = _write_multiwell_csv(tmp_path, n_wells=14, steps=60)
        report = train(
            TrainJobConfig(
                column_names=NAMES,
                column_types=TYPES,
                target="flow",
                data_path=path,
                well_column="well",
                model="lstm",
                model_kwargs={"hidden": 8},
                window=8,
                max_epochs=3,
                batch_size=16,
                verbose=False,
                n_devices=1,
                stream=True,
                stream_chunk_rows=100,
                stream_shuffle_buffer=32,
                stream_sample_rows=2000,
                stream_eval_rows=500,
            )
        )
        assert np.isfinite(report.test_loss)
        assert report.result.epochs_ran == 3
        assert report.gilbert_mae is not None

    def test_streaming_sequence_requires_well_column(self):
        from tpuflow.api import TrainJobConfig, train

        with pytest.raises(ValueError, match="well_column"):
            train(
                TrainJobConfig(
                    model="lstm", stream=True, data_path="x.csv",
                    verbose=False,
                )
            )


class TestMultiSplitMaterialization:
    def test_one_pass_matches_per_split(self, tmp_path):
        from tpuflow.data.stream_windows import materialize_window_splits

        path, _ = _write_multiwell_csv(tmp_path)
        norm = fit_window_normalizer(
            path, SCHEMA, "well", seed=0, window=8, sample_rows=2000
        )
        both = materialize_window_splits(
            path, SCHEMA, "well", norm, ("val", "test"), seed=0, window=8,
            raw_for=("test",),
        )
        for which in ("val", "test"):
            single = materialize_window_split(
                path, SCHEMA, "well", norm, which, seed=0, window=8
            )
            np.testing.assert_allclose(both[which][0], single[0], rtol=1e-6)
        # Raw arrays only kept where requested.
        assert both["val"][2] is None and both["val"][3] is None
        assert both["test"][2] is not None


class TestStreamedArtifactServing:
    def test_stream_train_then_predict_roundtrip(self, tmp_path):
        """An artifact trained fully out of core serves like any other:
        the sidecar carries the stream-fitted normalizer."""
        from tpuflow.api import TrainJobConfig, predict, train

        path, wells = _write_multiwell_csv(tmp_path, n_wells=14, steps=60)
        storage = str(tmp_path / "artifacts")
        train(
            TrainJobConfig(
                column_names=NAMES,
                column_types=TYPES,
                target="flow",
                data_path=path,
                well_column="well",
                model="lstm",
                model_kwargs={"hidden": 8},
                window=8,
                max_epochs=2,
                batch_size=16,
                verbose=False,
                n_devices=1,
                stream=True,
                stream_chunk_rows=100,
                stream_sample_rows=2000,
                stream_eval_rows=200,
                storage_path=storage,
            )
        )
        w = wells[0]
        columns = {
            "well": np.array(["w0"] * 30),
            "pressure": w.pressure[:30],
            "choke": w.choke[:30],
            "glr": w.glr[:30],
            "temperature": w.temperature[:30],
            "water_cut": w.water_cut[:30],
        }
        y, idx = predict(storage, "lstm", columns=columns, return_index=True)
        assert y.shape == (30 - 8 + 1, 8)  # one [window] row per window
        assert np.isfinite(y).all()
        assert (y > 0).mean() > 0.9  # flow predictions in plausible units
