"""Test harness config: run all tests on an 8-virtual-device CPU mesh.

Multi-chip TPU hardware isn't available in CI, so every test runs against
8 fake CPU devices (SURVEY.md §4's recommended strategy): sharding, psum
collectives, and pjit compilation are exercised for real, just on host
devices. Must run before the first ``import jax`` anywhere in the test
process.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    _flags = (_flags + " --xla_force_host_platform_device_count=8").strip()
if "xla_backend_optimization_level" not in _flags:
    # Tests are compile-bound on the single-core CI host (hundreds of
    # small jit programs); unoptimized CPU codegen compiles ~20% faster
    # and changes nothing semantically. Production never sets this.
    _flags = (_flags + " --xla_backend_optimization_level=0").strip()
os.environ["XLA_FLAGS"] = _flags

# The environment force-registers the axon TPU platform ahead of the env
# var (config resolves to "axon,cpu"); pin the config explicitly.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Shared ring size for the SP / ring-attention unit tests: XLA's compile
# time for transposed shard_map ring programs grows superlinearly in ring
# size (an 8-device grad test cost 137s on this one-core host vs ~15s at
# 4), and a 4-device ring exercises every ring behavior (multiple hops,
# carry rotation, padding paths). The 8-device composition stays covered
# by __graft_entry__.dryrun_multichip and test_api's multichip test.
RING_DEVICES = 4


def ring_mesh():
    from tpuflow.parallel import make_mesh

    return make_mesh(devices=jax.devices()[:RING_DEVICES])
