"""Streaming CSV ingest: bounded-memory chunks → fixed-shape batches."""

import numpy as np
import pytest

from tpuflow.data.schema import Schema
from tpuflow.data.stream import (
    fit_pipeline_on_sample,
    stream_batches,
    stream_csv_columns,
)
from tpuflow.data.synthetic import generate_wells, wells_to_table, write_csv

NAMES = "pressure,choke,glr,temperature,water_cut,completion,flow"
TYPES = "float,float,float,float,float,string,float"
SCHEMA = Schema.from_cli(NAMES, TYPES, "flow")


@pytest.fixture
def big_csv(tmp_path):
    table = wells_to_table(generate_wells(4, 256, seed=0))  # 1024 rows
    path = str(tmp_path / "big.csv")
    write_csv(path, table, NAMES.split(","))
    return path, table


class TestStreamColumns:
    def test_chunks_cover_all_rows(self, big_csv):
        path, table = big_csv
        chunks = list(stream_csv_columns(path, SCHEMA, chunk_rows=100))
        assert sum(len(c["flow"]) for c in chunks) == 1024
        assert len(chunks) == 11  # 10 full + tail
        got = np.concatenate([c["flow"] for c in chunks])
        np.testing.assert_allclose(got, table["flow"], rtol=1e-5)

    def test_single_chunk_when_large(self, big_csv):
        path, _ = big_csv
        chunks = list(stream_csv_columns(path, SCHEMA, chunk_rows=10_000))
        assert len(chunks) == 1


class TestStreamBatches:
    def test_fixed_batch_shapes_across_chunk_boundaries(self, big_csv):
        path, _ = big_csv
        pipe = fit_pipeline_on_sample(path, SCHEMA, sample_rows=512)
        # chunk_rows=100 not divisible by batch 64: remainder rows must
        # carry across chunks.
        bs = list(stream_batches(path, pipe, batch_size=64, chunk_rows=100))
        assert len(bs) == 16  # 1024 / 64
        assert all(x.shape == (64, pipe.feature_dim) for x, _ in bs)
        assert all(y.shape == (64,) for _, y in bs)

    def test_matches_materialized_pipeline(self, big_csv):
        path, table = big_csv
        pipe = fit_pipeline_on_sample(path, SCHEMA, sample_rows=2048)
        streamed = np.concatenate(
            [x for x, _ in stream_batches(path, pipe, 128, chunk_rows=300)]
        )
        np.testing.assert_allclose(
            streamed, pipe.transform(table), rtol=1e-5, atol=1e-6
        )

    def test_keep_remainder(self, big_csv):
        path, _ = big_csv
        pipe = fit_pipeline_on_sample(path, SCHEMA)
        bs = list(
            stream_batches(path, pipe, 100, chunk_rows=333, drop_remainder=False)
        )
        assert sum(len(x) for x, _ in bs) == 1024
        assert len(bs[-1][0]) == 24

    def test_shuffle_buffer_same_rows_different_order(self, big_csv):
        path, _ = big_csv
        pipe = fit_pipeline_on_sample(path, SCHEMA)
        plain = list(stream_batches(path, pipe, 64, chunk_rows=200))
        shuf = list(
            stream_batches(
                path, pipe, 64, chunk_rows=200, shuffle_buffer=128, seed=1
            )
        )
        assert len(shuf) == len(plain) == 16
        assert all(x.shape == plain[0][0].shape for x, _ in shuf)
        ys_plain = np.sort(np.concatenate([y for _, y in plain]))
        ys_shuf = np.sort(np.concatenate([y for _, y in shuf]))
        np.testing.assert_allclose(ys_shuf, ys_plain)  # same multiset
        # ...but not the same order.
        assert not np.allclose(shuf[0][1], plain[0][1])

    def test_shuffle_buffer_larger_than_chunk_still_shuffles(self, big_csv):
        """Regression: buffer >= chunk_rows must accumulate and shuffle,
        not silently pass rows through in file order."""
        path, _ = big_csv
        pipe = fit_pipeline_on_sample(path, SCHEMA)
        plain = list(stream_batches(path, pipe, 64, chunk_rows=100))
        shuf = list(
            stream_batches(
                path, pipe, 64, chunk_rows=100, shuffle_buffer=300, seed=3
            )
        )
        assert len(shuf) == len(plain) == 16
        ys_plain = np.sort(np.concatenate([y for _, y in plain]))
        ys_shuf = np.sort(np.concatenate([y for _, y in shuf]))
        np.testing.assert_allclose(ys_shuf, ys_plain)  # same multiset
        assert not np.allclose(shuf[0][1], plain[0][1])  # actually shuffled

    def test_shuffle_deterministic_by_seed(self, big_csv):
        path, _ = big_csv
        pipe = fit_pipeline_on_sample(path, SCHEMA)
        a = list(stream_batches(path, pipe, 64, shuffle_buffer=128, seed=7))
        b = list(stream_batches(path, pipe, 64, shuffle_buffer=128, seed=7))
        for (xa, ya), (xb, yb) in zip(a, b):
            np.testing.assert_array_equal(xa, xb)
            np.testing.assert_array_equal(ya, yb)

    def test_unfitted_pipeline_rejected(self, big_csv):
        path, _ = big_csv
        from tpuflow.data.features import FeaturePipeline

        with pytest.raises(RuntimeError, match="fitted"):
            next(stream_batches(path, FeaturePipeline(SCHEMA), 64))


class TestHashSplit:
    # Chunk-invariance and 64/16/20 uniformity of split_assignments are
    # covered property-based (any seed) in tests/test_properties.py
    # TestHashSplitProperties — the authoritative copy.

    def test_splits_partition_the_stream(self, big_csv):
        from tpuflow.data.stream import stream_split_columns

        path, table = big_csv
        rows = {
            w: np.concatenate(
                [
                    c["flow"]
                    for c in stream_split_columns(path, SCHEMA, w, seed=1, chunk_rows=97)
                ]
            )
            for w in ("train", "val", "test")
        }
        total = sum(len(v) for v in rows.values())
        assert total == 1024
        merged = np.sort(np.concatenate(list(rows.values())))
        np.testing.assert_allclose(merged, np.sort(table["flow"]), rtol=1e-5)

    def test_materialize_split_caps_rows(self, big_csv):
        from tpuflow.data.stream import materialize_split

        path, _ = big_csv
        pipe = fit_pipeline_on_sample(path, SCHEMA)
        x, y, raw = materialize_split(path, pipe, "train", seed=1, max_rows=100)
        assert len(x) == len(y) == 100
        assert len(raw["flow"]) == 100


class TestStreamingTrain:
    def test_train_stream_end_to_end(self, big_csv):
        """train(stream=True) over a CSV spanning many chunks: out-of-core
        training reachable from the public entry point (VERDICT r2 #6)."""
        from tpuflow.api import TrainJobConfig, train

        path, _ = big_csv
        report = train(
            TrainJobConfig(
                column_names=NAMES,
                column_types=TYPES,
                target="flow",
                data_path=path,
                model="static_mlp",
                max_epochs=3,
                batch_size=32,
                verbose=False,
                n_devices=1,
                stream=True,
                stream_chunk_rows=150,  # many chunks over 1024 rows
                stream_shuffle_buffer=64,
                stream_sample_rows=400,
                stream_eval_rows=500,
            )
        )
        assert np.isfinite(report.test_loss)
        assert report.result.epochs_ran == 3
        assert report.gilbert_mae is not None  # physical baseline computed

    def test_stream_requires_data_path(self):
        from tpuflow.api import TrainJobConfig, train

        with pytest.raises(ValueError, match="needs data_path"):
            train(TrainJobConfig(model="static_mlp", stream=True, verbose=False))
        # Streaming SEQUENCE ingest exists too, but needs a well column
        # (covered in tests/test_stream_windows.py).

    def test_stream_jit_epoch_rejected(self, big_csv):
        from tpuflow.api import TrainJobConfig, train

        path, _ = big_csv
        with pytest.raises(ValueError, match="bounded-memory stream"):
            train(
                TrainJobConfig(
                    column_names=NAMES,
                    column_types=TYPES,
                    target="flow",
                    data_path=path,
                    model="static_mlp",
                    max_epochs=1,
                    batch_size=32,
                    verbose=False,
                    n_devices=1,
                    stream=True,
                    jit_epoch=True,
                )
            )
