"""CLI smoke tests in a REAL subprocess — catches import-time regressions
and argument-wiring breaks that in-process tests can mask."""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(args, timeout=240):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    # The test process env pin doesn't reach a subprocess; the CLI module
    # itself must work under the standard env contract.
    return subprocess.run(
        [sys.executable, "-c",
         "import jax; jax.config.update('jax_platforms','cpu');"
         "import sys; from tpuflow.cli import main; sys.exit(main())"
         ] if args is None else
        [sys.executable, "-c",
         "import jax; jax.config.update('jax_platforms','cpu');"
         "import sys; from tpuflow.cli import main;"
         f"sys.exit(main({args!r}))"],
        capture_output=True,
        text=True,
        cwd=REPO,
        env=env,
        timeout=timeout,
    )


def test_help_exits_zero():
    out = subprocess.run(
        [sys.executable, "-m", "tpuflow.cli", "--help"],
        capture_output=True,
        text=True,
        cwd=REPO,
        timeout=120,
    )
    assert out.returncode == 0
    assert "columnNames" in out.stdout
    assert "--predict" in out.stdout


def test_tiny_train_job_subprocess(tmp_path):
    out = _run(
        ["--model", "static_mlp", "--epochs", "2", "--batch-size", "64",
         "--devices", "1", "--synthetic-wells", "2", "--synthetic-steps",
         "64", "--quiet"]
    )
    assert out.returncode == 0, out.stderr[-2000:]


def test_model_kwargs_flag(tmp_path):
    """--model-kwargs forwards a JSON dict to the model family; invalid
    JSON fails fast with rc=2 before any data prep."""
    out = _run(
        ["--model", "static_mlp", "--model-kwargs", '{"hidden": [8, 8]}',
         "--epochs", "1", "--batch-size", "64", "--devices", "1",
         "--synthetic-wells", "2", "--synthetic-steps", "64", "--quiet"]
    )
    assert out.returncode == 0, out.stderr[-2000:]

    bad = _run(["--model-kwargs", "{bad", "--quiet"])
    assert bad.returncode == 2
    assert "not valid JSON" in bad.stderr
