"""CLI smoke tests in a REAL subprocess — catches import-time regressions
and argument-wiring breaks that in-process tests can mask."""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(args, timeout=240, extra_env=None):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.update(extra_env or {})
    # The test process env pin doesn't reach a subprocess; the CLI module
    # itself must work under the standard env contract.
    return subprocess.run(
        [sys.executable, "-c",
         "import jax; jax.config.update('jax_platforms','cpu');"
         "import sys; from tpuflow.cli import main; sys.exit(main())"
         ] if args is None else
        [sys.executable, "-c",
         "import jax; jax.config.update('jax_platforms','cpu');"
         "import sys; from tpuflow.cli import main;"
         f"sys.exit(main({args!r}))"],
        capture_output=True,
        text=True,
        cwd=REPO,
        env=env,
        timeout=timeout,
    )


def test_help_exits_zero():
    out = subprocess.run(
        [sys.executable, "-m", "tpuflow.cli", "--help"],
        capture_output=True,
        text=True,
        cwd=REPO,
        timeout=120,
    )
    assert out.returncode == 0
    assert "columnNames" in out.stdout
    assert "--predict" in out.stdout


def test_tiny_train_job_subprocess(tmp_path):
    import json

    metrics = tmp_path / "metrics.jsonl"
    out = _run(
        ["--model", "static_mlp", "--epochs", "2", "--batch-size", "64",
         "--devices", "1", "--synthetic-wells", "2", "--synthetic-steps",
         "64", "--quiet", "--trace-id", "cli0smoke0000001",
         "--metrics", str(metrics)]
    )
    assert out.returncode == 0, out.stderr[-2000:]
    # --trace-id pins the run's trace (exported as TPUFLOW_TRACE_ID):
    # every span in the trail carries it.
    spans = [
        json.loads(l) for l in metrics.read_text().splitlines()
        if '"span"' in l
    ]
    assert spans
    assert {s["trace_id"] for s in spans} == {"cli0smoke0000001"}

    bad = _run(["--trace-id", "not a token!", "--quiet"])
    assert bad.returncode == 2
    assert "--trace-id" in bad.stderr and "Traceback" not in bad.stderr


def test_model_kwargs_flag(tmp_path):
    """--model-kwargs forwards a JSON dict to the model family; invalid
    JSON fails fast with rc=2 before any data prep."""
    out = _run(
        ["--model", "static_mlp", "--model-kwargs", '{"hidden": [8, 8]}',
         "--epochs", "1", "--batch-size", "64", "--devices", "1",
         "--synthetic-wells", "2", "--synthetic-steps", "64", "--quiet"]
    )
    assert out.returncode == 0, out.stderr[-2000:]

    bad = _run(["--model-kwargs", "{bad", "--quiet"])
    assert bad.returncode == 2
    assert "not valid JSON" in bad.stderr
    # the error names the flag AND shows the offending string, not a
    # bare json.JSONDecodeError traceback
    assert "--model-kwargs" in bad.stderr
    assert "{bad" in bad.stderr
    assert "Traceback" not in bad.stderr


def test_unknown_model_exits_2_listing_catalog():
    """An unknown --model dies at parse time with the valid names in the
    error — not minutes later as a KeyError deep in training."""
    out = _run(["--model", "resnet50", "--quiet"])
    assert out.returncode == 2
    assert "unknown model 'resnet50'" in out.stderr
    assert "static_mlp" in out.stderr and "lstm" in out.stderr
    assert "Traceback" not in out.stderr


def test_preflight_rejects_bad_spec_before_training():
    """Preflight-by-default: a non-dividing tp AND a typo'd TPUFLOW_FAULTS
    site are BOTH reported in one run, exit 2, before any data prep."""
    out = _run(
        ["--model", "static_mlp", "--tp", "3", "--devices", "8",
         "--batch-size", "32", "--quiet"],
        extra_env={"TPUFLOW_FAULTS": "chekpoint.save,at=3,mode=exit"},
    )
    assert out.returncode == 2
    assert "preflight" in out.stderr
    assert "not divisible by tp=3" in out.stderr
    assert "chekpoint.save" in out.stderr  # env fault typo, same run
    assert "TPUFLOW_FAULTS" in out.stderr


def test_obs_summary_subprocess(tmp_path):
    """python -m tpuflow.obs summary: the log-reading CLI works as a real
    subprocess (no jax needed) and aggregates a metrics trail."""
    import json

    trail = tmp_path / "metrics.jsonl"
    trail.write_text("\n".join(json.dumps(rec) for rec in [
        {"event": "epoch", "time": 1.0, "epoch": 1, "val_loss": 0.5},
        {"event": "epoch", "time": 2.0, "epoch": 2, "val_loss": 0.25},
        {"event": "span", "time": 2.5, "name": "step", "duration_s": 0.5},
        {"event": "fit_done", "time": 3.0, "epochs": 2},
    ]) + "\n")
    out = subprocess.run(
        [sys.executable, "-m", "tpuflow.obs", "summary", str(trail)],
        capture_output=True, text=True, cwd=REPO, timeout=120,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    assert "4 events" in out.stdout
    assert "epochs: 2" in out.stdout
    assert "step:" in out.stdout

    tail = subprocess.run(
        [sys.executable, "-m", "tpuflow.obs", "tail", str(trail), "-n", "1"],
        capture_output=True, text=True, cwd=REPO, timeout=120,
    )
    assert tail.returncode == 0
    assert json.loads(tail.stdout)["event"] == "fit_done"

    missing = subprocess.run(
        [sys.executable, "-m", "tpuflow.obs", "summary",
         str(tmp_path / "nope.jsonl")],
        capture_output=True, text=True, cwd=REPO, timeout=120,
    )
    assert missing.returncode == 2
    assert "nope.jsonl" in missing.stderr


def test_obs_timeline_subprocess(tmp_path):
    """python -m tpuflow.obs timeline: span trail -> Chrome trace-event
    JSON in a real subprocess (no jax needed), torn lines tolerated."""
    import json

    trail = tmp_path / "metrics.jsonl"
    with open(trail, "wb") as f:
        for rec in [
            {"event": "span", "name": "ingest", "time": 10.0,
             "duration_s": 2.0},
            {"event": "span", "name": "step", "time": 13.0,
             "duration_s": 0.5, "epoch": 1},
            {"event": "span", "name": "predict.dispatch", "time": 13.2,
             "duration_s": 0.01},
        ]:
            f.write(json.dumps(rec).encode() + b"\n")
        f.write(b'{"event": "span", "torn mid-wr')  # crash-truncated tail
    out = tmp_path / "trace.json"
    proc = subprocess.run(
        [sys.executable, "-m", "tpuflow.obs", "timeline", str(trail),
         "-o", str(out)],
        capture_output=True, text=True, cwd=REPO, timeout=120,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "skipped_lines: 1" in proc.stdout
    doc = json.loads(out.read_text())
    xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert len(xs) == 3
    ts = [e["ts"] for e in xs]
    assert ts == sorted(ts) and all(t >= 0 for t in ts)
    assert all(e["dur"] >= 0 for e in xs)
    # The serving span landed in its own lane.
    lanes = {e["args"]["name"] for e in doc["traceEvents"]
             if e["ph"] == "M"}
    assert {"train", "serving"} <= lanes

    empty = subprocess.run(
        [sys.executable, "-m", "tpuflow.obs", "timeline",
         str(tmp_path / "none.jsonl"), "-o", str(out)],
        capture_output=True, text=True, cwd=REPO, timeout=120,
    )
    assert empty.returncode == 2  # missing file is an OSError exit


def test_obs_fleet_subprocess(tmp_path):
    """python -m tpuflow.obs fleet: multi-trail discovery + merged
    timeline + summary, as a REAL subprocess (no jax needed). A trace
    id shared by two processes lands in cross_process_traces and draws
    flow arrows."""
    import json

    w = tmp_path / "worker0"
    w.mkdir()
    (w / "metrics.jsonl").write_text(json.dumps({
        "event": "span", "name": "step", "time": 10.0,
        "duration_s": 1.0, "trace_id": "abc0000000000001",
    }) + "\n")
    c = tmp_path / "elastic"
    c.mkdir()
    (c / "coordinator-metrics.jsonl").write_text(json.dumps({
        "event": "span", "name": "elastic.round", "time": 10.5,
        "duration_s": 0.1,
        "worker_traces": {"0": "abc0000000000001"},
    }) + "\n")
    out = tmp_path / "fleet.json"
    proc = subprocess.run(
        [sys.executable, "-m", "tpuflow.obs", "fleet", str(tmp_path),
         "-o", str(out)],
        capture_output=True, text=True, cwd=REPO, timeout=120,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    summary = json.loads(proc.stdout)
    assert summary["trails"] == 2
    assert summary["cross_process_traces"] == {
        "abc0000000000001": [
            "elastic/coordinator-metrics", "worker0/metrics",
        ]
    }
    doc = json.loads(out.read_text())
    assert {e["pid"] for e in doc["traceEvents"] if e["ph"] == "X"} \
        == {1, 2}
    assert any(e["ph"] in ("s", "t", "f") for e in doc["traceEvents"])

    missing = subprocess.run(
        [sys.executable, "-m", "tpuflow.obs", "fleet",
         str(tmp_path / "nope")],
        capture_output=True, text=True, cwd=REPO, timeout=120,
    )
    assert missing.returncode == 2
    assert "nope" in missing.stderr


def test_obs_slo_subprocess(tmp_path):
    """python -m tpuflow.obs slo: the report card from fleet trails in
    a REAL subprocess — schema-valid JSON on stdout, written to -o,
    and a malformed objectives file exits 2 with a message."""
    import json

    d = tmp_path / "online"
    d.mkdir()
    (d / "metrics.jsonl").write_text("\n".join(json.dumps(r) for r in [
        {"event": "drift_anomaly", "time": 100.0,
         "trace_id": "t0000000000000001"},
        {"event": "online_retrain", "time": 101.0, "reason": "drift",
         "trace_id": "t0000000000000001"},
        {"event": "serve_reload", "time": 130.0,
         "trace_id": "t0000000000000001"},
    ]) + "\n")
    objectives = tmp_path / "objectives.json"
    objectives.write_text(json.dumps([
        {"name": "tta", "kind": "time_to_adapt", "target": 300.0},
    ]))
    out = tmp_path / "card.json"
    proc = subprocess.run(
        [sys.executable, "-m", "tpuflow.obs", "slo", str(tmp_path),
         "--objectives", str(objectives), "-o", str(out)],
        capture_output=True, text=True, cwd=REPO, timeout=120,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    card = json.loads(out.read_text())
    assert card["schema"] == "tpuflow.slo.report_card/v1"
    [row] = card["objectives"]
    assert row["status"] == "ok" and row["measured"] == 30.0
    assert row["lifecycles"][0]["trace_id"] == "t0000000000000001"

    objectives.write_text(json.dumps([{"kind": "p42", "target": 1}]))
    bad = subprocess.run(
        [sys.executable, "-m", "tpuflow.obs", "slo", str(tmp_path),
         "--objectives", str(objectives)],
        capture_output=True, text=True, cwd=REPO, timeout=120,
    )
    assert bad.returncode == 2
    assert "unknown kind" in bad.stderr
    assert "Traceback" not in bad.stderr


def _write_history_spill(tmp_path):
    """A daemon-shaped metrics-history spill: a burn-rate lane breaching
    from t=0 (fires the imported SLO rule after its 15s hold-down) and
    a counter ramp."""
    import json

    spill = tmp_path / "history.jsonl"
    rows = []
    for t in range(0, 60, 5):
        rows.append({"event": "history_sample", "t": float(t), "samples": {
            "tpuflow_slo_burn_rate{objective=availability}": 4.0,
            "tpuflow_serving_admitted_total": float(t * 10),
        }})
    spill.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
    return spill


def test_obs_history_subprocess(tmp_path):
    """python -m tpuflow.obs history: replay a spill in a REAL
    subprocess — per-series summaries, --metric filtering, and honest
    exits on empty/missing input."""
    import json

    spill = _write_history_spill(tmp_path)
    proc = subprocess.run(
        [sys.executable, "-m", "tpuflow.obs", "history", str(spill),
         "--json"],
        capture_output=True, text=True, cwd=REPO, timeout=120,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    doc = json.loads(proc.stdout)
    assert doc["ticks"] == 12
    by_name = {r["series"]: r for r in doc["series"]}
    burn = by_name["tpuflow_slo_burn_rate"]
    assert burn["labels"] == {"objective": "availability"}
    assert burn["points"] == 12 and burn["last"] == 4.0
    ramp = by_name["tpuflow_serving_admitted_total"]
    assert ramp["min"] == 0.0 and ramp["max"] == 550.0

    filtered = subprocess.run(
        [sys.executable, "-m", "tpuflow.obs", "history", str(spill),
         "--metric", "serving", "--json"],
        capture_output=True, text=True, cwd=REPO, timeout=120,
    )
    assert filtered.returncode == 0
    assert [r["series"] for r in json.loads(filtered.stdout)["series"]] \
        == ["tpuflow_serving_admitted_total"]

    empty = tmp_path / "not_a_spill.jsonl"
    empty.write_text(json.dumps({"event": "span", "time": 1.0}) + "\n")
    no_ticks = subprocess.run(
        [sys.executable, "-m", "tpuflow.obs", "history", str(empty)],
        capture_output=True, text=True, cwd=REPO, timeout=120,
    )
    assert no_ticks.returncode == 1
    assert "no history_sample records" in no_ticks.stderr
    missing = subprocess.run(
        [sys.executable, "-m", "tpuflow.obs", "history",
         str(tmp_path / "nope.jsonl")],
        capture_output=True, text=True, cwd=REPO, timeout=120,
    )
    assert missing.returncode == 2
    assert "Traceback" not in missing.stderr


def test_obs_alerts_subprocess(tmp_path):
    """python -m tpuflow.obs alerts: the same spill through the
    committed SLO rules — the burn-rate page fires after its hold-down,
    --fail-on-firing gates, and rule-less invocation exits 2 with the
    usage message, never a traceback."""
    import json

    spill = _write_history_spill(tmp_path)
    proc = subprocess.run(
        [sys.executable, "-m", "tpuflow.obs", "alerts", str(spill),
         "--slo", "--json"],
        capture_output=True, text=True, cwd=REPO, timeout=120,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    doc = json.loads(proc.stdout)
    assert doc["ticks"] == 12
    assert doc["firing"] == ["burn_rate_availability"]
    [fired] = doc["transitions"]
    assert fired["state"] == "firing" and fired["value"] == 4.0
    assert fired["t"] >= 15.0                    # the for_s hold-down

    gated = subprocess.run(
        [sys.executable, "-m", "tpuflow.obs", "alerts", str(spill),
         "--slo", "--fail-on-firing"],
        capture_output=True, text=True, cwd=REPO, timeout=120,
    )
    assert gated.returncode == 1
    assert "burn_rate_availability" in gated.stderr

    no_rules = subprocess.run(
        [sys.executable, "-m", "tpuflow.obs", "alerts", str(spill)],
        capture_output=True, text=True, cwd=REPO, timeout=120,
    )
    assert no_rules.returncode == 2
    assert "--rules" in no_rules.stderr
    assert "Traceback" not in no_rules.stderr


def test_obs_profile_subprocess(tmp_path):
    """python -m tpuflow.obs profile: render a snapshot, and --diff the
    two COMMITTED snapshots (benchmarks/profiles/) — the acceptance
    demo: the storm capture regresses the batcher component, verdict is
    deterministic, exit 1 flags it for CI."""
    import json

    steady = os.path.join(REPO, "benchmarks", "profiles", "steady.json")
    storm = os.path.join(REPO, "benchmarks", "profiles", "storm.json")
    render = subprocess.run(
        [sys.executable, "-m", "tpuflow.obs", "profile", steady, "--top", "5"],
        capture_output=True, text=True, cwd=REPO, timeout=120,
    )
    assert render.returncode == 0, render.stderr[-2000:]
    assert "component" in render.stdout and "busy-share" in render.stdout

    diff = subprocess.run(
        [sys.executable, "-m", "tpuflow.obs", "profile", "--diff",
         steady, storm, "--json"],
        capture_output=True, text=True, cwd=REPO, timeout=120,
    )
    assert diff.returncode == 1, diff.stderr[-2000:]  # regression == exit 1
    verdict = json.loads(diff.stdout)
    assert verdict["verdict"] == "regression"
    assert verdict["regressions"] == ["batcher"]
    assert verdict["base_top"] == "serving"
    assert verdict["new_top"] == "batcher"
    # Deterministic: the same committed inputs give the same verdict.
    again = subprocess.run(
        [sys.executable, "-m", "tpuflow.obs", "profile", "--diff",
         steady, storm, "--json"],
        capture_output=True, text=True, cwd=REPO, timeout=120,
    )
    assert again.stdout == diff.stdout

    same = subprocess.run(
        [sys.executable, "-m", "tpuflow.obs", "profile", "--diff",
         steady, steady],
        capture_output=True, text=True, cwd=REPO, timeout=120,
    )
    assert same.returncode == 0
    assert "verdict=ok" in same.stdout

    one_file = subprocess.run(
        [sys.executable, "-m", "tpuflow.obs", "profile", "--diff", steady],
        capture_output=True, text=True, cwd=REPO, timeout=120,
    )
    assert one_file.returncode == 2
    assert "BASE NEW" in one_file.stderr
    assert "Traceback" not in one_file.stderr

    missing = subprocess.run(
        [sys.executable, "-m", "tpuflow.obs", "profile",
         str(tmp_path / "nope.json")],
        capture_output=True, text=True, cwd=REPO, timeout=120,
    )
    assert missing.returncode == 2
    assert "Traceback" not in missing.stderr


def test_obs_flight_subprocess(tmp_path):
    """python -m tpuflow.obs flight: list and inspect a real captured
    bundle in a subprocess; empty dirs exit 1, missing bundles exit 2,
    never a traceback."""
    import json

    from tpuflow.obs.flight import FlightRecorder
    from tpuflow.obs.profiler import SamplingProfiler

    root = tmp_path / "flight"
    profiler = SamplingProfiler(0.01)
    profiler.sample()
    rec = FlightRecorder(str(root), profiler=profiler)
    name = rec.capture("manual", reason="cli smoke", force=True)
    assert name

    listed = subprocess.run(
        [sys.executable, "-m", "tpuflow.obs", "flight", str(root)],
        capture_output=True, text=True, cwd=REPO, timeout=120,
    )
    assert listed.returncode == 0, listed.stderr[-2000:]
    assert name in listed.stdout and "[ok]" in listed.stdout

    inspect = subprocess.run(
        [sys.executable, "-m", "tpuflow.obs", "flight", str(root),
         "--inspect", name, "--json"],
        capture_output=True, text=True, cwd=REPO, timeout=120,
    )
    assert inspect.returncode == 0, inspect.stderr[-2000:]
    doc = json.loads(inspect.stdout)
    assert doc["problems"] == []
    assert doc["doc"]["schema"] == "tpuflow.obs.flight/v1"
    assert doc["doc"]["trigger"] == "manual"

    empty = tmp_path / "empty"
    empty.mkdir()
    none = subprocess.run(
        [sys.executable, "-m", "tpuflow.obs", "flight", str(empty)],
        capture_output=True, text=True, cwd=REPO, timeout=120,
    )
    assert none.returncode == 1
    assert "no flight bundles" in none.stderr

    missing = subprocess.run(
        [sys.executable, "-m", "tpuflow.obs", "flight", str(root),
         "--inspect", "bundle-that-is-not-there.json"],
        capture_output=True, text=True, cwd=REPO, timeout=120,
    )
    assert missing.returncode == 2
    assert "Traceback" not in missing.stderr


def test_analysis_module_entry_rejects_broken_spec(tmp_path):
    """python -m tpuflow.analysis: the CI entry point exits non-zero on a
    broken spec and prints the preflight diagnostic."""
    import json

    spec = tmp_path / "bad.json"
    spec.write_text(json.dumps({"model": "resnet50", "tp": 3}))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    out = subprocess.run(
        [sys.executable, "-m", "tpuflow.analysis", str(spec),
         "--devices", "8"],
        capture_output=True, text=True, cwd=REPO, env=env, timeout=240,
    )
    assert out.returncode == 1
    assert "unknown model 'resnet50'" in out.stdout
    assert "not divisible by tp=3" in out.stdout


def test_online_module_smoke(tmp_path):
    """python -m tpuflow.online spec.json --max-windows N: the
    continuous-training sidecar runs bounded as a REAL subprocess —
    scores windows against a trained artifact's sidecar stats and prints
    the loop summary JSON. (The retrain/swap machinery is covered in
    tests/test_online.py; the huge threshold here keeps the smoke to
    scoring only.) A bad spec exits 2 with a message, not a traceback."""
    import json

    import numpy as np

    from tpuflow.api import TrainJobConfig, train
    from tpuflow.data import wells_to_table
    from tpuflow.data.synthetic import generate_wells

    names = "pressure,choke,glr,temperature,water_cut,completion,flow"
    cols = wells_to_table(generate_wells(n_wells=2, steps=200, seed=0))
    csv_path = tmp_path / "d.csv"
    with open(csv_path, "w") as f:
        for i in range(len(cols["flow"])):
            f.write(",".join(
                str(cols[c][i]) for c in names.split(",")
            ) + "\n")
    storage = str(tmp_path / "art")
    train(TrainJobConfig(
        column_names=names,
        column_types="float,float,float,float,float,string,float",
        target="flow", storage_path=storage, data_path=str(csv_path),
        model="static_mlp", model_kwargs={"hidden": [4]},
        max_epochs=2, batch_size=64, verbose=False, health="off",
    ))
    spec = tmp_path / "spec.json"
    spec.write_text(json.dumps({
        "columnNames": names,
        "columnTypes": "float,float,float,float,float,string,float",
        "targetColumn": "flow", "storagePath": storage,
        "data": str(csv_path), "model": "static_mlp",
        "model_kwargs": {"hidden": [4]},
        "online": {"window_rows": 100, "threshold": 1e9,
                   "warmup_windows": 0},
    }))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    out = subprocess.run(
        [sys.executable, "-m", "tpuflow.online", str(spec),
         "--max-windows", "3"],
        capture_output=True, text=True, cwd=REPO, env=env, timeout=240,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    summary = json.loads(out.stdout.strip().splitlines()[-1])
    assert summary["windows"] == 3
    assert summary["swaps"] == 0

    bad = subprocess.run(
        [sys.executable, "-m", "tpuflow.online", str(spec), "--help"],
        capture_output=True, text=True, cwd=REPO, env=env, timeout=120,
    )
    assert bad.returncode == 0 and "--max-windows" in bad.stdout

    broken = tmp_path / "broken.json"
    broken.write_text(json.dumps({"model": "static_mlp",
                                  "online": {"mode": "bogus"}}))
    out = subprocess.run(
        [sys.executable, "-m", "tpuflow.online", str(broken)],
        capture_output=True, text=True, cwd=REPO, env=env, timeout=240,
    )
    assert out.returncode == 2
    assert "online" in out.stderr


def test_analysis_repo_subprocess(tmp_path):
    """python -m tpuflow.analysis repo: the repo-wide concurrency pass
    as a REAL subprocess — exit 0 on the package (the committed baseline
    covers triaged-accepted sites), exit 1 on a seeded-race fixture
    naming all three planted defects with file:line, exit 2 on a
    malformed baseline with the file/field in the error."""
    import json

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    gate = subprocess.run(
        [sys.executable, "-m", "tpuflow.analysis", "repo"],
        capture_output=True, text=True, cwd=REPO, env=env, timeout=240,
    )
    assert gate.returncode == 0, gate.stdout + gate.stderr[-2000:]
    assert "concurrency-clean" in gate.stdout

    from test_analysis import RACY_SOURCE, _planted_line

    (tmp_path / "racy.py").write_text(RACY_SOURCE)
    seeded = subprocess.run(
        [sys.executable, "-m", "tpuflow.analysis", "repo", str(tmp_path),
         "--json"],
        capture_output=True, text=True, cwd=REPO, env=env, timeout=240,
    )
    assert seeded.returncode == 1, seeded.stderr[-2000:]
    doc = json.loads(seeded.stdout)
    by_code = {f["code"]: f["where"] for f in doc["findings"]}
    assert set(by_code) == {"TPF016", "TPF017", "TPF018"}
    for code in ("TPF016", "TPF017", "TPF018"):
        line = _planted_line(RACY_SOURCE, f"PLANTED: {code}")
        assert by_code[code].endswith(f"racy.py:{line}")

    (tmp_path / "concurrency_baseline.json").write_text(
        '{"entries": [{"rule": "TPF099"}]}'
    )
    bad = subprocess.run(
        [sys.executable, "-m", "tpuflow.analysis", "repo", str(tmp_path)],
        capture_output=True, text=True, cwd=REPO, env=env, timeout=240,
    )
    assert bad.returncode == 2
    assert "concurrency_baseline.json" in bad.stderr
    assert "Traceback" not in bad.stderr


def test_analysis_repo_storage_subprocess(tmp_path):
    """python -m tpuflow.analysis repo --passes storage: the repo-wide
    storage-contract pass as a REAL subprocess — exit 0 on the package
    (the committed baseline covers the justified leaf sites), exit 1 on
    a seeded fixture naming all three planted defects with file:line,
    exit 2 on a malformed storage baseline."""
    import json

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    gate = subprocess.run(
        [sys.executable, "-m", "tpuflow.analysis", "repo",
         "--passes", "storage"],
        capture_output=True, text=True, cwd=REPO, env=env, timeout=240,
    )
    assert gate.returncode == 0, gate.stdout + gate.stderr[-2000:]
    assert "storage-clean" in gate.stdout

    from test_analysis import STORAGE_RACY_SOURCE, _planted_line

    (tmp_path / "leaky.py").write_text(STORAGE_RACY_SOURCE)
    seeded = subprocess.run(
        [sys.executable, "-m", "tpuflow.analysis", "repo", str(tmp_path),
         "--passes", "storage", "--json"],
        capture_output=True, text=True, cwd=REPO, env=env, timeout=240,
    )
    assert seeded.returncode == 1, seeded.stderr[-2000:]
    doc = json.loads(seeded.stdout)
    wheres_by_code: dict = {}
    for f in doc["findings"]:
        wheres_by_code.setdefault(f["code"], []).append(f["where"])
    assert set(wheres_by_code) == {"TPF019", "TPF020", "TPF021"}
    for code in ("TPF019", "TPF020", "TPF021"):
        line = _planted_line(STORAGE_RACY_SOURCE, f"PLANTED: {code}")
        assert any(
            w.endswith(f"leaky.py:{line}") for w in wheres_by_code[code]
        ), code

    (tmp_path / "storage_baseline.json").write_text(
        '{"entries": [{"rule": "TPF099"}]}'
    )
    bad = subprocess.run(
        [sys.executable, "-m", "tpuflow.analysis", "repo", str(tmp_path),
         "--passes", "storage"],
        capture_output=True, text=True, cwd=REPO, env=env, timeout=240,
    )
    assert bad.returncode == 2
    assert "storage_baseline.json" in bad.stderr
    assert "Traceback" not in bad.stderr


def test_runtime_soak_subprocess(tmp_path):
    """ISSUE 16 satellite: ``python -m tpuflow.runtime soak spec.json``
    in a REAL subprocess — the full day-in-the-life wiring (supervisor,
    gang, daemon, online loop, chaos schedule, report card) behind the
    module entrypoint, exit 0 iff the card is valid with zero drops."""
    import json

    from tpuflow.runtime.soak import mini_soak_spec

    spec_path = tmp_path / "soak-spec.json"
    out_path = tmp_path / "soak-out.json"
    root = tmp_path / "soak"
    # The mini preset, trimmed further for a cold process (every JAX
    # compile is paid fresh here, unlike the in-process mini soak).
    spec = mini_soak_spec(str(root))
    spec["deadline_s"] = 240.0
    spec["traffic"]["max_requests"] = 12
    spec_path.write_text(json.dumps(spec))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, "-m", "tpuflow.runtime", "soak", str(spec_path),
         "-o", str(out_path)],
        capture_output=True, text=True, cwd=REPO, env=env, timeout=420,
    )
    assert proc.returncode == 0, proc.stdout[-800:] + proc.stderr[-1200:]
    verdict = json.loads(proc.stdout.strip())
    assert verdict["ok"] is True
    assert verdict["dropped"] == 0
    assert verdict["time_to_adapt_s"] > 0
    full = json.loads(out_path.read_text())
    assert full["card"]["schema"] == "tpuflow.slo.report_card/v1"
    assert (root / "soak_report.json").exists()


def test_elastic_tree_module_subprocess(tmp_path):
    """ISSUE 18 satellite: ``python -m tpuflow.elastic spec.json
    --fanout 2`` in a REAL subprocess — the tree topology end to end
    (socket transport implied by --fanout, aggregator threads, delta
    pushes) behind the module entrypoint, summary JSON on stdout."""
    import json

    spec_path = tmp_path / "gang-spec.json"
    spec_path.write_text(json.dumps({
        "model": "static_mlp",
        "model_kwargs": {"hidden": []},
        "epochs": 2,
        "batchSize": 32,
        "patience": 100,
        "loss": "mse",
        "optimizer_kwargs": {"learning_rate": 0.1},
        "synthetic_wells": 4,
        "synthetic_steps": 64,
        "n_devices": 1,
        "verbose": False,
        "storagePath": str(tmp_path / "gang"),
    }))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, "-m", "tpuflow.elastic", str(spec_path),
         "--workers", "2", "--fanout", "2", "--delta",
         "--mode", "inprocess", "--heartbeat-timeout", "120",
         "--quiet"],
        capture_output=True, text=True, cwd=REPO, env=env, timeout=420,
    )
    assert proc.returncode == 0, proc.stdout[-800:] + proc.stderr[-1200:]
    summary = json.loads(proc.stdout.strip().splitlines()[-1])
    assert summary["ok"] is True
    assert summary["rounds"] >= 2
    assert summary["final_averaged_over"] == [0, 1]
    for w in summary["workers"]:
        assert w["error"] is None
