"""Elastic data-parallel training: churn drills, averaging, membership.

Three layers, mirroring the subsystem (tpuflow/elastic; docs/elastic.md):

- **Unit drills with an injectable clock** (no wall-clock waits): the
  param exchange's push/average/adopt file protocol, heartbeat
  classification, and the coordinator's evict-on-deadline /
  rejoin-on-fresh-heartbeat / round-deadline behaviors, each driven
  ``step()`` by ``step()`` under a fake clock.
- **2-worker in-process gangs** (tier-1): real ``train()`` loops as
  threads sharing one coordinator — fixed-membership averaging, and
  fault drills at the new ``elastic.push`` / ``elastic.join`` /
  ``elastic.heartbeat`` sites proving one worker's death never takes
  the gang down.
- **The churn acceptance drill** (tier-1): 3 supervised worker
  PROCESSES; one is killed mid-epoch by a registry-armed exit fault
  (``os._exit`` — the no-cleanup SIGKILL stand-in the supervisor drills
  standardize on). The run must evict it on the heartbeat deadline,
  keep averaging over the survivors, readmit the restarted worker, and
  land final averaged params matching a fixed-membership reference
  gang to float tolerance, with converged losses and no NaNs.

≥4-worker gangs and the repeated kill-and-rejoin soak are ``slow``.
"""

from __future__ import annotations

import json
import math
import os

import numpy as np
import pytest

from tpuflow.elastic import (
    ELASTIC_DEFAULTS,
    exchange,
    resolve_elastic,
    validate_elastic_block,
)
from tpuflow.elastic.coordinator import Coordinator, read_coordinator_state
from tpuflow.elastic.membership import (
    classify_members,
    read_members,
    write_heartbeat,
)
from tpuflow.elastic.runner import run_elastic, worker_spec

# The acceptance job: a LINEAR model (static_mlp with no hidden layers)
# under mse is near-convex, so local-SGD averaging converges to the same
# neighborhood whatever the transient membership — which is exactly what
# the float-tolerance parity assertion needs to be meaningful.
TINY = {
    "model": "static_mlp",
    "model_kwargs": {"hidden": []},
    "epochs": 4,
    "batchSize": 32,
    "patience": 100,  # elastic gangs run fixed epochs; no early stop
    "loss": "mse",
    "optimizer_kwargs": {"learning_rate": 0.1},
    "synthetic_wells": 4,
    "synthetic_steps": 64,
    "n_devices": 1,
    "verbose": False,
}

# Children must see the CPU pin (conftest sets it for THIS process only).
_ENV_KEYS = ("JAX_PLATFORMS", "XLA_FLAGS")


@pytest.fixture(autouse=True)
def _pass_platform_env(monkeypatch):
    for k in _ENV_KEYS:
        if os.environ.get(k):
            monkeypatch.setenv(k, os.environ[k])


class FakeClock:
    def __init__(self, t: float = 1000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def _params(seed: float):
    return {"w": np.full((2, 3), seed, np.float32),
            "b": np.full((3,), seed, np.float32)}


# ---------------------------------------------------------------------
# unit: the file exchange
# ---------------------------------------------------------------------


class TestExchange:
    def test_push_average_roundtrip(self, tmp_path):
        gang = str(tmp_path)
        exchange.push_params(gang, 1, 0, _params(1.0))
        exchange.push_params(gang, 1, 1, _params(3.0))
        assert exchange.pushed_ids(gang, 1) == {0, 1}
        leaves, used = exchange.average_pushes(gang, 1)
        assert used == [0, 1]
        for leaf in leaves:
            np.testing.assert_allclose(leaf, 2.0)
        exchange.publish_average(gang, 1, leaves)
        got = exchange.read_average(gang, 1)
        assert got is not None and len(got) == 2
        round_, latest = exchange.latest_average(gang)
        assert round_ == 1
        np.testing.assert_allclose(latest[0], 2.0)

    def test_average_respects_include_set(self, tmp_path):
        gang = str(tmp_path)
        exchange.push_params(gang, 2, 0, _params(1.0))
        exchange.push_params(gang, 2, 1, _params(9.0))
        leaves, used = exchange.average_pushes(gang, 2, include={0})
        assert used == [0]
        np.testing.assert_allclose(leaves[0], 1.0)

    def test_unflatten_rejects_mismatched_structure(self, tmp_path):
        template = _params(0.0)
        leaves = exchange.flatten_params(_params(5.0))
        restored = exchange.unflatten_like(template, leaves)
        np.testing.assert_allclose(restored["w"], 5.0)
        with pytest.raises(ValueError, match="leaves"):
            exchange.unflatten_like(template, leaves[:1])
        bad = [np.zeros((4, 4), np.float32), leaves[1]]
        with pytest.raises(ValueError, match="shape"):
            exchange.unflatten_like(template, bad)

    def test_missing_round_reads_as_none(self, tmp_path):
        gang = str(tmp_path)
        assert exchange.read_average(gang, 7) is None
        assert exchange.latest_average(gang) is None
        assert exchange.average_pushes(gang, 7) == (None, [])


# ---------------------------------------------------------------------
# unit: heartbeats + classification (fake clock — no wall-clock waits)
# ---------------------------------------------------------------------


class TestMembership:
    def test_live_then_stale_then_rejoin(self, tmp_path):
        gang, clock = str(tmp_path), FakeClock()
        write_heartbeat(gang, 0, epoch=2, clock=clock)
        view = classify_members(gang, 5.0, clock())
        assert view.live_ids == {0} and not view.stale
        clock.advance(6.0)
        view = classify_members(gang, 5.0, clock())
        assert view.stale_ids == {0} and not view.live
        write_heartbeat(gang, 0, epoch=3, clock=clock)  # the rejoin
        view = classify_members(gang, 5.0, clock())
        assert view.live_ids == {0}

    def test_terminal_status_never_waited_on(self, tmp_path):
        gang, clock = str(tmp_path), FakeClock()
        write_heartbeat(gang, 0, status="done", clock=clock)
        write_heartbeat(gang, 1, status="failed", clock=clock)
        clock.advance(100.0)  # age never matters for terminal members
        view = classify_members(gang, 5.0, clock())
        assert not view.live and not view.stale
        assert {m.worker_id for m in view.finished} == {0, 1}

    def test_unknown_status_rejected(self, tmp_path):
        with pytest.raises(ValueError, match="status"):
            write_heartbeat(str(tmp_path), 0, status="zombie")

    def test_torn_or_alien_member_files_skipped(self, tmp_path):
        gang, clock = str(tmp_path), FakeClock()
        write_heartbeat(gang, 0, clock=clock)
        (tmp_path / "members" / "1.json").write_text('{"worker_id": 1, "tim')
        # Valid JSON that isn't a heartbeat record (a stray operator
        # note, a list) must be skipped too, not crash every scan.
        (tmp_path / "members" / "notes.json").write_text('["x"]')
        (tmp_path / "members" / "2.json").write_text('"hello"')
        assert [m.worker_id for m in read_members(gang)] == [0]

    def test_goodbye_is_sticky_against_late_beats(self, tmp_path):
        """The wedged-heartbeat-thread drill: once ``done`` is written,
        a late in-flight ``running`` beat can never overwrite it — the
        write is suppressed (compare-before-write) and the coordinator
        keeps seeing the goodbye."""
        gang, clock = str(tmp_path), FakeClock()
        write_heartbeat(gang, 0, status="running", clock=clock)
        assert write_heartbeat(gang, 0, status="done", clock=clock)
        # The wedged thread's beat, landing after finish(): suppressed.
        assert write_heartbeat(gang, 0, status="running", clock=clock) is False
        [m] = read_members(gang)
        assert m.status == "done"
        view = classify_members(gang, 5.0, clock())
        assert not view.live and {m.worker_id for m in view.finished} == {0}

    def test_goodbye_overrides_a_racing_rename_at_read_time(self, tmp_path):
        """Even a beat whose rename slips PAST the compare-before-write
        check (simulated by forging the heartbeat file directly) is
        overridden by the standing goodbye marker when read."""
        from tpuflow.elastic.membership import heartbeat_path

        gang, clock = str(tmp_path), FakeClock()
        write_heartbeat(gang, 0, status="failed", clock=clock)
        (tmp_path / "members").mkdir(exist_ok=True)
        with open(heartbeat_path(gang, 0), "w", encoding="utf-8") as f:
            json.dump(
                {"worker_id": 0, "time": clock(), "status": "running"}, f
            )
        [m] = read_members(gang)
        assert m.status == "failed"

    def test_joining_beat_revokes_the_goodbye(self, tmp_path):
        """A restarted incarnation's ``joining`` hello must readmit the
        worker — stickiness binds late beats of the DEAD incarnation,
        not the supervised restart+rejoin path."""
        gang, clock = str(tmp_path), FakeClock()
        write_heartbeat(gang, 0, status="failed", clock=clock)
        assert write_heartbeat(gang, 0, status="joining", clock=clock)
        assert write_heartbeat(gang, 0, status="running", clock=clock)
        [m] = read_members(gang)
        assert m.status == "running"
        assert classify_members(gang, 5.0, clock()).live_ids == {0}


# ---------------------------------------------------------------------
# unit: coordinator rounds (fake clock, step()-driven)
# ---------------------------------------------------------------------


def _coordinator(tmp_path, clock, **kw):
    kw.setdefault("heartbeat_timeout", 5.0)
    kw.setdefault("round_timeout", 30.0)
    return Coordinator(str(tmp_path), clock=clock, sleep=lambda _: None, **kw)


class TestCoordinator:
    def test_waits_for_live_set_then_publishes(self, tmp_path):
        gang, clock = str(tmp_path), FakeClock()
        coord = _coordinator(tmp_path, clock)
        write_heartbeat(gang, 0, round=1, clock=clock)
        write_heartbeat(gang, 1, round=1, clock=clock)
        exchange.push_params(gang, 1, 0, _params(1.0))
        assert coord.step() is False  # worker 1 is live: hold the round
        exchange.push_params(gang, 1, 1, _params(3.0))
        assert coord.step() is True
        assert coord.rounds[1] == [0, 1]
        np.testing.assert_allclose(exchange.read_average(gang, 1)[0], 2.0)
        assert coord.round == 2

    def test_eviction_unblocks_the_round(self, tmp_path):
        gang, clock = str(tmp_path), FakeClock()
        coord = _coordinator(tmp_path, clock)
        write_heartbeat(gang, 0, round=1, clock=clock)
        write_heartbeat(gang, 1, round=1, clock=clock)
        exchange.push_params(gang, 1, 0, _params(1.0))
        assert coord.step() is False
        clock.advance(4.0)
        write_heartbeat(gang, 0, round=1, clock=clock)  # 0 stays fresh
        clock.advance(2.0)  # worker 1's heartbeat is now 6s old (> 5s)
        assert coord.step() is True  # evicted -> survivors cover the set
        assert coord.evicted == {1}
        assert coord.rounds[1] == [0]
        state = read_coordinator_state(gang)
        assert state["evicted"] == [1]

    def test_rejoin_readmits_and_counts(self, tmp_path):
        gang, clock = str(tmp_path), FakeClock()
        coord = _coordinator(tmp_path, clock)
        write_heartbeat(gang, 0, clock=clock)
        write_heartbeat(gang, 1, clock=clock)
        clock.advance(6.0)
        write_heartbeat(gang, 0, clock=clock)
        coord.step()
        assert coord.evicted == {1}
        write_heartbeat(gang, 1, clock=clock)  # back from the dead
        coord.step()
        assert coord.evicted == set() and coord.rejoins == 1

    def test_round_deadline_drops_live_stragglers(self, tmp_path):
        # A worker that heartbeats but never pushes (wedged between
        # progress writes) must not hold a round past round_timeout.
        gang, clock = str(tmp_path), FakeClock()
        coord = _coordinator(tmp_path, clock, round_timeout=10.0)
        write_heartbeat(gang, 0, clock=clock)
        write_heartbeat(gang, 1, clock=clock)
        exchange.push_params(gang, 1, 0, _params(1.0))
        assert coord.step() is False
        clock.advance(11.0)
        write_heartbeat(gang, 0, clock=clock)
        write_heartbeat(gang, 1, clock=clock)  # live, just not pushing
        assert coord.step() is True
        assert coord.rounds[1] == [0]
        assert coord.evicted == set()  # straggling is not eviction

    def test_late_push_from_dead_worker_still_averaged(self, tmp_path):
        # Push-then-die: the params are legitimate round data even though
        # the worker missed every heartbeat since.
        gang, clock = str(tmp_path), FakeClock()
        coord = _coordinator(tmp_path, clock)
        write_heartbeat(gang, 0, clock=clock)
        write_heartbeat(gang, 1, clock=clock)
        exchange.push_params(gang, 1, 1, _params(3.0))
        clock.advance(6.0)  # worker 1 dies right after its push
        write_heartbeat(gang, 0, clock=clock)
        exchange.push_params(gang, 1, 0, _params(1.0))
        assert coord.step() is True
        assert coord.rounds[1] == [0, 1]  # both pushes averaged

    def test_min_round_interval_paces_publication(self, tmp_path):
        gang, clock = str(tmp_path), FakeClock()
        coord = _coordinator(tmp_path, clock, min_round_interval=10.0)
        write_heartbeat(gang, 0, clock=clock)
        exchange.push_params(gang, 1, 0, _params(1.0))
        assert coord.step() is True  # first round: no previous publish
        exchange.push_params(gang, 2, 0, _params(1.0))
        write_heartbeat(gang, 0, clock=clock)
        assert coord.step() is False  # paced
        clock.advance(11.0)
        write_heartbeat(gang, 0, clock=clock)
        assert coord.step() is True

    def test_rounds_pruned_behind_the_gang(self, tmp_path):
        # Disk bound: old push dirs + averages go away once they are
        # behind BOTH keep_rounds and the slowest live member.
        gang, clock = str(tmp_path), FakeClock()
        coord = _coordinator(tmp_path, clock, keep_rounds=2)
        for r in range(1, 6):
            write_heartbeat(gang, 0, round=r, clock=clock)
            exchange.push_params(gang, r, 0, _params(float(r)))
            assert coord.step() is True
        # After round 5: prune below min(member_round=5, 6-2=4) = 4.
        assert exchange.read_average(gang, 3) is None
        assert exchange.pushed_ids(gang, 3) == set()
        assert exchange.read_average(gang, 4) is not None
        assert exchange.read_average(gang, 5) is not None
        assert exchange.latest_round(gang) == 5

    def test_lagging_member_neither_waited_on_nor_pruned_past(self, tmp_path):
        # A live catch-up worker (reported round behind the gang's)
        # must not hold rounds hostage to round_timeout — it only
        # adopts history — but its historic averages must survive
        # pruning until it catches up.
        gang, clock = str(tmp_path), FakeClock()
        coord = _coordinator(
            tmp_path, clock, keep_rounds=1, min_round=10
        )
        # The history worker 1 is still replaying.
        exchange.publish_average(
            gang, 3, exchange.flatten_params(_params(0.0))
        )
        for r in range(10, 14):
            exchange.push_params(gang, r, 0, _params(float(r)))
            write_heartbeat(gang, 0, round=r, clock=clock)
            # Worker 1 stays live but far behind (catching up at 3).
            write_heartbeat(gang, 1, round=3, clock=clock)
            # Publishes immediately: the catch-up member is excluded
            # from the waiting set, no round_timeout crawl.
            assert coord.step() is True
        assert coord.evicted == set()
        # Worker 1's historic average must survive pruning until it
        # catches up (prune stays behind the slowest live member).
        assert exchange.read_average(gang, 3) is not None

    def test_failed_goodbye_does_not_end_the_gang(self, tmp_path):
        # A 'failed' heartbeat may be followed by a supervisor restart
        # (the goodbye races the backoff window) — only 'done' workers
        # end the gang naturally; permanently-failed gangs are ended by
        # the runner's stop event.
        gang, clock = str(tmp_path), FakeClock()
        coord = _coordinator(tmp_path, clock)
        write_heartbeat(gang, 0, status="done", clock=clock)
        write_heartbeat(gang, 1, status="failed", clock=clock)
        coord.step()
        assert coord.all_finished() is False
        write_heartbeat(gang, 1, status="running", clock=clock)  # restart
        coord.step()
        assert coord.all_finished() is False
        write_heartbeat(gang, 1, status="done", clock=clock)
        assert coord.all_finished() is True

    def test_mixed_shapes_in_one_round_rejected(self, tmp_path):
        gang = str(tmp_path)
        exchange.push_params(gang, 1, 0, _params(1.0))
        exchange.push_params(
            gang, 1, 1,
            {"w": np.ones((1, 3), np.float32), "b": np.ones(3, np.float32)},
        )
        with pytest.raises(ValueError, match="mixed model configs"):
            exchange.average_pushes(gang, 1)

    def test_publication_waits_for_gang_assembly(self, tmp_path):
        # Launch stagger: a fast worker's round-1 push must not publish
        # before every expected worker has been SEEN once — early
        # rounds would otherwise average over a subset of a healthy
        # gang.
        gang, clock = str(tmp_path), FakeClock()
        coord = _coordinator(tmp_path, clock, expected_workers=2)
        write_heartbeat(gang, 0, round=1, clock=clock)
        exchange.push_params(gang, 1, 0, _params(1.0))
        assert coord.step() is False  # worker 1 never seen yet
        write_heartbeat(gang, 1, round=1, clock=clock)
        assert coord.step() is False  # seen: now waited on for a push
        exchange.push_params(gang, 1, 1, _params(3.0))
        assert coord.step() is True
        assert coord.rounds[1] == [0, 1]

    def test_assembly_gate_is_deadline_bounded(self, tmp_path):
        # A worker that never shows up costs one assembly window, not
        # the whole run's averaging.
        gang, clock = str(tmp_path), FakeClock()
        coord = _coordinator(
            tmp_path, clock, expected_workers=3, assembly_timeout=20.0,
        )
        write_heartbeat(gang, 0, round=1, clock=clock)
        exchange.push_params(gang, 1, 0, _params(1.0))
        assert coord.step() is False  # workers 1-2 never seen
        clock.advance(21.0)
        write_heartbeat(gang, 0, round=1, clock=clock)
        assert coord.step() is True  # window expired: proceed anyway
        assert coord.rounds[1] == [0]

    def test_expected_workers_gates_natural_end(self, tmp_path):
        # A fast first worker finishing before its siblings' first
        # heartbeat must not end the gang under them.
        gang, clock = str(tmp_path), FakeClock()
        coord = _coordinator(tmp_path, clock, expected_workers=2)
        write_heartbeat(gang, 0, status="done", clock=clock)
        coord.step()
        assert coord.all_finished() is False  # worker 1 never seen yet
        write_heartbeat(gang, 1, status="running", clock=clock)
        coord.step()
        assert coord.all_finished() is False
        write_heartbeat(gang, 1, status="done", clock=clock)
        assert coord.all_finished() is True

    def test_all_finished_ends_run(self, tmp_path):
        gang, clock = str(tmp_path), FakeClock()
        coord = _coordinator(tmp_path, clock)
        write_heartbeat(gang, 0, status="done", clock=clock)
        write_heartbeat(gang, 1, status="running", clock=clock)
        coord.step()
        assert coord.all_finished() is False
        write_heartbeat(gang, 1, status="done", clock=clock)
        assert coord.all_finished() is True
        # run() with everything done returns immediately (no stop event
        # needed), leaving the state file behind.
        state = coord.run(stop=None)
        assert sorted(state["ever_seen"]) == [0, 1]


# ---------------------------------------------------------------------
# the elastic config block (spec grammar + preflight integration)
# ---------------------------------------------------------------------


class TestElasticSpec:
    def test_defaults_merge_and_validate(self):
        block = {"dir": "/g", "worker_id": 0, "n_workers": 2}
        cfg = resolve_elastic(block)
        assert cfg["sync_every"] == ELASTIC_DEFAULTS["sync_every"]
        assert cfg["dir"] == "/g"

    def test_poll_interval_derived_from_heartbeat_cadence(self):
        """Unset poll_interval scales with heartbeat_interval (a fixed
        20 Hz scan is needless metadata load on NFS-class gang dirs);
        an explicit value is honored unchanged."""
        from tpuflow.elastic import POLL_BEATS, derive_poll_interval

        base = {"dir": "/g", "worker_id": 0, "n_workers": 2}
        slow = resolve_elastic({**base, "heartbeat_interval": 5.0})
        assert slow["poll_interval"] == pytest.approx(5.0 / POLL_BEATS)
        # The drill default derives the old 0.05 s cadence exactly.
        assert resolve_elastic(base)["poll_interval"] == pytest.approx(
            derive_poll_interval(ELASTIC_DEFAULTS["heartbeat_interval"])
        )
        pinned = resolve_elastic({**base, "poll_interval": 0.5})
        assert pinned["poll_interval"] == 0.5

    def test_coordinator_poll_derives_from_heartbeat_interval(self, tmp_path):
        from tpuflow.elastic import derive_poll_interval

        coord = Coordinator(str(tmp_path), heartbeat_interval=2.0)
        assert coord.poll_interval == pytest.approx(
            derive_poll_interval(2.0)
        )
        pinned = Coordinator(
            str(tmp_path), heartbeat_interval=2.0, poll_interval=0.01
        )
        assert pinned.poll_interval == 0.01

    def test_every_problem_reported(self):
        msgs = validate_elastic_block(
            {"worker_id": 3, "n_workers": 2, "sync_every": 0, "bogus": 1}
        )
        text = "; ".join(msgs)
        assert "elastic.dir is required" in text
        assert "outside the gang" in text
        assert "sync_every" in text
        assert "bogus" in text
        with pytest.raises(ValueError, match="invalid elastic config"):
            resolve_elastic({"dir": "", "worker_id": 0, "n_workers": 1})

    def test_preflight_spec_pass_rejects_bad_blocks(self):
        from tpuflow.analysis.spec import validate_spec
        from tpuflow.api import TrainJobConfig

        ok_block = {"dir": "/g", "worker_id": 0, "n_workers": 2}
        diags = validate_spec(
            TrainJobConfig(elastic={"worker_id": 9, "n_workers": 2})
        )
        assert any(d.code == "spec.elastic.invalid" for d in diags)
        diags = validate_spec(
            TrainJobConfig(elastic=ok_block, stream=True,
                           data_path="/d.csv", model="static_mlp")
        )
        assert any(d.code == "spec.elastic.stream" for d in diags)
        diags = validate_spec(TrainJobConfig(elastic=ok_block, tp=2))
        assert any(d.code == "spec.elastic.model_axis" for d in diags)
        # The fleet-of-meshes shape: an EXPLICIT n_devices > 1 makes
        # each worker data-parallel across its local devices and
        # preflights clean; only UNSET n_devices warns (every
        # co-located worker grabbing ALL visible devices).
        diags = validate_spec(TrainJobConfig(elastic=ok_block, n_devices=4))
        assert not [d for d in diags if d.code.startswith("spec.elastic")]
        diags = validate_spec(TrainJobConfig(elastic=ok_block))
        assert any(
            d.code == "spec.elastic.n_devices" and d.severity == "warning"
            for d in diags
        )
        # Runner-built blocks (n_devices=1) preflight clean of elastic
        # diagnostics.
        diags = validate_spec(TrainJobConfig(elastic=ok_block, n_devices=1))
        assert not [d for d in diags if d.code.startswith("spec.elastic")]

    def test_worker_spec_builds_disjoint_trees(self, tmp_path):
        spec = worker_spec(
            {**TINY, "storagePath": str(tmp_path)}, "/gang", 1, 3,
        )
        assert spec["storagePath"] == os.path.join(str(tmp_path), "worker1")
        assert spec["save_every"] == 1 and spec["n_devices"] == 1
        assert spec["elastic"]["worker_id"] == 1
        assert spec["elastic"]["n_workers"] == 3
        # asdict-style specs carry explicit Nones/zeros; still fixed up.
        spec = worker_spec(
            {**TINY, "storage_path": str(tmp_path), "save_every": 0,
             "n_devices": None},
            "/gang", 0, 2,
        )
        assert spec["save_every"] == 1 and spec["n_devices"] == 1

    def test_stale_gang_dir_refused(self, tmp_path):
        # Reusing a previous gang's dir would end the new gang
        # instantly (old 'done' heartbeats) and warm-start workers into
        # rounds nobody collects — refuse loudly instead.
        spec = {**TINY, "epochs": 2, "storagePath": str(tmp_path)}
        r = run_elastic(spec, 1, mode="inprocess", heartbeat_timeout=120.0)
        assert r.ok
        with pytest.raises(ValueError, match="previous gang's state"):
            run_elastic(spec, 1, mode="inprocess")

    def test_bad_knobs_rejected_at_submission(self, tmp_path):
        # A bad knob must die HERE, not as N child launches each dying
        # in train()'s preflight until the restart budget burns.
        spec = {**TINY, "storagePath": str(tmp_path)}
        with pytest.raises(ValueError, match="sync_every"):
            run_elastic(spec, 2, mode="inprocess", sync_every=0)
        with pytest.raises(ValueError, match="heartbeat_timeout"):
            run_elastic(spec, 2, mode="inprocess", heartbeat_timeout=-1.0)

    def test_inprocess_rejects_process_killing_faults(self, tmp_path):
        # In-process workers are THREADS: an exit/hang fault would take
        # down the coordinator and every worker (and the test runner).
        spec = {**TINY, "storagePath": str(tmp_path)}
        with pytest.raises(ValueError, match="kill or wedge"):
            run_elastic(
                spec, 2, mode="inprocess",
                worker_faults={1: ["train.epoch_start,at=1,mode=exit"]},
            )

    def test_catch_up_skips_pruned_rounds_without_waiting(self, tmp_path):
        # A returning worker whose historic round was pruned must not
        # burn pull_timeout on a file that cannot appear.
        from tpuflow.elastic.worker import ElasticWorkerClient

        gang = str(tmp_path)
        exchange.publish_average(
            gang, 5, exchange.flatten_params(_params(1.0))
        )
        exchange.prune_rounds(gang, 5)
        slept = []
        client = ElasticWorkerClient(
            {"dir": gang, "worker_id": 0, "n_workers": 2,
             "pull_timeout": 60.0},
            clock=FakeClock(), sleep=slept.append,
        )
        assert client._wait_for_average(2) is None  # pruned history
        assert slept == []  # decided on the first scan, no waiting
        got = client._wait_for_average(5)  # the kept round still reads
        assert got is not None

    def test_round_offset_survives_restart(self, tmp_path):
        # A late joiner's round offset must come back after a
        # supervisor restart, or its rounds would misalign with the
        # gang's forever (adopting R-rounds-stale averages every sync).
        from tpuflow.elastic.worker import ElasticWorkerClient

        class _State:
            def __init__(self, params):
                self.params = params

            def replace(self, params):
                return _State(params)

        gang = str(tmp_path)
        exchange.publish_average(
            gang, 7, exchange.flatten_params(_params(2.0))
        )
        block = {"dir": gang, "worker_id": 3, "n_workers": 4}
        fresh = ElasticWorkerClient(block)
        state = fresh.join(_State(_params(0.0)))
        assert fresh.round_offset == 7
        np.testing.assert_allclose(state.params["w"], 2.0)  # warm start
        fresh.finish(failed=True)  # "crash": no final push
        restarted = ElasticWorkerClient(block, resuming=True)
        restarted.join(_State(_params(0.0)))
        assert restarted.round_offset == 7  # persisted, not reset to 0
        restarted.finish(failed=True)

    def test_shard_rows_disjoint_and_covering(self):
        from tpuflow.data.pipeline import ArrayDataset
        from tpuflow.elastic.worker import shard_rows

        ds = ArrayDataset(np.arange(10, dtype=np.float32).reshape(10, 1),
                          np.arange(10, dtype=np.float32))
        shards = [shard_rows(ds, i, 3) for i in range(3)]
        seen = np.sort(np.concatenate([s.y for s in shards]))
        np.testing.assert_array_equal(seen, ds.y)  # disjoint + covering
        with pytest.raises(ValueError, match="empty train shard"):
            shard_rows(ArrayDataset(ds.x[:2], ds.y[:2]), 2, 3)


# ---------------------------------------------------------------------
# in-process gangs (tier-1; real train() loops as threads)
# ---------------------------------------------------------------------


def _finite(x) -> bool:
    return x is not None and not isinstance(x, str) and math.isfinite(x)


class TestInProcessGang:
    def test_two_worker_gang_averages_every_round(self, tmp_path):
        spec = {**TINY, "storagePath": str(tmp_path)}
        r = run_elastic(
            spec, 2, mode="inprocess", heartbeat_timeout=120.0,
        )
        assert r.ok, [w.error for w in r.workers]
        assert all(w.report["epochs_ran"] == TINY["epochs"] for w in r.workers)
        assert r.coordinator["round"] - 1 == TINY["epochs"]
        # Every round averaged over BOTH workers (fixed membership).
        assert all(ids == [0, 1] for ids in r.coordinator["rounds"].values())
        assert r.final_worker_ids == [0, 1]
        assert os.path.exists(r.final_path)
        # The final averaged params ARE the last round's rebroadcast:
        # every worker's closing sync adopted avg(last), so the final
        # pushes agree with it bit-for-bit.
        last = exchange.read_average(str(tmp_path) + "/elastic",
                                     TINY["epochs"])
        for a, b in zip(r.final_params, last):
            np.testing.assert_allclose(a, b, rtol=1e-6)
        assert all(_finite(w.report["best_val_loss"]) for w in r.workers)

    @pytest.mark.faultdrill
    def test_single_armed_push_fault_leaves_survivor_running(self, tmp_path):
        spec = {**TINY, "storagePath": str(tmp_path)}
        r = run_elastic(
            spec, 2, mode="inprocess", heartbeat_timeout=120.0,
            round_timeout=5.0,
            worker_faults={1: ["elastic.push,at=1"]},
        )
        # at=1 fires on whichever worker pushes round 1 first while the
        # spec is armed — worker 1 armed it, but the registry is
        # process-global in-process. Either way: exactly one worker
        # died at the site, the other finished every epoch, and the
        # coordinator kept publishing rounds for the survivor.
        errors = [w for w in r.workers if w.error]
        survivors = [w for w in r.workers if not w.error]
        assert len(errors) == 1 and len(survivors) == 1
        assert "injected fault" in errors[0].error
        assert survivors[0].report["epochs_ran"] == TINY["epochs"]
        assert r.coordinator["round"] - 1 == TINY["epochs"]
        # The dead worker said goodbye (status=failed) or was evicted;
        # either way the final average exists over the survivor.
        assert r.final_worker_ids == [survivors[0].worker_id]

    @pytest.mark.faultdrill
    def test_join_fault_fails_fast_and_labeled(self, tmp_path):
        from tpuflow.resilience import clear_faults

        spec = {**TINY, "storagePath": str(tmp_path), "epochs": 2}
        r = run_elastic(
            spec, 1, mode="inprocess", heartbeat_timeout=120.0,
            worker_faults={0: ["elastic.join,nth=1"]},
        )
        clear_faults()
        assert not r.ok
        assert "injected fault" in r.workers[0].error
        assert "elastic.join" in r.workers[0].error

    @pytest.mark.faultdrill
    def test_heartbeat_fault_fires_at_the_site(self, tmp_path):
        from tpuflow.resilience import (
            FaultInjected,
            FaultSpec,
            arm,
            clear_faults,
        )

        arm(FaultSpec(site="elastic.heartbeat", nth=1))
        try:
            with pytest.raises(FaultInjected, match="elastic.heartbeat"):
                write_heartbeat(str(tmp_path), 0)
        finally:
            clear_faults()
        # The write never happened — a half-written heartbeat would be
        # worse than none.
        assert read_members(str(tmp_path)) == []

    def test_warm_start_adopts_latest_average(self, tmp_path, capfd):
        # A late joiner with no checkpoint starts from gang progress:
        # run a 1-worker gang, then start a NEW worker id against the
        # same gang dir and assert it adopted the published average
        # before its first epoch (train/resume.py::apply_params).
        gang = str(tmp_path / "elastic")
        spec = {**TINY, "epochs": 2, "storagePath": str(tmp_path)}
        r = run_elastic(
            spec, 1, mode="inprocess", gang_dir=gang,
            heartbeat_timeout=120.0,
        )
        assert r.ok
        latest_round, _ = exchange.latest_average(gang)
        assert latest_round == 2
        late = worker_spec(
            {**TINY, "epochs": 3, "storagePath": str(tmp_path / "late")},
            gang, 1, 2, elastic_overrides={"pull_timeout": 2.0},
        )
        from tpuflow.api import train
        from tpuflow.serve import spec_to_config

        capfd.readouterr()
        train(spec_to_config(late))
        err = capfd.readouterr().err
        assert f"warm-started from round {latest_round}'s average" in err
        # ... and its rounds CONTINUE from the join point (a round-1
        # push would adopt the gang's ancient round-1 average and
        # clobber the warm start it just did).
        assert not os.path.exists(
            os.path.join(exchange.push_dir(gang, 1), "1.npz")
        )
        assert os.path.exists(
            os.path.join(exchange.push_dir(gang, latest_round + 1), "1.npz")
        )


# ---------------------------------------------------------------------
# the acceptance drill: kill, evict, keep averaging, readmit, converge
# ---------------------------------------------------------------------


@pytest.mark.faultdrill
class TestChurnAcceptance:
    def test_three_workers_survive_mid_epoch_kill(self, tmp_path):
        """ISSUE 6 acceptance: 3 supervised workers; worker 1 dies at
        the top of epoch 3 via a registry-armed exit fault (os._exit,
        no Python cleanup — the SIGKILL stand-in). End-to-end through
        the real coordinator and fault registry:

        - the dead worker is EVICTED on the heartbeat deadline and at
          least one round is averaged over exactly the survivors;
        - its supervisor restarts it (attempt 2) with resume=True and a
          fresh heartbeat READMITS it (rejoins >= 1);
        - every worker finishes all epochs, losses converge, no NaNs;
        - the final averaged params match a fixed-membership reference
          gang (same job, no faults) to float tolerance.
        """
        base = {**TINY, "epochs": 12}
        churn = run_elastic(
            {**base, "storagePath": str(tmp_path / "churn")}, 3,
            mode="supervised",
            heartbeat_timeout=1.0,
            heartbeat_interval=0.2,
            round_timeout=10.0,
            min_round_interval=1.2,  # rounds keep flowing while it's gone
            pull_timeout=300.0,
            max_restarts=2,
            backoff_base=3.0,  # hold the restart out past the eviction
            worker_faults={1: ["train.epoch_start,at=3,mode=exit,code=42"]},
        )
        assert churn.ok, [w.error for w in churn.workers]
        # The kill happened and was answered by a restart (the fault
        # registry's exit fault = rc 42 on attempt 1).
        victim = churn.workers[1]
        assert victim.attempts == 2
        assert victim.failures and victim.failures[0]["rc"] == 42
        assert victim.failures[0]["kind"] == "crash"
        # Everyone finished the whole job.
        for w in churn.workers:
            assert w.report["epochs_ran"] == base["epochs"]
            assert _finite(w.report["best_val_loss"])
            assert w.report["best_val_loss"] < 0.5  # converged, no NaNs
        # Eviction: averaging proceeded over the survivors — at least
        # one round excludes the dead worker (usually exactly [0, 2];
        # stated as exclusion so a scheduler-noise spurious eviction of
        # a survivor can't flake the drill).
        rounds = churn.coordinator["rounds"]
        assert any(1 not in ids for ids in rounds.values()), rounds
        # Readmission: the restarted worker's heartbeat brought it back.
        assert churn.coordinator["rejoins"] >= 1
        assert 1 not in churn.coordinator["evicted"]
        # All twelve rounds were published despite the churn.
        assert churn.coordinator["round"] - 1 == base["epochs"]
        assert churn.final_worker_ids == [0, 1, 2]

        # Fixed-membership reference: same job, no faults, in-process
        # (same averaging code path, no supervisors needed).
        ref = run_elastic(
            {**base, "storagePath": str(tmp_path / "ref")}, 3,
            mode="inprocess", heartbeat_timeout=300.0,
        )
        assert ref.ok, [w.error for w in ref.workers]
        assert all(
            ids == [0, 1, 2] for ids in ref.coordinator["rounds"].values()
        )
        # Float-tolerance parity (measured deltas ~0.003-0.02 for the
        # linear model; 0.12 gives ~6x headroom for scheduler noise in
        # how many rounds the victim missed).
        for got, want in zip(churn.final_params, ref.final_params):
            np.testing.assert_allclose(got, want, atol=0.12)


# ---------------------------------------------------------------------
# big gangs + soak (slow)
# ---------------------------------------------------------------------


@pytest.mark.slow
class TestBigGangs:
    def test_four_worker_gang(self, tmp_path):
        spec = {**TINY, "storagePath": str(tmp_path)}
        r = run_elastic(spec, 4, mode="inprocess", heartbeat_timeout=120.0)
        assert r.ok, [w.error for w in r.workers]
        assert all(
            ids == [0, 1, 2, 3] for ids in r.coordinator["rounds"].values()
        )
        assert r.final_worker_ids == [0, 1, 2, 3]

    @pytest.mark.faultdrill
    def test_kill_and_rejoin_soak_two_victims(self, tmp_path):
        # Two different workers die at different epochs; both restart,
        # both rejoin, the gang still lands every round.
        base = {**TINY, "epochs": 14}
        r = run_elastic(
            {**base, "storagePath": str(tmp_path)}, 3,
            mode="supervised",
            heartbeat_timeout=1.0, heartbeat_interval=0.2,
            round_timeout=10.0, min_round_interval=1.0,
            pull_timeout=300.0, max_restarts=2, backoff_base=2.0,
            worker_faults={
                1: ["train.epoch_start,at=3,mode=exit,code=42"],
                2: ["train.epoch_start,at=6,mode=exit,code=42"],
            },
        )
        assert r.ok, [w.error for w in r.workers]
        assert r.workers[1].attempts == 2 and r.workers[2].attempts == 2
        assert r.coordinator["rejoins"] >= 2
        assert r.coordinator["round"] - 1 == base["epochs"]
        for w in r.workers:
            assert w.report["epochs_ran"] == base["epochs"]
            assert _finite(w.report["best_val_loss"])

    def test_shell_entrypoint(self, tmp_path):
        import subprocess
        import sys

        spec_file = tmp_path / "spec.json"
        spec_file.write_text(
            json.dumps({**TINY, "epochs": 2, "storagePath": str(tmp_path)})
        )
        proc = subprocess.run(
            [sys.executable, "-m", "tpuflow.elastic", str(spec_file),
             "--workers", "2", "--mode", "inprocess", "--quiet"],
            capture_output=True, text=True, timeout=600,
        )
        assert proc.returncode == 0, proc.stderr[-800:]
        out = json.loads(proc.stdout.strip().splitlines()[-1])
        assert out["ok"] is True and out["rounds"] == 2


# ---------------------------------------------------------------------
# unit: wire framing + payload checksums (tpuflow/elastic/transport.py)
# ---------------------------------------------------------------------


class TestWireFormat:
    def test_frame_roundtrip_over_a_real_socketpair(self):
        import socket as _socket  # noqa: TPF012 (test harness, not tpuflow)

        from tpuflow.elastic.transport import recv_frame, send_frame

        a, b = _socket.socketpair()
        try:
            payload = exchange.encode_leaves(
                exchange.flatten_params(_params(2.5))
            )
            send_frame(a, {"op": "push", "round": 3}, payload)
            header, got = recv_frame(b)
            assert header == {"op": "push", "round": 3}
            leaves = exchange.decode_leaves(got)
            np.testing.assert_allclose(leaves[1], 2.5)
        finally:
            a.close()
            b.close()

    def test_corrupted_payload_detected_not_trusted(self):
        import socket as _socket  # noqa: TPF012 (test harness)

        from tpuflow.elastic.transport import (
            TransportError,
            recv_frame,
            send_frame,
        )

        a, b = _socket.socketpair()
        try:
            payload = exchange.encode_leaves(
                exchange.flatten_params(_params(1.0))
            )
            send_frame(a, {"op": "push"}, payload)
            raw = bytearray()
            while len(raw) < 20 + len(payload):
                raw += b.recv(1 << 16)
            raw[-8] ^= 0xFF  # flip one payload byte in flight
            c, d = _socket.socketpair()
            c.sendall(bytes(raw))
            with pytest.raises(TransportError, match="checksum"):
                recv_frame(d)
            c.close()
            d.close()
        finally:
            a.close()
            b.close()

    def test_alien_bytes_rejected(self):
        import socket as _socket  # noqa: TPF012 (test harness)

        from tpuflow.elastic.transport import TransportError, recv_frame

        a, b = _socket.socketpair()
        try:
            a.sendall(b"GET / HTTP/1.1\r\n\r\n" + b"\x00" * 16)
            with pytest.raises(TransportError, match="magic"):
                recv_frame(b)
        finally:
            a.close()
            b.close()

    def test_npz_payload_checksum_rejects_bit_flips(self):
        leaves = exchange.flatten_params(_params(4.0))
        data = bytearray(exchange.encode_leaves(leaves))
        assert exchange.decode_leaves(bytes(data))  # pristine reads
        data[-4] ^= 0x01  # damage an array byte inside the npz
        with pytest.raises(ValueError):
            exchange.decode_leaves(bytes(data))

    def test_parse_addr_fail_loud(self):
        from tpuflow.elastic.transport import parse_addr

        assert parse_addr("127.0.0.1:8000") == ("127.0.0.1", 8000)
        for bad in ("localhost", ":9", "h:", "h:port", ""):
            with pytest.raises(ValueError, match="host:port"):
                parse_addr(bad)


class TestFileChecksum:
    def test_torn_push_file_skipped_by_averaging(self, tmp_path):
        """A push file damaged AFTER its atomic rename (a torn NFS
        page, a bad disk) must fail its checksum and be skipped —
        ``np.load`` alone would average the garbage."""
        gang = str(tmp_path)
        exchange.push_params(gang, 1, 0, _params(1.0))
        exchange.push_params(gang, 1, 1, _params(3.0))
        victim = os.path.join(exchange.push_dir(gang, 1), "1.npz")
        data = bytearray(open(victim, "rb").read())
        data[-4] ^= 0xFF
        open(victim, "wb").write(bytes(data))
        leaves, used = exchange.average_pushes(gang, 1)
        assert used == [0]  # the damaged push is out, the round lives
        np.testing.assert_allclose(leaves[0], 1.0)

    def test_corrupt_average_reads_as_missing(self, tmp_path):
        """A damaged rebroadcast reads as None — the worker's wait loop
        re-pulls until a clean copy (or its timeout) instead of
        adopting poisoned params."""
        gang = str(tmp_path)
        exchange.publish_average(
            gang, 2, exchange.flatten_params(_params(5.0))
        )
        path = exchange.avg_path(gang, 2)
        data = bytearray(open(path, "rb").read())
        data[-4] ^= 0xFF
        open(path, "wb").write(bytes(data))
        assert exchange.read_average(gang, 2) is None
        assert exchange.latest_average(gang) is None

    def test_pre_checksum_files_stay_readable(self, tmp_path):
        # Back-compat: an npz written before the checksum field existed
        # (no crc32 entry) must still read.
        path = str(tmp_path / "old.npz")
        leaves = exchange.flatten_params(_params(1.5))
        with open(path, "wb") as f:
            np.savez(f, n_leaves=np.int64(len(leaves)),
                     **{f"arr_{i}": a for i, a in enumerate(leaves)})
        got = exchange._read_npz(path)
        np.testing.assert_allclose(got[0], 1.5)


# ---------------------------------------------------------------------
# unit: the in-memory gang store (socket transport's state)
# ---------------------------------------------------------------------


class TestGangStore:
    def _store(self, clock):
        from tpuflow.elastic.transport import GangStore

        return GangStore(clock=clock)

    def test_heartbeats_stamped_with_server_clock(self):
        from tpuflow.elastic.membership import classify_view

        clock = FakeClock()
        store = self._store(clock)
        store.write_heartbeat(0, epoch=1, round=1)
        view = classify_view(store.read_members(), 5.0, clock())
        assert view.live_ids == {0}
        clock.advance(6.0)  # beats stop ARRIVING: transport liveness
        view = classify_view(store.read_members(), 5.0, clock())
        assert view.stale_ids == {0}
        store.write_heartbeat(0, epoch=2, round=2)  # reconnect
        view = classify_view(store.read_members(), 5.0, clock())
        assert view.live_ids == {0}

    def test_goodbye_sticky_and_joining_revokes(self):
        store = self._store(FakeClock())
        assert store.write_heartbeat(0, status="done")
        assert store.write_heartbeat(0, status="running") is False
        [m] = store.read_members()
        assert m.status == "done"
        assert store.write_heartbeat(0, status="joining")  # new life
        assert store.write_heartbeat(0, status="running")
        [m] = store.read_members()
        assert m.status == "running"

    def test_push_average_latest_prune(self):
        store = self._store(FakeClock())
        store.push(1, 0, _params(1.0))
        store.push(1, 1, _params(3.0))
        assert store.pushed_ids(1) == {0, 1}
        leaves, used = exchange.average_leaf_sets(store.read_pushes(1))
        assert used == [0, 1]
        np.testing.assert_allclose(leaves[0], 2.0)
        store.publish(1, leaves)
        assert store.latest_round() == 1
        round_, got = store.latest_average()
        assert round_ == 1
        store.push(4, 0, _params(9.0))
        latest = store.latest_pushes(0)
        assert [(w, r) for w, r, _ in latest] == [(0, 4), (1, 1)]
        assert [(w, r) for w, r, _ in store.latest_pushes(2)] == [(0, 4)]
        store.prune(3)
        assert store.pushed_ids(1) == set()
        assert store.read_average(1) is None
        assert store.pushed_ids(4) == {0}

    def test_final_pushes_never_pruned(self):
        store = self._store(FakeClock())
        store.push(exchange.FINAL_ROUND, 0, _params(1.0))
        store.prune(10_000)
        assert store.pushed_ids(exchange.FINAL_ROUND) == {0}
        assert store.latest_pushes(0) == []  # final is not a round

    def test_offsets(self):
        store = self._store(FakeClock())
        assert store.get_offset(3) == (0, False)
        store.set_offset(3, 7)
        assert store.get_offset(3) == (7, True)


# ---------------------------------------------------------------------
# the socket exchange: real TCP, tier-1 (loopback, ephemeral port)
# ---------------------------------------------------------------------


@pytest.fixture()
def socket_gang():
    """A live exchange server over a fake-clock store + a client."""
    from tpuflow.elastic.transport import (
        ExchangeServer,
        GangStore,
        SocketExchange,
    )

    clock = FakeClock()
    store = GangStore(clock=clock)
    with ExchangeServer(store) as server:
        yield store, clock, SocketExchange(server.addr), server


class TestSocketExchange:
    def test_worker_ops_roundtrip(self, socket_gang):
        store, clock, ex, _ = socket_gang
        assert ex.ping()
        assert ex.write_heartbeat(0, epoch=1, round=1)
        ex.push(1, 0, _params(1.0))
        ex.push(1, 1, _params(3.0))
        assert ex.pushed_ids(1) == {0, 1}
        leaves, used = exchange.average_leaf_sets(store.read_pushes(1))
        store.publish(1, leaves)
        got = ex.read_average(1)
        np.testing.assert_allclose(got[0], 2.0)
        assert ex.latest_round() == 1
        round_, latest = ex.latest_average()
        assert round_ == 1
        np.testing.assert_allclose(latest[0], 2.0)
        assert ex.read_average(9) is None
        ex.set_offset(0, 4)
        assert ex.get_offset(0) == (4, True)

    def test_tpfx_headers_carry_the_worker_trace(
        self, socket_gang, tmp_path
    ):
        """Cross-process trace propagation (ISSUE 14): a push sent
        while a trace is bound carries it in the TPFX frame header,
        the coordinator-side store remembers it, and the published
        round's `elastic.round` span names the pushing workers'
        traces — the worker->coordinator link on the fleet timeline.
        An unbound (or garbage) trace simply yields no entry."""
        from tpuflow.obs import clear_events, recent_events, use_trace

        store, clock, ex, _ = socket_gang
        with use_trace("w0trace000000001"):
            ex.push(1, 0, _params(1.0))
            ex.write_heartbeat(0, round=1)
        ex.push(1, 1, _params(3.0))  # no bound trace: no entry
        ex.write_heartbeat(1, round=1)
        assert store.worker_traces() == {0: "w0trace000000001"}

        clear_events()
        coord = Coordinator(
            str(tmp_path / "gang-state"), backend=store,
            heartbeat_timeout=5.0, clock=clock, sleep=lambda _: None,
        )
        assert coord.step() is True
        [span] = [
            e for e in recent_events()
            if e.get("name") == "elastic.round"
        ]
        assert span["worker_traces"] == {"0": "w0trace000000001"}
        # The span also lands in the coordinator's on-disk trail (the
        # fleet lane), same worker_traces attached.
        trail = tmp_path / "gang-state" / "coordinator-metrics.jsonl"
        recs = [json.loads(l) for l in open(trail)]
        [rec] = [r for r in recs if r.get("name") == "elastic.round"]
        assert rec["worker_traces"] == {"0": "w0trace000000001"}

    def test_coordinator_over_the_store_publishes(self, socket_gang):
        store, clock, ex, _ = socket_gang
        coord = Coordinator(
            "/tmp/unused-gang-state", backend=store,
            heartbeat_timeout=5.0, clock=clock, sleep=lambda _: None,
        )
        ex.write_heartbeat(0, round=1)
        ex.write_heartbeat(1, round=1)
        ex.push(1, 0, _params(1.0))
        assert coord.step() is False  # worker 1 live: hold the round
        ex.push(1, 1, _params(3.0))
        assert coord.step() is True
        assert coord.rounds[1] == [0, 1]
        np.testing.assert_allclose(ex.read_average(1)[0], 2.0)

    def test_eviction_on_transport_silence(self, socket_gang):
        """The liveness verdict is transport-level: a worker whose
        beats stop ARRIVING goes stale on the coordinator's clock,
        whatever its own clock thinks."""
        store, clock, ex, _ = socket_gang
        coord = Coordinator(
            "/tmp/unused-gang-state", backend=store,
            heartbeat_timeout=5.0, clock=clock, sleep=lambda _: None,
        )
        ex.write_heartbeat(0, round=1)
        ex.write_heartbeat(1, round=1)
        ex.push(1, 0, _params(1.0))
        assert coord.step() is False
        clock.advance(4.0)
        ex.write_heartbeat(0, round=1)  # 0 keeps beating
        clock.advance(2.0)  # 1's last beat is now 6s old
        assert coord.step() is True
        assert coord.evicted == {1}
        ex.write_heartbeat(1, round=2)  # reconnect readmits
        coord.step()
        assert coord.evicted == set() and coord.rejoins == 1

    @pytest.mark.faultdrill
    def test_transient_send_fault_retried_within_deadline(
        self, socket_gang, monkeypatch
    ):
        """The retry satellite: a transient transport fault costs a
        backoff sleep, not the op — wired through the SAME io_policy
        the checkpoint/CSV sites use."""
        from tpuflow.resilience import FaultSpec, arm, clear_faults

        monkeypatch.setenv("TPUFLOW_RETRY_BASE", "0.001")
        store, clock, ex, _ = socket_gang
        arm(FaultSpec(
            site="elastic.transport.send", nth=1, transient=True,
        ))
        try:
            ex.push(1, 0, _params(1.0))  # retried, then lands
        finally:
            clear_faults()
        assert store.pushed_ids(1) == {0}

    @pytest.mark.faultdrill
    def test_hard_send_fault_exhausts_and_raises(
        self, socket_gang, monkeypatch
    ):
        from tpuflow.resilience import (
            FaultInjected,
            FaultSpec,
            arm,
            clear_faults,
        )

        monkeypatch.setenv("TPUFLOW_RETRY_ATTEMPTS", "2")
        monkeypatch.setenv("TPUFLOW_RETRY_BASE", "0.001")
        store, clock, ex, _ = socket_gang
        arm(FaultSpec(site="elastic.transport.send", p=1.0, seed=0))
        try:
            with pytest.raises(FaultInjected):
                ex.push(1, 0, _params(1.0))
        finally:
            clear_faults()
        assert store.pushed_ids(1) == set()

    def test_dead_server_raises_oserror_not_hang(self, monkeypatch):
        from tpuflow.elastic.transport import SocketExchange

        monkeypatch.setenv("TPUFLOW_RETRY_ATTEMPTS", "2")
        monkeypatch.setenv("TPUFLOW_RETRY_BASE", "0.001")
        monkeypatch.setenv("TPUFLOW_RETRY_DEADLINE", "2")
        ex = SocketExchange("127.0.0.1:1", timeout=0.2)  # nothing there
        with pytest.raises(OSError):
            ex.ping()


# ---------------------------------------------------------------------
# async push + staleness bounds (unit drills, fake clock)
# ---------------------------------------------------------------------


class TestAsyncStaleness:
    def _async_gang(self, tmp_path, clock, **kw):
        from tpuflow.elastic.transport import GangStore

        store = GangStore(clock=clock)
        kw.setdefault("max_staleness", 1)
        coord = Coordinator(
            str(tmp_path), backend=store, async_push=True,
            heartbeat_timeout=30.0, clock=clock, sleep=lambda _: None,
            **kw,
        )
        return store, coord

    def _push(self, store, wid, round, value):
        store.push_leaves(
            round, wid, [np.full((2,), value, np.float32)]
        )

    def test_stale_push_downweighted_at_the_bound(self, tmp_path):
        clock = FakeClock()
        store, coord = self._async_gang(tmp_path, clock)
        store.write_heartbeat(0, round=5)
        store.write_heartbeat(1, round=4)
        self._push(store, 0, 5, 1.0)  # at the frontier: weight 1
        self._push(store, 1, 4, 4.0)  # staleness 1: weight 1/2
        assert coord.step() is True
        # The average is published AT the frontier — the one round
        # numbering space workers, prune, and warm starts all share.
        (leaf,) = store.read_average(5)
        np.testing.assert_allclose(
            leaf, (1.0 + 0.5 * 4.0) / 1.5, rtol=1e-6
        )
        assert store.latest_round() == 5

    def test_push_beyond_bound_rejected_and_counted(self, tmp_path):
        clock = FakeClock()
        store, coord = self._async_gang(tmp_path, clock)
        self._push(store, 0, 5, 1.0)
        self._push(store, 1, 1, 9.0)  # staleness 4 > bound 1: rejected
        before = coord._stale.value()
        assert coord.step() is True
        (leaf,) = store.read_average(5)
        np.testing.assert_allclose(leaf, 1.0)  # the ancient push is OUT
        assert coord._stale.value() == before + 1
        # ... and counted ONCE, not once per scan.
        self._push(store, 0, 6, 2.0)
        assert coord.step() is True
        assert coord._stale.value() == before + 1

    def test_async_warm_start_offset_shares_the_round_space(
        self, tmp_path, socket_gang
    ):
        """Regression: the published round number IS the push frontier,
        so a late joiner's warm-start offset lands in the same space as
        everyone's pushes — a separate publish counter racing ahead of
        worker epochs would inflate the frontier and get the whole
        gang's pushes staleness-rejected forever."""
        from tpuflow.elastic.worker import ElasticWorkerClient

        class _State:
            def __init__(self, params):
                self.params = params

            def replace(self, params):
                return _State(params)

        store, clock, ex, server = socket_gang
        coord = Coordinator(
            str(tmp_path), backend=store, async_push=True,
            max_staleness=1, heartbeat_timeout=30.0, clock=clock,
            sleep=lambda _: None,
        )
        # Incumbent worker 0 marches to round 5 (one publish each).
        for r in range(1, 6):
            store.push_leaves(
                r, 0, exchange.flatten_params(_params(1.0))
            )
            store.write_heartbeat(0, round=r)
            assert coord.step() is True
        assert store.latest_round() == 5
        # A late joiner warm-starts: its offset is the frontier.
        joiner = ElasticWorkerClient(
            {"dir": str(tmp_path), "worker_id": 1, "n_workers": 2,
             "transport": "socket", "addr": server.addr,
             "async_push": True},
            clock=clock, sleep=lambda _: None,
        )
        state = joiner.join(_State(_params(0.0)))
        assert joiner.round_offset == 5
        # Its first sync pushes round 6; the incumbent's round-5 push
        # is staleness 1 — still IN the average, not rejected.
        state = joiner.sync(1, state)
        before = coord._stale.value()
        assert coord.step() is True
        assert coord._stale.value() == before  # nobody rejected
        assert sorted(coord.rounds[6]) == [0, 1]
        joiner.finish(failed=True)

    def test_async_prune_bounds_retained_averages(self, tmp_path):
        """Regression: with one round space, pruning keeps the retained
        push/average keys bounded over a long async run instead of
        leaking one param copy per publish."""
        clock = FakeClock()
        store, coord = self._async_gang(
            tmp_path, clock, max_staleness=1, keep_rounds=2
        )
        store.write_heartbeat(0, round=0)
        for r in range(1, 40):
            self._push(store, 0, r, float(r))
            store.write_heartbeat(0, round=r)
            assert coord.step() is True
        assert len(store._averages) <= 4
        assert len(store._pushes) <= 4

    def test_no_fresh_pushes_no_publish(self, tmp_path):
        clock = FakeClock()
        store, coord = self._async_gang(tmp_path, clock)
        self._push(store, 0, 3, 1.0)
        assert coord.step() is True
        assert coord.step() is False  # same pushes: nothing new
        self._push(store, 0, 4, 2.0)
        assert coord.step() is True

    def test_straggler_neither_stalls_nor_poisons(self, tmp_path):
        """The DeepSpark claim, as a unit drill: the gang publishes at
        the fast workers' cadence while the straggler is fresh-enough
        (down-weighted), and drops it once it falls past the bound —
        no round ever WAITS on it."""
        clock = FakeClock()
        store, coord = self._async_gang(tmp_path, clock)
        store.write_heartbeat(0, round=1)
        store.write_heartbeat(1, round=1)
        self._push(store, 1, 1, 100.0)  # the straggler's only push
        published = []
        for r in range(1, 6):  # worker 0 marches on alone
            self._push(store, 0, r, 1.0)
            published.append(coord.step())
        assert all(published)  # every scan published: zero stalls
        seq, leaves = store.latest_average()
        # By the last rounds the straggler is past the bound: the
        # average is exactly the fast worker's params.
        np.testing.assert_allclose(leaves[0], 1.0)

    def test_async_worker_adopts_freshest_without_waiting(
        self, socket_gang
    ):
        from tpuflow.elastic.worker import ElasticWorkerClient

        class _State:
            def __init__(self, params):
                self.params = params

            def replace(self, params):
                return _State(params)

        store, clock, ex, server = socket_gang
        client = ElasticWorkerClient(
            {"dir": "/tmp/unused", "worker_id": 0, "n_workers": 2,
             "transport": "socket", "addr": server.addr,
             "async_push": True},
            clock=clock, sleep=lambda _: None,
        )
        state = _State(_params(0.0))
        # No average published yet: the sync pushes and returns
        # IMMEDIATELY on local params — no round barrier.
        state = client.sync(1, state)
        np.testing.assert_allclose(state.params["w"], 0.0)
        assert store.pushed_ids(1) == {0}
        # An average appears; the next sync adopts it.
        store.publish(1, exchange.flatten_params(_params(7.0)))
        state = client.sync(2, state)
        np.testing.assert_allclose(state.params["w"], 7.0)
        # Same average again: no re-adopt (nothing fresher).
        state.params["w"][:] = 5.0
        state = client.sync(3, state)
        np.testing.assert_allclose(state.params["w"], 5.0)


# ---------------------------------------------------------------------
# graceful degradation: partition -> local training -> resync on heal
# ---------------------------------------------------------------------


@pytest.mark.faultdrill
class TestDegradation:
    def test_partition_degrades_then_heals(
        self, socket_gang, monkeypatch
    ):
        from tpuflow.elastic.worker import ElasticWorkerClient
        from tpuflow.resilience import FaultSpec, arm, clear_faults

        monkeypatch.setenv("TPUFLOW_RETRY_ATTEMPTS", "2")
        monkeypatch.setenv("TPUFLOW_RETRY_BASE", "0.001")
        monkeypatch.setenv("TPUFLOW_RETRY_DEADLINE", "1")

        class _State:
            def __init__(self, params):
                self.params = params

            def replace(self, params):
                return _State(params)

        store, clock, ex, server = socket_gang
        client = ElasticWorkerClient(
            {"dir": "/tmp/unused", "worker_id": 0, "n_workers": 2,
             "transport": "socket", "addr": server.addr,
             "async_push": True, "pull_timeout": 1.0},
            clock=clock, sleep=lambda _: None,
        )
        state = client.join(_State(_params(0.0)))
        state = client.sync(1, state)
        assert store.pushed_ids(1) == {0}
        assert client.degraded is False
        # Partition: every connect fires; the worker keeps training.
        spec = arm(FaultSpec(
            site="elastic.transport.partition", p=1.0, seed=0,
        ))
        try:
            state = client.sync(2, state)
            assert client.degraded is True
            assert store.pushed_ids(2) == set()  # nothing arrived
            assert np.isfinite(state.params["w"]).all()
        finally:
            clear_faults()
        # Heal: the next sync reconnects, pushes, and resyncs.
        store.publish(1, exchange.flatten_params(_params(3.0)))
        state = client.sync(3, state)
        assert client.degraded is False
        assert store.pushed_ids(3) == {0}
        np.testing.assert_allclose(state.params["w"], 3.0)  # resynced
        client.finish(state)
        assert store.pushed_ids(exchange.FINAL_ROUND) == {0}

    def test_nontransport_fault_still_kills_the_worker(
        self, socket_gang
    ):
        """The degradation guard must NOT swallow the worker's own kill
        drills: an injected elastic.push fault propagates even over the
        socket backend."""
        from tpuflow.elastic.worker import ElasticWorkerClient
        from tpuflow.resilience import (
            FaultInjected,
            FaultSpec,
            arm,
            clear_faults,
        )

        class _State:
            def __init__(self, params):
                self.params = params

        store, clock, ex, server = socket_gang
        client = ElasticWorkerClient(
            {"dir": "/tmp/unused", "worker_id": 0, "n_workers": 2,
             "transport": "socket", "addr": server.addr},
            clock=clock, sleep=lambda _: None,
        )
        arm(FaultSpec(site="elastic.push", at=1))
        try:
            with pytest.raises(FaultInjected, match="elastic.push"):
                client.sync(1, _State(_params(0.0)))
        finally:
            clear_faults()


# ---------------------------------------------------------------------
# socket gangs end to end (tier-1: real train() loops, real TCP)
# ---------------------------------------------------------------------


class TestSocketGang:
    def test_two_worker_gang_over_real_sockets(self, tmp_path):
        """The tentpole's tier-1 proof: a 2-worker gang whose exchange
        rides TCP — after the run the gang dir holds NO exchange state
        (no members/, no push/), only per-worker checkpoints, the
        coordinator's state mirror, and the final deliverable."""
        spec = {**TINY, "storagePath": str(tmp_path)}
        r = run_elastic(
            spec, 2, mode="inprocess", transport="socket",
            heartbeat_timeout=120.0,
        )
        assert r.ok, [w.error for w in r.workers]
        assert all(
            w.report["epochs_ran"] == TINY["epochs"] for w in r.workers
        )
        assert r.coordinator["round"] - 1 == TINY["epochs"]
        assert all(
            ids == [0, 1] for ids in r.coordinator["rounds"].values()
        )
        assert r.final_worker_ids == [0, 1]
        assert os.path.exists(r.final_path)
        gang = tmp_path / "elastic"
        assert not (gang / "members").exists()
        assert not (gang / "push").exists()
        assert all(_finite(w.report["best_val_loss"]) for w in r.workers)

    def test_async_socket_gang_converges(self, tmp_path):
        spec = {**TINY, "storagePath": str(tmp_path)}
        r = run_elastic(
            spec, 2, mode="inprocess", transport="socket",
            async_push=True, max_staleness=2, heartbeat_timeout=120.0,
        )
        assert r.ok, [w.error for w in r.workers]
        assert r.coordinator["round"] >= 2  # rounds flowed
        assert r.final_worker_ids == [0, 1]
        for w in r.workers:
            assert _finite(w.report["best_val_loss"])
            assert w.report["best_val_loss"] < 0.5

    def test_mesh_per_worker_gang(self, tmp_path):
        """The fleet-of-meshes rebase: each elastic worker is itself
        data-parallel across 2 local (virtual) devices through
        parallel/compat.py + make_mesh, inside a socket gang."""
        spec = {**TINY, "n_devices": 2, "storagePath": str(tmp_path)}
        r = run_elastic(
            spec, 2, mode="inprocess", transport="socket",
            heartbeat_timeout=120.0,
        )
        assert r.ok, [w.error for w in r.workers]
        assert r.final_worker_ids == [0, 1]
        for w in r.workers:
            assert _finite(w.report["best_val_loss"])


# ---------------------------------------------------------------------
# the transport/staleness env-knob family (validated at read time)
# ---------------------------------------------------------------------


class TestElasticEnvKnobs:
    BASE = {"dir": "/g", "worker_id": 0, "n_workers": 2}

    def test_env_supplies_defaults_spec_wins(self, monkeypatch):
        monkeypatch.setenv("TPUFLOW_ELASTIC_TRANSPORT", "socket")
        monkeypatch.setenv("TPUFLOW_ELASTIC_ADDR", "10.0.0.1:7000")
        monkeypatch.setenv("TPUFLOW_ELASTIC_ASYNC", "1")
        monkeypatch.setenv("TPUFLOW_ELASTIC_MAX_STALENESS", "5")
        cfg = resolve_elastic(dict(self.BASE))
        assert cfg["transport"] == "socket"
        assert cfg["addr"] == "10.0.0.1:7000"
        assert cfg["async_push"] is True
        assert cfg["max_staleness"] == 5
        # An explicit spec value beats the environment.
        cfg = resolve_elastic(
            {**self.BASE, "transport": "file", "max_staleness": 1}
        )
        assert cfg["transport"] == "file"
        assert cfg["max_staleness"] == 1

    @pytest.mark.parametrize("var,value", [
        ("TPUFLOW_ELASTIC_TRANSPORT", "carrier-pigeon"),
        ("TPUFLOW_ELASTIC_ADDR", "no-port-here"),
        ("TPUFLOW_ELASTIC_ASYNC", "ture"),
        ("TPUFLOW_ELASTIC_MAX_STALENESS", "-1"),
        ("TPUFLOW_ELASTIC_MAX_STALENESS", "lots"),
    ])
    def test_malformed_env_names_the_variable(
        self, monkeypatch, var, value
    ):
        monkeypatch.setenv(var, value)
        with pytest.raises(ValueError, match=var):
            resolve_elastic(dict(self.BASE))

    def test_connect_timeout_knob_validated(self, monkeypatch):
        from tpuflow.elastic.transport import connect_timeout

        assert connect_timeout() == 5.0
        monkeypatch.setenv("TPUFLOW_ELASTIC_CONNECT_TIMEOUT", "0.5")
        assert connect_timeout() == 0.5
        monkeypatch.setenv("TPUFLOW_ELASTIC_CONNECT_TIMEOUT", "soon")
        with pytest.raises(
            ValueError, match="TPUFLOW_ELASTIC_CONNECT_TIMEOUT"
        ):
            connect_timeout()

    def test_block_validation_of_transport_keys(self):
        msgs = "; ".join(validate_elastic_block({
            **self.BASE, "transport": "pigeon", "addr": "nohost",
            "async_push": "yes", "max_staleness": -2,
        }))
        assert "transport" in msgs
        assert "addr" in msgs
        assert "async_push" in msgs
        assert "max_staleness" in msgs
        with pytest.raises(ValueError, match="needs elastic.addr"):
            resolve_elastic({**self.BASE, "transport": "socket"})


# ---------------------------------------------------------------------
# churn over real sockets (slow): kill, evict, readmit, converge
# ---------------------------------------------------------------------


@pytest.mark.slow
@pytest.mark.faultdrill
class TestSocketChurn:
    def test_four_workers_survive_mid_epoch_kill_over_sockets(
        self, tmp_path
    ):
        """The acceptance drill re-run over real TCP with a 4-worker
        gang and NO shared exchange dir: worker 1 dies at epoch 3
        (registry exit fault), is evicted on transport liveness,
        averaging proceeds over the survivors, the restarted worker
        rejoins, and the final params match a fixed-membership
        reference gang within the PR 6 tolerance."""
        base = {**TINY, "epochs": 12}
        churn = run_elastic(
            {**base, "storagePath": str(tmp_path / "churn")}, 4,
            mode="supervised",
            transport="socket",
            heartbeat_timeout=1.0,
            heartbeat_interval=0.2,
            round_timeout=10.0,
            min_round_interval=1.2,
            pull_timeout=300.0,
            max_restarts=2,
            backoff_base=3.0,
            worker_faults={1: ["train.epoch_start,at=3,mode=exit,code=42"]},
        )
        assert churn.ok, [w.error for w in churn.workers]
        victim = churn.workers[1]
        assert victim.attempts == 2
        assert victim.failures and victim.failures[0]["rc"] == 42
        for w in churn.workers:
            assert w.report["epochs_ran"] == base["epochs"]
            assert _finite(w.report["best_val_loss"])
            assert w.report["best_val_loss"] < 0.5
        rounds = churn.coordinator["rounds"]
        assert any(1 not in ids for ids in rounds.values()), rounds
        assert churn.coordinator["rejoins"] >= 1
        assert 1 not in churn.coordinator["evicted"]
        assert churn.coordinator["round"] - 1 == base["epochs"]
        assert churn.final_worker_ids == [0, 1, 2, 3]
        # No shared exchange dir was ever used.
        gang = tmp_path / "churn" / "elastic"
        assert not (gang / "members").exists()
        assert not (gang / "push").exists()

        ref = run_elastic(
            {**base, "storagePath": str(tmp_path / "ref")}, 4,
            mode="inprocess", transport="socket",
            heartbeat_timeout=300.0,
        )
        assert ref.ok, [w.error for w in ref.workers]
        for got, want in zip(churn.final_params, ref.final_params):
            np.testing.assert_allclose(got, want, atol=0.12)
