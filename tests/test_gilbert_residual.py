"""Physics-informed GilbertResidualMLP: end-to-end train + serve.

The model multiplies the raw Gilbert prediction (appended as the last
feature) by a learned correction; on the synthetic wells — whose true flow
IS Gilbert × a state-dependent correction — it should handily beat the
plain physical baseline.
"""

import numpy as np

from tpuflow.api import TrainJobConfig, predict, train
from tpuflow.data.synthetic import generate_wells, wells_to_table


def _config(tmp_path=None, **kw):
    base = dict(
        model="gilbert_residual",
        max_epochs=30,
        batch_size=128,
        patience=10,
        seed=0,
        verbose=False,
        n_devices=1,
        # Enough wells to cover the completion-type / water-cut space —
        # the learned correction must generalize to unseen wells.
        synthetic_wells=10,
        synthetic_steps=256,
        storage_path=str(tmp_path) if tmp_path else None,
    )
    base.update(kw)
    return TrainJobConfig(**base)


class TestGilbertResidualTraining:
    def test_beats_plain_gilbert_baseline(self):
        report = train(_config())
        assert report.gilbert_mae is not None
        # Physics-informed correction must improve on raw physics.
        assert report.test_mae < report.gilbert_mae
        # Raw-unit reporting: target_std path must not rescale.
        assert np.isfinite(report.test_loss)

    def test_starts_at_physical_model(self):
        """Freshly-initialized output IS the standardized Gilbert
        prediction (zero-init head -> softplus == 1 exactly)."""
        import jax
        import jax.numpy as jnp

        from tpuflow.models import build_model

        rng = np.random.default_rng(0)
        feats = jnp.asarray(rng.standard_normal((16, 4)), jnp.float32)
        q = jnp.asarray(rng.uniform(100, 5000, 16), jnp.float32)
        x = jnp.concatenate([feats, q[:, None]], axis=1)
        t_mean, t_std = 1000.0, 250.0
        model = build_model(
            "gilbert_residual", target_mean=t_mean, target_std=t_std
        )
        params = model.init(jax.random.PRNGKey(0), x)["params"]
        out = model.apply({"params": params}, x)
        np.testing.assert_allclose(
            out, (q - t_mean) / t_std, rtol=1e-4, atol=1e-4
        )

    def test_standardized_loss_stays_in_clip_range(self):
        """The model standardizes its raw output internally, so the clip=6
        loss operates on genuinely small O(1) residuals — a broken internal
        standardization would saturate near 6."""
        report = train(_config())
        assert report.test_loss < 1.0


class TestGilbertResidualServing:
    def test_artifact_roundtrip(self, tmp_path):
        train(_config(tmp_path))
        table = wells_to_table(generate_wells(1, 64, seed=11))
        truth = table.pop("flow")
        y = predict(str(tmp_path), "gilbert_residual", columns=table)
        assert y.shape == (64,)
        # Served predictions beat the plain physical model on new data.
        from tpuflow.core.gilbert import gilbert_flow

        base = np.asarray(
            gilbert_flow(table["pressure"], table["choke"], table["glr"])
        )
        assert np.mean(np.abs(y - truth)) < np.mean(np.abs(base - truth))
