"""Job-runner control plane: cancellation, per-job timeouts, bounded queue.

Before these existed, a hung or runaway job wedged the whole service
forever (the chip-serial worker loop) and the queue accepted unbounded
backlog. Fast paths are tested at the JobRunner level with a stubbed
``_execute``; the cooperative stop path (cancel/timeout observed between
epochs) runs real training through the HTTP server.
"""

from __future__ import annotations

import json
import queue
import threading
import time
import urllib.error
import urllib.request

import pytest

from tpuflow.serve import JobRunner, make_server

SPEC = {"model": "static_mlp", "epochs": 2}


class _BlockingExecute:
    """Stands in for JobRunner._execute: blocks until released, records
    the stop_fn so tests can drive the cooperative path directly."""

    def __init__(self, ignore_stop: bool = False):
        self.release = threading.Event()
        self.started = threading.Event()
        self.stop_fns: list = []
        # True models a job whose last epoch finishes before the loop
        # would next poll stop_fn: the work completes despite the cancel.
        self.ignore_stop = ignore_stop

    def __call__(self, kind, config, stop_fn=None):
        self.stop_fns.append(stop_fn)
        self.started.set()
        assert self.release.wait(timeout=30)
        from tpuflow.train.loop import TrainingInterrupted

        reason = stop_fn() if (stop_fn and not self.ignore_stop) else None
        if reason:
            raise TrainingInterrupted(reason)
        return {"ok": True}


@pytest.fixture
def blocked_runner(monkeypatch):
    ex = _BlockingExecute()
    monkeypatch.setattr(JobRunner, "_execute", ex)
    runner = JobRunner(max_queued=2)
    yield runner, ex
    ex.release.set()  # let the worker drain


def _wait(predicate, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(0.02)
    return False


class TestCancelQueued:
    def test_queued_job_cancels_immediately(self, blocked_runner):
        runner, ex = blocked_runner
        running = runner.submit(SPEC)["job_id"]
        assert ex.started.wait(timeout=10)
        queued = runner.submit(SPEC)["job_id"]

        res = runner.cancel(queued)
        assert res == {"job_id": queued, "status": "cancelled"}
        assert runner.get(queued)["status"] == "cancelled"
        assert runner.metrics()["cancelled"] == 1

        # The worker skips the stale queue entry and finishes the rest.
        ex.release.set()
        assert _wait(lambda: runner.get(running)["status"] == "done")
        assert runner.get(queued)["status"] == "cancelled"

    def test_cancel_unknown_job_is_none(self, blocked_runner):
        runner, _ = blocked_runner
        assert runner.cancel("deadbeef") is None

    def test_cancel_terminal_job_conflicts(self, blocked_runner):
        runner, ex = blocked_runner
        job = runner.submit(SPEC)["job_id"]
        ex.release.set()
        assert _wait(lambda: runner.get(job)["status"] == "done")
        res = runner.cancel(job)
        assert res["conflict"] is True and res["status"] == "done"


class TestCancelRunning:
    def test_running_job_cancels_cooperatively(self, blocked_runner):
        runner, ex = blocked_runner
        job = runner.submit(SPEC)["job_id"]
        assert ex.started.wait(timeout=10)

        res = runner.cancel(job)
        assert res == {"job_id": job, "status": "cancelling"}
        assert runner.get(job)["status"] == "cancelling"
        # The stop_fn the worker handed to _execute now reports the cancel.
        assert ex.stop_fns[0]() == "cancelled"

        ex.release.set()  # _execute observes the stop and raises
        assert _wait(lambda: runner.get(job)["status"] == "cancelled")
        assert runner.get(job)["error"] == "cancelled while running"
        assert runner.metrics()["cancelled"] == 1
        assert runner.metrics()["running"] == 0

    def test_cancel_after_work_finished_reports_done(self, monkeypatch):
        # The cancel raced the last epoch and lost: the work completed
        # before the loop observed the stop — the job reports done with
        # its report intact (the cancel was a no-op).
        ex = _BlockingExecute(ignore_stop=True)
        monkeypatch.setattr(JobRunner, "_execute", ex)
        runner = JobRunner(max_queued=2)
        job = runner.submit(SPEC)["job_id"]
        assert ex.started.wait(timeout=10)
        assert runner.cancel(job)["status"] == "cancelling"
        ex.release.set()
        assert _wait(lambda: runner.get(job)["status"] == "done")
        assert runner.get(job)["report"] == {"ok": True}
        assert runner.metrics()["cancelled"] == 0


class TestTimeouts:
    def test_stop_fn_heartbeats_surface_in_job_record(self, blocked_runner):
        """Each stop_fn poll (one per epoch) bumps the job's heartbeat
        counter and running_s, so clients can see progress/liveness."""
        runner, ex = blocked_runner
        job = runner.submit(SPEC)["job_id"]
        assert ex.started.wait(timeout=10)
        for _ in range(3):
            ex.stop_fns[0]()
        rec = runner.get(job)
        assert rec["heartbeats"] == 3
        assert rec["running_s"] >= 0.0
        ex.release.set()

    def test_per_job_timeout_reported(self, blocked_runner):
        runner, ex = blocked_runner
        job = runner.submit({**SPEC, "timeoutSeconds": 0.05})["job_id"]
        assert ex.started.wait(timeout=10)
        # Let the budget lapse, then release: stop_fn reports the timeout.
        assert _wait(lambda: ex.stop_fns[0]() is not None, timeout=5)
        assert "timeout after" in ex.stop_fns[0]()
        ex.release.set()
        assert _wait(lambda: runner.get(job)["status"] == "failed")
        assert "timeout after" in runner.get(job)["error"]
        assert runner.metrics()["failed"] == 1

    def test_default_timeout_applies(self, monkeypatch):
        ex = _BlockingExecute()
        monkeypatch.setattr(JobRunner, "_execute", ex)
        runner = JobRunner(default_timeout=0.05)
        runner.submit(SPEC)
        assert ex.started.wait(timeout=10)
        assert _wait(lambda: ex.stop_fns[0]() is not None, timeout=5)
        assert "timeout after" in ex.stop_fns[0]()
        ex.release.set()

    def test_invalid_timeout_rejected(self, blocked_runner):
        runner, _ = blocked_runner
        with pytest.raises(ValueError, match="timeoutSeconds"):
            runner.submit({**SPEC, "timeoutSeconds": 0})


class TestBoundedQueue:
    def test_submit_past_capacity_raises_and_rolls_back(self, blocked_runner):
        runner, ex = blocked_runner  # max_queued=2
        running = runner.submit(SPEC)["job_id"]
        assert ex.started.wait(timeout=10)
        q1 = runner.submit(SPEC)["job_id"]
        q2 = runner.submit(SPEC)["job_id"]
        before = runner.metrics()

        with pytest.raises(queue.Full):
            runner.submit(SPEC)

        after = runner.metrics()
        assert after == before  # no phantom job record survives the 429
        assert {running, q1, q2} == {j["job_id"] for j in runner.list()}

        # Cancelling a queued job frees its admission slot immediately —
        # capacity is the LIVE queued count, not stale queue entries.
        runner.cancel(q2)
        replacement = runner.submit(SPEC)["job_id"]
        with pytest.raises(queue.Full):
            runner.submit(SPEC)
        assert runner.get(replacement)["status"] == "queued"
        ex.release.set()


def _get(url):
    with urllib.request.urlopen(url, timeout=10) as r:
        return r.status, json.loads(r.read())


def _request(url, method, payload=None):
    req = urllib.request.Request(
        url,
        data=json.dumps(payload).encode() if payload is not None else None,
        headers={"Content-Type": "application/json"},
        method=method,
    )
    try:
        with urllib.request.urlopen(req, timeout=10) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


@pytest.fixture
def server():
    srv = make_server("127.0.0.1", 0)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{srv.server_address[1]}"
    srv.shutdown()


LONG_JOB = {
    # Effectively endless at test scale: early stopping can't fire
    # (patience == epochs) and the budget is thousands of fast epochs.
    "model": "static_mlp",
    "epochs": 100000,
    "patience": 100000,
    "batchSize": 32,
    "n_devices": 1,
    "synthetic_wells": 4,
    "synthetic_steps": 64,
}


@pytest.mark.slow
class TestHTTPControlPlane:
    def test_delete_cancels_a_real_running_job(self, server):
        """End-to-end cooperative cancel: real training, stopped between
        epochs by DELETE /jobs/<id>."""
        status, body = _request(server + "/jobs", "POST", LONG_JOB)
        assert status == 202
        job = body["job_id"]
        assert _wait(
            lambda: _get(server + f"/jobs/{job}")[1]["status"] == "running",
            timeout=60,
        )
        status, body = _request(server + f"/jobs/{job}", "DELETE")
        assert status == 200
        assert body["status"] in ("cancelling", "cancelled")
        assert _wait(
            lambda: _get(server + f"/jobs/{job}")[1]["status"] == "cancelled",
            timeout=60,
        )
        # A second DELETE of the now-terminal job conflicts.
        status, body = _request(server + f"/jobs/{job}", "DELETE")
        assert status == 409

    def test_timeout_fails_a_real_running_job(self, server):
        """End-to-end per-job budget: real training, stopped between
        epochs when timeoutSeconds lapses."""
        status, body = _request(
            server + "/jobs", "POST", {**LONG_JOB, "timeoutSeconds": 3}
        )
        assert status == 202
        job = body["job_id"]
        assert _wait(
            lambda: _get(server + f"/jobs/{job}")[1]["status"] == "failed",
            timeout=120,
        )
        rec = _get(server + f"/jobs/{job}")[1]
        assert "timeout after 3" in rec["error"]

    def test_delete_unknown_job_404(self, server):
        status, _ = _request(server + "/jobs/deadbeef", "DELETE")
        assert status == 404
