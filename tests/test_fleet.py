"""The fleet telemetry plane (tpuflow/obs/fleet.py + slo.py): trail
discovery and merged timelines, trace-id flow across processes, the SLO
engine's burn-rate/error-budget math against hand-computed windows, the
committed report-card schema — and the tier-1 acceptance drill: a
2-worker socket elastic gang plus a live async daemon driven through an
online hot swap produce ONE merged timeline in which a single trace id
spans worker push → coordinator average, and a single trace id spans
drift → retrain → swap → daemon reload.
"""

from __future__ import annotations

import json
import math
import os
import urllib.request

import numpy as np
import pytest

from tpuflow.obs import Registry
from tpuflow.obs.fleet import (
    discover_trails,
    export_fleet,
    merge_fleet,
    read_fleet,
)
from tpuflow.obs.slo import (
    SloEngine,
    burn_rate,
    error_budget_remaining,
    normalize_objectives,
    report_card,
    serve_objectives,
    validate_report_card,
    windowed_burn_rates,
)

NAMES = "pressure,choke,glr,temperature,water_cut,completion,flow"
TYPES = "float,float,float,float,float,string,float"
_COLS = NAMES.split(",")


# ---------------------------------------------------------------------
# the error-budget algebra, against hand-computed windows
# ---------------------------------------------------------------------


class TestBudgetMath:
    def test_burn_rate_hand_computed(self):
        # target 0.9 => 10% budget. 2 bad of 10 = 20% observed => 2x.
        assert burn_rate(8, 2, 0.9) == pytest.approx(2.0)
        # Exactly sustainable spending reads 1.0.
        assert burn_rate(999, 1, 0.999) == pytest.approx(1.0)
        # No failures = zero burn; no traffic = honest None, not 0.0.
        assert burn_rate(50, 0, 0.999) == 0.0
        assert burn_rate(0, 0, 0.999) is None
        # A 100% target has no budget: any failure burns infinitely.
        assert burn_rate(1, 1, 1.0) == math.inf
        assert burn_rate(1, 0, 1.0) == 0.0

    def test_error_budget_remaining_hand_computed(self):
        # target 0.9 over 10 events buys exactly 1 failure.
        assert error_budget_remaining(10, 0, 0.9) == pytest.approx(1.0)
        assert error_budget_remaining(9, 1, 0.9) == pytest.approx(0.0)
        # 2 failures = 200% of the budget spent => -1.0 (violated).
        assert error_budget_remaining(8, 2, 0.9) == pytest.approx(-1.0)
        assert error_budget_remaining(0, 0, 0.9) is None

    def test_windowed_burn_rates_hand_computed(self):
        """Three 10s windows: all-good, half-bad, all-bad — each
        window's burn rate against target 0.5 (budget 50%) is 0, 1, 2;
        an empty window is OMITTED, not reported as healthy 0.0."""
        samples = [
            (0.0, True), (3.0, True),              # window [0, 10)
            (10.0, True), (14.0, False),           # window [10, 20)
            # window [20, 30): no traffic at all
            (30.0, False), (31.0, False),          # window [30, 40)
        ]
        w = windowed_burn_rates(samples, target=0.5, window_s=10.0)
        assert [x["burn_rate"] for x in w] == [
            pytest.approx(0.0), pytest.approx(1.0), pytest.approx(2.0),
        ]
        assert [(x["good"], x["bad"]) for x in w] == [(2, 0), (1, 1), (0, 2)]
        assert [x["start"] for x in w] == [0.0, 10.0, 30.0]
        # Budget per window: all-good untouched, half-bad exactly
        # spent, all-bad overspent (negative).
        assert [x["error_budget_remaining"] for x in w] == [
            pytest.approx(1.0), pytest.approx(0.0), pytest.approx(-1.0),
        ]

    def test_window_must_be_positive(self):
        with pytest.raises(ValueError, match="window_s"):
            windowed_burn_rates([(0, True)], target=0.9, window_s=0)


class TestObjectives:
    def test_unknown_kind_fails_loudly(self):
        with pytest.raises(ValueError, match="unknown kind"):
            normalize_objectives([{"kind": "latency_p50", "target": 1}])

    def test_bad_targets_fail_loudly(self):
        with pytest.raises(ValueError, match="ratio"):
            normalize_objectives(
                [{"kind": "availability", "target": 1.5}]
            )
        with pytest.raises(ValueError, match="numeric 'target'"):
            normalize_objectives([{"kind": "latency_p99"}])
        with pytest.raises(ValueError, match="duplicate"):
            normalize_objectives([
                {"name": "a", "kind": "latency_p99", "target": 1},
                {"name": "a", "kind": "goodput_floor", "target": 1},
            ])

    def test_serve_objective_env_targets_validated(self, monkeypatch):
        monkeypatch.setenv("TPUFLOW_SERVE_SLO_TARGET", "0.99")
        monkeypatch.setenv("TPUFLOW_SERVE_SLO_P99_MS", "250")
        objs = {o["kind"]: o for o in serve_objectives()}
        assert objs["availability"]["target"] == 0.99
        assert objs["latency_p99"]["target"] == 250.0
        monkeypatch.setenv("TPUFLOW_SERVE_SLO_TARGET", "1.7")
        with pytest.raises(ValueError, match="TPUFLOW_SERVE_SLO_TARGET"):
            serve_objectives()
        monkeypatch.setenv("TPUFLOW_SERVE_SLO_TARGET", "0.999")
        monkeypatch.setenv("TPUFLOW_SERVE_SLO_P99_MS", "fast")
        with pytest.raises(ValueError, match="TPUFLOW_SERVE_SLO_P99_MS"):
            serve_objectives()


class TestSloEngineRegistry:
    def test_availability_and_p99_from_counters(self):
        reg = Registry()
        reg.counter("serving_admitted_total").inc(995)
        shed = reg.counter("serving_shed_total")
        shed.inc(3, code="503")
        shed.inc(2, code="429")
        reg.summary(
            "predict_latency_ms", "",
            fn=lambda: {"quantiles": {0.5: 5.0, 0.99: 700.0},
                        "sum": 1.0, "count": 10},
        )
        engine = SloEngine([
            {"name": "availability", "kind": "availability",
             "target": 0.99, "good": ("serving_admitted_total",),
             "bad": ("serving_shed_total",)},
            {"name": "latency_p99", "kind": "latency_p99",
             "target": 500.0},
        ], registry=reg)
        rows = {
            r["name"]: r
            for r in engine.evaluate_registry(reg)["objectives"]
        }
        # 5 bad of 1000 at a 1% budget: half the budget spent.
        assert rows["availability"]["measured"] == pytest.approx(0.995)
        assert rows["availability"]["error_budget_remaining"] \
            == pytest.approx(0.5)
        assert rows["availability"]["burn_rate"] == pytest.approx(0.5)
        assert rows["availability"]["status"] == "ok"
        # p99 700ms over a 500ms ceiling: violated.
        assert rows["latency_p99"]["status"] == "violated"
        # The gauges render into the exposition for Prometheus.
        from tpuflow.obs import render_prometheus

        text = render_prometheus(reg)
        assert (
            'tpuflow_slo_error_budget_remaining{objective="availability"} '
            "0.5" in text
        )
        assert 'tpuflow_slo_burn_rate{objective="availability"}' in text

    def test_missing_families_read_no_data_not_zero(self):
        engine = SloEngine(registry=Registry())
        rows = engine.evaluate_registry(Registry())["objectives"]
        assert all(r["status"] == "no_data" for r in rows)
        assert all(r["measured"] is None for r in rows)


class TestReportCard:
    def test_time_to_adapt_lifecycles_grouped_by_trace(self):
        events = [
            {"event": "drift_anomaly", "time": 100.0, "trace_id": "t1"},
            {"event": "online_retrain", "time": 101.0, "trace_id": "t1",
             "reason": "drift"},
            {"event": "artifact_swap", "time": 130.0, "trace_id": "t1"},
            {"event": "serve_reload", "time": 131.0, "trace_id": "t1"},
            # A second, slower lifecycle on its own trace.
            {"event": "drift_anomaly", "time": 200.0, "trace_id": "t2"},
            {"event": "serve_reload", "time": 640.0, "trace_id": "t2"},
            # Noise: a trace with no completion never counts.
            {"event": "drift_anomaly", "time": 300.0, "trace_id": "t3"},
        ]
        card = report_card(events, [
            {"name": "tta", "kind": "time_to_adapt", "target": 300.0},
        ])
        validate_report_card(card)
        [row] = card["objectives"]
        lives = {lc["trace_id"]: lc for lc in row["lifecycles"]}
        assert set(lives) == {"t1", "t2"}
        assert lives["t1"]["seconds"] == pytest.approx(31.0)
        assert lives["t2"]["seconds"] == pytest.approx(440.0)
        assert row["measured"] == pytest.approx(440.0)  # worst case
        assert row["status"] == "violated"  # t2 blew the 300s ceiling

    def test_card_validates_against_committed_schema(self):
        card = report_card([], None)
        validate_report_card(card)  # jsonschema path (installed)
        # The dependency-light structural fallback agrees.
        from tpuflow.obs import slo as slo_mod

        with open(slo_mod.SCHEMA_PATH, encoding="utf-8") as f:
            schema = json.load(f)
        assert slo_mod._structural_check(card, schema) == []
        # ...and both reject a malformed card.
        bad = {**card, "objectives": [{"kind": "nope"}]}
        with pytest.raises(ValueError, match="schema"):
            validate_report_card(bad)
        assert slo_mod._structural_check(bad, schema)

    def test_availability_from_dispatch_spans_in_trails(self):
        events = [
            {"event": "span", "name": "predict.dispatch",
             "time": float(i), "duration_s": 0.01}
            for i in range(9)
        ] + [
            {"event": "span", "name": "predict.dispatch", "time": 9.0,
             "duration_s": 0.01, "ok": False},
        ]
        card = report_card(events, [
            {"name": "availability", "kind": "availability",
             "target": 0.9},
        ], window_s=100.0)
        validate_report_card(card)
        [row] = card["objectives"]
        assert row["measured"] == pytest.approx(0.9)
        assert row["error_budget_remaining"] == pytest.approx(0.0)
        assert row["windows"][0]["bad"] == 1


# ---------------------------------------------------------------------
# fleet discovery + merge on synthetic trails
# ---------------------------------------------------------------------


def _write_trail(path, records):
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        for rec in records:
            f.write(json.dumps(rec) + "\n")


class TestFleetMerge:
    def test_discovery_names_lanes_from_relative_paths(self, tmp_path):
        _write_trail(str(tmp_path / "worker0" / "metrics.jsonl"), [])
        _write_trail(
            str(tmp_path / "elastic" / "coordinator-metrics.jsonl"), []
        )
        trails = discover_trails([str(tmp_path)])
        assert [t["process"] for t in trails] == [
            "elastic/coordinator-metrics", "worker0/metrics",
        ]

    def test_merge_lanes_flows_and_summary(self, tmp_path):
        _write_trail(str(tmp_path / "worker0" / "metrics.jsonl"), [
            {"event": "span", "name": "step", "time": 10.0,
             "duration_s": 1.0, "trace_id": "aaa0000000000001"},
        ])
        _write_trail(
            str(tmp_path / "elastic" / "coordinator-metrics.jsonl"), [
                # The coordinator's own trace is unbound; the round
                # span NAMES the pushing worker's trace.
                {"event": "span", "name": "elastic.round", "time": 10.5,
                 "duration_s": 0.1,
                 "worker_traces": {"0": "aaa0000000000001"}},
            ],
        )
        doc, summary = merge_fleet([str(tmp_path)])
        xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
        assert {e["pid"] for e in xs} == {1, 2}
        # One fleet-wide time zero: the worker span starts at ts=0
        # (time 10.0 - 1.0s duration) and the coordinator round at its
        # own start, 10.5 - 0.1 - 9.0 = 1.4s later.
        by_name = {e["name"]: e for e in xs}
        assert by_name["step"]["ts"] == 0.0
        assert by_name["elastic.round"]["ts"] == pytest.approx(1.4e6)
        procs = [
            e["args"]["name"] for e in doc["traceEvents"]
            if e.get("name") == "process_name"
        ]
        assert set(procs) == {
            "worker0/metrics", "elastic/coordinator-metrics",
        }
        # worker_traces counts as a trace sighting: the flow arrow
        # links the worker's push to the coordinator's round.
        flows = [e for e in doc["traceEvents"] if e["ph"] in "stf"]
        assert {e["ph"] for e in flows} == {"s", "f"}
        assert all(e["id"] == "aaa0000000000001" for e in flows)
        assert summary["cross_process_traces"] == {
            "aaa0000000000001": [
                "elastic/coordinator-metrics", "worker0/metrics",
            ]
        }

    def test_torn_lines_counted_never_fatal(self, tmp_path):
        path = str(tmp_path / "w" / "metrics.jsonl")
        _write_trail(path, [
            {"event": "span", "name": "step", "time": 1.0,
             "duration_s": 0.5},
        ])
        with open(path, "ab") as f:
            f.write(b'{"event": "span", "torn mid-wr')
        _doc, summary = merge_fleet([str(tmp_path)])
        [proc] = summary["processes"]
        assert proc["skipped_lines"] == 1
        assert proc["events"] == 1

    def test_export_writes_doc_and_reports(self, tmp_path):
        _write_trail(str(tmp_path / "a" / "metrics.jsonl"), [
            {"event": "span", "name": "step", "time": 1.0,
             "duration_s": 0.5},
        ])
        out = str(tmp_path / "fleet.json")
        summary = export_fleet([str(tmp_path)], out)
        assert summary["timeline"]["spans"] == 1
        doc = json.loads(open(out).read())
        assert doc["displayTimeUnit"] == "ms"


# ---------------------------------------------------------------------
# the tier-1 acceptance drill: gang + daemon + online swap -> ONE
# merged timeline + a schema-valid report card
# ---------------------------------------------------------------------


def _table_rows(cols, scale=1.0):
    out = []
    for i in range(len(cols["flow"])):
        row = []
        for c in _COLS:
            v = cols[c][i]
            if c in ("pressure", "flow"):
                v = float(v) * scale
            row.append(str(v))
        out.append(",".join(row))
    return out


class TestFleetDrill:
    def test_gang_plus_daemon_hot_swap_is_one_timeline(self, tmp_path):
        """ISSUE 14's tier-1 drill. A 2-worker SOCKET elastic gang and
        a live async daemon (with an on-disk trail) driven through an
        online drift -> warm-start retrain -> shadow-eval -> swap ->
        reload, all under one storage root. `merge_fleet` then proves:

        - one trace id spans a worker's push and the coordinator's
          averaging round (TPFX header propagation);
        - one trace id spans drift-detect, retrain, swap, and the
          daemon's reload (the online lifecycle trace + X-Trace-Id);
        - the SLO report card computes an error budget from the
          daemon's own counters and a time-to-adapt lifecycle, and
          validates against the committed schema.
        """
        from tpuflow.api import TrainJobConfig, train
        from tpuflow.data import wells_to_table
        from tpuflow.data.synthetic import generate_wells
        from tpuflow.elastic.runner import run_elastic
        from tpuflow.online.controller import OnlineTrainer
        from tpuflow.serve_async import AsyncServer

        root = str(tmp_path)

        # --- leg 1: the 2-worker socket gang under {root}/gang -------
        gang_spec = {
            "model": "static_mlp",
            "model_kwargs": {"hidden": []},
            "epochs": 2,
            "batchSize": 32,
            "patience": 100,
            "loss": "mse",
            "synthetic_wells": 2,
            "synthetic_steps": 64,
            "n_devices": 1,
            "verbose": False,
            "storagePath": os.path.join(root, "gang"),
        }
        r = run_elastic(
            gang_spec, 2, mode="inprocess", transport="socket",
            heartbeat_timeout=120.0,
        )
        assert r.ok, [w.error for w in r.workers]

        # --- leg 2: serving artifact + daemon + online loop ----------
        serving = os.path.join(root, "serving")
        table = wells_to_table(generate_wells(n_wells=4, steps=200, seed=3))
        base_csv = os.path.join(root, "base.csv")
        with open(base_csv, "w", encoding="utf-8") as f:
            f.write("\n".join(_table_rows(table)) + "\n")

        def _config(**over):
            kw = dict(
                column_names=NAMES, column_types=TYPES, target="flow",
                storage_path=serving, data_path=base_csv,
                model="static_mlp", model_kwargs={"hidden": [4]},
                max_epochs=4, patience=100, batch_size=64,
                verbose=False, health="off",
            )
            kw.update(over)
            return TrainJobConfig(**kw)

        train(_config(metrics_path=os.path.join(serving, "metrics.jsonl")))

        srv = AsyncServer(
            "127.0.0.1", 0, enable_jobs=False,
            trail_path=os.path.join(root, "serve-metrics.jsonl"),
        ).start()
        url = f"http://127.0.0.1:{srv.port}"
        try:
            # Live traffic through the daemon (the availability
            # objective's good events).
            probe = {
                c: [float(v) if c != "completion" else str(v)
                    for v in np.asarray(table[c][:16])]
                for c in _COLS if c != "flow"
            }
            body = json.dumps({
                "storagePath": serving, "model": "static_mlp",
                "columns": probe,
            }).encode()
            for _ in range(5):
                req = urllib.request.Request(
                    url + "/predict", data=body,
                    headers={"Content-Type": "application/json"},
                )
                with urllib.request.urlopen(req, timeout=30) as resp:
                    assert resp.status == 200

            # The regime shift: healthy windows, then shifted ones.
            rng = np.random.default_rng(7)
            n = len(table["flow"])

            def _chunk(scale):
                idx = rng.integers(0, n, 120)
                return {
                    c: (
                        np.asarray(table[c])[idx] if c == "completion"
                        else np.asarray(table[c], np.float64)[idx]
                        * (scale if c in ("pressure", "flow") else 1.0)
                    )
                    for c in _COLS
                }

            chunks = [_chunk(1.0)] * 2 + [_chunk(3.0)] * 6
            cfg = _config(online={
                "warmup_windows": 1, "threshold": 3.0,
                "replay_windows": 4, "eval_every": 3,
                "retrain_epochs": 2, "margin": 1000.0,
                "min_retrain_gap": 100, "rollback": False,
                "daemon_url": url,
            })
            tr = OnlineTrainer(
                cfg, source=iter(chunks), registry=Registry()
            )
            summary = tr.run()
            assert summary["retrains"] >= 1, summary
            assert summary["swaps"] >= 1, summary
        finally:
            srv.shutdown()

        # --- the merged fleet timeline -------------------------------
        doc, fleet = merge_fleet([root])
        procs = {p["process"] for p in fleet["processes"]}
        assert {
            "gang/worker0/metrics", "gang/worker1/metrics",
            "gang/elastic/coordinator-metrics",
            "serving/online/metrics", "serve-metrics",
        } <= procs, procs

        # (a) worker push -> coordinator average: a worker's run trace
        # appears in BOTH the worker's own trail and the coordinator's
        # elastic.round span (via the TPFX frame header).
        coord_events = next(
            t for t in read_fleet([root])[0]
            if t["process"] == "gang/elastic/coordinator-metrics"
        )["events"]
        round_traces = set()
        for rec in coord_events:
            if rec.get("name") == "elastic.round":
                round_traces.update(
                    (rec.get("worker_traces") or {}).values()
                )
        assert round_traces, "no worker traces on any averaging round"
        cross = fleet["cross_process_traces"]
        gang_links = {
            tid: procs_ for tid, procs_ in cross.items()
            if tid in round_traces
        }
        assert gang_links, (round_traces, cross)
        assert any(
            "gang/elastic/coordinator-metrics" in ps
            and any(p.startswith("gang/worker") for p in ps)
            for ps in gang_links.values()
        ), gang_links

        # (b) drift -> retrain -> swap -> reload: ONE trace id on the
        # whole lifecycle, across the online loop's trail AND the
        # daemon's.
        online_events = next(
            t for t in read_fleet([root])[0]
            if t["process"] == "serving/online/metrics"
        )["events"]
        swap_traces = {
            rec["trace_id"] for rec in online_events
            if rec.get("event") == "online_swap" and rec.get("trace_id")
        }
        assert swap_traces, "no traced swap in the online trail"
        lifecycle = None
        for tid in swap_traces:
            kinds = {
                rec["event"] for rec in online_events
                if rec.get("trace_id") == tid
            }
            if {"drift_anomaly", "online_retrain", "online_swap"} <= kinds:
                lifecycle = tid
        assert lifecycle, "no single trace spans drift+retrain+swap"
        daemon_events = next(
            t for t in read_fleet([root])[0]
            if t["process"] == "serve-metrics"
        )["events"]
        assert any(
            rec.get("event") == "serve_reload"
            and rec.get("trace_id") == lifecycle
            for rec in daemon_events
        ), "the daemon's reload record does not carry the lifecycle trace"
        assert set(cross.get(lifecycle, ())) >= {
            "serving/online/metrics", "serve-metrics",
        }
        # The merged doc draws flow arrows for the lifecycle trace.
        flow_ids = {
            e["id"] for e in doc["traceEvents"] if e["ph"] in "stf"
        }
        assert lifecycle in flow_ids

        # --- the SLO report card -------------------------------------
        _trails, events = read_fleet([root])
        card = report_card(
            events,
            [
                {"name": "availability", "kind": "availability",
                 "target": 0.999,
                 "good": ("serving_admitted_total",),
                 "bad": ("serving_shed_total",)},
                {"name": "time_to_adapt", "kind": "time_to_adapt",
                 "target": 600.0},
            ],
            registry=srv.registry,
        )
        validate_report_card(card)
        rows = {r["name"]: r for r in card["objectives"]}
        # Availability: every request the drill sent was admitted, so
        # the budget is untouched and the burn-rate math had real
        # traffic to chew on.
        assert rows["availability"]["measured"] == 1.0
        assert rows["availability"]["error_budget_remaining"] \
            == pytest.approx(1.0)
        assert rows["availability"]["status"] == "ok"
        # Time-to-adapt: the lifecycle trace yields a measurable
        # drift->reload duration.
        assert rows["time_to_adapt"]["measured"] is not None
        assert rows["time_to_adapt"]["status"] == "ok"
        assert any(
            lc["trace_id"] == lifecycle
            for lc in rows["time_to_adapt"]["lifecycles"]
        )
