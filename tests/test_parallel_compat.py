"""The jax-compat seam (tpuflow/parallel/compat.py) and its guards.

Three obligations, per ISSUE 7:

- the resolved ``make_mesh`` / ``shard_map`` / axis-type fallback behave
  identically under the installed jax (shape, axis names, device
  assignment, replicated/data shardings);
- every ``tpuflow.parallel`` submodule imports — an API regression on a
  jax upgrade fails HERE as one loud smoke failure instead of 74
  scattered errors;
- lint rule TPF008 flags direct ``jax.make_mesh`` / raw ``shard_map``
  imports outside the compat module (and the package itself is clean).
"""

import importlib
import pkgutil

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpuflow.parallel import compat
from tpuflow.parallel.mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    data_axis_size,
    data_sharding,
    make_mesh,
    replicated,
)


class TestImportSmoke:
    def test_every_parallel_submodule_imports(self):
        """The one-loud-failure guard: a jax API move that breaks any
        strategy module fails this smoke by name instead of resurfacing
        as dozens of downstream errors."""
        import tpuflow.parallel as pkg

        names = [m.name for m in pkgutil.iter_modules(pkg.__path__)]
        assert "compat" in names and "mesh" in names and "dp" in names
        for name in names:
            importlib.import_module(f"tpuflow.parallel.{name}")

    def test_compat_probes_resolved(self):
        # Whatever line is installed, the probe must have landed on a
        # real shard_map and recorded where it came from.
        assert compat.SHARD_MAP_SOURCE in (
            "jax.shard_map", "jax.experimental.shard_map"
        )
        assert isinstance(compat.AXIS_TYPES_SUPPORTED, bool)


class TestMakeMesh:
    def test_shape_axis_names_devices(self):
        mesh = make_mesh()
        assert isinstance(mesh, Mesh)
        assert mesh.axis_names == (DATA_AXIS, MODEL_AXIS)
        assert mesh.shape == {DATA_AXIS: 8, MODEL_AXIS: 1}
        assert set(mesh.devices.flat) == set(jax.devices())

    def test_explicit_device_subset_assignment(self):
        devs = jax.devices()[:4]
        mesh = make_mesh(devices=devs)
        assert mesh.shape == {DATA_AXIS: 4, MODEL_AXIS: 1}
        assert set(mesh.devices.flat) == set(devs)

    def test_model_axis_layout(self):
        mesh = make_mesh(n_data=2, n_model=4)
        assert mesh.shape == {DATA_AXIS: 2, MODEL_AXIS: 4}
        assert mesh.devices.shape == (2, 4)

    def test_axis_types_hint_accepted_on_any_jax(self):
        # The advisory axis-type hint must never raise — supported jax
        # lines select the type, older lines drop it (compat policy).
        mesh = make_mesh(
            axis_types=(compat.AxisType.Auto, compat.AxisType.Auto)
        )
        assert mesh.shape[DATA_AXIS] == 8

    def test_divisibility_shared_rule(self):
        # data_axis_size IS the rule make_mesh and analysis/plan share.
        assert data_axis_size(8, 2) == 4
        with pytest.raises(ValueError, match="not divisible"):
            data_axis_size(8, 3)
        with pytest.raises(ValueError):
            make_mesh(n_data=3)

    def test_compat_make_mesh_mismatched_axes_rejected(self):
        with pytest.raises(ValueError, match="mesh axes mismatch"):
            compat.make_mesh((2, 4), ("data",))


class TestShardings:
    def test_data_and_replicated_shardings(self):
        mesh = make_mesh()
        ds = data_sharding(mesh)
        rep = replicated(mesh)
        assert isinstance(ds, NamedSharding) and ds.spec == P(DATA_AXIS)
        assert rep.spec == P()
        x = np.arange(32, dtype=np.float32).reshape(8, 4)
        xd = jax.device_put(x, ds)
        # One row-shard per data-axis device, full copies when replicated.
        assert len(xd.sharding.device_set) == 8
        assert xd.addressable_shards[0].data.shape == (1, 4)
        xr = jax.device_put(x, rep)
        assert xr.addressable_shards[0].data.shape == (8, 4)


class TestResolvedShardMap:
    def test_psum_and_axis_size(self):
        mesh = make_mesh()

        def body(x):
            n = compat.axis_size(DATA_AXIS)
            assert isinstance(n, int)  # static: ring schedules need it
            return jax.lax.psum(x, DATA_AXIS) / n

        out = jax.jit(
            compat.shard_map(
                body,
                mesh=mesh,
                in_specs=P(DATA_AXIS),
                out_specs=P(),
                check_vma=False,
            )
        )(jnp.arange(8.0))
        assert float(np.asarray(out)[0]) == pytest.approx(3.5)

    def test_check_vma_translated_not_rejected(self):
        # The modern kwarg spelling must work regardless of whether the
        # installed shard_map calls it check_vma or check_rep.
        mesh = make_mesh()
        out = compat.shard_map(
            lambda x: x * 2.0,
            mesh=mesh,
            in_specs=P(DATA_AXIS),
            out_specs=P(DATA_AXIS),
            check_vma=False,
        )(jnp.ones(8))
        np.testing.assert_allclose(np.asarray(out), 2.0)

    def test_set_mesh_is_a_context_manager(self):
        mesh = make_mesh(devices=jax.devices()[:4])
        with compat.set_mesh(mesh):
            pass  # entering/exiting must work on any supported jax

    def test_reshard_pins_replication(self):
        mesh = make_mesh()
        x = jax.device_put(
            np.ones((8, 2), np.float32), data_sharding(mesh)
        )
        out = compat.reshard(x, replicated(mesh))
        assert out.sharding.is_equivalent_to(replicated(mesh), out.ndim)
        # And traceable under jit as a mid-graph constraint (the
        # AttentionRegressor ring-backend use).
        total = jax.jit(
            lambda a: (compat.reshard(a, replicated(mesh)) * 2.0).sum()
        )(x)
        assert float(total) == pytest.approx(32.0)


class TestTPF008:
    def test_flags_direct_use_outside_compat(self, tmp_path):
        from tpuflow.analysis.linter import lint_file

        bad = tmp_path / "strategy.py"
        bad.write_text(
            "import jax\n"
            "from jax.experimental.shard_map import shard_map\n"
            "mesh = jax.make_mesh((8,), ('data',))\n"
        )
        codes = [d.code for d in lint_file(str(bad))]
        assert codes.count("TPF008") == 2  # the import and the call

    def test_flags_plain_module_import_bypass(self, tmp_path):
        from tpuflow.analysis.linter import lint_file

        bad = tmp_path / "bypass.py"
        bad.write_text(
            "import jax.experimental.shard_map as smap\n"
            "f = smap.shard_map\n"
        )
        assert [d.code for d in lint_file(str(bad))].count("TPF008") == 1

    def test_compat_module_exempt(self, tmp_path):
        from tpuflow.analysis.linter import lint_file

        compat_dir = tmp_path / "parallel"
        compat_dir.mkdir()
        good = compat_dir / "compat.py"
        good.write_text(
            "import jax\n"
            "from jax.experimental.shard_map import shard_map\n"
            "_probe = getattr(jax, 'make_mesh', None)\n"
        )
        assert not [
            d for d in lint_file(str(good)) if d.code == "TPF008"
        ]

    def test_package_self_lint_clean(self):
        from tpuflow.analysis.linter import lint_package

        assert [
            d for d in lint_package() if d.code == "TPF008"
        ] == []
