"""Hyperparameter sweep API: grid application, ranking, error isolation."""

import numpy as np
import pytest

from tpuflow.api import TrainJobConfig
from tpuflow.api.sweep import SweepReport, SweepResult, _apply, sweep


class TestApply:
    def test_plain_and_dotted_fields(self):
        base = TrainJobConfig(model="lstm", model_kwargs={"num_layers": 2})
        cfg = _apply(
            base, {"batch_size": 64, "model_kwargs.hidden": 32}
        )
        assert cfg.batch_size == 64
        assert cfg.model_kwargs == {"num_layers": 2, "hidden": 32}
        # base untouched (dataclasses.replace + dict merge)
        assert base.model_kwargs == {"num_layers": 2}

    def test_unknown_fields_rejected(self):
        with pytest.raises(ValueError, match="unknown sweep field"):
            _apply(TrainJobConfig(), {"batchsize": 64})
        with pytest.raises(ValueError, match="unknown sweep field"):
            _apply(TrainJobConfig(), {"nested.thing": 1})


class TestSweep:
    def test_grid_trains_and_ranks(self):
        base = TrainJobConfig(
            model="static_mlp",
            max_epochs=2,
            batch_size=32,
            verbose=False,
            n_devices=1,
            synthetic_wells=4,
            synthetic_steps=64,
        )
        report = sweep(
            {"model_kwargs.hidden": [(8,), (16, 16)], "seed": [0]}, base
        )
        assert len(report.results) == 2
        assert all(r.error is None for r in report.results)
        ranked = report.ranked
        assert ranked[0].test_mae <= ranked[-1].test_mae
        assert np.isfinite(report.best.test_mae)
        assert "test MAE" in report.table()

    def test_failing_point_recorded_not_fatal(self):
        base = TrainJobConfig(
            model="static_mlp",
            max_epochs=1,
            batch_size=32,
            verbose=False,
            n_devices=1,
            synthetic_wells=4,
            synthetic_steps=64,
        )
        report = sweep({"loss": ["mae", "not_a_loss"]}, base)
        ok = [r for r in report.results if r.error is None]
        bad = [r for r in report.results if r.error is not None]
        assert len(ok) == 1 and len(bad) == 1
        assert "FAILED" in report.table()
        assert report.best.assignment == {"loss": "mae"}


class TestReportEdgeCases:
    def test_typo_axis_raises_before_training(self):
        with pytest.raises(ValueError, match="unknown sweep field"):
            sweep({"batchsize": [32, 64]}, TrainJobConfig())

    def test_nan_mae_excluded_from_ranking(self):
        rep = SweepReport(
            results=[
                SweepResult({"a": 1}, float("nan"), 0.1, None, 5, 1.0),
                SweepResult({"a": 2}, 123.0, 0.1, None, 5, 1.0),
            ]
        )
        assert [r.assignment for r in rep.ranked] == [{"a": 2}]
        assert rep.best.test_mae == 123.0

    def test_plain_and_dotted_same_dict_compose(self):
        cfg = _apply(
            TrainJobConfig(),
            {"model_kwargs": {"hidden": 8}, "model_kwargs.num_layers": 2},
        )
        assert cfg.model_kwargs == {"hidden": 8, "num_layers": 2}
