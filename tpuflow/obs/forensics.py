"""Crash forensics: a bounded ring of recent events, dumped on failure.

When a run dies, the question is "what was it doing just before?" — and
the answer is usually gone with the process's stdout buffer. This module
keeps a bounded in-memory ring of recent observability events (spans,
fault firings, supervisor attempts, dispatch records); on unhandled
failure the ring is dumped to ``<run_dir>/forensics.jsonl`` — the last
~512 events, newest last, each with a wall-clock timestamp and whatever
trace ID was bound when it was recorded.

Dump triggers installed elsewhere:

- ``tpuflow.api.train``: any exception escaping a run with a
  ``storage_path`` dumps to ``{storage_path}/forensics.jsonl``.
- ``tpuflow.train.supervisor``: crash-loop classification and
  restart-budget exhaustion dump next to the job's storage path.

Reading a dump (or any run's ``metrics.jsonl``):
``python -m tpuflow.obs tail|summary <file>``.

The ring is process-global and append-cheap (deque under a lock); it is
deliberately NOT the metrics registry — counters aggregate, the ring
remembers order.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque

DEFAULT_CAPACITY = 512
HOT_CAPACITY = 256

_LOCK = threading.Lock()
# TWO rings: run-lifecycle events (spans, fault firings, attempt
# deaths) and the HOT ring for high-rate serving events (per-dispatch
# spans — tens per second under load). Without the split, a serve
# daemon's dispatch spans would evict a crashed train job's entire
# trail from a single shared ring minutes before the dump fires.
_RING: deque = deque(maxlen=DEFAULT_CAPACITY)
_HOT_RING: deque = deque(maxlen=HOT_CAPACITY)


def record_event(event: str, hot: bool = False, **fields) -> dict:
    """Append one event to the ring (``hot=True`` for high-rate serving
    events, which get their own bounded ring). The bound trace ID (if
    any) is stamped in, so every ring event — fault firings, eviction
    notices, swap records — is causally linkable across the fleet
    timeline, not just the span events. Never raises — forensics must
    not fail the code path it observes."""
    rec = {"event": event, "time": time.time(), **fields}
    if "trace_id" not in rec:
        try:
            from tpuflow.obs.tracing import current_trace_id

            tid = current_trace_id()
            if tid is not None:
                rec["trace_id"] = tid
        except Exception:
            pass
    try:
        with _LOCK:
            (_HOT_RING if hot else _RING).append(rec)
    except Exception:
        pass
    return rec


def forensics_path(storage: str, identity: str | None = None) -> str:
    """The dump path under a storage root: ``forensics.jsonl`` for a
    plain run, ``forensics-{identity}.jsonl`` when the process carries a
    fleet identity (an elastic worker id, a daemon role). Processes
    sharing one storage root MUST dump to distinct names — the crash
    trail is exactly the file a concurrent sibling's dump would clobber
    — and ``python -m tpuflow.obs tail|summary|fleet`` read the whole
    ``forensics*.jsonl`` family."""
    import os

    name = f"forensics-{identity}.jsonl" if identity else "forensics.jsonl"
    try:
        from tpuflow.utils.paths import join_path

        return join_path(storage, name)
    except Exception:
        return os.path.join(storage, name)


def recent_events(n: int | None = None) -> list[dict]:
    """The newest ``n`` events across both rings (all, when None),
    oldest first (merged by recording time)."""
    with _LOCK:
        events = sorted(
            [*_RING, *_HOT_RING], key=lambda r: r.get("time", 0.0)
        )
    return events if n is None else events[-n:]


def clear_events() -> None:
    """Empty the rings (tests and fresh-run hygiene)."""
    with _LOCK:
        _RING.clear()
        _HOT_RING.clear()


def dump_forensics(path: str, reason: str = "") -> str | None:
    """Write the ring to ``path`` as JSONL (oldest first), ending with a
    ``forensics_dump`` marker naming the reason. Returns the path on
    success, None on failure — best-effort by contract: a full disk at
    crash time must not mask the original failure."""
    events = recent_events()
    events.append(
        {
            "event": "forensics_dump",
            "time": time.time(),
            "reason": reason,
            "events": len(events),
        }
    )
    try:
        from tpuflow.utils.paths import open_file

        with open_file(path, "w", encoding="utf-8") as f:
            for rec in events:
                try:
                    f.write(json.dumps(rec) + "\n")
                except (TypeError, ValueError):
                    # One unserializable field loses ITS line only.
                    f.write(json.dumps(
                        {"event": "unserializable", "time": rec.get("time")}
                    ) + "\n")
        return path
    except Exception as e:
        import sys

        print(
            f"tpuflow.obs: forensics dump to {path!r} failed "
            f"({type(e).__name__}: {e})",
            file=sys.stderr,
        )
        return None
