"""Span tracing: run/trace IDs propagated end-to-end, spans as events.

Two propagation paths share this module:

- **Serving.** ``POST /predict`` resolves a trace ID (the caller's
  ``X-Trace-Id`` header, else a fresh one), binds it for the handler
  thread (``use_trace``), and echoes it in the response. The
  MicroBatcher captures ``current_trace_id()`` at enqueue time, so the
  coalesced-dispatch span event names every trace it answered — the
  observable link between one caller's request and the shared device
  dispatch that served it.
- **Training.** ``train()`` binds a run-scoped trace ID; ``fit`` emits
  ingest/step/eval/checkpoint spans to the run's ``metrics.jsonl``
  (via the extended ``MetricsLogger``) with durations, each carrying
  the run's trace ID.

Every span is also recorded into the crash-forensics ring
(``tpuflow/obs/forensics.py``), so the last ~N spans survive into
``forensics.jsonl`` on an unhandled failure.

Context propagation uses ``contextvars``: thread-safe (HTTP handler
threads don't share state) and cheap. The dispatcher thread of the
MicroBatcher does NOT inherit a request's context — that's why entries
carry their trace ID explicitly.
"""

from __future__ import annotations

import contextlib
import contextvars
import os
import random
import threading
import time

_TRACE: contextvars.ContextVar[str | None] = contextvars.ContextVar(
    "tpuflow_trace_id", default=None
)

# The cross-process propagation env var: a supervisor exports it so
# every restart attempt of one job shares ONE trace; train() reads it
# (below the explicitly-bound trace in precedence) so a child's spans
# land on the parent's trail. Validated at read (utils/env.py).
TRACE_ENV = "TPUFLOW_TRACE_ID"

# urandom-seeded PRNG, not uuid4: trace IDs are generated per /predict
# request on the serving hot path, and getrandbits is ~5x cheaper than
# a UUID while still collision-safe at 64 bits per process.
_ID_RNG = random.Random(int.from_bytes(os.urandom(8), "big"))
_ID_LOCK = threading.Lock()


def new_trace_id() -> str:
    """16 hex chars: unique enough per process fleet, cheap to log."""
    with _ID_LOCK:
        return f"{_ID_RNG.getrandbits(64):016x}"


def current_trace_id() -> str | None:
    """The trace ID bound to this thread/context, if any."""
    return _TRACE.get()


def clean_trace_id(raw: str | None) -> str | None:
    """Clamp an externally-supplied trace ID (a client's ``X-Trace-Id``
    header, a frame field off the wire): tokens only, bounded length.
    A 64KB header retained per entry in the process-global forensics
    ring (and echoed into span events) would pin attacker-controlled
    memory; anything non-token-ish yields None (caller mints fresh)."""
    if not raw:
        return None
    raw = str(raw).strip()
    if 0 < len(raw) <= 64 and all(
        c.isalnum() or c in "-_." for c in raw
    ):
        return raw
    return None


def trace_from_env() -> str | None:
    """The validated ``TPUFLOW_TRACE_ID`` (None when unset): how a
    supervised child attempt joins its parent's trace. Malformed values
    fail loudly naming the variable (utils/env.py contract)."""
    from tpuflow.utils.env import env_trace_id

    return env_trace_id(TRACE_ENV)


@contextlib.contextmanager
def use_trace(trace_id: str | None = None):
    """Bind ``trace_id`` (fresh if None) for the enclosed block; yields
    the bound ID. Nesting restores the outer binding on exit."""
    tid = trace_id or new_trace_id()
    token = _TRACE.set(tid)
    try:
        yield tid
    finally:
        _TRACE.reset(token)


@contextlib.contextmanager
def span(name: str, logger=None, **fields):
    """Time the enclosed block as one span event.

    The event ``{"event": "span", "name": name, "duration_s": ...,
    "trace_id": <bound id>}`` is recorded into the forensics ring
    always, and appended to ``logger`` (a ``MetricsLogger``) when one
    is given. Never raises from the recording itself — observability
    must not fail the work it observes. The block's own exception
    propagates, with the span recorded as ``ok: false`` first.
    """
    t0 = time.perf_counter()
    ok = True
    try:
        yield
    except BaseException:
        ok = False
        raise
    finally:
        _emit(name, time.perf_counter() - t0, ok, logger, fields)


def record_span(
    name: str, duration_s: float, logger=None, hot: bool = False, **fields
) -> None:
    """Record an already-measured span (for callers that time blocks
    themselves, e.g. the dispatcher's per-group timing). ``hot=True``
    routes it to the forensics hot ring — for per-dispatch-rate spans
    that must not evict a run's lifecycle trail."""
    _emit(name, duration_s, True, logger, fields, hot=hot)


def _emit(name, duration_s, ok, logger, fields, hot=False) -> None:
    rec = {
        "name": name,
        "duration_s": round(float(duration_s), 6),
        "trace_id": current_trace_id(),
        **fields,
    }
    if not ok:
        rec["ok"] = False
    try:
        from tpuflow.obs.forensics import record_event

        record_event("span", hot=hot, **rec)
        if logger is not None:
            logger.write("span", **rec)
    except Exception:
        # A closed logger / full disk must not fail training or serving.
        pass
