"""``python -m tpuflow.obs`` — read any run's event trail from the shell.

Usage::

    python -m tpuflow.obs tail     <trail.jsonl|glob|dir> [...] [-n N]
    python -m tpuflow.obs summary  <trail.jsonl|glob|dir> [...]
    python -m tpuflow.obs timeline <metrics.jsonl> -o trace.json
    python -m tpuflow.obs fleet    <dir...> [-o fleet.json] [--summary P]
    python -m tpuflow.obs slo      <dir...> [--objectives F] [-o card.json]
    python -m tpuflow.obs history  <spill.jsonl|glob|dir> [...] [--metric M]
    python -m tpuflow.obs alerts   <spill.jsonl|glob|dir> [...] [--rules F]
    python -m tpuflow.obs profile  <snap.json|spill.jsonl> [--top N] [--folded]
    python -m tpuflow.obs profile  --diff BASE NEW [--threshold T]
    python -m tpuflow.obs flight   <bundle-dir> [--inspect NAME] [--json]

``tail``/``summary`` read the JSONL event format every tpuflow sink
writes — a training run's ``metrics.jsonl`` (``--metrics`` /
``metrics_path``), a crash dump's ``forensics.jsonl``, a serve journal —
and accept several of them at once: multiple paths, shell-style glob
patterns (``'store/forensics*.jsonl'`` — elastic workers suffix their
dumps with a worker identity, so a shared storage root holds a family),
or a directory (every ``*.jsonl`` under it). Events merge ordered by
timestamp. ``tail`` prints the newest N records (default 20), newest
last; ``summary`` aggregates the whole trail: events by type, the
epoch-loss trajectory, span time by name, the wall-clock window.
``timeline`` exports one trail's spans as Chrome trace-event JSON,
loadable in Perfetto (https://ui.perfetto.dev).

``history`` replays a daemon's metrics-history spill
(``TPUFLOW_OBS_HISTORY_SPILL`` — ``history_sample`` ticks written by
``tpuflow/obs/history.py``) and prints per-series summaries; ``alerts``
replays the same spill through an offline
:class:`~tpuflow.obs.alerts.AlertEngine` against a JSON rules file (or
the committed SLO burn-rate rules with ``--slo``) and prints every
firing/resolved transition — alerting forensics after the fact, same
math as the live daemons.

``profile`` renders a sampling-profiler snapshot (a JSON document or a
``TPUFLOW_OBS_PROFILE_SPILL`` JSONL, latest record winning) as the
component table + top-N busy frames, ``--folded`` flamegraph text, or
``--json``; ``--diff BASE NEW`` compares two snapshots' busy-share per
component and exits 1 on a ``regression`` verdict (CI gating). ``flight``
lists the flight-recorder bundles under a storage root (newest last) and
``--inspect`` pretty-prints one bundle: validation, per-component thread
census, firing alerts, and the embedded profile's top components.

``fleet`` is the multi-process view (``tpuflow/obs/fleet.py``): discover
every trail under one or more storage roots, merge them into ONE
Chrome-trace timeline — a lane group per process, a fleet-wide time
zero, and flow arrows connecting every trace id seen in more than one
process — and print the fleet summary JSON. ``slo`` scores the same
merged events against declarative objectives
(``tpuflow/obs/slo.py``) and emits the SLO report card, validated
against the committed ``slo_report_card.schema.json``.

Torn trails are data, not errors: corrupt/truncated lines (a forensics
dump written during a crash can end mid-line, even mid-UTF-8-sequence)
are skipped and reported as ``skipped_lines: N``, never raised on.

Deliberately dependency-light (no jax import): usable on a machine that
only has the log files.
"""

from __future__ import annotations

import argparse
import glob as _glob
import json
import os
import sys

from tpuflow.obs.trail import read_events as _read_events


def _expand(patterns: list[str]) -> list[str]:
    """Paths from a mix of files, glob patterns, and directories
    (directories walk through ``fleet.iter_jsonl`` — the SAME discovery
    the fleet merger uses, so tail/summary and fleet agree on what a
    storage root contains). Missing literal paths stay in the list so
    the caller's OSError handling names them (a typo'd path must not
    silently vanish)."""
    from tpuflow.obs.fleet import iter_jsonl

    out: list[str] = []
    for pat in patterns:
        if os.path.isdir(pat):
            out.extend(iter_jsonl(pat))
            continue
        matches = sorted(_glob.glob(pat))
        out.extend(matches if matches else [pat])
    # De-dup, order-preserving: one file named twice must not count
    # its events twice.
    seen, unique = set(), []
    for path in out:
        key = os.path.abspath(path)
        if key not in seen:
            seen.add(key)
            unique.append(path)
    return unique


def _read_all(patterns: list[str]) -> tuple[list[dict], int, int]:
    """Merged events (time-ordered) + skipped-line count + file count
    across every expanded path."""
    from tpuflow.obs.fleet import event_time_key

    events: list[dict] = []
    skipped = 0
    paths = _expand(patterns)
    for path in paths:
        evs, skip = _read_events(path)
        events.extend(evs)
        skipped += skip
    events.sort(key=event_time_key)
    return events, skipped, len(paths)


def _tail(patterns: list[str], n: int) -> int:
    events, skipped, _ = _read_all(patterns)
    for rec in events[-n:]:
        print(json.dumps(rec))
    if skipped:
        print(f"skipped_lines: {skipped}", file=sys.stderr)
    return 0


def _fmt_seconds(s: float) -> str:
    return f"{s:.3f}s" if s < 120 else f"{s / 60:.1f}m"


def _summary(patterns: list[str]) -> int:
    events, skipped, n_files = _read_all(patterns)
    label = patterns[0] if len(patterns) == 1 and n_files == 1 else (
        f"{n_files} trails ({', '.join(patterns)})"
    )
    if not events:
        print(f"{label}: no events"
              + (f" (skipped_lines: {skipped})" if skipped else ""))
        return 1
    by_type: dict[str, int] = {}
    for rec in events:
        kind = str(rec.get("event", "?"))
        by_type[kind] = by_type.get(kind, 0) + 1
    print(f"{label}: {len(events)} events"
          + (f" (skipped_lines: {skipped})" if skipped else ""))
    times = [rec["time"] for rec in events if isinstance(rec.get("time"), (int, float))]
    if times:
        print(f"  window: {_fmt_seconds(max(times) - min(times))} "
              f"({min(times):.0f} .. {max(times):.0f} epoch-seconds)")
    print("  by event: " + ", ".join(
        f"{k}={v}" for k, v in sorted(by_type.items())
    ))
    # Epoch trajectory (the fit loop's per-epoch records).
    epochs = [rec for rec in events if rec.get("event") == "epoch"]
    if epochs:
        losses = [rec.get("val_loss") for rec in epochs
                  if isinstance(rec.get("val_loss"), (int, float))]
        line = f"  epochs: {len(epochs)}"
        if losses:
            line += (f"; val_loss first={losses[0]:.4f} "
                     f"last={losses[-1]:.4f} best={min(losses):.4f}")
        print(line)
    # Span time by name — where the run's time actually went.
    spans: dict[str, tuple[int, float]] = {}
    for rec in events:
        if rec.get("event") != "span":
            continue
        name = str(rec.get("name", "?"))
        dur = rec.get("duration_s")
        if not isinstance(dur, (int, float)):
            continue
        n, total = spans.get(name, (0, 0.0))
        spans[name] = (n + 1, total + float(dur))
    if spans:
        print("  spans:")
        for name, (n, total) in sorted(
            spans.items(), key=lambda kv: -kv[1][1]
        ):
            print(f"    {name}: n={n} total={_fmt_seconds(total)} "
                  f"mean={total / n * 1000:.1f}ms")
    done = [rec for rec in events if rec.get("event") == "fit_done"]
    if done:
        rec = done[-1]
        print(f"  fit_done: epochs={rec.get('epochs')} "
              f"best_val_loss={rec.get('best_val_loss')} "
              f"samples_per_sec={rec.get('samples_per_sec')}")
    anomalies = [
        rec for rec in events if rec.get("event") == "numerics_anomaly"
    ]
    if anomalies:
        kinds: dict[str, int] = {}
        for rec in anomalies:
            k = str(rec.get("kind", "?"))
            kinds[k] = kinds.get(k, 0) + 1
        print("  numerics anomalies: " + ", ".join(
            f"{k}={v}" for k, v in sorted(kinds.items())
        ))
    dumps = [rec for rec in events if rec.get("event") == "forensics_dump"]
    if dumps:
        print(f"  forensics dump: reason={dumps[-1].get('reason')!r}")
    return 0


def _timeline(path: str, out: str) -> int:
    from tpuflow.obs.timeline import export_timeline

    stats = export_timeline(path, out)
    line = (f"{out}: {stats['events']} trace events "
            f"({stats['spans']} spans)")
    if stats["skipped_lines"]:
        line += f"; skipped_lines: {stats['skipped_lines']}"
    print(line)
    if not stats["spans"]:
        print(f"{path}: no span records to draw", file=sys.stderr)
        return 1
    return 0


def _fleet(roots: list[str], out: str, summary_path: str | None) -> int:
    from tpuflow.obs.fleet import export_fleet

    missing = [r for r in roots if not os.path.exists(r)]
    if missing:
        print(f"no such path(s): {missing}", file=sys.stderr)
        return 2
    summary = export_fleet(roots, out)
    if summary_path:
        with open(summary_path, "w", encoding="utf-8") as f:
            json.dump(summary, f, indent=2)
            f.write("\n")
    print(json.dumps(summary, indent=2))
    if not summary["trails"]:
        print("no trails discovered", file=sys.stderr)
        return 1
    return 0


def _slo(
    roots: list[str], objectives_path: str | None, out: str | None,
    window_s: float,
) -> int:
    from tpuflow.obs.fleet import read_fleet
    from tpuflow.obs.slo import (
        load_objectives,
        report_card,
        validate_report_card,
    )

    missing = [r for r in roots if not os.path.exists(r)]
    if missing:
        print(f"no such path(s): {missing}", file=sys.stderr)
        return 2
    objectives = (
        load_objectives(objectives_path) if objectives_path else None
    )
    trails, events = read_fleet(roots)
    card = report_card(
        events, objectives, window_s=window_s,
        source={"roots": [os.path.abspath(r) for r in roots],
                "trails": [t["path"] for t in trails]},
    )
    validate_report_card(card)
    if out:
        with open(out, "w", encoding="utf-8") as f:
            json.dump(card, f, indent=2)
            f.write("\n")
    print(json.dumps(card, indent=2))
    return 0


def _offline_history():
    """An unbounded offline MetricsHistory — replay must never
    downsample or drop what the live daemon already bounded."""
    from tpuflow.obs.history import MetricsHistory

    return MetricsHistory(
        None, interval_s=1.0, max_points=100000, max_series=100000,
        retention_s=10**9,
    )


def _replay_history(patterns: list[str]) -> tuple:
    """Rebuild an offline MetricsHistory from spilled ``history_sample``
    ticks (time-ordered, merged across files). Returns ``(history,
    ticks, skipped)``."""
    return _replay_history_into(_offline_history(), patterns)


def _history(patterns: list[str], metric: str | None, as_json: bool) -> int:
    history, ticks, skipped = _replay_history(patterns)
    rows = []
    for s in history.all_series():
        if metric and metric not in s["name"]:
            continue
        values = [v for _, v in s["points"]]
        if not values:
            continue
        rows.append({
            "series": s["name"], "labels": s["labels"],
            "points": len(values),
            "first_t": round(s["points"][0][0], 3),
            "last_t": round(s["points"][-1][0], 3),
            "min": min(values), "max": max(values), "last": values[-1],
        })
    if as_json:
        print(json.dumps({
            "ticks": ticks, "series": rows, "skipped_lines": skipped,
        }, indent=2))
    else:
        print(f"{ticks} history ticks, {len(rows)} series"
              + (f" (skipped_lines: {skipped})" if skipped else ""))
        from tpuflow.obs.history import format_series

        for r in rows:
            print(f"  {format_series(r['series'], r['labels'])}: "
                  f"n={r['points']} last={r['last']:g} "
                  f"min={r['min']:g} max={r['max']:g}")
    if not ticks:
        print("no history_sample records found", file=sys.stderr)
        return 1
    return 0


def _alerts(
    patterns: list[str], rules_path: str | None, use_slo: bool,
    as_json: bool, fail_on_firing: bool,
) -> int:
    from tpuflow.obs.alerts import (
        AlertEngine,
        rules_from_objectives,
        validate_rules,
    )

    if rules_path:
        with open(rules_path, encoding="utf-8") as f:
            rules = json.load(f)
        problems = validate_rules(rules)
        if problems:
            raise ValueError(
                f"{rules_path}: " + "; ".join(problems)
            )
    elif use_slo:
        rules = rules_from_objectives()
    else:
        raise ValueError(
            "alerts needs --rules FILE (a JSON list of rule objects) or "
            "--slo (the committed SLO burn-rate rules)"
        )
    history = _offline_history()
    engine = AlertEngine(history, rules).attach()
    _, ticks, skipped = _replay_history_into(history, patterns)
    summary = engine.summary()
    out = {
        "ticks": ticks,
        "transitions": engine.transitions,
        "firing": engine.firing(),
        "rules": summary["rules"],
        "skipped_lines": skipped,
    }
    if as_json:
        print(json.dumps(out, indent=2))
    else:
        print(f"{ticks} history ticks, {len(rules)} rules, "
              f"{len(engine.transitions)} transitions"
              + (f" (skipped_lines: {skipped})" if skipped else ""))
        for rec in engine.transitions:
            print(f"  t={rec['t']:g} {rec['state'].upper():>8} "
                  f"{rec['rule']} value={rec['value']:g} "
                  f"threshold={rec['threshold']:g}")
        for row in summary["rules"]:
            print(f"  final: {row['name']} state={row['state']} "
                  f"value={row['value']}")
    if not ticks:
        print("no history_sample records found", file=sys.stderr)
        return 1
    if fail_on_firing and out["firing"]:
        print(f"firing: {out['firing']}", file=sys.stderr)
        return 1
    return 0


def _replay_history_into(history, patterns: list[str]) -> tuple:
    """Feed spilled ticks into an EXISTING history (one with listeners
    already attached — the alerts replay path)."""
    events, skipped, _ = _read_all(patterns)
    ticks = 0
    for rec in events:
        if rec.get("event") != "history_sample":
            continue
        samples = rec.get("samples")
        t = rec.get("t", rec.get("time"))
        if not isinstance(samples, dict) or not isinstance(t, (int, float)):
            skipped += 1
            continue
        history.ingest(float(t), samples)
        ticks += 1
    return history, ticks, skipped


def _profile(
    files: list[str], diff: bool, threshold: float, top: int,
    folded: bool, as_json: bool,
) -> int:
    from tpuflow.obs.profiler import (
        diff_snapshots,
        load_snapshot,
        render_diff,
        render_folded,
        render_profile,
    )

    if diff:
        if len(files) != 2:
            raise ValueError("profile --diff takes exactly two snapshots: BASE NEW")
        verdict = diff_snapshots(
            load_snapshot(files[0]), load_snapshot(files[1]),
            threshold=threshold,
        )
        print(json.dumps(verdict, indent=2) if as_json else render_diff(verdict))
        return 1 if verdict["verdict"] == "regression" else 0
    if len(files) == 1:
        snap = load_snapshot(files[0])
    else:
        from tpuflow.obs.profiler import merge_snapshots

        snap = load_snapshot(files[0])
        for path in files[1:]:
            snap = merge_snapshots(snap, load_snapshot(path))
    if as_json:
        print(json.dumps(snap, indent=2))
    elif folded:
        print(render_folded(snap))
    else:
        print(render_profile(snap, top=top))
    if not snap.get("thread_samples"):
        print("snapshot holds no samples", file=sys.stderr)
        return 1
    return 0


def _flight(root: str, inspect: str | None, as_json: bool) -> int:
    from tpuflow.obs.flight import list_bundles, load_bundle, validate_bundle
    from tpuflow.obs.profiler import top_component

    if inspect:
        doc = load_bundle(root, inspect)
        problems = validate_bundle(doc)
        if as_json:
            print(json.dumps({"bundle": inspect, "problems": problems,
                              "doc": doc}, indent=2, default=str))
        else:
            print(f"{inspect}: trigger={doc.get('trigger')} "
                  f"rule={doc.get('rule')} reason={doc.get('reason')!r}")
            by_comp: dict[str, int] = {}
            for row in doc.get("threads", []) or []:
                c = row.get("component", "?")
                by_comp[c] = by_comp.get(c, 0) + 1
            print("  threads: " + ", ".join(
                f"{k}={v}" for k, v in sorted(by_comp.items())
            ))
            alerts = doc.get("alerts") or {}
            firing = [r["name"] for r in alerts.get("rules", [])
                      if r.get("state") == "firing"]
            print(f"  alerts firing: {firing}")
            profile = doc.get("profile")
            if profile:
                comps = sorted(
                    profile.get("components", {}).items(),
                    key=lambda kv: (-kv[1].get("busy", 0), kv[0]),
                )
                print(f"  profile top: {top_component(profile)} ("
                      + ", ".join(
                          f"{k}:{v.get('share', 0.0):.0%}" for k, v in comps[:4]
                      ) + ")")
            history = doc.get("history") or {}
            for name, series in (history.get("series") or {}).items():
                print(f"  history[{name}]: {len(series.get('points', []))} "
                      f"points over {series.get('window_s')}s")
            if problems:
                print("  INVALID: " + "; ".join(problems))
        if problems:
            print(f"{inspect}: schema-invalid bundle", file=sys.stderr)
            return 2
        return 0
    names = list_bundles(root)
    if as_json:
        rows = []
        for name in names:
            doc = load_bundle(root, name)
            rows.append({
                "bundle": name,
                "trigger": doc.get("trigger"),
                "rule": doc.get("rule"),
                "captured_unix": doc.get("captured_unix"),
                "valid": not validate_bundle(doc),
            })
        print(json.dumps({"root": root, "bundles": rows}, indent=2))
    else:
        for name in names:
            doc = load_bundle(root, name)
            valid = "ok" if not validate_bundle(doc) else "INVALID"
            print(f"{name}  trigger={doc.get('trigger')} "
                  f"rule={doc.get('rule')} [{valid}]")
    if not names:
        print(f"{root}: no flight bundles", file=sys.stderr)
        return 1
    return 0


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tpuflow.obs",
        description="summarize/tail/export tpuflow JSONL event trails",
    )
    sub = ap.add_subparsers(dest="cmd", required=True)
    p_tail = sub.add_parser("tail", help="print the newest N records")
    p_tail.add_argument("file", nargs="+",
                        help="trail file(s), glob pattern(s), or dir(s)")
    p_tail.add_argument("-n", type=int, default=20)
    p_sum = sub.add_parser("summary", help="aggregate the whole trail")
    p_sum.add_argument("file", nargs="+",
                       help="trail file(s), glob pattern(s), or dir(s)")
    p_tl = sub.add_parser(
        "timeline",
        help="export spans as Chrome trace-event JSON (Perfetto-loadable)",
    )
    p_tl.add_argument("file")
    p_tl.add_argument("-o", "--out", default="trace.json")
    p_fleet = sub.add_parser(
        "fleet",
        help="merge every trail under storage root(s) into one "
        "fleet timeline (per-process lanes + trace flow arrows) "
        "and print the fleet summary",
    )
    p_fleet.add_argument("root", nargs="+",
                         help="storage root(s) to discover trails under")
    p_fleet.add_argument("-o", "--out", default="fleet-trace.json",
                         help="merged Chrome trace-event JSON output")
    p_fleet.add_argument("--summary", default=None, metavar="PATH",
                         help="also write the fleet summary JSON here")
    p_slo = sub.add_parser(
        "slo",
        help="score fleet trails against SLO objectives and emit the "
        "report card (validated against slo_report_card.schema.json)",
    )
    p_slo.add_argument("root", nargs="+",
                       help="storage root(s) to discover trails under")
    p_slo.add_argument("--objectives", default=None, metavar="FILE",
                       help="JSON objectives file — a list of {name, "
                       "kind, target, ...} dicts (default: the "
                       "availability + latency_p99 pair; add a "
                       "time_to_adapt objective to grade drift "
                       "lifecycles — docs/observability.md)")
    p_slo.add_argument("-o", "--out", default=None, metavar="PATH",
                       help="also write the report card JSON here")
    p_slo.add_argument("--window", type=float, default=300.0,
                       metavar="S", help="burn-rate window seconds")
    p_hist = sub.add_parser(
        "history",
        help="replay a metrics-history spill (history_sample ticks) "
        "and print per-series summaries",
    )
    p_hist.add_argument("file", nargs="+",
                        help="spill file(s), glob pattern(s), or dir(s)")
    p_hist.add_argument("--metric", default=None, metavar="SUBSTR",
                        help="only series whose name contains SUBSTR")
    p_hist.add_argument("--json", action="store_true", dest="as_json",
                        help="machine-readable output")
    p_alerts = sub.add_parser(
        "alerts",
        help="replay a metrics-history spill through alert rules and "
        "print every firing/resolved transition",
    )
    p_alerts.add_argument("file", nargs="+",
                          help="spill file(s), glob pattern(s), or dir(s)")
    p_alerts.add_argument("--rules", default=None, metavar="FILE",
                          help="JSON rules file — a list of rule objects "
                          "(docs/observability.md has the grammar)")
    p_alerts.add_argument("--slo", action="store_true",
                          help="use the committed SLO objectives as "
                          "burn-rate/latency rules instead of --rules")
    p_alerts.add_argument("--json", action="store_true", dest="as_json",
                          help="machine-readable output")
    p_alerts.add_argument("--fail-on-firing", action="store_true",
                          help="exit 1 if any rule is firing at the end "
                          "of the replay (CI gating)")
    p_prof = sub.add_parser(
        "profile",
        help="render a sampling-profiler snapshot, or --diff two of "
        "them (exit 1 on a regression verdict)",
    )
    p_prof.add_argument("file", nargs="+",
                        help="snapshot JSON file(s) or profile spill "
                        "JSONL(s); several merge into one view")
    p_prof.add_argument("--diff", action="store_true",
                        help="treat the two files as BASE NEW and emit "
                        "the component-share regression verdict")
    p_prof.add_argument("--threshold", type=float, default=0.05,
                        metavar="FRAC",
                        help="busy-share growth that counts as a "
                        "regression (default 0.05)")
    p_prof.add_argument("--top", type=int, default=15, metavar="N",
                        help="rows in the self/cumulative frame table")
    p_prof.add_argument("--folded", action="store_true",
                        help="emit flamegraph-ready folded-stack text")
    p_prof.add_argument("--json", action="store_true", dest="as_json",
                        help="machine-readable output")
    p_flight = sub.add_parser(
        "flight",
        help="list flight-recorder bundles under a storage root, or "
        "--inspect one (validation + thread/alert/profile digest)",
    )
    p_flight.add_argument("root",
                          help="bundle dir or storage URL "
                          "(TPUFLOW_OBS_FLIGHT_DIR)")
    p_flight.add_argument("--inspect", default=None, metavar="NAME",
                          help="bundle name to pretty-print")
    p_flight.add_argument("--json", action="store_true", dest="as_json",
                          help="machine-readable output")
    args = ap.parse_args(argv)
    try:
        if args.cmd == "tail":
            return _tail(args.file, args.n)
        if args.cmd == "timeline":
            return _timeline(args.file, args.out)
        if args.cmd == "fleet":
            return _fleet(args.root, args.out, args.summary)
        if args.cmd == "slo":
            return _slo(args.root, args.objectives, args.out, args.window)
        if args.cmd == "history":
            return _history(args.file, args.metric, args.as_json)
        if args.cmd == "alerts":
            return _alerts(args.file, args.rules, args.slo, args.as_json,
                           args.fail_on_firing)
        if args.cmd == "profile":
            return _profile(args.file, args.diff, args.threshold,
                            args.top, args.folded, args.as_json)
        if args.cmd == "flight":
            return _flight(args.root, args.inspect, args.as_json)
        return _summary(args.file)
    except OSError as e:
        print(f"{e}", file=sys.stderr)
        return 2
    except ValueError as e:
        print(f"{e}", file=sys.stderr)
        return 2


if __name__ == "__main__":
    sys.exit(main())
