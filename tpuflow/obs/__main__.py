"""``python -m tpuflow.obs`` — read any run's event trail from the shell.

Usage::

    python -m tpuflow.obs tail     <metrics.jsonl> [-n N]
    python -m tpuflow.obs summary  <metrics.jsonl>
    python -m tpuflow.obs timeline <metrics.jsonl> -o trace.json

All subcommands read the JSONL event format every tpuflow sink writes —
a training run's ``metrics.jsonl`` (``--metrics`` / ``metrics_path``),
a crash dump's ``forensics.jsonl``, or a serve journal. ``tail`` prints
the newest N records (default 20), one per line, newest last. ``summary``
aggregates the whole trail: events by type, epoch-loss trajectory, span
time by name, and the wall-clock window covered — the two-second answer
to "what did this run do and where did the time go". ``timeline``
exports the trail's spans as Chrome trace-event JSON, loadable in
Perfetto (https://ui.perfetto.dev) — "where did the time go", drawn.

Torn trails are data, not errors: corrupt/truncated lines (a forensics
dump written during a crash can end mid-line, even mid-UTF-8-sequence)
are skipped and reported as ``skipped_lines: N``, never raised on.

Deliberately dependency-light (no jax import): usable on a machine that
only has the log files.
"""

from __future__ import annotations

import argparse
import json
import sys

from tpuflow.obs.trail import read_events as _read_events


def _tail(path: str, n: int) -> int:
    events, skipped = _read_events(path)
    for rec in events[-n:]:
        print(json.dumps(rec))
    if skipped:
        print(f"skipped_lines: {skipped}", file=sys.stderr)
    return 0


def _fmt_seconds(s: float) -> str:
    return f"{s:.3f}s" if s < 120 else f"{s / 60:.1f}m"


def _summary(path: str) -> int:
    events, skipped = _read_events(path)
    if not events:
        print(f"{path}: no events"
              + (f" (skipped_lines: {skipped})" if skipped else ""))
        return 1
    by_type: dict[str, int] = {}
    for rec in events:
        kind = str(rec.get("event", "?"))
        by_type[kind] = by_type.get(kind, 0) + 1
    print(f"{path}: {len(events)} events"
          + (f" (skipped_lines: {skipped})" if skipped else ""))
    times = [rec["time"] for rec in events if isinstance(rec.get("time"), (int, float))]
    if times:
        print(f"  window: {_fmt_seconds(max(times) - min(times))} "
              f"({min(times):.0f} .. {max(times):.0f} epoch-seconds)")
    print("  by event: " + ", ".join(
        f"{k}={v}" for k, v in sorted(by_type.items())
    ))
    # Epoch trajectory (the fit loop's per-epoch records).
    epochs = [rec for rec in events if rec.get("event") == "epoch"]
    if epochs:
        losses = [rec.get("val_loss") for rec in epochs
                  if isinstance(rec.get("val_loss"), (int, float))]
        line = f"  epochs: {len(epochs)}"
        if losses:
            line += (f"; val_loss first={losses[0]:.4f} "
                     f"last={losses[-1]:.4f} best={min(losses):.4f}")
        print(line)
    # Span time by name — where the run's time actually went.
    spans: dict[str, tuple[int, float]] = {}
    for rec in events:
        if rec.get("event") != "span":
            continue
        name = str(rec.get("name", "?"))
        dur = rec.get("duration_s")
        if not isinstance(dur, (int, float)):
            continue
        n, total = spans.get(name, (0, 0.0))
        spans[name] = (n + 1, total + float(dur))
    if spans:
        print("  spans:")
        for name, (n, total) in sorted(
            spans.items(), key=lambda kv: -kv[1][1]
        ):
            print(f"    {name}: n={n} total={_fmt_seconds(total)} "
                  f"mean={total / n * 1000:.1f}ms")
    done = [rec for rec in events if rec.get("event") == "fit_done"]
    if done:
        rec = done[-1]
        print(f"  fit_done: epochs={rec.get('epochs')} "
              f"best_val_loss={rec.get('best_val_loss')} "
              f"samples_per_sec={rec.get('samples_per_sec')}")
    anomalies = [
        rec for rec in events if rec.get("event") == "numerics_anomaly"
    ]
    if anomalies:
        kinds: dict[str, int] = {}
        for rec in anomalies:
            k = str(rec.get("kind", "?"))
            kinds[k] = kinds.get(k, 0) + 1
        print("  numerics anomalies: " + ", ".join(
            f"{k}={v}" for k, v in sorted(kinds.items())
        ))
    dumps = [rec for rec in events if rec.get("event") == "forensics_dump"]
    if dumps:
        print(f"  forensics dump: reason={dumps[-1].get('reason')!r}")
    return 0


def _timeline(path: str, out: str) -> int:
    from tpuflow.obs.timeline import export_timeline

    stats = export_timeline(path, out)
    line = (f"{out}: {stats['events']} trace events "
            f"({stats['spans']} spans)")
    if stats["skipped_lines"]:
        line += f"; skipped_lines: {stats['skipped_lines']}"
    print(line)
    if not stats["spans"]:
        print(f"{path}: no span records to draw", file=sys.stderr)
        return 1
    return 0


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tpuflow.obs",
        description="summarize/tail/export a tpuflow JSONL event trail",
    )
    sub = ap.add_subparsers(dest="cmd", required=True)
    p_tail = sub.add_parser("tail", help="print the newest N records")
    p_tail.add_argument("file")
    p_tail.add_argument("-n", type=int, default=20)
    p_sum = sub.add_parser("summary", help="aggregate the whole trail")
    p_sum.add_argument("file")
    p_tl = sub.add_parser(
        "timeline",
        help="export spans as Chrome trace-event JSON (Perfetto-loadable)",
    )
    p_tl.add_argument("file")
    p_tl.add_argument("-o", "--out", default="trace.json")
    args = ap.parse_args(argv)
    try:
        if args.cmd == "tail":
            return _tail(args.file, args.n)
        if args.cmd == "timeline":
            return _timeline(args.file, args.out)
        return _summary(args.file)
    except OSError as e:
        print(f"{args.file}: {e}", file=sys.stderr)
        return 2


if __name__ == "__main__":
    sys.exit(main())
