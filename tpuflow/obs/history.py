"""Bounded in-memory metrics history: the substrate scrapes can't give.

A ``Registry`` (``tpuflow/obs/metrics.py``) answers "what is the value
NOW"; every consumer that needs "what happened over the last window" —
burn-rate alerting with hold-downs, the serving autoscaler's
sustained-win hysteresis — has to difference snapshots itself, badly.
:class:`MetricsHistory` is the one copy of that differencing: a
sampler (an injectable-clock cadence on a stop-event-bound daemon
thread, or explicit :meth:`sample` calls from tests and scrape
handlers) appends every family's collected samples to bounded
per-series rings, and windowed queries (:meth:`rate`, :meth:`mean`,
:meth:`max`, :meth:`quantile`, :meth:`delta`, :meth:`latest`) read
them back.

Memory is provably bounded: at most ``max_series`` series, each at
most ``max_points`` points of two floats. A series that would exceed
``max_points`` is **downsampled in place** (every other point dropped,
newest kept — counted by ``obs_history_downsamples_total``), so a
long-running daemon keeps a coarser-but-complete past instead of
forgetting it; points older than ``retention_s`` are pruned on append.
New series past ``max_series`` are dropped and counted
(``obs_history_dropped_series_total``) — never an unbounded dict.

The optional JSONL spill (``spill_path`` /
``TPUFLOW_OBS_HISTORY_SPILL``) appends one ``history_sample`` record
per tick through :class:`~tpuflow.utils.logging.MetricsLogger`, so
``python -m tpuflow.obs history`` (and ``fleet``/``timeline``, which
merge any JSONL trail) can replay a daemon's history lanes offline —
:meth:`ingest` is the replay side of the same format.

Lock discipline (the PR 15 concurrency gate): every mutation of the
series table happens under ``self._lock``; family collection, the
spill write, and listener callbacks all run OUTSIDE it (collection
takes each family's own lock; file I/O under a held lock is TPF017).
The sampler loop waits on its stop event — never a bare ``time.sleep``
(TPF022) — so shutdown is drillable and cadence injectable.

Deliberately dependency-light (no jax): usable offline on a machine
that only has the spill files.
"""

from __future__ import annotations

import math
import os
import threading
import time

from tpuflow.utils.env import env_num

HISTORY_DEFAULTS = {
    "interval_s": 1.0,
    "max_points": 512,
    "max_series": 512,
    "retention_s": 900.0,
}


def format_series(name: str, labels: dict | None = None) -> str:
    """The spill/CLI series key: ``name`` or ``name{k=v,...}`` with
    labels sorted — one stable spelling per series."""
    if not labels:
        return name
    inner = ",".join(f"{k}={v}" for k, v in sorted(labels.items()))
    return f"{name}{{{inner}}}"


def parse_series(text: str) -> tuple[str, dict]:
    """Invert :func:`format_series`. Malformed label text raises
    ValueError naming the series — a corrupt spill line must be
    reported as such, not half-parsed into a phantom series."""
    text = text.strip()
    if "{" not in text:
        return text, {}
    if not text.endswith("}"):
        raise ValueError(f"malformed series key {text!r}")
    name, _, inner = text[:-1].partition("{")
    labels = {}
    for part in inner.split(","):
        if not part:
            continue
        if "=" not in part:
            raise ValueError(f"malformed series key {text!r}")
        k, _, v = part.partition("=")
        labels[k] = v
    return name, labels


class _Series:
    __slots__ = ("name", "labels", "kind", "points")

    def __init__(self, name: str, labels: dict, kind: str):
        self.name = name
        self.labels = dict(labels)
        self.kind = kind
        self.points: list[tuple[float, float]] = []


class MetricsHistory:
    """Sample a :class:`~tpuflow.obs.metrics.Registry` into bounded
    per-series time rings and answer windowed queries over them.

    ``registry=None`` is the offline-replay mode (``python -m
    tpuflow.obs history``): :meth:`ingest` feeds spilled ticks back in
    and every query works identically.
    """

    def __init__(
        self,
        registry=None,
        *,
        interval_s: float | None = None,
        max_points: int | None = None,
        max_series: int | None = None,
        retention_s: float | None = None,
        spill_path: str | None = None,
        clock=time.monotonic,
    ):
        if interval_s is None:
            interval_s = env_num(
                "TPUFLOW_OBS_HISTORY_INTERVAL_S",
                HISTORY_DEFAULTS["interval_s"], float, minimum=0.05,
                form="a sampling cadence in seconds >= 0.05",
            )
        if max_points is None:
            max_points = env_num(
                "TPUFLOW_OBS_HISTORY_MAX_POINTS",
                HISTORY_DEFAULTS["max_points"], int, minimum=8,
                form="an integer per-series point bound >= 8",
            )
        if max_series is None:
            max_series = env_num(
                "TPUFLOW_OBS_HISTORY_MAX_SERIES",
                HISTORY_DEFAULTS["max_series"], int, minimum=1,
                form="an integer series bound >= 1",
            )
        if retention_s is None:
            retention_s = env_num(
                "TPUFLOW_OBS_HISTORY_RETENTION_S",
                HISTORY_DEFAULTS["retention_s"], float, minimum=1.0,
                form="a retention window in seconds >= 1",
            )
        self.registry = registry
        self.interval_s = float(interval_s)
        self.max_points = int(max_points)
        self.max_series = int(max_series)
        self.retention_s = float(retention_s)
        self.clock = clock
        self._lock = threading.Lock()
        self._series: dict[tuple[str, tuple], _Series] = {}
        self._last_t: float | None = None
        self._listeners: list = []
        self._pre_sample: list = []
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        if spill_path is None:
            spill_path = os.environ.get("TPUFLOW_OBS_HISTORY_SPILL") or None
        self._spill = None
        if spill_path:
            from tpuflow.utils.logging import MetricsLogger

            self._spill = MetricsLogger(spill_path)
        self._samples_total = self._downsamples = self._dropped = None
        if registry is not None:
            self._samples_total = registry.counter(
                "obs_history_samples_total",
                "history sampler ticks recorded",
            )
            self._downsamples = registry.counter(
                "obs_history_downsamples_total",
                "series halvings forced by the per-series point bound "
                "(the memory-bounding decimation)",
            )
            self._dropped = registry.counter(
                "obs_history_dropped_series_total",
                "new series refused by the series bound",
            )
            registry.gauge(
                "obs_history_series",
                "time series currently held by the metrics history",
                fn=self._series_count,
            )

    # ---- wiring ----

    def add_pre_sample(self, fn) -> None:
        """Run ``fn()`` before each tick's collection — the seam that
        refreshes pull-published gauges (the SLO engine's
        ``evaluate_registry``) so their history is as fresh as the
        counters'. Exceptions are swallowed: a broken hook must not
        stop the sampler."""
        self._pre_sample.append(fn)

    def add_listener(self, fn) -> None:
        """Call ``fn(now)`` after each tick (sample or ingest) — the
        alert engine's evaluation hook. Exceptions are swallowed."""
        self._listeners.append(fn)

    def _series_count(self) -> int:
        with self._lock:
            return len(self._series)

    # ---- sampling ----

    def sample(self, now: float | None = None) -> int:
        """One tick: collect every family's current samples and append
        them. Returns the number of values recorded. Histogram
        ``_bucket`` rows are skipped (high label cardinality, no
        windowed-query value — the ``_sum``/``_count`` rows carry the
        rate story)."""
        now = self.clock() if now is None else float(now)
        if self.registry is None:
            return 0
        for fn in self._pre_sample:
            try:
                fn()
            except Exception:
                pass
        rows = []
        for fam in self.registry.collect():
            for suffix, labels, value in fam.collect():
                if suffix == "_bucket":
                    continue
                kind = (
                    "counter"
                    if fam.kind == "counter" or suffix in ("_sum", "_count")
                    else "gauge"
                )
                rows.append((fam.name + suffix, labels, kind, value))
        recorded = self._append_rows(now, rows)
        if self._samples_total is not None:
            self._samples_total.inc()
        self._spill_tick(now, rows)
        self._notify(now)
        return recorded

    def maybe_sample(self, now: float | None = None) -> int:
        """Scrape-driven sampling (the threaded daemon has no sampler
        thread): tick only if at least ``interval_s`` has passed since
        the last one."""
        now = self.clock() if now is None else float(now)
        with self._lock:
            due = self._last_t is None or now - self._last_t >= self.interval_s
        if not due:
            return 0
        return self.sample(now)

    def ingest(self, t: float, samples: dict) -> int:
        """Replay one spilled tick (``{series_key: value}``) — the
        offline side of the spill format; fires listeners exactly like
        a live tick so alert replay is faithful."""
        rows = []
        for key, value in samples.items():
            name, labels = parse_series(str(key))
            rows.append((name, labels, "gauge", value))
        recorded = self._append_rows(float(t), rows)
        self._notify(float(t))
        return recorded

    def _append_rows(self, now: float, rows) -> int:
        cutoff = now - self.retention_s
        recorded = 0
        dropped = downsampled = 0
        with self._lock:
            self._last_t = now
            for name, labels, kind, value in rows:
                try:
                    v = float(value)
                except (TypeError, ValueError):
                    continue
                if not math.isfinite(v):
                    continue
                key = (name, tuple(sorted(labels.items())))
                series = self._series.get(key)
                if series is None:
                    if len(self._series) >= self.max_series:
                        dropped += 1
                        continue
                    series = _Series(name, labels, kind)
                    self._series[key] = series
                pts = series.points
                pts.append((now, v))
                while pts and pts[0][0] < cutoff:
                    pts.pop(0)
                if len(pts) > self.max_points:
                    # Decimate in place: drop every other point,
                    # keeping the newest — coarser past, bounded
                    # memory, nothing forgotten outright.
                    del pts[-2::-2]
                    downsampled += 1
                recorded += 1
        if dropped and self._dropped is not None:
            self._dropped.inc(dropped)
        if downsampled and self._downsamples is not None:
            self._downsamples.inc(downsampled)
        return recorded

    def _spill_tick(self, now: float, rows) -> None:
        if self._spill is None:
            return
        try:
            self._spill.write(
                "history_sample", t=round(now, 6),
                samples={
                    format_series(name, labels): value
                    for name, labels, _, value in rows
                },
            )
        except Exception:
            pass

    def _notify(self, now: float) -> None:
        for fn in self._listeners:
            try:
                fn(now)
            except Exception:
                pass

    # ---- sampler thread ----

    def start(self) -> "MetricsHistory":
        """Start the background sampler (idempotent). The loop waits on
        the stop event — injectable cadence in tests (call
        :meth:`sample` directly), drillable shutdown in production."""
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="tpuflow-obs-history", daemon=True
        )
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.sample()
            except Exception:
                pass
            self._stop.wait(self.interval_s)

    def stop(self, timeout: float = 5.0) -> None:
        """Stop and join the sampler; close the spill. Idempotent."""
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout)
        self._thread = None
        if self._spill is not None:
            try:
                self._spill.close()
            except Exception:
                pass

    # ---- queries ----

    def _resolve(self, name: str) -> str:
        with self._lock:
            if any(k[0] == name for k in self._series):
                return name
        ns = getattr(self.registry, "namespace", None) or "tpuflow"
        return f"{ns}_{name}"

    def all_series(self) -> list[dict]:
        """Every series with its points snapshotted — the replay/CLI
        view (``python -m tpuflow.obs history``)."""
        with self._lock:
            rows = [
                {
                    "name": s.name, "labels": dict(s.labels),
                    "kind": s.kind, "points": list(s.points),
                }
                for s in self._series.values()
            ]
        rows.sort(key=lambda r: (r["name"], sorted(r["labels"].items())))
        return rows

    def labelsets(self, name: str) -> list[dict]:
        """Every labelset seen for ``name`` (accepts the registry-
        namespaced or bare spelling, like ``Registry.peek``)."""
        full = self._resolve(name)
        with self._lock:
            return [
                dict(s.labels) for k, s in self._series.items()
                if k[0] == full
            ]

    def points(
        self, name: str, window_s: float | None = None,
        now: float | None = None, **labels,
    ) -> list[tuple[float, float]]:
        """The raw ``(t, value)`` points of one series, newest last,
        optionally restricted to the trailing window ending at ``now``
        (default: the last tick — deterministic under a fake clock)."""
        full = self._resolve(name)
        key = (full, tuple(sorted(labels.items())))
        with self._lock:
            series = self._series.get(key)
            pts = list(series.points) if series is not None else []
            last_t = self._last_t
        if window_s is None or not pts:
            return pts
        end = (
            float(now) if now is not None
            else (last_t if last_t is not None else pts[-1][0])
        )
        start = end - float(window_s)
        return [(t, v) for t, v in pts if start <= t <= end]

    def latest(self, name: str, **labels) -> float | None:
        pts = self.points(name, None, **labels)
        return pts[-1][1] if pts else None

    def delta(
        self, name: str, window_s: float, now: float | None = None, **labels
    ) -> float | None:
        """last - first over the window (a counter's raw growth)."""
        pts = self.points(name, window_s, now, **labels)
        if len(pts) < 2:
            return None
        return pts[-1][1] - pts[0][1]

    def rate(
        self, name: str, window_s: float, now: float | None = None, **labels
    ) -> float | None:
        """Per-second rate over the window: ``delta / elapsed`` between
        the first and last points inside it. Needs two points; a
        zero-elapsed window (same-tick points) returns None, never a
        division blowup."""
        pts = self.points(name, window_s, now, **labels)
        if len(pts) < 2:
            return None
        dt = pts[-1][0] - pts[0][0]
        if dt <= 0:
            return None
        return (pts[-1][1] - pts[0][1]) / dt

    def mean(
        self, name: str, window_s: float, now: float | None = None, **labels
    ) -> float | None:
        pts = self.points(name, window_s, now, **labels)
        if not pts:
            return None
        return sum(v for _, v in pts) / len(pts)

    def max(
        self, name: str, window_s: float, now: float | None = None, **labels
    ) -> float | None:
        pts = self.points(name, window_s, now, **labels)
        if not pts:
            return None
        return max(v for _, v in pts)

    def quantile(
        self, name: str, q: float, window_s: float,
        now: float | None = None, **labels,
    ) -> float | None:
        """Linear-interpolated quantile of the window's values."""
        pts = self.points(name, window_s, now, **labels)
        if not pts:
            return None
        values = sorted(v for _, v in pts)
        if len(values) == 1:
            return values[0]
        q = min(1.0, max(0.0, float(q)))
        pos = q * (len(values) - 1)
        lo = int(pos)
        hi = min(lo + 1, len(values) - 1)
        frac = pos - lo
        return values[lo] * (1.0 - frac) + values[hi] * frac

    def summary(self) -> dict:
        """The bounds and occupancy — the `history` slice of a JSON
        metrics view or a debug dump."""
        with self._lock:
            n_series = len(self._series)
            n_points = sum(len(s.points) for s in self._series.values())
            last_t = self._last_t
        return {
            "series": n_series,
            "points": n_points,
            "max_series": self.max_series,
            "max_points": self.max_points,
            "interval_s": self.interval_s,
            "retention_s": self.retention_s,
            "last_sample_t": last_t,
        }
