"""Chrome trace-event export: any tpuflow span trail, loadable in Perfetto.

``python -m tpuflow.obs timeline <trail.jsonl> -o trace.json`` converts
the span records every tpuflow sink writes — a training run's
``metrics.jsonl`` (ingest/step/eval/checkpoint spans, xla.compile
recompile spans), a crash dump's ``forensics.jsonl``, a serve journal's
``predict.dispatch`` spans — into the Chrome trace-event JSON format
(https://ui.perfetto.dev loads it directly; chrome://tracing too).

Span records carry an END wall-clock ``time`` and a ``duration_s``
(they are emitted when the timed block finishes), so each becomes one
complete ``"ph": "X"`` event at ``ts = time - duration_s``, normalized
to the trail's earliest span start. Point events worth seeing on the
timeline (``numerics_anomaly``, ``lr_halved``, ``fault_injected``,
``forensics_dump``) become instant ``"ph": "i"`` marks. Events are
sorted by ``ts``; thread-name metadata rows group spans into train /
serving / xla lanes.

Deliberately dependency-light (no jax import): usable on a machine that
only has the log files.
"""

from __future__ import annotations

import json
import math

from tpuflow.obs.trail import read_events

# Span-name prefix -> (tid, lane name). Longest match wins; unmatched
# names land in the "other" lane rather than being dropped.
_LANES = (
    ("predict", 2, "serving"),
    ("serve", 2, "serving"),
    ("xla", 3, "xla"),
    ("autotune", 4, "autotune"),
    ("elastic", 5, "elastic"),
    ("online", 6, "online"),
    ("drift", 6, "online"),
    ("flight", 8, "obs"),
)
_TRAIN_TID, _OTHER_TID = 1, 9
_AUTOTUNE_TID = 4
_TRAIN_NAMES = {"ingest", "step", "eval", "checkpoint"}
_INSTANT_EVENTS = {
    "numerics_anomaly", "lr_halved", "fault_injected", "forensics_dump",
    "supervisor_attempt_died", "autotune_freeze", "autotune_revert",
    # Fleet-lifecycle marks (tpuflow/obs/fleet.py): the drift ->
    # retrain -> swap -> reload chain and gang membership churn line up
    # against the spans of the processes they happened in.
    "drift_anomaly", "online_retrain", "online_swap", "online_rollback",
    "artifact_swap", "artifact_rollback", "serve_reload",
    "elastic_worker_evicted", "elastic_worker_rejoined",
    "elastic_stale_push_rejected",
    # Flight-recorder captures (tpuflow/obs/flight.py): an alert or
    # crash froze a forensic bundle here — the mark names the bundle to
    # open next to the spans around it.
    "flight_capture",
}
_PID = 1


def _lane(name: str) -> tuple[int, str]:
    if name in _TRAIN_NAMES:
        return _TRAIN_TID, "train"
    for prefix, tid, lane in _LANES:
        if name.startswith(prefix):
            return tid, lane
    return _OTHER_TID, "other"


def _finite(v):
    """Non-finite floats become strings: an inf_loss anomaly's value IS
    infinity, and ``json.dump`` would write a bare ``Infinity`` token —
    invalid per RFC 8259, rejected by Perfetto, exactly when the anomaly
    marks are the thing the user opened the trace to see."""
    if isinstance(v, float) and not math.isfinite(v):
        return repr(v)
    return v


def _args(rec: dict) -> dict:
    """Everything the record carries beyond the envelope, for Perfetto's
    detail pane (epoch, trace_id, shapes, ...)."""
    return {
        k: _finite(v) for k, v in rec.items()
        if k not in ("event", "time", "ts", "seq", "name", "duration_s")
        and v is not None
    }


def split_events(events: list[dict]) -> tuple[list[dict], list[dict]]:
    """``(spans, instants)`` with a finite time envelope — the shared
    classification the single-trail exporter and the fleet merger
    (``tpuflow/obs/fleet.py``) both build on."""
    spans, instants = [], []
    for rec in events:
        kind = rec.get("event")
        t = rec.get("time")
        # Finite-only envelope: a NaN time/duration would poison ts/dur
        # into tokens JSON cannot carry (anomaly VALUES may be non-finite
        # — _finite stringifies those in args).
        if not isinstance(t, (int, float)) or not math.isfinite(t):
            continue
        dur = rec.get("duration_s")
        if kind == "span" and isinstance(dur, (int, float)) and (
            math.isfinite(dur)
        ):
            spans.append(rec)
        elif kind in _INSTANT_EVENTS:
            instants.append(rec)
    return spans, instants


def earliest_start(events: list[dict]) -> float | None:
    """The trail's earliest span start / instant time (the ``ts=0``
    anchor), or None for a trail with nothing drawable."""
    spans, instants = split_events(events)
    starts = [r["time"] - r["duration_s"] for r in spans]
    starts += [r["time"] for r in instants]
    return min(starts) if starts else None


def to_trace_events(
    events: list[dict], *, pid: int = _PID, base: float | None = None
) -> dict:
    """Convert parsed trail records into a Chrome trace-event document:
    ``{"traceEvents": [...], "displayTimeUnit": "ms"}``. Spans become
    complete ``X`` events (microsecond ``ts``/``dur``, sorted by
    ``ts``); known point events become instant ``i`` marks; metadata
    ``M`` rows (emitted first) name the lanes. ``pid``/``base`` let the
    fleet merger give each process its own lane group while normalizing
    every trail against ONE fleet-wide time zero."""
    spans, instants = split_events(events)
    if not spans and not instants:
        return {"traceEvents": [], "displayTimeUnit": "ms"}
    if base is None:
        base = earliest_start(events)

    out: list[dict] = []
    lanes_used: dict[int, str] = {}
    for rec in spans:
        name = str(rec.get("name", "span"))
        tid, lane = _lane(name)
        lanes_used[tid] = lane
        out.append({
            "name": name,
            "cat": lane,
            "ph": "X",
            "ts": round((rec["time"] - rec["duration_s"] - base) * 1e6, 3),
            "dur": round(float(rec["duration_s"]) * 1e6, 3),
            "pid": pid,
            "tid": tid,
            "args": _args(rec),
        })
    for rec in instants:
        # Marks follow their subject: a fault injected at a serving
        # site must line up with the dispatch spans it interrupted,
        # not sit in the train lane — and the tuner's freeze/revert
        # marks sit in the autotune lane with the autotune.step spans
        # whose trajectory they punctuate.
        name = str(rec.get("event", ""))
        site = str(rec.get("site", ""))
        if name.startswith("autotune"):
            tid, lane = _AUTOTUNE_TID, "autotune"
        else:
            # A sited mark follows its subject; otherwise the event
            # NAME's own prefix routes it (online_/elastic_/serve_
            # lifecycle marks sit with their subsystem's spans), and
            # anything unrecognized defaults to the train lane.
            tid, lane = _lane(site) if site else _lane(name)
        if lane == "other":
            tid, lane = _TRAIN_TID, "train"
        lanes_used.setdefault(tid, lane)
        out.append({
            "name": str(rec["event"]),
            "cat": "marker",
            "ph": "i",
            "s": "p",  # process-scoped mark: visible across the lanes
            "ts": round((rec["time"] - base) * 1e6, 3),
            "pid": pid,
            "tid": tid,
            "args": _args(rec),
        })
    out.sort(key=lambda e: (e["ts"], e.get("dur", 0.0)))
    meta = [
        {
            "name": "thread_name",
            "ph": "M",
            "pid": pid,
            "tid": tid,
            "args": {"name": lane},
        }
        for tid, lane in sorted(lanes_used.items())
    ]
    return {"traceEvents": meta + out, "displayTimeUnit": "ms"}


def export_timeline(trail_path: str, out_path: str) -> dict:
    """Read ``trail_path`` (tolerantly — torn lines are skipped, not
    fatal) and write the trace-event JSON to ``out_path``. Returns
    ``{"events", "spans", "skipped_lines"}`` for the caller's report."""
    events, skipped = read_events(trail_path)
    doc = to_trace_events(events)
    n_spans = sum(1 for e in doc["traceEvents"] if e.get("ph") == "X")
    with open(out_path, "w", encoding="utf-8") as f:
        json.dump(doc, f)
        f.write("\n")
    return {
        "events": len(doc["traceEvents"]),
        "spans": n_spans,
        "skipped_lines": skipped,
    }
