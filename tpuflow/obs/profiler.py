"""Continuous wall-clock sampling profiler (stdlib-only).

A daemon thread walks ``sys._current_frames()`` on a fixed cadence and
aggregates **folded stacks** (``module:function`` frames joined with ``;``)
keyed by *component* — derived from thread names, which is why every
thread in the package carries an explicit ``tpuflow-*`` name (TPF023).
Samples are classified **busy** vs **idle** by the leaf Python frame: a
thread parked in a wait primitive (``threading``, ``queue``, ``selectors``,
``socket``, ``asyncio`` …) is idle; everything else — including
``time.sleep``, whose Python-visible leaf is the *caller* — counts as
busy wall-clock. Component shares and regression verdicts rank by busy
samples so parked worker pools do not drown out the thread that is
actually burning the budget.

The aggregate is bounded (``max_stacks`` distinct folded stacks; overflow
is counted, never grows memory), snapshots are plain JSON documents under
schema ``tpuflow.obs.profile/v1``, and two snapshots can be ``merge``d or
``diff``ed — the diff emits a deterministic per-component share delta and
an overall ``regression``/``ok`` verdict used by ``obs profile --diff``.
Cumulative snapshots can be spilled as JSONL through
:class:`tpuflow.utils.logging.MetricsLogger` (latest record wins on
replay).

Everything is off by default; ``profiler_from_env`` wires the
``TPUFLOW_OBS_PROFILE_*`` knobs (validated via :mod:`tpuflow.utils.env`).
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time

from tpuflow.utils.env import env_flag, env_num

SNAPSHOT_SCHEMA = "tpuflow.obs.profile/v1"
DIFF_SCHEMA = "tpuflow.obs.profile_diff/v1"

DEFAULT_INTERVAL_S = 0.05
DEFAULT_MAX_STACKS = 512
DEFAULT_SPILL_EVERY_S = 30.0
DEFAULT_DIFF_THRESHOLD = 0.05

_MAX_FRAMES = 48
_OVERFLOW_STACK = "<overflow>"

# Thread-name prefix -> component, first match wins (ordered most-specific
# first so "tpuflow-serve-autoscale" does not land in "serving").
_COMPONENTS: tuple[tuple[str, str], ...] = (
    ("tpuflow-serve-autoscale", "autoscaler"),
    ("tpuflow-runtime-online", "online"),
    ("tpuflow-runtime-gang", "gang"),
    ("tpuflow-runtime-autoscale", "autoscaler"),
    ("tpuflow-runtime-traffic", "traffic"),
    ("tpuflow-runtime", "supervisor"),
    ("tpuflow-online", "online"),
    ("tpuflow-elastic", "gang"),
    ("tpuflow-lane", "batcher"),
    ("tpuflow-microbatch", "batcher"),
    ("tpuflow-prep", "serving"),
    ("tpuflow-serve", "serving"),
    ("tpuflow-jobs", "jobs"),
    ("tpuflow-data", "data"),
    ("tpuflow-obs", "obs"),
    ("tpuflow-soak", "traffic"),
    ("MainThread", "main"),
)

# Leaf-frame modules that mean "parked, not burning wall-clock".
_WAIT_MODULES = frozenset(
    {
        "threading",
        "queue",
        "selectors",
        "socket",
        "socketserver",
        "ssl",
        "subprocess",
    }
)
_WAIT_PREFIXES = ("asyncio", "concurrent.futures", "multiprocessing")


def component_for(thread_name: str) -> str:
    """Map a thread name to its profiling component (``other`` if unknown)."""
    for prefix, component in _COMPONENTS:
        if thread_name.startswith(prefix):
            return component
    return "other"


def _frame_module(frame) -> str:
    mod = frame.f_globals.get("__name__")
    if isinstance(mod, str) and mod:
        return mod
    base = os.path.basename(frame.f_code.co_filename)
    return base[:-3] if base.endswith(".py") else base


def _is_wait_module(module: str) -> bool:
    top = module.split(".", 1)[0]
    return top in _WAIT_MODULES or any(top == p.split(".")[0] for p in _WAIT_PREFIXES)


def fold_frame(frame) -> tuple[str, bool]:
    """Fold a frame chain into ``mod:func;…;leaf`` text plus an idle flag."""
    parts: list[str] = []
    leaf_module = ""
    f = frame
    while f is not None:
        module = _frame_module(f)
        if not leaf_module:
            leaf_module = module
        parts.append(f"{module}:{f.f_code.co_name}")
        f = f.f_back
    parts.reverse()
    if len(parts) > _MAX_FRAMES:
        parts = ["<truncated>"] + parts[-_MAX_FRAMES:]
    return ";".join(parts), _is_wait_module(leaf_module)


class SamplingProfiler:
    """Wall-clock sampler over ``sys._current_frames()``.

    ``include`` (thread-name prefixes) scopes sampling to one subsystem's
    threads — essential when several planes share a process (the soak) and
    a serving-side profile must not be dominated by training compute.
    ``None`` samples every thread except the sampler itself.
    """

    def __init__(
        self,
        interval_s: float = DEFAULT_INTERVAL_S,
        *,
        max_stacks: int = DEFAULT_MAX_STACKS,
        include: tuple[str, ...] | None = None,
        registry=None,
        spill_path: str | None = None,
        spill_every_s: float = DEFAULT_SPILL_EVERY_S,
    ):
        if interval_s <= 0:
            raise ValueError(f"interval_s must be > 0, got {interval_s!r}")
        if max_stacks < 1:
            raise ValueError(f"max_stacks must be >= 1, got {max_stacks!r}")
        self.interval_s = float(interval_s)
        self.max_stacks = int(max_stacks)
        self.include = tuple(include) if include is not None else None
        self.spill_every_s = float(spill_every_s)
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._started_unix = time.time()
        # (component, folded) -> [count, idle]; bounded by max_stacks.
        self._stacks: dict[tuple[str, str], list] = {}
        # component -> [samples, busy]
        self._components: dict[str, list] = {}
        self._ticks = 0
        self._thread_samples = 0
        self._dropped = 0
        self._overhead_s = 0.0
        self._spill = None
        if spill_path:
            from tpuflow.utils.logging import MetricsLogger

            self._spill = MetricsLogger(spill_path)
        self._m_samples = None
        self._m_stacks = None
        self._m_dropped = None
        self._m_overhead = None
        if registry is not None:
            self._m_samples = registry.counter(
                "obs_profiler_samples_total",
                "Thread samples aggregated by the sampling profiler",
            )
            self._m_stacks = registry.gauge(
                "obs_profiler_stacks",
                "Distinct folded stacks currently held by the profiler",
            )
            self._m_dropped = registry.counter(
                "obs_profiler_dropped_stacks_total",
                "Samples folded into the overflow bucket because max_stacks was hit",
            )
            self._m_overhead = registry.counter(
                "obs_profiler_overhead_seconds_total",
                "Wall-clock seconds the profiler spent walking frames",
            )

    # -- sampling -------------------------------------------------------

    def start(self) -> "SamplingProfiler":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="tpuflow-obs-profiler", daemon=True
        )
        self._thread.start()
        return self

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=timeout)
            self._thread = None
        spill = self._spill
        if spill is not None:
            try:
                spill.write("profile_snapshot", snapshot=self.snapshot())
                spill.close()
            except Exception:
                pass
            self._spill = None

    def _run(self) -> None:
        last_spill = time.monotonic()
        while not self._stop.is_set():
            try:
                self.sample()
            except Exception:
                pass
            if self._spill is not None and self.spill_every_s > 0:
                now = time.monotonic()
                if now - last_spill >= self.spill_every_s:
                    last_spill = now
                    try:
                        self._spill.write("profile_snapshot", snapshot=self.snapshot())
                    except Exception:
                        pass
            self._stop.wait(self.interval_s)

    def sample(self) -> int:
        """Take one sample pass; returns the number of threads sampled."""
        t0 = time.perf_counter()
        me = threading.get_ident()
        names = {}
        for t in threading.enumerate():
            if t.ident is not None:
                names[t.ident] = t.name
        frames = sys._current_frames()
        sampled = 0
        batch: list[tuple[str, str, bool]] = []
        for ident, frame in frames.items():
            if ident == me:
                continue
            name = names.get(ident, f"thread-{ident}")
            if name == "tpuflow-obs-profiler":
                continue
            if self.include is not None and not name.startswith(self.include):
                continue
            folded, idle = fold_frame(frame)
            batch.append((component_for(name), folded, idle))
            sampled += 1
        del frames
        with self._lock:
            self._ticks += 1
            self._thread_samples += sampled
            for component, folded, idle in batch:
                self._ingest_locked(component, folded, idle, 1)
            stacks = len(self._stacks)
        elapsed = time.perf_counter() - t0
        with self._lock:
            self._overhead_s += elapsed
        if self._m_samples is not None:
            self._m_samples.inc(sampled)
            self._m_stacks.set(stacks)
            self._m_overhead.inc(elapsed)
        return sampled

    def _ingest_locked(self, component: str, folded: str, idle: bool, n: int) -> None:
        comp = self._components.setdefault(component, [0, 0])
        comp[0] += n
        if not idle:
            comp[1] += n
        key = (component, folded)
        slot = self._stacks.get(key)
        if slot is None and len(self._stacks) >= self.max_stacks:
            # Bound hit: fold the sample into the per-component overflow
            # bucket (may overshoot the bound by one entry per component).
            self._dropped += n
            if self._m_dropped is not None:
                self._m_dropped.inc(n)
            key = (component, _OVERFLOW_STACK)
            idle = False
            slot = self._stacks.get(key)
        if slot is None:
            slot = self._stacks.setdefault(key, [0, idle])
        slot[0] += n

    # -- snapshots ------------------------------------------------------

    def snapshot(self) -> dict:
        """Cumulative state as a plain ``tpuflow.obs.profile/v1`` document."""
        with self._lock:
            stacks = [
                {"component": c, "stack": s, "count": v[0], "idle": bool(v[1])}
                for (c, s), v in self._stacks.items()
            ]
            components = {c: {"samples": v[0], "busy": v[1]} for c, v in self._components.items()}
            doc = {
                "schema": SNAPSHOT_SCHEMA,
                "started_unix": self._started_unix,
                "captured_unix": time.time(),
                "interval_s": self.interval_s,
                "ticks": self._ticks,
                "thread_samples": self._thread_samples,
                "dropped_stacks": self._dropped,
                "overhead_s": round(self._overhead_s, 6),
            }
        total_busy = sum(v["busy"] for v in components.values())
        for v in components.values():
            v["share"] = round(v["busy"] / total_busy, 6) if total_busy else 0.0
        stacks.sort(key=lambda r: (-r["count"], r["component"], r["stack"]))
        doc["components"] = dict(sorted(components.items()))
        doc["stacks"] = stacks
        return doc


def validate_snapshot(doc) -> list[str]:
    """Structural check; returns a list of problems (empty == valid)."""
    problems: list[str] = []
    if not isinstance(doc, dict):
        return ["snapshot is not an object"]
    if doc.get("schema") != SNAPSHOT_SCHEMA:
        problems.append(f"schema is {doc.get('schema')!r}, want {SNAPSHOT_SCHEMA!r}")
    if not isinstance(doc.get("components"), dict):
        problems.append("components missing or not an object")
    if not isinstance(doc.get("stacks"), list):
        problems.append("stacks missing or not a list")
    else:
        for i, rec in enumerate(doc["stacks"]):
            if not isinstance(rec, dict) or not {"component", "stack", "count"} <= set(rec):
                problems.append(f"stacks[{i}] malformed")
                break
    return problems


def top_component(doc: dict) -> str | None:
    """Component with the most *busy* wall-clock samples, or None if all idle."""
    best, best_busy = None, 0
    for name, rec in sorted((doc.get("components") or {}).items()):
        busy = rec.get("busy", 0)
        if busy > best_busy:
            best, best_busy = name, busy
    return best


def merge_snapshots(a: dict, b: dict) -> dict:
    """Sum two snapshots (same schema) into one."""
    for doc in (a, b):
        problems = validate_snapshot(doc)
        if problems:
            raise ValueError(f"cannot merge invalid snapshot: {problems[0]}")
    components: dict[str, dict] = {}
    for doc in (a, b):
        for name, rec in doc["components"].items():
            slot = components.setdefault(name, {"samples": 0, "busy": 0})
            slot["samples"] += rec.get("samples", 0)
            slot["busy"] += rec.get("busy", 0)
    stacks: dict[tuple[str, str], dict] = {}
    for doc in (a, b):
        for rec in doc["stacks"]:
            key = (rec["component"], rec["stack"])
            slot = stacks.setdefault(
                key,
                {
                    "component": rec["component"],
                    "stack": rec["stack"],
                    "count": 0,
                    "idle": bool(rec.get("idle", False)),
                },
            )
            slot["count"] += rec["count"]
    total_busy = sum(v["busy"] for v in components.values())
    for v in components.values():
        v["share"] = round(v["busy"] / total_busy, 6) if total_busy else 0.0
    merged_stacks = sorted(
        stacks.values(), key=lambda r: (-r["count"], r["component"], r["stack"])
    )
    return {
        "schema": SNAPSHOT_SCHEMA,
        "started_unix": min(a.get("started_unix", 0), b.get("started_unix", 0)),
        "captured_unix": max(a.get("captured_unix", 0), b.get("captured_unix", 0)),
        "interval_s": a.get("interval_s"),
        "ticks": a.get("ticks", 0) + b.get("ticks", 0),
        "thread_samples": a.get("thread_samples", 0) + b.get("thread_samples", 0),
        "dropped_stacks": a.get("dropped_stacks", 0) + b.get("dropped_stacks", 0),
        "overhead_s": round(a.get("overhead_s", 0.0) + b.get("overhead_s", 0.0), 6),
        "components": dict(sorted(components.items())),
        "stacks": merged_stacks,
    }


def diff_snapshots(base: dict, new: dict, *, threshold: float = DEFAULT_DIFF_THRESHOLD) -> dict:
    """Compare busy-share per component; verdict ``regression`` when any
    component's share of busy wall-clock grew by more than ``threshold``."""
    for label, doc in (("base", base), ("new", new)):
        problems = validate_snapshot(doc)
        if problems:
            raise ValueError(f"{label} snapshot invalid: {problems[0]}")
    names = sorted(set(base["components"]) | set(new["components"]))
    rows = []
    for name in names:
        b = base["components"].get(name, {}).get("share", 0.0)
        n = new["components"].get(name, {}).get("share", 0.0)
        rows.append(
            {
                "component": name,
                "base_share": round(b, 6),
                "new_share": round(n, 6),
                "delta": round(n - b, 6),
            }
        )
    rows.sort(key=lambda r: (-r["delta"], r["component"]))
    regressions = [r["component"] for r in rows if r["delta"] > threshold]
    return {
        "schema": DIFF_SCHEMA,
        "threshold": threshold,
        "base_top": top_component(base),
        "new_top": top_component(new),
        "components": rows,
        "regressions": regressions,
        "verdict": "regression" if regressions else "ok",
    }


# -- rendering ----------------------------------------------------------


def render_folded(doc: dict) -> str:
    """Flamegraph-ready folded-stack text (``component;frames count``)."""
    lines = [f"{r['component']};{r['stack']} {r['count']}" for r in doc.get("stacks", [])]
    return "\n".join(lines)


def _frame_table(doc: dict, top: int) -> list[tuple[str, int, int]]:
    self_counts: dict[str, int] = {}
    cum_counts: dict[str, int] = {}
    for rec in doc.get("stacks", []):
        if rec.get("idle"):
            continue
        frames = rec["stack"].split(";")
        count = rec["count"]
        self_counts[frames[-1]] = self_counts.get(frames[-1], 0) + count
        for frame in set(frames):
            cum_counts[frame] = cum_counts.get(frame, 0) + count
    rows = [
        (frame, self_counts.get(frame, 0), cum)
        for frame, cum in cum_counts.items()
    ]
    rows.sort(key=lambda r: (-r[1], -r[2], r[0]))
    return rows[:top]


def render_profile(doc: dict, *, top: int = 15) -> str:
    """Human-readable component table + top-N self/cumulative frames."""
    out = []
    busy_total = sum(v.get("busy", 0) for v in doc.get("components", {}).values())
    out.append(
        f"profile  ticks={doc.get('ticks', 0)}  thread_samples={doc.get('thread_samples', 0)}"
        f"  busy={busy_total}  interval={doc.get('interval_s')}s"
        f"  overhead={doc.get('overhead_s', 0.0)}s  dropped={doc.get('dropped_stacks', 0)}"
    )
    out.append("")
    out.append(f"{'component':<12} {'samples':>8} {'busy':>8} {'busy-share':>10}")
    comps = sorted(
        doc.get("components", {}).items(), key=lambda kv: (-kv[1].get("busy", 0), kv[0])
    )
    for name, rec in comps:
        out.append(
            f"{name:<12} {rec.get('samples', 0):>8} {rec.get('busy', 0):>8}"
            f" {rec.get('share', 0.0):>9.1%}"
        )
    rows = _frame_table(doc, top)
    if rows:
        out.append("")
        out.append(f"{'self':>8} {'cum':>8}  frame (busy samples, top {top})")
        for frame, self_n, cum_n in rows:
            out.append(f"{self_n:>8} {cum_n:>8}  {frame}")
    return "\n".join(out)


def render_diff(doc: dict) -> str:
    out = [
        f"profile diff  verdict={doc['verdict']}  threshold={doc['threshold']:.1%}"
        f"  base_top={doc.get('base_top')}  new_top={doc.get('new_top')}"
    ]
    out.append("")
    out.append(f"{'component':<12} {'base':>8} {'new':>8} {'delta':>8}")
    for row in doc["components"]:
        marker = "  << regression" if row["component"] in doc["regressions"] else ""
        out.append(
            f"{row['component']:<12} {row['base_share']:>7.1%} {row['new_share']:>7.1%}"
            f" {row['delta']:>+7.1%}{marker}"
        )
    return "\n".join(out)


def load_snapshot(path: str) -> dict:
    """Load a snapshot from a JSON file or a JSONL spill (latest record wins)."""
    with open(path, encoding="utf-8") as fh:
        text = fh.read()
    text = text.strip()
    if not text:
        raise ValueError(f"{path}: empty file")
    try:
        doc = json.loads(text)
    except ValueError:
        doc = None
    if isinstance(doc, dict):
        if doc.get("event") == "profile_snapshot":
            snap = doc.get("snapshot")
        elif "event" in doc:
            # A one-record JSONL trail of some other event kind.
            raise ValueError(f"{path}: no profile_snapshot records found")
        else:
            snap = doc
        problems = validate_snapshot(snap)
        if problems:
            raise ValueError(f"{path}: {problems[0]}")
        return snap
    snap = None
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if isinstance(rec, dict) and rec.get("event") == "profile_snapshot":
            candidate = rec.get("snapshot")
            if isinstance(candidate, dict) and not validate_snapshot(candidate):
                snap = candidate
    if snap is None:
        raise ValueError(f"{path}: no profile_snapshot records found")
    return snap


def profiler_from_env(
    registry=None, *, include: tuple[str, ...] | None = None
) -> SamplingProfiler | None:
    """Build a profiler from ``TPUFLOW_OBS_PROFILE_*`` knobs; None when off."""
    if not env_flag("TPUFLOW_OBS_PROFILE", False):
        return None
    return SamplingProfiler(
        env_num("TPUFLOW_OBS_PROFILE_INTERVAL_S", DEFAULT_INTERVAL_S, float, minimum=1e-4),
        max_stacks=env_num("TPUFLOW_OBS_PROFILE_MAX_STACKS", DEFAULT_MAX_STACKS, int, minimum=1),
        include=include,
        registry=registry,
        spill_path=os.environ.get("TPUFLOW_OBS_PROFILE_SPILL") or None,
        spill_every_s=env_num(
            "TPUFLOW_OBS_PROFILE_SPILL_EVERY_S", DEFAULT_SPILL_EVERY_S, float, minimum=0.1
        ),
    )
