"""Unified telemetry: metrics registry, span tracing, Prometheus, forensics.

Four legs (docs/observability.md), replacing the four disconnected
fragments that grew ad hoc (``utils/logging.MetricsLogger``,
``utils/profiling.StepTimer``, the hand-rolled ``/metrics`` dicts in
``serve.py``, the supervisor's progress file — all of which remain, now
wired into one substrate):

- ``metrics``    — :class:`Registry` of counters/gauges/histograms/
  summaries; a process-wide default (framework signals) plus run-scoped
  instances (services). Lock-cheap; never record inside jit (TPF005).
- ``tracing``    — run/trace IDs + ``span(...)`` events, propagated
  from a ``/predict`` request through the MicroBatcher's coalesced
  dispatch and from ``train()`` through the fit loop's JSONL.
- ``prometheus`` — ``render_prometheus(*registries)`` text exposition,
  served at ``GET /metrics?format=prometheus``.
- ``forensics``  — bounded event ring dumped to ``forensics.jsonl`` on
  unhandled failure / crash-loop classification;
  ``python -m tpuflow.obs tail|summary <file>`` reads any event trail.

Plus the interpretation layer on top of the substrate:

- ``health``     — numerics watchdog (NaN/Inf/spike over per-epoch
  loss/grad aux; warn|abort|halve_lr policies, the typed
  :class:`NumericsDivergence` the supervisor treats as terminal),
  recompile detector (per-step signature churn + the process-wide
  ``jax.monitoring`` compile counter), live MFU/roofline gauges.
- ``timeline``   — Chrome trace-event export of any span trail
  (``python -m tpuflow.obs timeline <jsonl> -o trace.json``), loadable
  in Perfetto.
- ``history``    — :class:`MetricsHistory`: bounded time-series rings
  sampled from a Registry on an injectable-clock cadence, windowed
  queries (rate/mean/max/quantile/delta), JSONL spill for offline
  replay (``python -m tpuflow.obs history``).
- ``alerts``     — :class:`AlertEngine`: declarative threshold +
  ``for_s`` hold-down rules over history windows, firing/resolved
  lifecycle into forensics/trail/``obs_alerts_firing`` gauges; the SLO
  objectives import as burn-rate rules
  (:func:`rules_from_objectives`).
- ``profiler``   — :class:`SamplingProfiler`: stdlib wall-clock sampler
  over ``sys._current_frames()``, folded stacks keyed by component
  (thread-name derived), busy/idle split, snapshot merge/diff with
  regression verdicts (``python -m tpuflow.obs profile``).
- ``flight``     — :class:`FlightRecorder`: alert/crash-triggered
  atomic forensic bundles (threads + profile + history window + alerts
  + registry + env) through the storage seam
  (``python -m tpuflow.obs flight``).
"""

from tpuflow.obs.alerts import (
    AlertEngine,
    rules_from_objectives,
    validate_rules,
)
from tpuflow.obs.flight import FlightRecorder, flight_from_env, validate_bundle
from tpuflow.obs.forensics import (
    clear_events,
    dump_forensics,
    recent_events,
    record_event,
)
from tpuflow.obs.health import (
    HEALTH_POLICIES,
    NumericsDivergence,
    NumericsWatchdog,
    RecompileDetector,
    install_compile_listener,
    publish_roofline,
)
from tpuflow.obs.history import MetricsHistory
from tpuflow.obs.metrics import (
    DEFAULT_COUNT_BUCKETS,
    DEFAULT_TIME_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    Registry,
    Summary,
    default_registry,
)
from tpuflow.obs.profiler import (
    SamplingProfiler,
    diff_snapshots,
    merge_snapshots,
    profiler_from_env,
)
from tpuflow.obs.prometheus import render_prometheus
from tpuflow.obs.tracing import (
    TRACE_ENV,
    clean_trace_id,
    current_trace_id,
    new_trace_id,
    record_span,
    span,
    trace_from_env,
    use_trace,
)

__all__ = [
    "DEFAULT_COUNT_BUCKETS",
    "DEFAULT_TIME_BUCKETS",
    "HEALTH_POLICIES",
    "AlertEngine",
    "Counter",
    "FlightRecorder",
    "Gauge",
    "Histogram",
    "MetricsHistory",
    "NumericsDivergence",
    "NumericsWatchdog",
    "RecompileDetector",
    "Registry",
    "SamplingProfiler",
    "Summary",
    "TRACE_ENV",
    "clean_trace_id",
    "clear_events",
    "current_trace_id",
    "default_registry",
    "diff_snapshots",
    "dump_forensics",
    "flight_from_env",
    "install_compile_listener",
    "merge_snapshots",
    "new_trace_id",
    "profiler_from_env",
    "publish_roofline",
    "recent_events",
    "record_event",
    "record_span",
    "render_prometheus",
    "rules_from_objectives",
    "span",
    "trace_from_env",
    "use_trace",
    "validate_bundle",
    "validate_rules",
]
