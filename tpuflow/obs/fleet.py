"""Fleet aggregation: every process's trail, one merged timeline.

A multi-process tpuflow deployment — elastic workers under supervisors,
the averaging coordinator, serving daemons, the online retrain loop —
leaves one JSONL trail per process under the shared storage root
(workers' ``metrics.jsonl``, the coordinator's
``coordinator-metrics.jsonl``, the online loop's
``online/metrics.jsonl``, daemon trails, ``forensics*.jsonl`` crash
dumps). Each is readable alone (``python -m tpuflow.obs summary``); the
BigDL lesson (PAPERS.md) is that a distributed job is debuggable only
from the merged, driver-side view. This module builds it:

- :func:`discover_trails` walks storage roots and finds every ``*.jsonl``
  trail, naming each process lane from its relative path
  (``worker0/metrics``, ``elastic/coordinator-metrics``, ...).
- :func:`merge_fleet` reads them all (tolerantly — ``trail.py``; torn
  lines are counted, never fatal), normalizes every trail against ONE
  fleet-wide time zero, and emits a single Chrome trace-event document:
  one ``pid`` (lane group) per process, plus **trace-id flow arrows**
  (``ph: s/t/f``) connecting the spans/marks of any trace id observed
  in more than one process — a worker's push visibly flows into the
  coordinator's averaging round; a drift window flows through retrain,
  swap, and the daemon's reload.
- :func:`fleet_summary` rolls the same trails up per process (events,
  span time by name, anomalies, faults, trace ids) plus the
  cross-process trace table — the two-second answer to "what did the
  FLEET do".

Deliberately dependency-light (no jax import): usable on a machine that
only has the log files. ``python -m tpuflow.obs fleet <dir...>`` is the
shell entry; the SLO report card over the same merged events lives in
``tpuflow/obs/slo.py`` (``python -m tpuflow.obs slo``).
"""

from __future__ import annotations

import json
import os

from tpuflow.obs.timeline import (
    earliest_start,
    split_events,
    to_trace_events,
)
from tpuflow.obs.trail import read_events

# Filenames that are JSONL but NOT event trails (job journals hold
# request/job records the timeline cannot draw; they still merge fine —
# non-span records are simply not drawable — so this is only a naming
# nicety, not a correctness filter).
_TRAIL_SUFFIX = ".jsonl"


def iter_jsonl(root: str) -> list[str]:
    """Every ``*.jsonl`` under ``root``, deterministically ordered —
    THE one directory walk trail discovery uses (``discover_trails``
    here, ``python -m tpuflow.obs tail|summary`` for directory
    arguments), so every consumer agrees on what a storage root
    contains."""
    out = []
    for dirpath, dirs, files in sorted(os.walk(root)):
        dirs.sort()
        out.extend(
            os.path.join(dirpath, fn) for fn in sorted(files)
            if fn.endswith(_TRAIL_SUFFIX)
        )
    return out


def event_time_key(rec: dict):
    """Sort key for merged fleet events: by timestamp, records without
    a finite time first — shared by every multi-trail reader."""
    t = rec.get("time")
    return t if isinstance(t, (int, float)) else float("-inf")


def discover_trails(roots) -> list[dict]:
    """Every ``*.jsonl`` under each root (a file argument names itself),
    as ``{"path", "process"}`` — ``process`` is the lane label, derived
    from the path relative to its root (extension dropped; a bare
    ``metrics`` at the root keeps its directory's name for context)."""
    if isinstance(roots, (str, os.PathLike)):
        roots = [roots]
    out, seen = [], set()
    for root in roots:
        root = os.fspath(root)
        if os.path.isfile(root):
            path = os.path.abspath(root)
            if path not in seen:
                seen.add(path)
                out.append({
                    "path": path,
                    "process": os.path.splitext(os.path.basename(path))[0],
                })
            continue
        for found in iter_jsonl(root):
            path = os.path.abspath(found)
            if path in seen:
                continue
            seen.add(path)
            rel = os.path.relpath(path, root)
            process = os.path.splitext(rel)[0].replace(os.sep, "/")
            out.append({"path": path, "process": process})
    return out


def read_fleet(roots) -> tuple[list[dict], list[dict]]:
    """``(trails, all_events)``: each trail dict grows ``events`` and
    ``skipped_lines``; ``all_events`` is every record across the fleet,
    sorted by time (records without a finite time sort first)."""
    trails = discover_trails(roots)
    all_events: list[dict] = []
    for trail in trails:
        events, skipped = read_events(trail["path"])
        trail["events"] = events
        trail["skipped_lines"] = skipped
        all_events.extend(events)
    all_events.sort(key=event_time_key)
    return trails, all_events


def _trace_refs(rec: dict):
    """Every trace id a record REFERENCES: its own bound ``trace_id``,
    plus cross-process links carried as data — the coordinator's
    ``worker_traces`` map (an averaging round naming the pushing
    workers' traces) and singular ``worker_trace`` fields (staleness
    rejections). A record that names a trace belongs on that trace's
    flow arrow even when its own process had nothing bound."""
    tid = rec.get("trace_id")
    if tid:
        yield str(tid)
    wt = rec.get("worker_trace")
    if wt:
        yield str(wt)
    wts = rec.get("worker_traces")
    if isinstance(wts, dict):
        for v in wts.values():
            if v:
                yield str(v)


def _flow_events(trails: list[dict], base: float) -> list[dict]:
    """Chrome trace flow arrows (``ph`` s/t/f, one ``id`` per trace id)
    for every trace id that appears in MORE THAN ONE process: the
    cross-process causal links the propagation legs exist to create.
    Each arrow point binds to its process's lane at the record's
    timestamp; within one trace, points are ordered by time."""
    sightings: dict[str, list[tuple[float, int, dict]]] = {}
    for pid, trail in enumerate(trails, start=1):
        spans, instants = split_events(trail["events"])
        for is_span, recs in ((True, spans), (False, instants)):
            for rec in recs:
                t = rec["time"] - (rec["duration_s"] if is_span else 0.0)
                for tid in set(_trace_refs(rec)):
                    sightings.setdefault(tid, []).append((t, pid, rec))
    out = []
    for trace_id, points in sorted(sightings.items()):
        if len({pid for _, pid, _ in points}) < 2:
            continue
        points.sort(key=lambda p: p[0])
        # One arrow point per (process, trace): first sighting in each
        # process — N points per process would draw a hairball.
        first_in: dict[int, tuple[float, int, dict]] = {}
        for p in points:
            first_in.setdefault(p[1], p)
        chain = sorted(first_in.values(), key=lambda p: p[0])
        for i, (t, pid, rec) in enumerate(chain):
            ph = "s" if i == 0 else ("f" if i == len(chain) - 1 else "t")
            evt = {
                "name": f"trace {trace_id}",
                "cat": "trace",
                "ph": ph,
                "id": trace_id,
                "ts": round((t - base) * 1e6, 3),
                "pid": pid,
                "tid": _tid_of(rec),
            }
            if ph == "f":
                evt["bp"] = "e"  # bind to the enclosing slice
            out.append(evt)
    return out


def _tid_of(rec: dict) -> int:
    """The lane (tid) ``to_trace_events`` draws this record in — flow
    endpoints must anchor to the SAME lane as the span/mark they
    reference, so the routing mirrors the exporter's: spans by name;
    instants by ``site`` when set, else by event name, defaulting to
    the train lane."""
    from tpuflow.obs.timeline import _lane

    if rec.get("event") == "span":
        return _lane(str(rec.get("name", "")))[0]
    site = str(rec.get("site", ""))
    tid, lane = _lane(site) if site else _lane(str(rec.get("event", "")))
    return 1 if lane == "other" else tid


def merge_fleet(roots) -> tuple[dict, dict]:
    """Merge every discovered trail into ONE Chrome trace-event document
    (per-process lane groups, fleet-wide time zero, trace-id flow
    arrows) and the fleet summary JSON. Returns ``(doc, summary)``."""
    trails, all_events = read_fleet(roots)
    bases = [
        b for b in (earliest_start(t["events"]) for t in trails)
        if b is not None
    ]
    if not bases:
        return (
            {"traceEvents": [], "displayTimeUnit": "ms"},
            fleet_summary(trails, all_events),
        )
    base = min(bases)
    merged: list[dict] = []
    for pid, trail in enumerate(trails, start=1):
        doc = to_trace_events(trail["events"], pid=pid, base=base)
        if doc["traceEvents"]:
            merged.append({
                "name": "process_name",
                "ph": "M",
                "pid": pid,
                "args": {"name": trail["process"]},
            })
            merged.extend(doc["traceEvents"])
    merged.extend(_flow_events(trails, base))
    return (
        {"traceEvents": merged, "displayTimeUnit": "ms"},
        fleet_summary(trails, all_events),
    )


def fleet_summary(trails: list[dict], all_events: list[dict]) -> dict:
    """Per-process rollups + the cross-process trace table."""
    processes = []
    trace_procs: dict[str, set] = {}
    for trail in trails:
        events = trail["events"]
        by_type: dict[str, int] = {}
        spans: dict[str, list] = {}
        traces = set()
        anomalies = faults = 0
        for rec in events:
            kind = str(rec.get("event", "?"))
            by_type[kind] = by_type.get(kind, 0) + 1
            for tid in set(_trace_refs(rec)):
                traces.add(tid)
                trace_procs.setdefault(tid, set()).add(trail["process"])
            if kind == "span":
                name = str(rec.get("name", "?"))
                dur = rec.get("duration_s")
                n_total = spans.setdefault(name, [0, 0.0])
                n_total[0] += 1
                if isinstance(dur, (int, float)):
                    n_total[1] += float(dur)
            elif kind in ("numerics_anomaly", "drift_anomaly"):
                anomalies += 1
            elif kind == "fault_injected":
                faults += 1
        processes.append({
            "process": trail["process"],
            "path": trail["path"],
            "events": len(events),
            "skipped_lines": trail["skipped_lines"],
            "by_event": dict(sorted(by_type.items())),
            "spans": {
                name: {"n": n, "total_s": round(total, 6)}
                for name, (n, total) in sorted(spans.items())
            },
            "anomalies": anomalies,
            "faults": faults,
            "trace_ids": len(traces),
        })
    cross = {
        tid: sorted(procs)
        for tid, procs in sorted(trace_procs.items())
        if len(procs) > 1
    }
    times = [
        r["time"] for r in all_events
        if isinstance(r.get("time"), (int, float))
    ]
    return {
        "processes": processes,
        "trails": len(trails),
        "events": len(all_events),
        "window_s": round(max(times) - min(times), 3) if times else 0.0,
        "cross_process_traces": cross,
    }


def export_fleet(roots, out_path: str) -> dict:
    """Read every trail under ``roots``, write the merged trace-event
    JSON to ``out_path``, and return the fleet summary (the CLI's
    report)."""
    doc, summary = merge_fleet(roots)
    with open(out_path, "w", encoding="utf-8") as f:
        json.dump(doc, f)
        f.write("\n")
    summary["timeline"] = {
        "path": out_path,
        "events": len(doc["traceEvents"]),
        "spans": sum(
            1 for e in doc["traceEvents"] if e.get("ph") == "X"
        ),
        "flows": sum(
            1 for e in doc["traceEvents"]
            if e.get("ph") in ("s", "t", "f")
        ),
    }
    return summary
