"""The SLO engine: declarative objectives, error budgets, burn rates.

The chaos-soak report card (ROADMAP item 5) needs substrate: something
that turns "the daemon served through the swap" into numbers a run can
be GRADED by. This module is that substrate, in three layers:

1. **Pure math** — :func:`burn_rate`, :func:`error_budget_remaining`,
   :func:`windowed_burn_rates`: the standard SRE error-budget algebra
   (a target of 0.999 over N requests buys ``(1-0.999)*N`` failures;
   burn rate is the observed error rate divided by the budgeted one, so
   ``1.0`` = spending exactly sustainably, ``>1`` = the budget dies
   before the window does). Unit-tested against hand-computed windows.
2. **Declarative objectives** — :func:`normalize_objectives` validates
   ``{"name", "kind", "target", ...}`` dicts of four kinds:
   ``availability`` (good/bad event ratio), ``latency_p99`` (summary
   quantile vs a ceiling), ``goodput_floor`` (completed work per second
   vs a floor), and ``time_to_adapt`` (drift-detect -> reload lifecycle
   duration vs a ceiling — computable BECAUSE the trace propagation
   makes a lifecycle one trace id).
3. **Evaluation** — :class:`SloEngine` scores objectives from a live
   metrics :class:`~tpuflow.obs.metrics.Registry` (both serve daemons
   evaluate at scrape time: the ``slo`` section of the JSON ``/metrics``
   view and ``slo_error_budget_remaining{objective=}`` /
   ``slo_burn_rate{objective=}`` gauges in the Prometheus exposition),
   and :func:`report_card` scores them from merged fleet trail events
   (``python -m tpuflow.obs slo <dir...>``).

The report card is a committed JSON contract
(``tpuflow/obs/slo_report_card.schema.json``);
:func:`validate_report_card` checks a card against it — with
``jsonschema`` when installed, and a built-in structural check
otherwise, so the log-reading CLI stays dependency-light (no jax, no
hard third-party requirement).
"""

from __future__ import annotations

import json
import math
import os
import threading
import time

SCHEMA_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "slo_report_card.schema.json"
)
SCHEMA_ID = "tpuflow.slo.report_card/v1"

KINDS = ("availability", "latency_p99", "goodput_floor", "time_to_adapt")
STATUSES = ("ok", "at_risk", "violated", "no_data")

# The serve daemons' default objective set: availability over
# admitted-vs-shed and a p99 ceiling over the request latency summary.
# Counter names are tried in order (the async daemon has admission
# counters, the threaded daemon doesn't); "bad" names are SUMMED over
# whichever exist. Targets are env-tunable (TPUFLOW_SERVE_SLO_*).
DEFAULT_SERVE_OBJECTIVES = (
    {
        "name": "availability",
        "kind": "availability",
        "target": 0.999,
        "good": ("serving_admitted_total", "predict_requests_total"),
        "bad": (
            "serving_shed_total",
            "predict_batch_rejected_total",
            "predict_batch_expired_total",
        ),
    },
    {
        "name": "latency_p99",
        "kind": "latency_p99",
        "target": 500.0,  # ms
        "summary": "predict_latency_ms",
    },
)


def serve_objectives(objectives=None) -> list[dict]:
    """The serve daemons' objective list: an explicit list passes
    through :func:`normalize_objectives` untouched; None builds the
    default availability + p99 pair with env-tunable targets
    (``TPUFLOW_SERVE_SLO_TARGET`` — the availability ratio;
    ``TPUFLOW_SERVE_SLO_P99_MS`` — the latency ceiling), validated at
    read time like every other ``TPUFLOW_SERVE_*`` knob."""
    if objectives is not None:
        return normalize_objectives(objectives)
    from tpuflow.utils.env import env_num

    target = env_num(
        "TPUFLOW_SERVE_SLO_TARGET", 0.999, float, minimum=1e-9,
        form="an availability ratio in (0, 1]",
    )
    if target > 1.0:
        raise ValueError(
            f"invalid TPUFLOW_SERVE_SLO_TARGET={target!r}: expected an "
            "availability ratio in (0, 1]"
        )
    p99_ms = env_num(
        "TPUFLOW_SERVE_SLO_P99_MS", 500.0, float, minimum=1e-9,
        form="a positive p99 latency ceiling in milliseconds",
    )
    out = []
    for obj in DEFAULT_SERVE_OBJECTIVES:
        obj = dict(obj)
        if obj["kind"] == "availability":
            obj["target"] = target
        elif obj["kind"] == "latency_p99":
            obj["target"] = p99_ms
        out.append(obj)
    return normalize_objectives(out)


# ---------------------------------------------------------------------
# the pure error-budget algebra
# ---------------------------------------------------------------------


def burn_rate(good: float, bad: float, target: float) -> float | None:
    """Observed error rate over budgeted error rate. ``1.0`` = spending
    the budget exactly as fast as the window replenishes it; ``>1`` =
    the budget runs out before the window does. None when there is no
    traffic to judge (a missing sample is honest; a fake 0.0 would
    suppress the alert the number exists to fire)."""
    total = good + bad
    if total <= 0:
        return None
    rate = bad / total
    budget = 1.0 - float(target)
    if budget <= 0:
        # A 100% target has no budget: any failure burns infinitely.
        return math.inf if bad > 0 else 0.0
    return rate / budget


def error_budget_remaining(
    good: float, bad: float, target: float
) -> float | None:
    """Fraction of the window's error budget left: ``1.0`` = untouched,
    ``0.0`` = exactly spent, negative = overspent (the objective is
    violated). None when there was no traffic."""
    total = good + bad
    if total <= 0:
        return None
    allowed = (1.0 - float(target)) * total
    if allowed <= 0:
        return 1.0 if bad == 0 else -math.inf
    return 1.0 - (bad / allowed)


def windowed_burn_rates(
    samples,
    *,
    target: float,
    window_s: float,
    t0: float | None = None,
) -> list[dict]:
    """Bucket ``(time, ok)`` samples into fixed windows and compute each
    window's burn rate — the windowed view that distinguishes "bled
    0.1% all day" from "died completely for 90 seconds", which a single
    cumulative ratio cannot. Windows with no traffic are omitted (no
    sample is honest; burn rate 0.0 would read as health)."""
    if window_s <= 0:
        raise ValueError(f"window_s must be > 0, got {window_s}")
    pts = sorted(
        (float(t), bool(ok)) for t, ok in samples
    )
    if not pts:
        return []
    base = float(t0) if t0 is not None else pts[0][0]
    buckets: dict[int, list[int]] = {}
    for t, ok in pts:
        if t < base:
            continue
        idx = int((t - base) // window_s)
        g_b = buckets.setdefault(idx, [0, 0])
        g_b[0 if ok else 1] += 1
    out = []
    for idx in sorted(buckets):
        good, bad = buckets[idx]
        out.append({
            "start": base + idx * window_s,
            "end": base + (idx + 1) * window_s,
            "good": good,
            "bad": bad,
            "burn_rate": burn_rate(good, bad, target),
            "error_budget_remaining": error_budget_remaining(
                good, bad, target
            ),
        })
    return out


def _status(
    budget_remaining: float | None,
    rate: float | None,
    measured=None,
    ceiling: float | None = None,
) -> str:
    """One objective's verdict. Ratio objectives judge the budget
    (negative remaining = violated; burning >1x = at risk); ceiling
    objectives (latency, time-to-adapt without lifecycles enough for a
    budget) judge measured vs target."""
    if budget_remaining is None and rate is None:
        if measured is None or ceiling is None:
            return "no_data"
        return "ok" if float(measured) <= float(ceiling) else "violated"
    if budget_remaining is not None and budget_remaining < 0:
        return "violated"
    if rate is not None and rate > 1.0:
        return "at_risk"
    return "ok"


# ---------------------------------------------------------------------
# objectives
# ---------------------------------------------------------------------


def normalize_objectives(raw) -> list[dict]:
    """Validate a declarative objective list; fail-loud on unknown
    kinds/shapes (a typo'd objective silently scoring no_data forever
    is exactly what a report card must not do). Accepts tuples/lists of
    dicts; returns plain dicts with the target coerced to float."""
    if raw is None:
        raw = DEFAULT_SERVE_OBJECTIVES
    out = []
    seen = set()
    for i, obj in enumerate(raw):
        if not isinstance(obj, dict):
            raise ValueError(
                f"objective #{i} must be a dict, got {type(obj).__name__}"
            )
        kind = obj.get("kind")
        if kind not in KINDS:
            raise ValueError(
                f"objective #{i} has unknown kind {kind!r}; valid: "
                f"{', '.join(KINDS)}"
            )
        name = str(obj.get("name") or kind)
        if name in seen:
            raise ValueError(f"duplicate objective name {name!r}")
        seen.add(name)
        try:
            target = float(obj["target"])
        except (KeyError, TypeError, ValueError):
            raise ValueError(
                f"objective {name!r} needs a numeric 'target' "
                f"(got {obj.get('target')!r})"
            ) from None
        if kind == "availability" and not (0.0 < target <= 1.0):
            raise ValueError(
                f"availability objective {name!r}: target must be a "
                f"ratio in (0, 1], got {target}"
            )
        if kind != "availability" and target <= 0:
            raise ValueError(
                f"objective {name!r}: target must be > 0, got {target}"
            )
        out.append({**obj, "name": name, "kind": kind, "target": target})
    return out


def load_objectives(path: str) -> list[dict]:
    """Objectives from a JSON file: either a bare list or
    ``{"objectives": [...]}``."""
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    if isinstance(doc, dict):
        doc = doc.get("objectives")
    if not isinstance(doc, list):
        raise ValueError(
            f"{path}: expected a JSON list of objectives or "
            '{"objectives": [...]}'
        )
    return normalize_objectives(doc)


# ---------------------------------------------------------------------
# registry-side evaluation (the live daemons)
# ---------------------------------------------------------------------


def _counter_total(registry, name: str) -> float | None:
    """A counter family's total across every labelset (None when the
    family was never registered — absence, not zero)."""
    fam = registry.peek(name)
    if fam is None:
        return None
    return sum(v for suffix, _, v in fam.collect() if suffix == "")


def _summary_quantile(registry, name: str, q: str) -> float | None:
    fam = registry.peek(name)
    if fam is None:
        return None
    for suffix, labels, v in fam.collect():
        if suffix == "" and labels.get("quantile") == q:
            return float(v)
    return None


class SloEngine:
    """Objective evaluation over a live registry, with the verdicts
    published back INTO a registry so both ``/metrics`` formats carry
    them: ``slo_error_budget_remaining{objective=}`` and
    ``slo_burn_rate{objective=}`` gauges for the Prometheus scrape, and
    the dict :meth:`evaluate_registry` returns for the JSON view.

    Burn rates are computed over the **scrape window** (the counter
    delta since the previous evaluation) so a dashboard sees current
    spending, plus cumulatively since the daemon started — the
    fast/slow window pair of standard burn-rate alerting, with the
    scrape cadence as the fast window.
    """

    def __init__(self, objectives=None, registry=None, clock=time.monotonic):
        self.objectives = normalize_objectives(objectives)
        self.registry = registry
        self.clock = clock
        # The previous evaluation's counter snapshot per objective —
        # the fast burn window is "since the last evaluation from ANY
        # endpoint" (JSON and Prometheus scrapes share one engine), so
        # the read-modify-write is guarded: concurrent scraper threads
        # (ThreadingHTTPServer handlers) must not interleave a delta.
        self._last: dict[str, tuple[float, float, float]] = {}
        self._last_lock = threading.Lock()
        self._budget_gauge = None
        self._burn_gauge = None
        if registry is not None:
            self._budget_gauge = registry.gauge(
                "slo_error_budget_remaining",
                "fraction of each objective's error budget left "
                "(cumulative; negative = violated)",
            )
            self._burn_gauge = registry.gauge(
                "slo_burn_rate",
                "each objective's cumulative burn rate (1.0 = spending "
                "the budget exactly as fast as it replenishes)",
            )

    def _publish(self, name: str, budget, rate) -> None:
        if self._budget_gauge is not None and budget is not None and (
            math.isfinite(budget)
        ):
            self._budget_gauge.set(budget, objective=name)
        if self._burn_gauge is not None and rate is not None and (
            math.isfinite(rate)
        ):
            self._burn_gauge.set(rate, objective=name)

    def _eval_availability(self, obj: dict, registry) -> dict:
        good = bad = None
        for name in obj.get("good", ()):
            good = _counter_total(registry, name)
            if good is not None:
                break
        bad_total, bad_seen = 0.0, False
        for name in obj.get("bad", ()):
            v = _counter_total(registry, name)
            if v is not None:
                bad_total, bad_seen = bad_total + v, True
        bad = bad_total if bad_seen else 0.0
        if good is None:
            return {"measured": None, "budget": None, "rate": None}
        target = obj["target"]
        total = good + bad
        now = self.clock()
        with self._last_lock:
            pg, pb = 0.0, 0.0
            if obj["name"] in self._last:
                _, pg, pb = self._last[obj["name"]]
            self._last[obj["name"]] = (now, good, bad)
        dg, db = max(good - pg, 0.0), max(bad - pb, 0.0)
        return {
            "measured": (good / total) if total > 0 else None,
            "good": good,
            "bad": bad,
            "budget": error_budget_remaining(good, bad, target),
            "rate": burn_rate(good, bad, target),
            "window_burn_rate": burn_rate(dg, db, target),
        }

    def evaluate_registry(self, registry=None) -> dict:
        """Score every objective against ``registry`` (defaults to the
        engine's own); returns the ``slo`` section for the JSON
        ``/metrics`` view and refreshes the exposition gauges. Never
        raises — a broken objective must not fail the scrape."""
        registry = registry if registry is not None else self.registry
        rows = []
        for obj in self.objectives:
            kind, name, target = obj["kind"], obj["name"], obj["target"]
            row = {
                "name": name,
                "kind": kind,
                "target": target,
                "measured": None,
                "error_budget_remaining": None,
                "burn_rate": None,
                "status": "no_data",
            }
            try:
                if kind == "availability":
                    got = self._eval_availability(obj, registry)
                    row["measured"] = got["measured"]
                    row["error_budget_remaining"] = got["budget"]
                    row["burn_rate"] = got["rate"]
                    if "window_burn_rate" in got:
                        row["window_burn_rate"] = got["window_burn_rate"]
                    row["status"] = _status(got["budget"], got["rate"])
                    self._publish(name, got["budget"], got["rate"])
                elif kind == "latency_p99":
                    p99 = _summary_quantile(
                        registry, obj.get("summary", "predict_latency_ms"),
                        "0.99",
                    )
                    row["measured"] = p99
                    row["status"] = _status(
                        None, None, measured=p99, ceiling=target
                    )
                    if p99 is not None:
                        # Ceiling objectives publish headroom as the
                        # budget analogue: 1 - measured/target (negative
                        # = over the ceiling).
                        headroom = 1.0 - p99 / target
                        row["error_budget_remaining"] = headroom
                        self._publish(name, headroom, None)
                elif kind == "goodput_floor":
                    good = None
                    for cname in obj.get(
                        "good",
                        ("serving_admitted_total", "predict_requests_total"),
                    ):
                        good = _counter_total(registry, cname)
                        if good is not None:
                            break
                    uptime = None
                    fam = registry.peek(
                        obj.get("uptime", "uptime_seconds")
                    )
                    if fam is not None:
                        samples = fam.collect()
                        if samples:
                            uptime = float(samples[0][2])
                    if good is not None and uptime and uptime > 0:
                        rps = good / uptime
                        row["measured"] = rps
                        headroom = rps / target - 1.0
                        row["error_budget_remaining"] = headroom
                        row["status"] = (
                            "ok" if rps >= target else "violated"
                        )
                        self._publish(name, headroom, None)
                # time_to_adapt needs the fleet trails (report_card);
                # a registry alone cannot see lifecycle durations.
            except Exception:
                row["status"] = "no_data"
            rows.append(rows_finite(row))
        return {"schema": SCHEMA_ID, "objectives": rows}


def rows_finite(row: dict) -> dict:
    """JSON-safe: +-inf budget/rate values become None (RFC 8259 has no
    Infinity token, and the card must stay loadable everywhere)."""
    out = {}
    for k, v in row.items():
        if isinstance(v, float) and not math.isfinite(v):
            out[k] = None
        else:
            out[k] = v
    return out


# ---------------------------------------------------------------------
# trail-side evaluation (the fleet report card)
# ---------------------------------------------------------------------


def adapt_lifecycles(events: list[dict]) -> list[dict]:
    """Drift-adaptation lifecycles from merged fleet events, grouped by
    trace id — the payoff of propagating ONE trace through drift-detect
    -> retrain -> swap -> reload: "how long did adapting take" becomes
    arithmetic. A lifecycle is a trace that saw a drift signal
    (``drift_anomaly`` / a drift-reason ``online_retrain``) and a
    completion (``artifact_swap`` / ``online_swap`` / ``serve_reload``);
    its duration is last-completion minus first-signal."""
    by_trace: dict[str, list[dict]] = {}
    for rec in events:
        tid = rec.get("trace_id")
        if tid:
            by_trace.setdefault(str(tid), []).append(rec)
    out = []
    for tid, recs in sorted(by_trace.items()):
        starts = [
            r["time"] for r in recs
            if isinstance(r.get("time"), (int, float)) and (
                r.get("event") == "drift_anomaly"
                or (r.get("event") == "online_retrain"
                    and r.get("reason", "drift") == "drift")
            )
        ]
        ends = [
            r["time"] for r in recs
            if isinstance(r.get("time"), (int, float))
            and r.get("event") in (
                "artifact_swap", "online_swap", "serve_reload"
            )
        ]
        if starts and ends and max(ends) >= min(starts):
            out.append({
                "trace_id": tid,
                "start": min(starts),
                "end": max(ends),
                "seconds": max(ends) - min(starts),
                "events": len(recs),
            })
    return out


def report_card(
    events: list[dict],
    objectives=None,
    *,
    window_s: float = 300.0,
    registry=None,
    source=None,
) -> dict:
    """The fleet SLO report card from merged trail events (plus an
    optional live registry for the counter-backed objectives) — the
    artifact the chaos soak grades itself with, validating against
    ``slo_report_card.schema.json``."""
    objectives = normalize_objectives(objectives)
    engine = SloEngine(objectives)
    reg_rows: dict[str, dict] = {}
    if registry is not None:
        got = SloEngine(objectives).evaluate_registry(registry)
        reg_rows = {r["name"]: r for r in got["objectives"]}
    lifecycles = adapt_lifecycles(events)
    times = [
        r["time"] for r in events
        if isinstance(r.get("time"), (int, float))
    ]
    rows = []
    for obj in engine.objectives:
        kind, name, target = obj["kind"], obj["name"], obj["target"]
        if kind == "time_to_adapt":
            row = {
                "name": name, "kind": kind, "target": target,
                "measured": None, "error_budget_remaining": None,
                "burn_rate": None, "status": "no_data",
                "lifecycles": lifecycles,
            }
            if lifecycles:
                worst = max(lc["seconds"] for lc in lifecycles)
                good = sum(
                    1 for lc in lifecycles if lc["seconds"] <= target
                )
                bad = len(lifecycles) - good
                # Within-target ratio judged at three nines: one slow
                # adaptation out of a handful IS a budget event.
                row["measured"] = worst
                row["error_budget_remaining"] = error_budget_remaining(
                    good, bad, 0.999
                )
                row["burn_rate"] = burn_rate(good, bad, 0.999)
                row["status"] = _status(
                    row["error_budget_remaining"], row["burn_rate"],
                    measured=worst, ceiling=target,
                )
                if row["status"] == "ok" and worst > target:
                    row["status"] = "violated"
            rows.append(rows_finite(row))
            continue
        if name in reg_rows:
            rows.append(rows_finite(reg_rows[name]))
            continue
        # Trail fallback for counter-backed kinds: per-dispatch serving
        # spans when present (ok iff the span didn't record ok=false).
        spans = [
            r for r in events
            if r.get("event") == "span"
            and str(r.get("name", "")).startswith("predict.")
            and isinstance(r.get("time"), (int, float))
        ]
        row = {
            "name": name, "kind": kind, "target": target,
            "measured": None, "error_budget_remaining": None,
            "burn_rate": None, "status": "no_data",
        }
        if spans and kind == "availability":
            samples = [
                (r["time"], r.get("ok", True) is not False) for r in spans
            ]
            good = sum(1 for _, ok in samples if ok)
            bad = len(samples) - good
            row["measured"] = good / len(samples)
            row["error_budget_remaining"] = error_budget_remaining(
                good, bad, target
            )
            row["burn_rate"] = burn_rate(good, bad, target)
            row["windows"] = [
                rows_finite(w) for w in windowed_burn_rates(
                    samples, target=target, window_s=window_s
                )
            ]
            row["status"] = _status(
                row["error_budget_remaining"], row["burn_rate"]
            )
        elif spans and kind == "latency_p99":
            durs = sorted(
                float(r["duration_s"]) * 1000.0 for r in spans
                if isinstance(r.get("duration_s"), (int, float))
            )
            if durs:
                p99 = durs[min(
                    int(math.ceil(0.99 * len(durs))) - 1, len(durs) - 1
                )]
                row["measured"] = p99
                row["error_budget_remaining"] = 1.0 - p99 / target
                row["status"] = _status(
                    None, None, measured=p99, ceiling=target
                )
        elif spans and kind == "goodput_floor":
            ts = [r["time"] for r in spans]
            elapsed = max(ts) - min(ts)
            if elapsed > 0:
                rps = len(spans) / elapsed
                row["measured"] = rps
                row["error_budget_remaining"] = rps / target - 1.0
                row["status"] = "ok" if rps >= target else "violated"
        rows.append(rows_finite(row))
    card = {
        "schema": SCHEMA_ID,
        "generated_unix": time.time(),
        "window_s": float(window_s),
        "events": len(events),
        "span": rows_finite({
            "start": min(times) if times else None,
            "end": max(times) if times else None,
        }),
        "objectives": rows,
    }
    if source is not None:
        card["source"] = source
    return card


# ---------------------------------------------------------------------
# schema validation
# ---------------------------------------------------------------------


def _structural_check(card: dict, schema: dict) -> list[str]:
    """Minimal required/type/enum validation for environments without
    jsonschema — enough to catch a malformed card, deliberately not a
    full JSON Schema implementation."""
    errors = []
    if not isinstance(card, dict):
        return ["card must be a JSON object"]
    for key in schema.get("required", []):
        if key not in card:
            errors.append(f"missing required key {key!r}")
    if card.get("schema") != SCHEMA_ID:
        errors.append(
            f"schema must be {SCHEMA_ID!r}, got {card.get('schema')!r}"
        )
    objectives = card.get("objectives")
    if not isinstance(objectives, list):
        errors.append("objectives must be a list")
        return errors
    obj_schema = (
        schema.get("properties", {}).get("objectives", {}).get("items", {})
    )
    required = obj_schema.get("required", [])
    for i, row in enumerate(objectives):
        if not isinstance(row, dict):
            errors.append(f"objectives[{i}] must be an object")
            continue
        for key in required:
            if key not in row:
                errors.append(f"objectives[{i}] missing {key!r}")
        if row.get("kind") not in KINDS:
            errors.append(f"objectives[{i}].kind {row.get('kind')!r} unknown")
        if row.get("status") not in STATUSES:
            errors.append(
                f"objectives[{i}].status {row.get('status')!r} unknown"
            )
    return errors


def validate_report_card(card: dict, schema_path: str | None = None) -> None:
    """Raise ``ValueError`` listing every violation when ``card`` does
    not match the committed report-card schema."""
    with open(schema_path or SCHEMA_PATH, encoding="utf-8") as f:
        schema = json.load(f)
    try:
        import jsonschema
    except ImportError:
        jsonschema = None
    if jsonschema is not None:
        validator = jsonschema.Draft202012Validator(schema)
        errors = [
            f"{'/'.join(str(p) for p in e.absolute_path) or '<root>'}: "
            f"{e.message}"
            for e in validator.iter_errors(card)
        ]
    else:
        errors = _structural_check(card, schema)
    if errors:
        raise ValueError(
            "report card does not match slo_report_card.schema.json:\n  "
            + "\n  ".join(sorted(errors)[:20])
        )
