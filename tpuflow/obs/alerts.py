"""Declarative alerts over the metrics history: threshold + hold-down.

A gauge crossing a line for one scrape is noise; crossing it for
``for_s`` seconds is an incident. :class:`AlertEngine` evaluates
declarative rules against :class:`~tpuflow.obs.history.MetricsHistory`
windows on every history tick, with the standard firing lifecycle:

``ok`` → (breach observed) → ``pending`` → (breach sustained for
``for_s``) → ``firing`` → (recovery observed) → ``ok`` again, with an
``alert_resolved`` record.

Each transition is recorded three ways: the forensics ring
(``alert_firing`` / ``alert_resolved`` events — causally linkable in
the fleet timeline), the daemon's JSONL trail when one is attached,
and the ``obs_alerts_firing{rule=}`` gauge (1 while firing, 0 after
resolve) plus ``obs_alerts_transitions_total{rule=,state=}`` counters
for the Prometheus view. Both daemons render :meth:`summary` as the
``alerts`` section of JSON ``/metrics``; ``python -m tpuflow.obs
alerts`` replays a spilled history against a rules file offline.

Rule grammar (one dict per rule; :func:`validate_rules` is the
never-raises preflight, docs/observability.md has the table)::

    {"name": "burn_availability",        # unique, required
     "metric": "slo_burn_rate",          # series name, required
     "labels": {"objective": "availability"},
     "query": "mean",                    # latest|rate|mean|max|quantile|delta
     "q": 0.99,                          # quantile only
     "op": ">",                          # > >= < <=
     "threshold": 1.0,                   # required
     "window_s": 60.0,
     "for_s": 30.0,                      # hold-down before firing
     "severity": "page"}                 # page|warn

Firing state is keyed by RULE, not by history points: a downsample
(the history's memory-bounding decimation) thins the window a firing
rule is evaluated over but cannot re-fire it — the
no-double-fire-across-downsample drill in tests/test_obs_history.py.

:func:`rules_from_objectives` imports the SLO engine's committed
objectives as burn-rate / latency-ceiling rules, so the alerting
thresholds and the report-card math share one source of truth.

Dependency-light (no jax): usable offline on spill files alone.
"""

from __future__ import annotations

import threading
import time

SCHEMA_ID = "tpuflow.obs.alerts/v1"

QUERIES = ("latest", "rate", "mean", "max", "quantile", "delta")
OPS = {
    ">": lambda v, t: v > t,
    ">=": lambda v, t: v >= t,
    "<": lambda v, t: v < t,
    "<=": lambda v, t: v <= t,
}
SEVERITIES = ("page", "warn")

RULE_DEFAULTS = {
    "labels": {},
    "query": "latest",
    "q": 0.99,
    "op": ">",
    "window_s": 60.0,
    "for_s": 0.0,
    "severity": "warn",
    "description": "",
}

_WINDOWED = ("rate", "mean", "max", "quantile", "delta")


def validate_rules(rules) -> list[str]:
    """Every problem with a rules list, as strings — never raises (the
    validate_autotune_block contract: a preflight diagnostic, not a
    crash deep inside the evaluation loop)."""
    problems: list[str] = []
    if not isinstance(rules, (list, tuple)):
        return [f"rules must be a list of rule objects, got {type(rules).__name__}"]
    seen: set[str] = set()
    for i, rule in enumerate(rules):
        where = f"rule[{i}]"
        if not isinstance(rule, dict):
            problems.append(f"{where}: must be an object, got {type(rule).__name__}")
            continue
        name = rule.get("name")
        if not name or not isinstance(name, str):
            problems.append(f"{where}: needs a non-empty string 'name'")
        elif name in seen:
            problems.append(f"{where}: duplicate rule name {name!r}")
        else:
            seen.add(name)
            where = f"rule {name!r}"
        if not rule.get("metric") or not isinstance(rule.get("metric"), str):
            problems.append(f"{where}: needs a non-empty string 'metric'")
        if "threshold" not in rule or not isinstance(
            rule["threshold"], (int, float)
        ) or isinstance(rule["threshold"], bool):
            problems.append(f"{where}: needs a numeric 'threshold'")
        q = rule.get("query", RULE_DEFAULTS["query"])
        if q not in QUERIES:
            problems.append(
                f"{where}: query {q!r} is not one of {'/'.join(QUERIES)}"
            )
        op = rule.get("op", RULE_DEFAULTS["op"])
        if op not in OPS:
            problems.append(
                f"{where}: op {op!r} is not one of {'/'.join(OPS)}"
            )
        sev = rule.get("severity", RULE_DEFAULTS["severity"])
        if sev not in SEVERITIES:
            problems.append(
                f"{where}: severity {sev!r} is not one of "
                f"{'/'.join(SEVERITIES)}"
            )
        labels = rule.get("labels", {})
        if not isinstance(labels, dict):
            problems.append(f"{where}: labels must be an object")
        for key, minimum in (("window_s", 0.0), ("for_s", 0.0)):
            v = rule.get(key, RULE_DEFAULTS[key])
            if not isinstance(v, (int, float)) or isinstance(v, bool) or (
                v < minimum
            ):
                problems.append(f"{where}: {key} must be a number >= {minimum}")
        unknown = sorted(
            set(rule) - {"name", "metric", "threshold"} - set(RULE_DEFAULTS)
        )
        if unknown:
            problems.append(f"{where}: unknown keys {unknown}")
    return problems


def normalize_rule(rule: dict) -> dict:
    """Defaults applied, types coerced. Raises ValueError listing every
    problem (the fail-loud constructor path; :func:`validate_rules` is
    the never-raises preflight)."""
    problems = validate_rules([rule])
    if problems:
        raise ValueError("invalid alert rule: " + "; ".join(problems))
    out = {**RULE_DEFAULTS, **rule}
    out["labels"] = dict(out["labels"])
    out["threshold"] = float(out["threshold"])
    out["window_s"] = float(out["window_s"])
    out["for_s"] = float(out["for_s"])
    out["q"] = float(out["q"])
    return out


def rules_from_objectives(
    objectives=None, *, window_s: float = 60.0, for_s: float = 15.0,
    burn_threshold: float = 1.0,
) -> list[dict]:
    """The SLO engine's objectives as alert rules — one source of truth
    for "what does violated mean". Availability objectives become
    burn-rate rules over the ``slo_burn_rate{objective=}`` gauge
    history (threshold 1.0 = spending the budget exactly as fast as it
    replenishes); latency-ceiling objectives become rules over the
    summary's p99 series with the objective's own target as the line.
    ``objectives=None`` imports the committed serving objectives
    (env-tunable targets included) from ``tpuflow/obs/slo.py``."""
    from tpuflow.obs.slo import normalize_objectives, serve_objectives

    objs = (
        serve_objectives() if objectives is None
        else normalize_objectives(objectives)
    )
    rules: list[dict] = []
    for obj in objs:
        if obj["kind"] == "availability":
            rules.append({
                "name": f"burn_rate_{obj['name']}",
                "metric": "slo_burn_rate",
                "labels": {"objective": obj["name"]},
                "query": "mean",
                "op": ">",
                "threshold": float(burn_threshold),
                "window_s": float(window_s),
                "for_s": float(for_s),
                "severity": "page",
                "description": (
                    f"{obj['name']} error budget burning faster than it "
                    f"replenishes (target {obj['target']})"
                ),
            })
        elif obj["kind"] == "latency_p99":
            rules.append({
                "name": f"p99_over_target_{obj['name']}",
                "metric": obj.get("summary", "predict_latency_ms"),
                "labels": {"quantile": "0.99"},
                "query": "max",
                "op": ">",
                "threshold": float(obj["target"]),
                "window_s": float(window_s),
                "for_s": float(for_s),
                "severity": "warn",
                "description": (
                    f"p99 latency over the {obj['target']} ms objective"
                ),
            })
    return rules


class AlertEngine:
    """Evaluate rules over a history on every tick; own the lifecycle.

    State is guarded by ``self._lock`` (evaluations may come from the
    sampler thread AND a scrape handler); the forensics/trail/metric
    emissions happen outside it — recording must never hold the
    engine's lock across I/O (TPF017)."""

    def __init__(
        self, history, rules=(), *, registry=None, logger=None, clock=None,
        max_transitions: int = 256,
    ):
        self.history = history
        self.rules = [normalize_rule(dict(r)) for r in rules]
        self.clock = clock or getattr(history, "clock", None) or time.monotonic
        self.logger = logger
        self._lock = threading.Lock()
        self._states: dict[str, dict] = {
            r["name"]: {"state": "ok", "since": None, "breach_since": None,
                        "value": None}
            for r in self.rules
        }
        self.max_transitions = int(max_transitions)
        self.transitions: list[dict] = []
        self._listeners: list = []
        self._firing_gauge = self._transitions_total = None
        if registry is not None:
            self._firing_gauge = registry.gauge(
                "obs_alerts_firing",
                "1 while the rule is firing, 0 after it resolves",
            )
            self._transitions_total = registry.counter(
                "obs_alerts_transitions_total",
                "alert lifecycle transitions, by rule and new state",
            )

    def attach(self) -> "AlertEngine":
        """Subscribe to the history's tick notifications — evaluation
        then rides the sampler's cadence."""
        self.history.add_listener(self._on_tick)
        return self

    def _on_tick(self, now: float) -> None:
        self.evaluate(now)

    def add_listener(self, fn) -> None:
        """Subscribe to firing/resolved transition records. Listeners run
        OUTSIDE the engine lock (same discipline as ``_emit``'s I/O) and
        must not raise — this is the flight recorder's capture seam."""
        self._listeners.append(fn)

    def _query(self, rule: dict, now: float):
        h, metric, labels = self.history, rule["metric"], rule["labels"]
        q = rule["query"]
        if q == "latest":
            return h.latest(metric, **labels)
        if q == "quantile":
            return h.quantile(
                metric, rule["q"], rule["window_s"], now=now, **labels
            )
        return getattr(h, q)(metric, rule["window_s"], now=now, **labels)

    def evaluate(self, now: float | None = None) -> list[dict]:
        """One evaluation pass; returns the per-rule status rows (the
        ``alerts.rules`` section of ``/metrics``). A rule whose series
        has no data keeps its current state — absence is not recovery:
        resolving a firing alert because the sampler missed a tick
        would hide exactly the incident it exists to report."""
        now = self.clock() if now is None else float(now)
        rows: list[dict] = []
        emissions: list[dict] = []
        for rule in self.rules:
            try:
                value = self._query(rule, now)
            except Exception:
                value = None
            breach = (
                OPS[rule["op"]](value, rule["threshold"])
                if value is not None else None
            )
            with self._lock:
                st = self._states[rule["name"]]
                st["value"] = value
                if breach is True:
                    if st["state"] == "ok":
                        st["state"] = "pending"
                        st["breach_since"] = now
                    if st["state"] == "pending" and (
                        now - st["breach_since"] >= rule["for_s"]
                    ):
                        st["state"] = "firing"
                        st["since"] = now
                        emissions.append(
                            self._transition_locked(rule, "firing", now, value)
                        )
                elif breach is False:
                    if st["state"] == "firing":
                        st["state"] = "ok"
                        st["since"] = now
                        st["breach_since"] = None
                        emissions.append(
                            self._transition_locked(rule, "resolved", now, value)
                        )
                    elif st["state"] == "pending":
                        st["state"] = "ok"
                        st["breach_since"] = None
                rows.append({
                    "name": rule["name"],
                    "state": st["state"],
                    "value": value,
                    "query": rule["query"],
                    "metric": rule["metric"],
                    "op": rule["op"],
                    "threshold": rule["threshold"],
                    "window_s": rule["window_s"],
                    "for_s": rule["for_s"],
                    "severity": rule["severity"],
                    "since": st["since"],
                })
        for rec in emissions:
            self._emit(rec)
        return rows

    def _transition_locked(self, rule, state, now, value) -> dict:
        rec = {
            "rule": rule["name"],
            "state": state,
            "severity": rule["severity"],
            "value": value,
            "threshold": rule["threshold"],
            "metric": rule["metric"],
            "t": now,
        }
        self.transitions.append(rec)
        if len(self.transitions) > self.max_transitions:
            del self.transitions[0]
        return rec

    def _emit(self, rec: dict) -> None:
        from tpuflow.obs.forensics import record_event

        event = "alert_firing" if rec["state"] == "firing" else "alert_resolved"
        record_event(event, **{k: v for k, v in rec.items() if k != "state"})
        if self.logger is not None:
            try:
                self.logger.write(event, **{
                    k: v for k, v in rec.items() if k != "state"
                })
            except Exception:
                pass
        if self._firing_gauge is not None:
            self._firing_gauge.set(
                1.0 if rec["state"] == "firing" else 0.0, rule=rec["rule"]
            )
        if self._transitions_total is not None:
            self._transitions_total.inc(rule=rec["rule"], state=rec["state"])
        for fn in self._listeners:
            try:
                fn(rec)
            except Exception:
                pass

    def firing(self) -> list[str]:
        with self._lock:
            return sorted(
                name for name, st in self._states.items()
                if st["state"] == "firing"
            )

    def summary(self) -> dict:
        """The ``alerts`` section of JSON ``/metrics``: every rule's
        current state (NO re-evaluation — a scrape reports, it doesn't
        advance hold-down clocks)."""
        with self._lock:
            rows = [
                {
                    "name": rule["name"],
                    "state": self._states[rule["name"]]["state"],
                    "value": self._states[rule["name"]]["value"],
                    "threshold": rule["threshold"],
                    "severity": rule["severity"],
                    "since": self._states[rule["name"]]["since"],
                }
                for rule in self.rules
            ]
        return {
            "schema": SCHEMA_ID,
            "firing": sum(1 for r in rows if r["state"] == "firing"),
            "rules": rows,
        }
