"""Training health: numerics watchdog, recompile detector, live roofline.

PR 4 gave tpuflow a telemetry substrate (registry, spans, forensics);
this module *interprets* a run while it is still running — the MFU
accounting of PaLM (Chowdhery et al., 2022) and the always-on fleet
profiling of Kanev et al. (PAPERS.md), scaled down to one training job:

- :class:`NumericsWatchdog` — NaN/Inf and EWMA-spike detection over the
  per-epoch ``loss``/``grad_norm`` aux the train steps already return.
  Strictly host-side and strictly POST-epoch: the fit loop hands it
  host floats after the epoch's device work is done, never per-step
  inside the scanned program (the TPF006 lint contract). Anomalies
  increment ``train_numerics_anomalies_total{kind=...}``, land in the
  forensics ring (and a dump next to the artifacts), and — per the
  configured policy — warn, halve the optimizer LR, or abort the run
  with the typed :class:`NumericsDivergence` the supervisor classifies
  as terminal (a diverged run replays deterministically; restarting it
  burns the whole backoff budget on a foregone conclusion).

- :class:`RecompileDetector` — counts XLA compilations per step
  function by argument signature (shapes/dtypes of the data args). A
  compile after the first signature is a *recompile*; a recompile after
  the warmup epoch is *steady-state shape churn* — the failure mode
  that looks exactly like slow hardware from the outside. Each one is
  recorded as an ``xla.compile`` span (with the offending shapes), the
  ``train_recompiles`` gauge tracks the count, and the run summary
  carries a preflight-style diagnostic. :func:`install_compile_listener`
  additionally counts every backend compile process-wide via
  ``jax.monitoring``, where the running jax exposes it.

- :func:`publish_roofline` — the live MFU leg: given this epoch's
  samples/sec/chip and the model's FLOPs/bytes-per-sample
  (``tpuflow/utils/roofline.py``), publishes ``train_mfu`` /
  ``train_hbm_util`` / ``train_bound{bound=...}`` into the registry
  (scraped at ``GET /metrics?format=prometheus``) and a ``roofline``
  record into the run's metrics JSONL.

Import-light by design: no jax at module import (the supervisor parent
classifies :class:`NumericsDivergence` without touching a chip);
``jax.monitoring`` is reached lazily and best-effort.
"""

from __future__ import annotations

import math
import sys
import time

from tpuflow.obs.forensics import dump_forensics, record_event
from tpuflow.obs.metrics import default_registry
from tpuflow.obs.tracing import record_span

# The watchdog's policy vocabulary — validated by the preflight spec
# pass (tpuflow/analysis/spec.py) so a typo'd policy dies at submission.
HEALTH_POLICIES = ("warn", "abort", "halve_lr")
# Values that disable the watchdog entirely.
HEALTH_OFF = (None, "", "off", "none")


class NumericsDivergence(RuntimeError):
    """The numerics watchdog aborted a diverging run (``policy="abort"``).

    ``epoch`` is the epoch the fatal anomaly landed on; ``anomalies`` is
    the run's full anomaly trail (``{"epoch", "kind", "value"}`` dicts).
    Deliberately distinct from ``CrashLoopError``: the supervisor treats
    it as terminal WITHOUT burning restart-backoff attempts — a diverged
    optimizer state replays deterministically from the checkpoint.
    """

    def __init__(self, message: str, epoch: int | None = None, anomalies=()):
        super().__init__(message)
        self.epoch = epoch
        self.anomalies = list(anomalies)


class NumericsWatchdog:
    """Per-epoch numeric-health checks over already-host loss/grad aux.

    The fit loop calls :meth:`observe_epoch` once per epoch with the
    epoch's batch losses and grad norms as HOST floats (it converts
    them post-epoch anyway for the epoch-mean log line — the watchdog
    adds no device syncs and nothing inside jit). Detection:

    - ``nan_loss`` / ``inf_loss`` / ``nan_grad`` / ``inf_grad``: any
      non-finite value in the epoch's aux — the unambiguous signals.
    - ``spike_loss`` / ``spike_grad``: the epoch mean exceeds
      ``spike_factor`` x the EWMA of previous healthy epochs, after
      ``warmup_epochs`` healthy epochs have seeded the EWMA. Anomalous
      epochs never update the EWMA (a spike must not raise its own bar).

    Policies: ``warn`` logs and continues; ``halve_lr`` scales the
    optimizer's LR by 0.5 through the ``with_lr_scale`` leaf in the
    optimizer state (up to ``max_halvings`` times, then warns);
    ``abort`` raises :class:`NumericsDivergence`.
    """

    def __init__(
        self,
        policy: str = "warn",
        *,
        ewma_alpha: float = 0.3,
        spike_factor: float = 10.0,
        warmup_epochs: int = 1,
        max_halvings: int = 4,
        storage_path: str | None = None,
        model_name: str = "model",
        logger=None,
        registry=None,
        verbose: bool = True,
        dump_identity: str | None = None,
    ):
        if policy not in HEALTH_POLICIES:
            raise ValueError(
                f"unknown health policy {policy!r}; "
                f"valid: {', '.join(HEALTH_POLICIES)}"
            )
        self.policy = policy
        self.ewma_alpha = float(ewma_alpha)
        self.spike_factor = float(spike_factor)
        self.warmup_epochs = int(warmup_epochs)
        self.max_halvings = int(max_halvings)
        self.storage_path = storage_path
        self.model_name = model_name
        self.logger = logger
        self.verbose = verbose
        # Fleet identity suffix for the dump file (an elastic worker id):
        # siblings sharing one storage root must not clobber each other.
        self.dump_identity = dump_identity
        self.anomalies: list[dict] = []
        self.halvings = 0
        self._ewma_loss: float | None = None
        self._ewma_grad: float | None = None
        self._healthy_epochs = 0
        self._dumped = False
        self._counter = (registry or default_registry()).counter(
            "train_numerics_anomalies_total",
            "numerics anomalies (NaN/Inf/spike) detected by the training "
            "watchdog, by kind",
        )

    # --- detection -----------------------------------------------------

    @staticmethod
    def _classify(values, nan_kind: str, inf_kind: str):
        """(anomaly kind or None, representative value, finite mean)."""
        finite, bad_kind, bad_value = [], None, None
        for v in values:
            v = float(v)
            if math.isnan(v):
                bad_kind, bad_value = nan_kind, v
            elif math.isinf(v):
                if bad_kind != nan_kind:  # nan outranks inf in the report
                    bad_kind, bad_value = inf_kind, v
            else:
                finite.append(v)
        mean = sum(finite) / len(finite) if finite else None
        return bad_kind, bad_value, mean

    def _spike(self, mean: float | None, ewma: float | None) -> bool:
        if mean is None or ewma is None:
            return False
        if self._healthy_epochs < self.warmup_epochs:
            return False
        # The epsilon keeps a near-zero converged EWMA from flagging
        # ordinary float noise as a 10x "spike".
        return mean > self.spike_factor * max(ewma, 1e-12)

    def observe_epoch(self, epoch: int, losses, grad_norms=None, state=None):
        """Check one epoch's host-float aux; returns the (possibly
        LR-halved) train state. Raises :class:`NumericsDivergence` under
        ``policy="abort"``. ``losses``/``grad_norms`` are sequences of
        host floats — convert device aux ONCE, after the epoch's batch
        loop (TPF006)."""
        found: list[dict] = []
        kind, value, loss_mean = self._classify(
            losses, "nan_loss", "inf_loss"
        )
        if kind:
            found.append({"kind": kind, "value": value})
        if grad_norms:
            gkind, gvalue, grad_mean = self._classify(
                grad_norms, "nan_grad", "inf_grad"
            )
            if gkind:
                found.append({"kind": gkind, "value": gvalue})
        else:
            grad_mean = None
        if not kind and self._spike(loss_mean, self._ewma_loss):
            found.append({"kind": "spike_loss", "value": loss_mean})
        if grad_norms and not any(
            a["kind"] in ("nan_grad", "inf_grad") for a in found
        ) and self._spike(grad_mean, self._ewma_grad):
            found.append({"kind": "spike_grad", "value": grad_mean})

        if not found:
            # Healthy epoch: seed/advance the EWMAs.
            a = self.ewma_alpha
            if loss_mean is not None:
                self._ewma_loss = (
                    loss_mean if self._ewma_loss is None
                    else a * loss_mean + (1 - a) * self._ewma_loss
                )
            if grad_mean is not None:
                self._ewma_grad = (
                    grad_mean if self._ewma_grad is None
                    else a * grad_mean + (1 - a) * self._ewma_grad
                )
            self._healthy_epochs += 1
            return state

        for a in found:
            a["epoch"] = epoch
            self.anomalies.append(a)
            self._counter.inc(kind=a["kind"])
            record_event("numerics_anomaly", **a)
            if self.logger is not None:
                self.logger.write("numerics_anomaly", **a)
        self._dump_once(found)
        return self._apply_policy(epoch, found, state)

    # --- response ------------------------------------------------------

    def _dump_once(self, found: list[dict]) -> None:
        """First anomaly dumps the forensics ring next to the artifacts —
        even under ``warn``, the trail of what led up to the divergence
        is the evidence the policy decision gets judged by later."""
        if self._dumped or not self.storage_path:
            return
        self._dumped = True
        from tpuflow.obs.forensics import forensics_path

        kinds = ",".join(a["kind"] for a in found)
        dump_forensics(
            forensics_path(self.storage_path, identity=self.dump_identity),
            reason=f"numerics watchdog: {kinds} in {self.model_name}",
        )

    def _warn(self, message: str) -> None:
        if self.verbose:
            print(f"tpuflow.obs.health: {message}", file=sys.stderr)

    def _apply_policy(self, epoch: int, found: list[dict], state):
        kinds = ", ".join(f"{a['kind']}={a['value']:g}" for a in found)
        if self.policy == "abort":
            raise NumericsDivergence(
                f"numerics watchdog aborting {self.model_name} at epoch "
                f"{epoch}: {kinds} (policy=abort; a diverged run replays "
                "deterministically — restarts cannot fix it)",
                epoch=epoch,
                anomalies=self.anomalies,
            )
        if self.policy == "halve_lr" and state is not None:
            if self.halvings >= self.max_halvings:
                self._warn(
                    f"epoch {epoch}: {kinds}; LR already halved "
                    f"{self.halvings}x (max_halvings reached) — continuing"
                )
                return state
            from tpuflow.train.optim import scale_lr_in_state

            scaled = scale_lr_in_state(state, 0.5)
            if scaled is None:
                self._warn(
                    f"epoch {epoch}: {kinds}; policy=halve_lr but the "
                    "optimizer state carries no with_lr_scale leaf "
                    "(custom optimizer?) — warning instead"
                )
                return state
            self.halvings += 1
            record_event(
                "lr_halved", epoch=epoch, halvings=self.halvings
            )
            if self.logger is not None:
                self.logger.write(
                    "lr_halved", epoch=epoch, halvings=self.halvings
                )
            self._warn(
                f"epoch {epoch}: {kinds}; halving LR "
                f"(x{0.5 ** self.halvings:g} total)"
            )
            return scaled
        self._warn(f"epoch {epoch}: {kinds} (policy=warn; continuing)")
        return state


# --- recompile detection ----------------------------------------------


def _arg_signature(args, kwargs) -> tuple:
    """Shape/dtype fingerprint of a step call's data arguments. Array
    leaves contribute ``(shape, dtype)``; everything else its type name
    (a changed python-scalar VALUE is not a retrace — same shape/dtype
    hits the same executable)."""
    sig = []
    for a in list(args) + sorted(kwargs.items()):
        if isinstance(a, tuple) and len(a) == 2 and isinstance(a[0], str):
            name, a = a
            sig.append(name)
        shape = getattr(a, "shape", None)
        dtype = getattr(a, "dtype", None)
        if shape is not None and dtype is not None:
            sig.append((tuple(shape), str(dtype)))
        else:
            sig.append(type(a).__name__)
    return tuple(sig)


class RecompileDetector:
    """Counts XLA compilations per wrapped step fn by data-arg signature.

    ``wrap(fn, name)`` returns ``fn`` behind a signature check over its
    NON-state arguments (the state's shapes are fixed for a run; the
    batch args are where churn comes from). The first signature per
    step is the expected compile; every later one is a recompile —
    timed (the compile happens inside that call) and recorded as an
    ``xla.compile`` span naming the offending shapes. The
    ``train_recompiles`` gauge tracks the running count;
    :meth:`summary` renders the run-report diagnostic, with recompiles
    after ``steady_after`` flagged as steady-state shape churn.
    """

    def __init__(self, *, registry=None, logger=None):
        self.events: list[dict] = []
        self.epoch = 0
        self.logger = logger
        self._signatures: dict[str, set] = {}
        self._expected: str | None = None
        self._gauge = (registry or default_registry()).gauge(
            "train_recompiles",
            "XLA recompilations observed by the current run (signature "
            "churn on wrapped step functions)",
        )
        self._gauge.set(0.0)

    @property
    def count(self) -> int:
        """Recompiles observed so far — the occupancy autotuner's
        budget reads the delta of this across its moves."""
        return len(self.events)

    def expect(self, reason: str | None) -> None:
        """Tag the NEXT recompile event as expected (``reason``, e.g.
        "autotune"): a compile the controller deliberately paid for is
        budget accounting, not the steady-state shape churn
        :meth:`summary` diagnoses. One-shot — consumed by the next
        event, replaced by the next call."""
        self._expected = reason

    def wrap(self, fn, name: str, count_first: bool = False):
        """``count_first=True`` records even the FIRST compile of this
        step name as an event: step variants the autotuner builds
        mid-run (a remat toggle, a late scan program) are recompiles of
        the RUN even though they are compile #1 of their wrapper —
        without this their cost would be invisible to the budget and
        the timeline."""
        if fn is None:
            return None
        seen = self._signatures.setdefault(name, set())

        def wrapped(*args, **kwargs):
            sig = _arg_signature(args[1:], kwargs)
            if sig in seen:
                return fn(*args, **kwargs)
            first = not seen
            seen.add(sig)
            t0 = time.perf_counter()
            out = fn(*args, **kwargs)
            if not first or count_first:
                dur = time.perf_counter() - t0
                event = {
                    "epoch": self.epoch,
                    "step": name,
                    "signature": repr(sig),
                }
                if self._expected is not None:
                    event["expected"] = self._expected
                    self._expected = None
                self.events.append(event)
                self._gauge.set(float(len(self.events)))
                record_span(
                    "xla.compile", dur, logger=self.logger, **event
                )
            return out

        return wrapped

    def summary(self, steady_after: int = 1) -> dict | None:
        """The run-report diagnostic, or None when no recompiles fired.
        ``steady_after``: recompiles at epochs strictly beyond it are
        steady-state churn (the first epoch's compiles are the price of
        admission; later ones mean the run never reaches a fixed set of
        programs)."""
        if not self.events:
            return None
        # Expected compiles (the autotuner's budgeted moves) are charged
        # and visible in the trail, but they are not shape CHURN — the
        # diagnostic exists for recompiles nobody asked for.
        steady = [
            e for e in self.events
            if e["epoch"] > steady_after and not e.get("expected")
        ]
        rec = {
            "recompiles": len(self.events),
            "steady_state": len(steady),
            "expected": sum(
                1 for e in self.events if e.get("expected")
            ),
            "by_step": sorted({e["step"] for e in self.events}),
            "last_signature": self.events[-1]["signature"],
        }
        if steady:
            rec["diagnostic"] = (
                f"{len(steady)} steady-state XLA recompile(s) after epoch "
                f"{steady_after} (steps: {', '.join(rec['by_step'])}; last "
                f"shapes {rec['last_signature']}) — shape churn makes a "
                "run look like slow hardware; pad/bucket batch shapes"
            )
        return rec


_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"
_LISTENER = {"installed": False}


def install_compile_listener(registry=None) -> bool:
    """Count every XLA backend compile process-wide into
    ``xla_compilations_total`` via ``jax.monitoring``, where available.
    Idempotent and best-effort: returns False (and stays silent) on a
    jax without the monitoring surface — the per-run
    :class:`RecompileDetector` wrapper is the portable fallback."""
    if _LISTENER["installed"]:
        return True
    try:
        from jax import monitoring
    except Exception:
        return False
    counter = (registry or default_registry()).counter(
        "xla_compilations_total",
        "XLA backend compilations in this process (jax.monitoring)",
    )

    def _on_event(name: str, duration: float, **_kw) -> None:
        if name == _COMPILE_EVENT:
            counter.inc()

    try:
        monitoring.register_event_duration_secs_listener(_on_event)
    except Exception:
        return False
    _LISTENER["installed"] = True
    return True


# --- live MFU / roofline ----------------------------------------------


def publish_roofline(
    samples_per_sec_per_chip: float,
    flops_per_sample: float,
    bytes_per_sample: float,
    device_kind: str,
    *,
    compute_dtype: str | None = None,
    registry=None,
    logger=None,
    epoch: int | None = None,
) -> dict:
    """One live roofline reading: MFU/HBM-util/bound for the epoch just
    measured, published as ``train_mfu`` / ``train_hbm_util`` /
    ``train_bound{bound=...}`` gauges (rendered by
    ``GET /metrics?format=prometheus`` via the default registry) and a
    ``roofline`` record in the run's metrics JSONL. On a chip without a
    peaks entry (cpu) the gauges are left untouched — an MFU of 0.0 for
    "unknown chip" would read as a real measurement — but the JSONL
    record still lands, carrying the verdict string."""
    from tpuflow.utils.roofline import roofline_report

    rep = roofline_report(
        samples_per_sec_per_chip, flops_per_sample, bytes_per_sample,
        device_kind, compute_dtype=compute_dtype,
    )
    reg = registry or default_registry()
    if rep.get("mfu") is not None:
        reg.gauge(
            "train_mfu",
            "model FLOPs utilization of the last measured epoch",
        ).set(rep["mfu"])
        reg.gauge(
            "train_hbm_util",
            "HBM bandwidth utilization of the last measured epoch",
        ).set(rep["hbm_util"])
        bound = reg.gauge(
            "train_bound",
            "what bounds the run: the bound=... label with value 1",
        )
        for b in ("hbm", "mxu"):
            bound.set(1.0 if rep["bound"] == b else 0.0, bound=b)
    if logger is not None:
        logger.write(
            "roofline",
            epoch=epoch,
            samples_per_sec_per_chip=round(
                float(samples_per_sec_per_chip), 3
            ),
            **rep,
        )
    return rep
