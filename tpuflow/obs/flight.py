"""Alert-triggered flight recorder: atomic forensic bundles.

When an :class:`~tpuflow.obs.alerts.AlertEngine` rule starts firing (or a
supervised service is declared FAILED), the evidence that explains *why*
is usually gone by the time anyone looks — threads have moved on, the
history ring has rotated, the profiler keeps averaging the spike away.
The recorder captures one **bundle** at that instant: an all-thread stack
dump, the profiler snapshot, the rule-relevant :class:`MetricsHistory`
window, the trail tail, alerts state, a registry snapshot, and an
env/knob fingerprint — written in a single ``put_atomic`` through the
storage seam under manifest schema ``tpuflow.obs.flight/v1`` so a
concurrent ``obs flight`` reader never sees a torn bundle.

Captures are rate-limited (``min_interval_s``; a crash capture passes
``force=True`` — crashes are rare and must never be suppressed by alert
chatter) and retention-bounded (``max_bundles`` newest kept; bundle names
sort by capture time). Everything is off by default; ``flight_from_env``
wires the ``TPUFLOW_OBS_FLIGHT_*`` knobs.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
import traceback

from tpuflow.storage import join_key, resolve_store
from tpuflow.utils.env import env_flag, env_num

SCHEMA = "tpuflow.obs.flight/v1"

DEFAULT_MIN_INTERVAL_S = 30.0
DEFAULT_MAX_BUNDLES = 8

_TRAIL_TAIL_LINES = 64
_FORENSICS_TAIL = 64
_ENV_PREFIXES = ("TPUFLOW_", "JAX_", "XLA_", "BENCH_")


def _thread_dump() -> list[dict]:
    from tpuflow.obs.profiler import component_for

    names = {}
    for t in threading.enumerate():
        if t.ident is not None:
            names[t.ident] = (t.name, t.daemon)
    me = threading.get_ident()
    rows = []
    for ident, frame in sys._current_frames().items():
        name, daemon = names.get(ident, (f"thread-{ident}", True))
        rows.append(
            {
                "name": name,
                "ident": ident,
                "daemon": daemon,
                "component": component_for(name),
                "current": ident == me,
                "stack": [
                    {"file": fs.filename, "line": fs.lineno, "func": fs.name}
                    for fs in traceback.extract_stack(frame)
                ],
            }
        )
    rows.sort(key=lambda r: (r["name"], r["ident"]))
    return rows


def _env_fingerprint() -> dict:
    return {
        "python": sys.version.split()[0],
        "platform": sys.platform,
        "pid": os.getpid(),
        "argv": list(sys.argv),
        "knobs": {
            k: os.environ[k]
            for k in sorted(os.environ)
            if k.startswith(_ENV_PREFIXES)
        },
    }


def _tail_lines(path: str | None, n: int) -> list[str]:
    if not path:
        return []
    try:
        with open(path, encoding="utf-8", errors="replace") as fh:
            return [line.rstrip("\n") for line in fh][-n:]
    except OSError:
        return []


class FlightRecorder:
    """Capture forensic bundles into ``root`` through the storage seam.

    All wiring is optional — a recorder with nothing but a root still
    produces a useful bundle (threads + env + forensics tail). ``attach``
    subscribes it to an engine's transitions; the supervisor calls
    ``capture("crash", ..., force=True)`` directly.
    """

    def __init__(
        self,
        root: str,
        *,
        history=None,
        profiler=None,
        alerts=None,
        registry=None,
        logger=None,
        min_interval_s: float = DEFAULT_MIN_INTERVAL_S,
        max_bundles: int = DEFAULT_MAX_BUNDLES,
        clock=time.monotonic,
    ):
        if max_bundles < 1:
            raise ValueError(f"max_bundles must be >= 1, got {max_bundles!r}")
        self.root = root
        self.history = history
        self.profiler = profiler
        self.alerts = alerts
        self.registry = registry
        self.logger = logger
        self.min_interval_s = float(min_interval_s)
        self.max_bundles = int(max_bundles)
        self.clock = clock
        self._store, self._prefix = resolve_store(root)
        self._lock = threading.Lock()
        self._last_capture: float | None = None
        self._seq = 0
        self._m_bundles = self._m_suppressed = None
        if registry is not None:
            self._m_bundles = registry.counter(
                "obs_flight_bundles_total",
                "Flight-recorder bundles captured, by trigger",
            )
            self._m_suppressed = registry.counter(
                "obs_flight_suppressed_total",
                "Flight captures suppressed by the rate limit",
            )

    def attach(self, alerts) -> "FlightRecorder":
        """Subscribe to an AlertEngine: every ``firing`` transition
        becomes a (rate-limited) capture."""
        self.alerts = alerts
        alerts.add_listener(self._on_transition)
        return self

    def _on_transition(self, rec: dict) -> None:
        if rec.get("state") != "firing":
            return
        self.capture(
            "alert",
            reason=(
                f"rule {rec.get('rule')} firing: {rec.get('metric')}"
                f"={rec.get('value')} vs {rec.get('threshold')}"
            ),
            rule_name=rec.get("rule"),
        )

    # -- capture --------------------------------------------------------

    def capture(
        self,
        trigger: str,
        *,
        reason: str = "",
        rule_name: str | None = None,
        force: bool = False,
    ) -> str | None:
        """Capture one bundle; returns its key suffix (bundle name) or
        None when rate-limited or the write failed. Never raises — the
        recorder must not take down the plane it is documenting."""
        now = self.clock()
        with self._lock:
            if (
                not force
                and self._last_capture is not None
                and now - self._last_capture < self.min_interval_s
            ):
                if self._m_suppressed is not None:
                    self._m_suppressed.inc()
                return None
            self._last_capture = now
            self._seq += 1
            seq = self._seq
        try:
            doc = self._build(trigger, reason, rule_name)
            name = (
                f"bundle-{int(doc['captured_unix'] * 1000):013d}"
                f"-{os.getpid()}-{seq:03d}-{trigger}.json"
            )
            data = json.dumps(doc, default=str, sort_keys=True).encode("utf-8")
            self._store.put_atomic(join_key(self._prefix, name), data)
            self._enforce_retention()
        except Exception:
            return None
        if self._m_bundles is not None:
            self._m_bundles.inc(trigger=trigger)
        try:
            from tpuflow.obs.forensics import record_event

            record_event("flight_capture", bundle=name, trigger=trigger, reason=reason)
        except Exception:
            pass
        if self.logger is not None:
            try:
                self.logger.write(
                    "flight_capture", bundle=name, trigger=trigger, reason=reason
                )
            except Exception:
                pass
        return name

    def _build(self, trigger: str, reason: str, rule_name: str | None) -> dict:
        from tpuflow.obs.forensics import recent_events

        doc = {
            "schema": SCHEMA,
            "trigger": trigger,
            "reason": reason,
            "rule": rule_name,
            "captured_unix": time.time(),
            "threads": _thread_dump(),
            "env": _env_fingerprint(),
            "forensics_tail": recent_events(_FORENSICS_TAIL),
            "trail_tail": _tail_lines(
                getattr(self.logger, "path", None), _TRAIL_TAIL_LINES
            ),
        }
        if self.profiler is not None:
            try:
                doc["profile"] = self.profiler.snapshot()
            except Exception:
                doc["profile"] = None
        if self.alerts is not None:
            try:
                doc["alerts"] = self.alerts.summary()
            except Exception:
                doc["alerts"] = None
        if self.registry is not None:
            try:
                doc["registry"] = {
                    fam.name: {
                        "kind": fam.kind,
                        "samples": [
                            [suffix, labels, value]
                            for suffix, labels, value in fam.collect()
                        ],
                    }
                    for fam in self.registry.collect()
                }
            except Exception:
                doc["registry"] = None
        if self.history is not None:
            doc["history"] = self._history_window(rule_name)
        return doc

    def _history_window(self, rule_name: str | None) -> dict | None:
        try:
            out = {"summary": self.history.summary(), "series": {}}
            rule = None
            if self.alerts is not None and rule_name:
                for r in self.alerts.rules:
                    if r["name"] == rule_name:
                        rule = r
                        break
            if rule is not None:
                window = 2 * rule["window_s"] + rule["for_s"]
                pts = self.history.points(
                    rule["metric"], window, **rule["labels"]
                )
                out["series"][rule["metric"]] = {
                    "labels": rule["labels"],
                    "window_s": window,
                    "points": [[t, v] for t, v in pts],
                }
            return out
        except Exception:
            return None

    # -- retention / access ---------------------------------------------

    def _enforce_retention(self) -> None:
        names = self.list_bundles()
        for name in names[: -self.max_bundles]:
            try:
                self._store.delete(join_key(self._prefix, name))
            except Exception:
                pass

    def list_bundles(self) -> list[str]:
        """Bundle names, oldest first (names embed capture time)."""
        prefix = join_key(self._prefix, "bundle-")
        return sorted(
            key.rsplit("/", 1)[-1] for key in self._store.list(prefix)
        )

    def load(self, name: str) -> dict:
        return json.loads(
            self._store.get(join_key(self._prefix, name)).decode("utf-8")
        )


def validate_bundle(doc) -> list[str]:
    """Structural check for a flight bundle; empty list == schema-valid."""
    problems: list[str] = []
    if not isinstance(doc, dict):
        return ["bundle is not an object"]
    if doc.get("schema") != SCHEMA:
        problems.append(f"schema is {doc.get('schema')!r}, want {SCHEMA!r}")
    if not isinstance(doc.get("trigger"), str) or not doc.get("trigger"):
        problems.append("trigger missing or not a string")
    if not isinstance(doc.get("captured_unix"), (int, float)):
        problems.append("captured_unix missing or not a number")
    threads = doc.get("threads")
    if not isinstance(threads, list) or not threads:
        problems.append("threads missing or empty")
    else:
        for i, row in enumerate(threads):
            if not isinstance(row, dict) or not {"name", "component", "stack"} <= set(row):
                problems.append(f"threads[{i}] malformed")
                break
    if not isinstance(doc.get("env"), dict) or "knobs" not in doc.get("env", {}):
        problems.append("env fingerprint missing")
    if "profile" in doc and doc["profile"] is not None:
        from tpuflow.obs.profiler import validate_snapshot

        problems.extend(
            f"profile: {p}" for p in validate_snapshot(doc["profile"])
        )
    return problems


def list_bundles(root: str) -> list[str]:
    """Bundle names under ``root``, oldest first (CLI helper)."""
    store, prefix = resolve_store(root)
    return sorted(
        key.rsplit("/", 1)[-1]
        for key in store.list(join_key(prefix, "bundle-"))
    )


def load_bundle(root: str, name: str) -> dict:
    store, prefix = resolve_store(root)
    return json.loads(store.get(join_key(prefix, name)).decode("utf-8"))


def flight_from_env(
    *,
    default_root: str | None = None,
    history=None,
    profiler=None,
    alerts=None,
    registry=None,
    logger=None,
) -> FlightRecorder | None:
    """Build a recorder from ``TPUFLOW_OBS_FLIGHT_*`` knobs; None when off.

    ``TPUFLOW_OBS_FLIGHT_DIR`` (or ``default_root``) names the bundle
    store — enabling the recorder without a destination is a config
    error and fails loud."""
    if not env_flag("TPUFLOW_OBS_FLIGHT", False):
        return None
    root = os.environ.get("TPUFLOW_OBS_FLIGHT_DIR") or default_root
    if not root:
        raise ValueError(
            "TPUFLOW_OBS_FLIGHT=1 requires TPUFLOW_OBS_FLIGHT_DIR=<dir-or-url> "
            "(where should bundles go?)"
        )
    return FlightRecorder(
        root,
        history=history,
        profiler=profiler,
        alerts=alerts,
        registry=registry,
        logger=logger,
        min_interval_s=env_num(
            "TPUFLOW_OBS_FLIGHT_MIN_INTERVAL_S", DEFAULT_MIN_INTERVAL_S, float, minimum=0.0
        ),
        max_bundles=env_num(
            "TPUFLOW_OBS_FLIGHT_MAX_BUNDLES", DEFAULT_MAX_BUNDLES, int, minimum=1
        ),
    )
