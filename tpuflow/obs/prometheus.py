"""Prometheus text exposition (text/plain; version=0.0.4) rendering.

``render_prometheus(*registries)`` turns one or more
:class:`~tpuflow.obs.metrics.Registry` instances into the exposition
format any Prometheus-compatible scraper ingests::

    # HELP tpuflow_predict_requests_total /predict requests served
    # TYPE tpuflow_predict_requests_total counter
    tpuflow_predict_requests_total 42

The serve daemon exposes it at ``GET /metrics?format=prometheus``
(docs/observability.md has the scrape config); the JSON ``/metrics``
view is unchanged. Families from later registries with a name already
rendered are skipped (first wins) — the serve endpoint renders its
run-scoped registry first, then the process-wide default registry, so
a name collision can't produce a duplicate family in one scrape.
"""

from __future__ import annotations

import math


def _escape_help(text: str) -> str:
    return text.replace("\\", "\\\\").replace("\n", "\\n")


def _escape_label(value: str) -> str:
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace("\n", "\\n")
        .replace('"', '\\"')
    )


def _fmt_value(v: float) -> str:
    f = float(v)
    # Non-finite first: int(nan)/int(inf) raise, and one poisoned value
    # must not kill the whole scrape (Prometheus spells these NaN/+Inf).
    if math.isnan(f):
        return "NaN"
    if math.isinf(f):
        return "+Inf" if f > 0 else "-Inf"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def render_prometheus(*registries) -> str:
    """Render registries to exposition text (trailing newline included,
    as the format requires)."""
    lines: list[str] = []
    seen: set[str] = set()
    for registry in registries:
        for family in registry.collect():
            if family.name in seen:
                continue
            seen.add(family.name)
            lines.append(
                f"# HELP {family.name} {_escape_help(family.help or family.name)}"
            )
            lines.append(f"# TYPE {family.name} {family.kind}")
            for suffix, labels, value in family.collect():
                if labels:
                    label_str = ",".join(
                        f'{k}="{_escape_label(v)}"'
                        for k, v in sorted(labels.items())
                    )
                    lines.append(
                        f"{family.name}{suffix}{{{label_str}}} "
                        f"{_fmt_value(value)}"
                    )
                else:
                    lines.append(
                        f"{family.name}{suffix} {_fmt_value(value)}"
                    )
    return "\n".join(lines) + "\n"
