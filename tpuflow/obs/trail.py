"""Tolerant JSONL trail reading, shared by every log-consuming tool.

A trail written during a crash (a forensics dump racing a dying
process, a metrics file on a preempted VM) can end mid-line — or
mid-UTF-8-sequence. Every reader of the format (``python -m tpuflow.obs
tail|summary|timeline``) must treat that as data loss to REPORT, not an
exception to die on: the whole point of the trail is to be readable
after something went wrong.

Deliberately dependency-light (no jax import): usable on a machine that
only has the log files.
"""

from __future__ import annotations

import json


def read_events(path: str) -> tuple[list[dict], int]:
    """Parse a JSONL trail; returns ``(events, skipped_lines)``.

    Corrupt lines — crash-truncated tails, torn multi-byte sequences,
    non-object records — are counted, never fatal. ``errors="replace"``
    on the decode: a line torn mid-UTF-8-sequence must skip THAT line,
    not raise ``UnicodeDecodeError`` over the readable rest of the file.
    """
    events, skipped = [], 0
    with open(path, encoding="utf-8", errors="replace") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                skipped += 1
                continue
            if isinstance(rec, dict):
                events.append(rec)
            else:
                skipped += 1
    return events, skipped
