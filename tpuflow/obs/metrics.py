"""The metrics registry: counters, gauges, histograms, summaries.

The reference's only observability is a lost print of wall-clock and
final loss (SURVEY.md §5.5, reference cnn.py:126-134); the distributed
lineage (SparkNet/BigDL, PAPERS.md) treats per-node throughput and
straggler visibility as first-class. This module is the shared substrate
the rest of tpuflow records into: one :class:`Registry` per scope — a
process-wide default for framework-level signals (fault injections, I/O
retries, train-loop throughput) plus run-scoped instances for services
that must not bleed counts across instances (each ``PredictService`` /
``JobRunner`` owns one).

Design constraints:

- **Lock-cheap.** One ``threading.Lock`` per metric family; a counter
  increment is a lock + dict add. Cheap enough for per-batch paths
  (prefetch, micro-batch dispatch), NOT cheap enough for inside-jit.
- **Never inside jit.** Recording forces host work; a ``.inc()`` on a
  traced value would also be a host sync. Record OUTSIDE jitted code —
  enforced by the TPF005 lint rule (``tpuflow/analysis/linter.py``).
- **Pull-consistent.** Gauges may carry a callback evaluated at
  collect time, so "queued jobs right now" is read under the owner's
  own lock instead of being pushed on every transition. The callback
  RUNS ON THE SCRAPE THREAD — so it must actually take the owner's
  lock when the value it reads is lock-guarded (the TPF016 rule of the
  repo concurrency pass: pass a bound ``_read_*`` method that acquires
  the lock, never a bare ``lambda: self._guarded_thing``). Safe by
  construction: ``collect`` holds no metric-family lock while
  evaluating a callback, so owner-lock → family-lock stays the one
  ordering in the process.

Rendering to Prometheus text exposition lives in
``tpuflow/obs/prometheus.py``; :meth:`Registry.collect` is the seam.
"""

from __future__ import annotations

import threading
from typing import Callable, Iterable

# Fixed default buckets (seconds) for latency-ish histograms: a pow-2
# ladder wide enough for both micro-batch dispatches and whole epochs.
DEFAULT_TIME_BUCKETS = (
    0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 30.0, 120.0,
)
# Fixed buckets for request-count histograms (batch sizes coalesce on
# pow-2 padding, so pow-2 edges describe the real dispatch shapes).
DEFAULT_COUNT_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256)

_NO_LABELS = ()


def _label_key(labels: dict) -> tuple:
    return tuple(sorted(labels.items()))


class _Family:
    """Shared base: name, help text, a lock, and per-labelset values."""

    kind = "untyped"

    def __init__(self, name: str, help: str):
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._values: dict[tuple, float] = {}

    def labels_seen(self) -> list[dict]:
        with self._lock:
            return [dict(k) for k in self._values]

    def collect(self) -> list[tuple[str, dict, float]]:
        """``(suffix, labels, value)`` samples; suffix appended to the
        family name (histograms/summaries emit ``_sum``/``_count``)."""
        with self._lock:
            return [("", dict(k), v) for k, v in sorted(self._values.items())]


class Counter(_Family):
    """Monotonic counter, optionally labeled: ``c.inc()`` or
    ``c.inc(3, site="csv.read")``."""

    kind = "counter"

    def inc(self, amount: float = 1.0, **labels) -> None:
        if amount < 0:
            raise ValueError(f"counter {self.name} cannot decrease ({amount})")
        key = _label_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def value(self, **labels) -> float:
        with self._lock:
            return self._values.get(_label_key(labels), 0.0)


class Gauge(_Family):
    """Point-in-time value. ``set``/``inc``/``dec`` for pushed values, or
    construct with ``fn`` for a pull gauge evaluated at collect time
    (e.g. "queued jobs"). ``fn`` runs on the SCRAPE thread: if it reads
    lock-guarded state, it must take the owner's lock itself — the
    module-docstring contract the TPF016 pass enforces."""

    kind = "gauge"

    def __init__(self, name: str, help: str, fn: Callable[[], float] | None = None):
        super().__init__(name, help)
        self._fn = fn

    def set(self, value: float, **labels) -> None:
        with self._lock:
            self._values[_label_key(labels)] = float(value)

    def inc(self, amount: float = 1.0, **labels) -> None:
        key = _label_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def dec(self, amount: float = 1.0, **labels) -> None:
        self.inc(-amount, **labels)

    def value(self, **labels) -> float:
        if self._fn is not None:
            return float(self._fn())
        with self._lock:
            return self._values.get(_label_key(labels), 0.0)

    def collect(self) -> list[tuple[str, dict, float]]:
        if self._fn is not None:
            # Callback gauges never throw the whole scrape away — but a
            # dead callback must OMIT its sample, not report a
            # legitimate-looking 0.0: "jobs_queued 0" during an incident
            # would suppress the exact alert the gauge exists to fire
            # (Prometheus treats a missing sample as stale, which is
            # honest).
            try:
                return [("", {}, float(self._fn()))]
            except Exception:
                return []
        return super().collect()


class Histogram(_Family):
    """Fixed-bucket histogram (cumulative ``le`` exposition). Buckets are
    fixed at construction — no re-bucketing, no per-observe allocation."""

    kind = "histogram"

    def __init__(self, name: str, help: str, buckets: Iterable[float]):
        super().__init__(name, help)
        edges = tuple(sorted(float(b) for b in buckets))
        if not edges:
            raise ValueError(f"histogram {self.name} needs at least one bucket")
        self.buckets = edges
        self._counts = [0] * (len(edges) + 1)  # + overflow (+Inf)
        self._sum = 0.0
        self._count = 0

    def observe(self, value: float) -> None:
        value = float(value)
        with self._lock:
            self._sum += value
            self._count += 1
            for i, edge in enumerate(self.buckets):
                if value <= edge:
                    self._counts[i] += 1
                    return
            self._counts[-1] += 1

    def snapshot(self) -> dict:
        with self._lock:
            counts = list(self._counts)
            total, s = self._count, self._sum
        cumulative, acc = [], 0
        for c in counts:
            acc += c
            cumulative.append(acc)
        return {
            "buckets": list(self.buckets),
            "cumulative": cumulative,  # last entry == count (+Inf bucket)
            "sum": s,
            "count": total,
        }

    def collect(self) -> list[tuple[str, dict, float]]:
        snap = self.snapshot()
        out = []
        for edge, cum in zip(snap["buckets"], snap["cumulative"]):
            le = f"{edge:g}"
            out.append(("_bucket", {"le": le}, float(cum)))
        out.append(("_bucket", {"le": "+Inf"}, float(snap["count"])))
        out.append(("_sum", {}, snap["sum"]))
        out.append(("_count", {}, float(snap["count"])))
        return out


class Summary(_Family):
    """Pull-style quantile summary: ``fn`` returns ``{"quantiles":
    {0.5: v, 0.99: v}, "sum": s, "count": n}`` at collect time — the
    bridge from an existing reservoir (``microbatch.LatencyStats``) to
    exposition without double-recording every sample."""

    kind = "summary"

    def __init__(self, name: str, help: str, fn: Callable[[], dict]):
        super().__init__(name, help)
        self._fn = fn

    def collect(self) -> list[tuple[str, dict, float]]:
        try:
            snap = self._fn() or {}
        except Exception:
            snap = {}
        out = []
        for q, v in sorted((snap.get("quantiles") or {}).items()):
            if v is not None:
                out.append(("", {"quantile": f"{q:g}"}, float(v)))
        out.append(("_sum", {}, float(snap.get("sum") or 0.0)))
        out.append(("_count", {}, float(snap.get("count") or 0)))
        return out


class Registry:
    """A namespace of metric families. Get-or-create semantics: asking
    for an existing name returns the existing family (so module-level
    helpers don't need to coordinate), but a kind mismatch — or a
    same-kind re-registration with a DIFFERENT callback/bucket config —
    fails loudly: two subsystems silently sharing one name is a
    scrape-corruption bug either way (the second registrant's values
    would silently never be scraped)."""

    def __init__(self, namespace: str = "tpuflow"):
        self.namespace = namespace
        self._lock = threading.Lock()
        self._families: dict[str, _Family] = {}

    def _get_or_create(self, cls, name: str, *args, check=None, **kwargs):
        full = f"{self.namespace}_{name}" if self.namespace else name
        with self._lock:
            fam = self._families.get(full)
            if fam is not None:
                if not isinstance(fam, cls):
                    raise ValueError(
                        f"metric {full!r} already registered as "
                        f"{fam.kind}, not {cls.kind}"
                    )
                if check is not None and not check(fam):
                    raise ValueError(
                        f"metric {full!r} already registered with a "
                        "different callback/bucket configuration — the "
                        "new registrant's values would silently never "
                        "be scraped (give it its own Registry or name)"
                    )
                return fam
            fam = cls(full, *args, **kwargs)
            self._families[full] = fam
            return fam

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get_or_create(Counter, name, help)

    def gauge(self, name: str, help: str = "", fn=None) -> Gauge:
        return self._get_or_create(
            Gauge, name, help, fn,
            check=(None if fn is None else lambda fam: fam._fn is fn),
        )

    def histogram(
        self, name: str, help: str = "",
        buckets: Iterable[float] = DEFAULT_TIME_BUCKETS,
    ) -> Histogram:
        edges = tuple(sorted(float(b) for b in buckets))
        return self._get_or_create(
            Histogram, name, help, edges,
            check=lambda fam: fam.buckets == edges,
        )

    def summary(self, name: str, help: str = "", fn=None) -> Summary:
        return self._get_or_create(
            Summary, name, help, fn,
            check=(None if fn is None else lambda fam: fam._fn is fn),
        )

    def peek(self, name: str) -> _Family | None:
        """Read an existing family WITHOUT creating it: consumers of
        someone else's signal (the occupancy autotuner reading the live
        roofline gauges) must not register an empty family under the
        producer's name — get-or-create would pin an empty-help stub as
        the first registrant and misreport honest absence (an unknown
        chip publishes no MFU gauge at all) as a zero."""
        full = f"{self.namespace}_{name}" if self.namespace else name
        with self._lock:
            return self._families.get(full)

    def collect(self) -> list[_Family]:
        with self._lock:
            return sorted(self._families.values(), key=lambda f: f.name)

    def reset(self) -> None:
        """Drop every family (tests only — production registries are
        append-only for the life of their scope)."""
        with self._lock:
            self._families.clear()


# The process-wide default registry: framework-level signals (fault
# injections, I/O retries, train-loop throughput, prefetch depth).
# Services that must not bleed counts across instances (PredictService,
# JobRunner, MicroBatcher) construct their own run-scoped Registry.
_DEFAULT = Registry()


def default_registry() -> Registry:
    return _DEFAULT
