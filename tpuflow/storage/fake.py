"""FakeRemoteStore — an object store with deliberately NO rename.

The test double that proves the seam without GCS credentials: a
dict-backed backend whose primitives are exactly what a bucket store
gives you — atomic whole-object PUT (last-writer-wins), GET, flat
prefix LIST, DELETE — and **nothing else**. There is no rename method
to call, so any code path that only works by renaming cannot pass a
test against this store; promotion must go through the base class's
pointer indirection. The inherited op log is the proof artifact: the
checkpoint, artifact-swap, and elastic-gang drills assert it contains
zero ``rename`` entries end to end.

``fake://bucket[/prefix]`` URIs resolve here (``tpuflow.storage
.resolve_store``): each bucket name maps to one process-global store,
so a coordinator thread and two worker threads dialing the same URI
share the same "remote" — the in-process gang drill's transport.
"""

from __future__ import annotations

import threading

from tpuflow.storage.base import ObjectStore


class FakeRemoteStore(ObjectStore):
    """In-memory bucket semantics; see the module docstring."""

    name = "fake"
    supports_rename = False

    def __init__(self, bucket: str = "fake"):
        super().__init__()
        self.bucket = bucket
        self._lock = threading.Lock()
        self._objects: dict[str, bytes] = {}

    def _put(self, key: str, data: bytes) -> None:
        with self._lock:
            self._objects[key] = data  # whole-object, last-writer-wins

    def _get(self, key: str) -> bytes:
        with self._lock:
            try:
                return self._objects[key]
            except KeyError:
                raise FileNotFoundError(
                    f"fake://{self.bucket}/{key}: no such object"
                ) from None

    def _list(self, prefix: str) -> list[str]:
        with self._lock:
            return [k for k in self._objects if k.startswith(prefix)]

    def _delete(self, key: str) -> bool:
        with self._lock:
            return self._objects.pop(key, None) is not None

    def _exists(self, key: str) -> bool:
        with self._lock:
            return key in self._objects

    def clear(self) -> None:
        """Drop every object and the op log (test isolation)."""
        with self._lock:
            self._objects.clear()
        self.op_log.clear()


_FAKES: dict[str, FakeRemoteStore] = {}
_FAKES_LOCK = threading.Lock()


def fake_store(bucket: str) -> FakeRemoteStore:
    """The process-global store for ``fake://bucket`` (created on first
    use — every thread dialing the bucket shares one instance)."""
    with _FAKES_LOCK:
        store = _FAKES.get(bucket)
        if store is None:
            store = _FAKES[bucket] = FakeRemoteStore(bucket)
        return store


def reset_fakes() -> None:
    """Forget every registered fake bucket (test isolation)."""
    with _FAKES_LOCK:
        _FAKES.clear()
