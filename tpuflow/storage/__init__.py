"""tpuflow.storage — the object-store seam (ROADMAP item 1).

One contract (:class:`~tpuflow.storage.base.ObjectStore`), two
backends — :class:`~tpuflow.storage.local.LocalStore` (POSIX reference;
atomic put = tmp+fsync+rename) and
:class:`~tpuflow.storage.fake.FakeRemoteStore` (bucket semantics,
deliberately no rename) — plus the resolvers and small JSON helpers the
migrated subsystems use. The repo-wide storage analyzer
(``tpuflow/analysis/storage.py``, TPF019–TPF021) enforces that direct
path I/O stays inside this seam and a short allow-list of leaf modules;
see docs/storage.md.
"""

from __future__ import annotations

import json
import os

from tpuflow.storage.base import ObjectStore, StorageError  # noqa: F401
from tpuflow.storage.fake import (  # noqa: F401
    FakeRemoteStore,
    fake_store,
    reset_fakes,
)
from tpuflow.storage.local import LocalStore  # noqa: F401

FAKE_SCHEME = "fake://"


def is_store_uri(path) -> bool:
    """True when ``path`` names an object-store root this package can
    resolve (``fake://bucket[/prefix]`` today; ``gs://`` is the next
    backend — ROADMAP item 1 is landed-except-gs)."""
    return isinstance(path, str) and path.startswith(FAKE_SCHEME)


def resolve_store(root: str) -> tuple[ObjectStore, str]:
    """``root`` -> ``(store, key_prefix)``.

    ``fake://bucket/prefix`` resolves to the process-global fake bucket
    with ``prefix`` as the key namespace; any other string is a local
    directory backed by :class:`LocalStore` with an empty prefix."""
    if is_store_uri(root):
        rest = root[len(FAKE_SCHEME):]
        bucket, _, prefix = rest.partition("/")
        if not bucket:
            raise ValueError(f"store URI {root!r} names no bucket")
        return fake_store(bucket), prefix.strip("/")
    return LocalStore(root), ""


def join_key(prefix: str, *parts: str) -> str:
    """Join key components under an optional namespace prefix."""
    pieces = [p.strip("/") for p in (prefix, *parts) if p and p.strip("/")]
    return "/".join(pieces)


def for_path(path: str) -> tuple[ObjectStore, str]:
    """A single file path -> ``(store, key)`` — the helper behind
    ``read_json``/``write_json`` so sidecar-sized records ride the seam
    whether the path is local or a store URI."""
    if is_store_uri(path):
        store, key = resolve_store(path)
        if not key:
            raise ValueError(f"store URI {path!r} names no object key")
        return store, key
    parent, name = os.path.split(os.path.abspath(path))
    return LocalStore(parent), name


def read_json(path: str):
    """Load one JSON record through the seam; raises
    ``FileNotFoundError``/``ValueError`` exactly like a direct read."""
    store, key = for_path(path)
    return json.loads(store.get(key).decode("utf-8"))


def write_json(path: str, obj) -> None:
    """Atomically publish one JSON record through the seam (local paths
    get tmp+fsync+rename; store URIs a single-object PUT)."""
    store, key = for_path(path)
    store.put_atomic(key, json.dumps(obj).encode("utf-8"))
