"""Pointer-indirected artifact promotion/rollback over an ObjectStore.

``online/swap.py``'s local implementation retains the incumbent by
renaming directories — the exact idiom a bucket store cannot express.
This is the store-native equivalent the swap seam dispatches to for
store-URI artifact roots: each promotion uploads the candidate's files
under a fresh **generation prefix**, writes a manifest object, and
flips the ``CURRENT`` pointer at it (old-or-new, never torn, zero
renames). Rollback is another pointer flip — back to the generation the
pointer doc recorded as ``previous`` — so the incumbent is retained by
*not deleting it*, which is how retention works when rename does not
exist.

Layout under ``{prefix}/``::

    gen-{n:06d}/{file...}        one promoted candidate's files
    gen-{n:06d}/MANIFEST.json    {"files": [...], "meta": {...}}
    CURRENT                      promotion pointer -> the manifest
"""

from __future__ import annotations

import json
import time

from tpuflow.storage import join_key
from tpuflow.storage.base import ObjectStore

POINTER = "CURRENT"
MANIFEST = "MANIFEST.json"


def _manifest_key(prefix: str, generation: int) -> str:
    return join_key(prefix, f"gen-{generation:06d}", MANIFEST)


def promote_files(
    store: ObjectStore,
    files: dict[str, bytes],
    *,
    prefix: str = "online",
    meta: dict | None = None,
    clock=time.time,
) -> dict:
    """Publish one candidate: upload every file under the next
    generation prefix, write the manifest, flip CURRENT. Returns the
    new pointer doc. Write order (files, manifest, pointer) means a
    crash anywhere mid-promotion leaves the old generation serving."""
    if not files:
        raise ValueError("promote_files: candidate has no files")
    pointer = join_key(prefix, POINTER)
    doc = store.resolve(pointer)
    generation = (doc["generation"] + 1) if doc else 1
    gen_prefix = join_key(prefix, f"gen-{generation:06d}")
    for name, data in sorted(files.items()):
        store.put(join_key(gen_prefix, name), data)
    store.put_atomic(
        _manifest_key(prefix, generation),
        json.dumps({
            "files": sorted(files),
            "meta": meta or {},
            "generation": generation,
        }).encode("utf-8"),
    )
    return store.promote(
        pointer, _manifest_key(prefix, generation),
        meta={**(meta or {}), "generation": generation},
        clock=clock,
    )


def rollback(
    store: ObjectStore, *, prefix: str = "online", clock=time.time
) -> dict:
    """Flip CURRENT back at the previous generation's manifest (which
    was never deleted — see the module docstring). Raises
    ``FileNotFoundError`` when there is nothing promoted or no previous
    generation to return to."""
    pointer = join_key(prefix, POINTER)
    doc = store.resolve(pointer)
    if doc is None:
        raise FileNotFoundError(
            f"rollback: pointer {pointer!r} has never been promoted"
        )
    previous = doc.get("previous")
    if not previous:
        raise FileNotFoundError(
            f"rollback: {pointer!r} has no previous generation "
            "(nothing was retained before the current promotion)"
        )
    return store.promote(
        pointer, previous,
        meta={"rolled_back_from": doc["target"]},
        clock=clock,
    )


def current_manifest(
    store: ObjectStore, *, prefix: str = "online"
) -> dict | None:
    """The manifest CURRENT points at, or None pre-first-promotion."""
    doc = store.resolve(join_key(prefix, POINTER))
    if doc is None:
        return None
    try:
        return json.loads(store.get(doc["target"]).decode("utf-8"))
    except (FileNotFoundError, ValueError):
        return None


def current_files(
    store: ObjectStore, *, prefix: str = "online"
) -> dict[str, bytes]:
    """Every file of the currently promoted generation, by name."""
    doc = store.resolve(join_key(prefix, POINTER))
    manifest = current_manifest(store, prefix=prefix)
    if doc is None or manifest is None:
        raise FileNotFoundError(
            f"{prefix}: no promoted generation to read"
        )
    gen_prefix = doc["target"].rsplit("/", 1)[0]
    return {
        name: store.get(join_key(gen_prefix, name))
        for name in manifest["files"]
    }
