"""Store-backed best-checkpointing: save/restore through the seam.

The Orbax ``BestCheckpointer`` (train/checkpoint.py) owns local trees;
this is its object-store twin for storage roots that resolve through
``tpuflow.storage`` (``fake://`` today, ``gs://`` next). Same contract
the train loop speaks — ``maybe_save`` keeps only the best-by-val_loss
checkpoint, reads wait for nothing (every put is synchronous and
atomic) — but built exclusively from seam primitives: the params ride
as the elastic exchange's checksummed npz payload (one object per
step), the best step is published by **pointer promotion** (never
rename), and superseded step objects are deleted after the pointer
flip, so a crash at any instant leaves a resolvable BEST pointer.

Layout under ``models/{name}/``::

    steps/{step:08d}.npz    checksummed leaves (exchange encoding)
    steps/{step:08d}.json   sidecar: val_loss + per-leaf shapes/dtypes
    BEST                    promotion pointer -> the winning .npz

``checkpoint.save`` / ``checkpoint.restore`` fire here exactly as in
the Orbax path (index = step), under the shared I/O retry policy.
"""

from __future__ import annotations

import json

from tpuflow.resilience import fault_point, io_policy, retry_call
from tpuflow.storage import join_key, resolve_store


class StoreCheckpointer:
    """Best-by-val-loss checkpointing against any ``ObjectStore``; see
    the module docstring. ``storage_root`` is a store URI or local
    directory (resolved through ``tpuflow.storage.resolve_store``)."""

    def __init__(self, storage_root: str, name: str = "model"):
        self.store, prefix = resolve_store(storage_root)
        self.prefix = join_key(prefix, "models", name)
        self.directory = storage_root

    def _step_key(self, step: int, ext: str) -> str:
        return join_key(self.prefix, "steps", f"{step:08d}.{ext}")

    @property
    def _pointer(self) -> str:
        return join_key(self.prefix, "BEST")

    def maybe_save(self, step: int, params, val_loss: float) -> bool:
        """Offer a checkpoint; kept only when it beats the current best.
        Write order is payload, sidecar, pointer, THEN the superseded
        step's deletes — a crash mid-save never breaks the standing
        BEST."""
        from tpuflow.elastic.exchange import encode_leaves, flatten_params

        doc = self.store.resolve(self._pointer)
        if doc is not None and float(val_loss) >= float(
            doc["meta"].get("val_loss", float("inf"))
        ):
            return False
        leaves = flatten_params(params)

        def _save():
            fault_point("checkpoint.save", index=step)
            self.store.put(self._step_key(step, "npz"),
                           encode_leaves(leaves))
            self.store.put_atomic(
                self._step_key(step, "json"),
                json.dumps({
                    "step": int(step),
                    "val_loss": float(val_loss),
                    "leaves": [
                        {"shape": list(leaf.shape),
                         "dtype": str(leaf.dtype)}
                        for leaf in leaves
                    ],
                }).encode("utf-8"),
            )
            self.store.promote(
                self._pointer, self._step_key(step, "npz"),
                meta={"step": int(step), "val_loss": float(val_loss)},
            )

        retry_call(io_policy(), _save)
        if doc is not None:  # max_to_keep=1: drop the superseded step
            old = int(doc["meta"].get("step", -1))
            if old >= 0 and old != int(step):
                self.store.delete(self._step_key(old, "npz"))
                self.store.delete(self._step_key(old, "json"))
        return True

    @property
    def best_step(self) -> int | None:
        doc = self.store.resolve(self._pointer)
        return None if doc is None else int(doc["meta"]["step"])

    def best_structure(self):
        """The best checkpoint's per-leaf shapes/dtypes (sidecar read,
        no array data) — the cheap compatibility probe."""
        step = self.best_step
        if step is None:
            raise FileNotFoundError(
                f"no checkpoint under {self.directory}"
            )
        doc = json.loads(
            self.store.get(self._step_key(step, "json")).decode("utf-8")
        )
        return doc["leaves"]

    def restore_best(self, params_like=None):
        """Restore the best params (into ``params_like``'s structure
        when given, else as the raw leaf list)."""
        doc = self.store.resolve(self._pointer)
        if doc is None:
            raise FileNotFoundError(
                f"no checkpoint under {self.directory}"
            )
        from tpuflow.elastic.exchange import decode_leaves, unflatten_like

        step = int(doc["meta"]["step"])

        def _restore():
            fault_point("checkpoint.restore", index=step)
            return decode_leaves(self.store.get(doc["target"]))

        leaves = retry_call(io_policy(), _restore)
        if params_like is None:
            return leaves
        return unflatten_like(params_like, leaves)

    def close(self):  # parity with BestCheckpointer; nothing in flight
        return None
