"""The object-store contract every backend implements.

ROADMAP item 1, PAPERS.md's BigDL/MMLSpark lesson: scale-out is a
storage-contract problem as much as a compute one. Every subsystem that
assumes "one shared POSIX filesystem with atomic rename" breaks the day
the artifact root becomes ``gs://`` — object stores have **no rename**,
no append, and no directories; they have atomic single-object PUT and
last-writer-wins overwrite. This module states the contract the rest of
tpuflow is allowed to rely on:

- ``put``/``get``/``list``/``delete``/``exists`` — whole-object ops on
  ``/``-separated keys. ``put`` is **last-writer-wins**: two concurrent
  writers of one key leave one complete object, never an interleave.
- ``put_atomic`` — a reader concurrently fetching the key sees the old
  object or the new one, never a torn write. On an object store this IS
  ``put`` (single-object PUT is atomic); on a local filesystem it is
  tmp + fsync + rename.
- ``promote`` — publish-by-**pointer-indirection**: a small JSON pointer
  object is atomically overwritten to name the new target key. This is
  the only publish primitive; rename-as-publish is exactly the idiom
  that cannot exist on ``gs://``, and the repo-wide storage analyzer
  (TPF020, ``tpuflow/analysis/storage.py``) flags it outside this seam.
- ``tail`` — read a growing object from an offset (trail followers).

``storage.put`` / ``storage.get`` / ``storage.promote`` are registered
fault sites, and every public op lands in ``storage_ops_total{op=,
backend=}`` + the ``storage_op_seconds`` histogram. Each store also
keeps an **op log** (``op_log``) of ``(op, key)`` tuples — the tests'
proof artifact: a promotion cycle on :class:`FakeRemoteStore
<tpuflow.storage.fake.FakeRemoteStore>` shows zero ``rename`` entries,
while :class:`LocalStore <tpuflow.storage.local.LocalStore>` honestly
records the rename its atomic put performs.

See docs/storage.md for the contract table and backend matrix.
"""

from __future__ import annotations

import json
import time

from tpuflow.resilience import fault_point

POINTER_SCHEMA = "tpuflow.storage.pointer/v1"


class StorageError(OSError):
    """A store operation failed (missing key, backend refusal). Subclass
    of OSError so existing ``except OSError`` I/O policies apply."""


def _check_key(key: str) -> str:
    if not isinstance(key, str) or not key or key.startswith("/"):
        raise ValueError(
            f"store key must be a non-empty relative string, got {key!r}"
        )
    if ".." in key.split("/"):
        raise ValueError(f"store key must not contain '..': {key!r}")
    return key


class ObjectStore:
    """Abstract base: backends implement the ``_``-prefixed primitives;
    callers use the public ops, which add fault sites, metrics, and the
    op log uniformly. ``supports_rename`` advertises whether the backend
    has an atomic server-side rename at all — nothing in the public
    contract exposes one either way, which is the point."""

    name = "object"            # backend label in storage_ops_total
    supports_rename = False

    def __init__(self):
        from tpuflow.obs.metrics import default_registry

        self.op_log: list[tuple] = []
        reg = default_registry()
        self._ops = reg.counter(
            "storage_ops_total",
            "object-store operations by op= and backend=",
        )
        self._seconds = reg.histogram(
            "storage_op_seconds", "object-store operation latency",
        )

    # ---- backend primitives (implement these) ----

    def _put(self, key: str, data: bytes) -> None:
        raise NotImplementedError

    def _get(self, key: str) -> bytes:
        raise NotImplementedError

    def _list(self, prefix: str) -> list[str]:
        raise NotImplementedError

    def _delete(self, key: str) -> bool:
        raise NotImplementedError

    def _exists(self, key: str) -> bool:
        raise NotImplementedError

    # ---- instrumentation ----

    def _record(self, op: str, key: str, t0: float) -> None:
        self.op_log.append((op, key))
        self._ops.inc(op=op, backend=self.name)
        self._seconds.observe(time.perf_counter() - t0)

    # ---- the public contract ----

    def put(self, key: str, data: bytes) -> None:
        """Write one whole object (last-writer-wins)."""
        t0 = time.perf_counter()
        fault_point("storage.put")
        self._put(_check_key(key), bytes(data))
        self._record("put", key, t0)

    def put_atomic(self, key: str, data: bytes) -> None:
        """Write such that a concurrent reader sees old-or-new, never a
        torn object. The base delegates to ``put`` (object PUT is
        atomic); filesystem backends override with tmp+fsync+rename."""
        self.put(key, data)

    def get(self, key: str) -> bytes:
        """The whole object; ``FileNotFoundError`` when absent."""
        t0 = time.perf_counter()
        fault_point("storage.get")
        data = self._get(_check_key(key))
        self._record("get", key, t0)
        return data

    def list(self, prefix: str = "") -> list[str]:
        """Sorted keys under ``prefix`` (flat namespace scan — object
        stores have no directories, so neither does this)."""
        t0 = time.perf_counter()
        keys = sorted(self._list(prefix))
        self._record("list", prefix, t0)
        return keys

    def delete(self, key: str) -> bool:
        """Remove one object; True when it existed."""
        t0 = time.perf_counter()
        existed = self._delete(_check_key(key))
        self._record("delete", key, t0)
        return existed

    def exists(self, key: str) -> bool:
        t0 = time.perf_counter()
        found = self._exists(_check_key(key))
        self._record("exists", key, t0)
        return found

    def tail(self, key: str, offset: int = 0) -> bytes:
        """Bytes of a growing object from ``offset`` (may be empty).
        Backends with ranged reads override; the base fetches whole."""
        t0 = time.perf_counter()
        fault_point("storage.get")
        data = self._get(_check_key(key))[offset:]
        self._record("tail", key, t0)
        return data

    # ---- pointer-indirected promotion ----

    def promote(
        self, pointer: str, target: str, meta: dict | None = None,
        clock=time.time,
    ) -> dict:
        """Atomically repoint ``pointer`` at ``target`` — THE publish
        primitive. The pointer object is a small JSON doc recording the
        target key, a monotonic generation, and the previous target (the
        rollback seam artifacts.py rides). Write order is
        target-first-pointer-second by convention: callers put the
        target object(s) before promoting, so a crash in between leaves
        the old pointer valid — the same old-or-new contract
        ``put_atomic`` gives a single object, lifted to a tree of them.
        """
        t0 = time.perf_counter()
        fault_point("storage.promote")
        _check_key(target)
        prior = self.resolve(pointer)
        doc = {
            "schema": POINTER_SCHEMA,
            "target": target,
            "generation": (prior["generation"] + 1) if prior else 1,
            "previous": prior["target"] if prior else None,
            "time": clock(),
            "meta": meta or {},
        }
        self._put(
            _check_key(pointer),
            json.dumps(doc, sort_keys=True).encode("utf-8"),
        )
        self._record("promote", pointer, t0)
        return doc

    def resolve(self, pointer: str) -> dict | None:
        """The pointer doc, or None when the pointer does not exist or
        is unreadable (pre-first-promote)."""
        try:
            doc = json.loads(self._get(_check_key(pointer)))
        except (FileNotFoundError, ValueError):
            return None
        if not isinstance(doc, dict) or "target" not in doc:
            return None
        doc.setdefault("generation", 1)
        doc.setdefault("previous", None)
        doc.setdefault("meta", {})
        return doc

    def get_promoted(self, pointer: str) -> bytes:
        """Fetch the object the pointer currently names."""
        doc = self.resolve(pointer)
        if doc is None:
            raise FileNotFoundError(
                f"{self.name} store: pointer {pointer!r} has never been "
                "promoted"
            )
        return self.get(doc["target"])
