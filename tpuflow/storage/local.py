"""LocalStore — the POSIX reference backend, plus the local-move seam.

Two things live here:

- :class:`LocalStore`: the ``ObjectStore`` contract over a directory.
  Every ``put`` is atomic (tmp + **fsync** + rename — the fsync is the
  torn-write fix: without it a crash between write and rename can
  publish a zero-length "atomic" file), so ``put_atomic`` needs no
  override. The op log honestly records the ``rename`` each put
  performs — the contrast the FakeRemoteStore drills assert against.

- The **local-move seam**: ``replace_file`` / ``move_tree`` /
  ``remove_tree``, the only blessed home for ``os.replace`` /
  ``os.rename`` / ``shutil.move`` outside ``utils/paths.py``. Callers
  that still operate on local directory trees (``online/swap.py``'s
  incumbent retention, ``serve.py``'s journal compaction) route their
  moves through here, so the repo-wide storage analyzer (TPF020) keeps
  exactly one place to audit when a backend without rename arrives.
"""

from __future__ import annotations

import os
import shutil
import threading

from tpuflow.storage.base import ObjectStore


def fsync_write(path: str, data: bytes) -> None:
    """Write ``data`` to ``path`` via tmp + fsync + ``os.replace``.

    The fsync-before-rename is load-bearing: rename alone orders the
    DIRECTORY entry, not the data blocks — after a crash the new name
    can point at a zero-length or partial file. The tmp name is unique
    per (process, thread), same discipline as
    ``utils.paths.atomic_write_json``."""
    parent = os.path.dirname(path)
    if parent:
        os.makedirs(parent, exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def replace_file(src: str, dst: str) -> None:
    """Atomically move ``src`` over ``dst`` (same-filesystem rename).
    Local seam only — a store-backed path publishes via
    ``ObjectStore.promote`` instead."""
    parent = os.path.dirname(dst)
    if parent:
        os.makedirs(parent, exist_ok=True)
    os.replace(src, dst)


def move_tree(src: str, dst: str) -> None:
    """Move a file or directory tree to ``dst`` (parent created).
    Rename when possible, copy+delete across filesystems —
    ``shutil.move`` semantics behind the seam."""
    parent = os.path.dirname(dst)
    if parent:
        os.makedirs(parent, exist_ok=True)
    shutil.move(src, dst)


def remove_tree(path: str) -> None:
    """Best-effort recursive delete (missing path is fine)."""
    shutil.rmtree(path, ignore_errors=True)


def remove_file(path: str) -> bool:
    """Delete one file; True when it existed."""
    try:
        os.remove(path)
        return True
    except FileNotFoundError:
        return False


class LocalStore(ObjectStore):
    """The contract over a local directory: keys are ``/``-separated
    paths under ``root``. ``put`` is atomic by construction (see
    :func:`fsync_write`), so local callers get the same old-or-new
    guarantee a single-object PUT gives on a real object store."""

    name = "local"
    supports_rename = True

    def __init__(self, root: str):
        super().__init__()
        self.root = os.path.abspath(root)

    def _path(self, key: str) -> str:
        return os.path.join(self.root, *key.split("/"))

    def _put(self, key: str, data: bytes) -> None:
        fsync_write(self._path(key), data)
        self.op_log.append(("rename", key))  # what the atomic put did

    def _get(self, key: str) -> bytes:
        with open(self._path(key), "rb") as f:
            return f.read()

    def _list(self, prefix: str) -> list[str]:
        out = []
        for dirpath, _dirnames, filenames in os.walk(self.root):
            for name in filenames:
                full = os.path.join(dirpath, name)
                key = os.path.relpath(full, self.root).replace(os.sep, "/")
                if key.startswith(prefix):
                    out.append(key)
        return out

    def _delete(self, key: str) -> bool:
        try:
            os.remove(self._path(key))
            return True
        except FileNotFoundError:
            return False

    def _exists(self, key: str) -> bool:
        return os.path.isfile(self._path(key))

    def tail(self, key: str, offset: int = 0) -> bytes:
        """Ranged read: seek instead of fetching the whole object."""
        import time as _time

        t0 = _time.perf_counter()
        from tpuflow.resilience import fault_point

        fault_point("storage.get")
        with open(self._path(key), "rb") as f:
            f.seek(offset)
            data = f.read()
        self._record("tail", key, t0)
        return data
