"""Async serving control plane: admission → continuous batching → dispatch.

The threaded front end (``tpuflow/serve.py``) spends a whole OS thread
per connection and a deliberate ``max_wait_ms`` timer per coalesced
dispatch — fine at 16 closed-loop clients, a liability at the "heavy
traffic from millions of users" scale the north star names (ROADMAP
item 2; MMLSpark's Spark Serving and BigDL both treat low-latency
serving as its own concurrency model, PAPERS.md). This module is the
event-loop replacement: ONE thread parses every connection, admission
is an explicit bounded resource, and the device is driven by the
continuous batcher (``tpuflow/microbatch.py``) so coalescing emerges
from device latency instead of a timer.

The request pipeline (docs/serving.md has the full diagram)::

    accept → parse (non-blocking, event loop)
           → admission     [token-bucket per client → 429]
                           [bounded in-flight count  → 503]
           → prepare       (executor thread: resolve artifact,
                            per-request feature transform)
           → enqueue       [deadline attached; full queue/lanes → 503]
           → dispatch      (ContinuousBatcher lane: double-buffered
                            device dispatches; expired entries shed —
                            a dead request never occupies a slot → 504)
           → respond       (event loop; latency recorded either way)

Load shedding is split by meaning, and the split is load-bearing for
clients: **429** = YOUR quota (retry after your bucket refills), **503**
= MY capacity (retry with backoff, any client), **504** = this request's
deadline passed while it waited (a retry may still make it). All three
are counted (``serving_shed_total{code=...}``) and the admission
pressure is visible live (``serving_inflight_requests``, the batcher's
queue-depth and in-flight-dispatch gauges) in ``GET /metrics`` — JSON
and Prometheus both, the same registry the threaded daemon renders.

Optional hedged re-dispatch: with ``hedge_ms`` set, a coalesced forward
that hasn't answered within the hedge window runs a duplicate forward
on an executor thread — OUTSIDE the artifact's dispatch lane, whose
single thread is busy running the straggler itself — with the same
predictor instance and rows, and the first completion wins: the
classic tail-latency trade (a straggling dispatch behind a cold
compile or a GC pause no longer defines p99, at the cost of duplicate
device work). Off by default; ``serving_hedges_total`` /
``serving_hedge_wins_total`` make the trade observable.

Jobs endpoints (``POST /jobs`` etc.) ride along unchanged: the same
``JobRunner`` serves them, called on executor threads so its journal
I/O never stalls the event loop. A deployment that only predicts can
pass ``enable_jobs=False``.

Knobs resolve argument > env > default, and every ``TPUFLOW_SERVE_*``
env value is validated at read time with an error naming the variable
and the expected form (``tpuflow.serve.env_num``; the
``TPUFLOW_RETRY_*`` precedent): ``TPUFLOW_SERVE_ADMIT_MAX`` (in-flight
bound, default 256), ``TPUFLOW_SERVE_QUOTA_RPS`` /
``TPUFLOW_SERVE_QUOTA_BURST`` (per-client token bucket, 0 = off),
``TPUFLOW_SERVE_DEADLINE_MS`` (default per-request deadline, 0 = off),
``TPUFLOW_SERVE_HEDGE_MS`` (hedged re-dispatch, 0 = off),
``TPUFLOW_SERVE_PREP_WORKERS`` (executor width),
``TPUFLOW_SERVE_DRIFT_ADMISSION`` / ``TPUFLOW_SERVE_DRIFT_THRESHOLD``
(drift-aware admission, below), plus the ``PredictService`` fast-path
family (``TPUFLOW_SERVE_BATCH*``, ``TPUFLOW_SERVE_RESIDENT``,
``TPUFLOW_SERVE_REPLICAS``...).

Data plane (ISSUE 12): with ``TPUFLOW_SERVE_REPLICAS=R`` /
``--replicas R`` the service places R predictor replicas per artifact
across devices, each with its own dispatch lane, and every enqueue
joins the shortest queue (``tpuflow/serve_replica.py``;
docs/serving.md#the-multi-replica-data-plane---replicas). Drift-aware
admission (``--drift-admission off|flag|shed``) scores request
features against the artifact sidecar's reference stats at the front
door: far-out-of-distribution requests are flagged (``X-Drift-Score``
header + ``serving_drift_admissions_total``) or shed 429 BEFORE they
occupy a dispatch slot — the online drift watchdog
(``tpuflow/online/drift.py``) as a front-line defense.

Run: ``python -m tpuflow.serve_async --port 8700`` (or
``python -m tpuflow.cli serve``); stop with SIGINT/SIGTERM.
Benchmarked against the threaded front end by
``benchmarks/bench_serving.py --open-loop`` (Poisson arrivals, hundreds
of clients; committed numbers in ``benchmarks/serving_results.json``).
"""

from __future__ import annotations

import asyncio
import json
import os
import threading
import time
from concurrent.futures import ThreadPoolExecutor

from tpuflow.microbatch import DeadlineExpired, QueueFull
from tpuflow.serve import (
    JobRunner,
    PredictService,
    _clean_trace_id,
    env_choice,
    env_flag,
    env_num,
)

_MAX_HEADERS = 64
_MAX_BODY = 64 * 1024 * 1024  # a 64MB body cap: parse errors, not OOM

_STATUS_TEXT = {
    200: "OK", 202: "Accepted", 400: "Bad Request", 404: "Not Found",
    409: "Conflict", 413: "Payload Too Large", 429: "Too Many Requests",
    500: "Internal Server Error", 501: "Not Implemented",
    503: "Service Unavailable", 504: "Gateway Timeout",
}


class _RequestError(ValueError):
    """A request the HTTP layer itself rejects (malformed line/headers,
    oversized body): carries the status to answer with before the
    connection closes — a client over the body cap gets a 413 it can
    act on, not a bare connection reset."""

    def __init__(self, status: int, msg: str):
        super().__init__(msg)
        self.status = status


class TokenBuckets:
    """Per-client token buckets: ``rate`` tokens/s refill up to
    ``burst``; one token per request. The client table is bounded —
    past ``max_clients`` the stalest bucket is dropped (it re-admits as
    full on return, which only ever errs in the client's favor), so an
    attacker cycling client IDs can't pin memory. ``clock`` is
    injectable for zero-wall-clock tests.

    Runs entirely on the event-loop thread — no lock. ``rate <= 0``
    disables quotas (every ``allow`` is True)."""

    def __init__(
        self, rate: float, burst: float, max_clients: int = 4096,
        clock=time.monotonic,
    ):
        if burst < 1:
            raise ValueError(f"quota burst must be >= 1, got {burst}")
        self.rate = float(rate)
        self.burst = float(burst)
        self.max_clients = max_clients
        self._clock = clock
        self._buckets: dict[str, list[float]] = {}  # id -> [tokens, t]

    def allow(self, client: str) -> bool:
        if self.rate <= 0:
            return True
        now = self._clock()
        bucket = self._buckets.get(client)
        if bucket is None:
            if len(self._buckets) >= self.max_clients:
                stalest = min(
                    self._buckets, key=lambda c: self._buckets[c][1]
                )
                del self._buckets[stalest]
            bucket = self._buckets[client] = [self.burst, now]
        tokens = min(self.burst, bucket[0] + (now - bucket[1]) * self.rate)
        bucket[1] = now
        if tokens < 1.0:
            bucket[0] = tokens
            return False
        bucket[0] = tokens - 1.0
        return True


class _Admission:
    """The bounded front door: at most ``max_inflight`` requests past
    admission at once (parsing done, response not yet written) — the
    explicit backlog bound every downstream queue inherits — plus the
    per-client quota gate. ``inflight`` is mutated on the EVENT-LOOP
    THREAD ONLY (that single-writer discipline is the lock; the TPF016
    pass infers guarding only where locks exist, so keep all mutation
    on the loop). The one cross-thread access is the gauge callback's
    read on the scrape thread — a GIL-atomic int load whose staleness a
    point-in-time gauge tolerates by definition. The counters are the
    observable 429/503 split."""

    def __init__(self, max_inflight: int, buckets: TokenBuckets, registry):
        self.max_inflight = max_inflight
        self.buckets = buckets
        self.inflight = 0
        self._shed = registry.counter(
            "serving_shed_total",
            "requests shed at admission or in the queue, by status code",
        )
        self._admitted = registry.counter(
            "serving_admitted_total", "requests past admission control"
        )
        registry.gauge(
            "serving_inflight_requests",
            "requests admitted and not yet answered (the admission "
            "queue depth; the bound is max_inflight)",
            fn=lambda: self.inflight,
        )

    def try_admit(self, client: str) -> int | None:
        """None = admitted (caller MUST release()); else the shed status
        code. The admission span records the decision either way — the
        shed path is the one an operator most wants to see."""
        from tpuflow.obs import record_span

        if not self.buckets.allow(client):
            self._shed.inc(code="429")
            record_span("serve.admission", 0.0, hot=True,
                        outcome="shed_quota", client=client)
            return 429
        if self.inflight >= self.max_inflight:
            self._shed.inc(code="503")
            record_span("serve.admission", 0.0, hot=True,
                        outcome="shed_capacity", inflight=self.inflight)
            return 503
        self.inflight += 1
        self._admitted.inc()
        record_span("serve.admission", 0.0, hot=True,
                    outcome="admitted", inflight=self.inflight)
        return None

    def shed_deadline(self) -> None:
        self._shed.inc(code="504")

    def shed_queue(self) -> None:
        """A batcher-capacity (QueueFull) shed: counted with the same
        503 label as an admission-bound shed — both are 'my capacity,
        back off' to the client."""
        self._shed.inc(code="503")

    def shed_drift(self) -> None:
        """A drift-admission shed: 429-class (the CLIENT's data sits
        outside the artifact's training distribution — retrying the
        same features buys nothing; the server is fine)."""
        self._shed.inc(code="429")

    def shed_draining(self) -> None:
        """A drain-window shed: 503-class ('my capacity, back off') —
        the server is going away on purpose, and a load balancer treats
        503 as 'retry elsewhere', which is exactly right mid-drain."""
        self._shed.inc(code="503")

    def release(self) -> None:
        self.inflight -= 1

    def metrics(self) -> dict:
        return {
            "admitted": int(self._admitted.value()),
            "shed_429": int(self._shed.value(code="429")),
            "shed_503": int(self._shed.value(code="503")),
            "shed_504": int(self._shed.value(code="504")),
            "inflight": self.inflight,
            "max_inflight": self.max_inflight,
            "quota_rps": self.buckets.rate,
        }


class AsyncServer:
    """The asyncio daemon. Construct, then either ``serve_forever()``
    (foreground, ``main()``'s path) or ``start()`` / ``shutdown()``
    (background thread — tests and benchmarks embed it exactly like
    ``make_server``'s ThreadingHTTPServer)."""

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 8700,
        *,
        max_inflight: int | None = None,
        quota_rps: float | None = None,
        quota_burst: float | None = None,
        deadline_ms: float | None = None,
        hedge_ms: float | None = None,
        prep_workers: int | None = None,
        batch_predicts: bool | None = None,
        batch_max_rows: int | None = None,
        warmup_buckets: int | None = None,
        donate_forward: bool | None = None,
        max_resident: int | None = None,
        replicas: int | None = None,
        drift_admission: str | None = None,
        drift_threshold: float | None = None,
        enable_jobs: bool = True,
        max_queued: int = 64,
        default_timeout: float | None = None,
        journal_path: str | None = None,
        service: PredictService | None = None,
        trail_path: str | None = None,
        slo_objectives=None,
        autoscale: bool | None = None,
        autoscale_block: dict | None = None,
    ):
        from tpuflow.obs import Registry

        self.host, self.port = host, port
        if max_inflight is None:
            max_inflight = env_num(
                "TPUFLOW_SERVE_ADMIT_MAX", 256, int, minimum=1,
                form="an integer in-flight bound >= 1",
            )
        if quota_rps is None:
            quota_rps = env_num(
                "TPUFLOW_SERVE_QUOTA_RPS", 0.0, float,
                form="a non-negative requests-per-second rate (0 = off)",
            )
        if quota_burst is None:
            quota_burst = env_num(
                "TPUFLOW_SERVE_QUOTA_BURST", 16.0, float, minimum=1,
                form="a burst size >= 1",
            )
        if deadline_ms is None:
            deadline_ms = env_num(
                "TPUFLOW_SERVE_DEADLINE_MS", 0.0, float,
                form="a non-negative deadline in milliseconds (0 = off)",
            )
        if hedge_ms is None:
            hedge_ms = env_num(
                "TPUFLOW_SERVE_HEDGE_MS", 0.0, float,
                form="a non-negative hedge delay in milliseconds (0 = off)",
            )
        if prep_workers is None:
            prep_workers = env_num(
                "TPUFLOW_SERVE_PREP_WORKERS", 4, int, minimum=1,
                form="an integer worker count >= 1",
            )
        self.deadline_ms = float(deadline_ms)
        self.hedge_ms = float(hedge_ms)
        # Drift-aware admission (the PR 9 follow-up): score request
        # features against the artifact sidecar's reference stats at
        # the front door — BEFORE the request occupies a dispatch slot.
        # off = never score; flag = X-Drift-Score header + counter on
        # far-out-of-distribution requests; shed = answer them 429
        # (caller-side data problem, not server capacity).
        if drift_admission is None:
            drift_admission = env_choice(
                "TPUFLOW_SERVE_DRIFT_ADMISSION", "off",
                ("off", "flag", "shed"),
            )
        if drift_admission not in ("off", "flag", "shed"):
            raise ValueError(
                f"drift_admission must be 'off', 'flag' or 'shed', "
                f"got {drift_admission!r}"
            )
        if drift_threshold is None:
            drift_threshold = env_num(
                "TPUFLOW_SERVE_DRIFT_THRESHOLD", 6.0, float,
                minimum=1e-9,
                form="a positive standardized-shift threshold",
            )
        self.drift_admission = drift_admission
        self.drift_threshold = float(drift_threshold)
        # Per-artifact reference stats, loaded lazily from the sidecar
        # on first scored request (None = sidecar has no stats; scoring
        # is skipped, never guessed). Dropped on /artifacts/reload — a
        # swapped artifact brings its own baseline.
        self._drift_refs: dict[tuple, object] = {}
        self._drift_lock = threading.Lock()
        self._started = time.monotonic()
        # ONE run-scoped registry for the whole daemon (the make_server
        # discipline): admission, batcher, predictor, and job counters
        # render in a single Prometheus scrape. An injected service
        # (tests, embedding) brings its own registry — adopt it, so its
        # batcher families still land in this daemon's exposition.
        if service is not None:
            # The service-construction knobs belong to the injected
            # service's own constructor — accepting and dropping them
            # here would be silent misconfiguration (hedge/deadline/
            # admission knobs ARE honored, which makes the asymmetry
            # easy to miss), so conflicting kwargs fail loudly.
            conflicting = sorted(
                k for k, v in {
                    "batch_predicts": batch_predicts,
                    "batch_max_rows": batch_max_rows,
                    "warmup_buckets": warmup_buckets,
                    "donate_forward": donate_forward,
                    "max_resident": max_resident,
                    "replicas": replicas,
                }.items() if v is not None
            )
            if conflicting:
                raise ValueError(
                    f"service was injected; pass {conflicting} to "
                    "PredictService(...) instead"
                )
            self.service = service
            self.registry = service.registry
        else:
            # The env family applies here too, with async-appropriate
            # DEFAULTS (batching on, continuous engine) — an operator's
            # TPUFLOW_SERVE_BATCH=0 or BATCH_MODE=micro is honored, not
            # silently ignored.
            if batch_predicts is None:
                batch_predicts = env_flag("TPUFLOW_SERVE_BATCH", True)
            self.registry = Registry()
            self.service = PredictService(
                batch_predicts=batch_predicts,
                batch_mode=env_choice(
                    "TPUFLOW_SERVE_BATCH_MODE", "continuous",
                    ("micro", "continuous"),
                ),
                batch_max_rows=batch_max_rows,
                warmup_buckets=warmup_buckets,
                donate_forward=donate_forward,
                max_resident=max_resident,
                replicas=replicas,
                registry=self.registry,
            )
        self.registry.gauge(
            "uptime_seconds", "seconds since the daemon started",
            fn=lambda: time.monotonic() - self._started,
        )
        self.admission = _Admission(
            int(max_inflight),
            TokenBuckets(float(quota_rps), float(quota_burst)),
            self.registry,
        )
        self._hedges = self.registry.counter(
            "serving_hedges_total", "duplicate dispatches enqueued by "
            "the hedge timer",
        )
        self._hedge_wins = self.registry.counter(
            "serving_hedge_wins_total", "requests answered by their "
            "hedge dispatch first",
        )
        self._drift_admissions = self.registry.counter(
            "serving_drift_admissions_total",
            "requests whose features scored past the drift-admission "
            "threshold, by action (flagged = served with X-Drift-Score; "
            "shed = answered 429 before occupying a dispatch slot)",
        )
        # The daemon's on-disk trail (its fleet-timeline lane, found by
        # `python -m tpuflow.obs fleet`): lifecycle events — startup,
        # trace-stamped /artifacts/reload records — appended as JSONL.
        # None (default) = env TPUFLOW_SERVE_TRAIL; unset = no trail.
        if trail_path is None:
            trail_path = os.environ.get("TPUFLOW_SERVE_TRAIL") or None
        self._trail = None
        if trail_path:
            from tpuflow.utils.logging import MetricsLogger

            self._trail = MetricsLogger(trail_path)
            self._trail.write(
                "serve_started", daemon="async", host=host, port=port,
            )
        # The SLO engine (tpuflow/obs/slo.py): objectives scored at
        # scrape time from this daemon's own counters — the `slo`
        # section of the JSON /metrics view, and the
        # slo_error_budget_remaining{objective=}/slo_burn_rate gauges
        # in the Prometheus exposition. Targets are env-tunable.
        from tpuflow.obs.slo import SloEngine, serve_objectives

        self.slo = SloEngine(
            serve_objectives(slo_objectives), registry=self.registry,
        )
        # The metrics history plane + alert engine (tpuflow/obs/
        # history.py, alerts.py): a sampler thread (started in _amain,
        # stopped in shutdown) ticks the registry into bounded
        # time-series rings; the SLO pre-sample hook refreshes the
        # slo_* gauges before every tick so burn-rate rules — imported
        # from the same committed objectives — score current values.
        # Firing/resolved transitions land in forensics, the trail, and
        # the obs_alerts_firing gauges of this daemon's exposition.
        from tpuflow.obs.alerts import AlertEngine, rules_from_objectives
        from tpuflow.obs.history import MetricsHistory

        self.history = MetricsHistory(self.registry)
        self.history.add_pre_sample(
            lambda: self.slo.evaluate_registry(self.registry)
        )
        self.alerts = AlertEngine(
            self.history,
            rules_from_objectives(
                serve_objectives(slo_objectives),
                for_s=env_num("TPUFLOW_SERVE_ALERT_FOR_S", 15.0, float),
            ),
            registry=self.registry,
            logger=self._trail,
        )
        self.alerts.attach()
        # The profiling plane + flight recorder (tpuflow/obs/profiler.py,
        # flight.py), both env-gated off by default. The profiler samples
        # ONLY this daemon's thread families — in a shared process (the
        # soak) a serving bundle must profile serving, not whatever the
        # training gang is computing. The recorder subscribes to the
        # alert engine above: every firing transition captures an atomic
        # forensic bundle through the storage seam.
        from tpuflow.obs.flight import flight_from_env
        from tpuflow.obs.profiler import profiler_from_env

        self.profiler = profiler_from_env(
            self.registry,
            include=("tpuflow-serve", "tpuflow-prep", "tpuflow-lane",
                     "tpuflow-microbatch", "tpuflow-jobs"),
        )
        self.flight = flight_from_env(
            history=self.history,
            profiler=self.profiler,
            registry=self.registry,
            logger=self._trail,
        )
        if self.flight is not None:
            self.flight.attach(self.alerts)
        # The SLO-driven autoscaler (tpuflow/serve_autoscale.py):
        # opt-in (flag/env), hill-climbs replicas/max_inflight/hedge/
        # drift threshold against the history's burn-rate lanes through
        # the set_* seams below. Runs on its own thread, started with
        # the sampler in _amain.
        if autoscale is None:
            autoscale = env_flag("TPUFLOW_SERVE_AUTOSCALE", False)
        self.autoscaler = None
        if autoscale:
            from tpuflow.serve_autoscale import ObservingController

            self.autoscaler = ObservingController(
                self, self.history,
                registry=self.registry,
                block=autoscale_block,
                logger=self._trail,
            )
        self.runner = None
        if enable_jobs:
            self.runner = JobRunner(
                on_artifact_change=self._invalidate_artifact,
                max_queued=max_queued,
                default_timeout=default_timeout,
                journal_path=journal_path,
                registry=self.registry,
            )
        # Bounded-width executor for every blocking step (artifact
        # loads, feature transforms, unbatched forwards, job-journal
        # I/O). Its backlog is bounded BY ADMISSION — at most
        # max_inflight requests can be queued behind it.
        self._pool = ThreadPoolExecutor(
            max_workers=int(prep_workers), thread_name_prefix="tpuflow-prep"
        )
        self._loop: asyncio.AbstractEventLoop | None = None
        self._aserver = None
        self._thread: threading.Thread | None = None
        self._ready = threading.Event()
        self._announce = False  # main() flips it: print URL post-bind
        self._boot_error: BaseException | None = None
        # drain(): set from the caller's thread, read on the event loop
        # at each /predict — an Event, so both sides are race-free.
        self._draining = threading.Event()

    def _record_reload(self, storage_path: str, name: str) -> None:
        """One trace-stamped reload record: the forensics ring always,
        the on-disk trail when configured — the daemon-side end of the
        online loop's swap lifecycle on the fleet timeline."""
        from tpuflow.obs import record_event

        rec = record_event(
            "serve_reload", daemon="async", storage_path=storage_path,
            model=name,
        )
        if self._trail is not None:
            self._trail.write(
                "serve_reload",
                **{k: v for k, v in rec.items() if k not in ("event", "time")},
            )

    # ---- drift-aware admission ----

    def _invalidate_artifact(self, storage_path: str, name: str) -> None:
        """An artifact was rewritten (retrain job or reload): drop the
        cached predictor AND the cached drift baseline — the new
        artifact brings its own reference stats, and scoring admission
        against the retired generation's mean/std would shed the wrong
        requests. One helper so the job path and the /artifacts/reload
        route cannot drift apart."""
        self.service.invalidate(storage_path, name)
        with self._drift_lock:
            self._drift_refs.pop((storage_path, name), None)

    def _drift_ref(self, key: tuple):
        """The artifact's reference stats, cached per key. None caches
        too — but ONLY for an artifact whose sidecar genuinely carries
        no scoreable stats (the ValueError contract of
        ``reference_stats_from_sidecar``): a transient read failure
        (storage blip) must be retried on the next request, not pinned
        as a silently-disabled gate. Blocking (sidecar read) —
        executor-thread only."""
        with self._drift_lock:
            if key in self._drift_refs:
                return self._drift_refs[key]
        try:
            from tpuflow.online.drift import reference_stats_from_sidecar

            ref = reference_stats_from_sidecar(*key)
        except (ValueError, KeyError):
            # No numeric stats / malformed sidecar: deterministic for
            # this artifact generation — cache the never-score verdict.
            ref = None
        except Exception:
            # Transient (I/O, parse-of-truncated-read): score nothing
            # THIS time, probe again on the next request.
            return None
        with self._drift_lock:
            self._drift_refs.setdefault(key, ref)
            return self._drift_refs[key]

    def _drift_score(self, key: tuple, payload) -> float | None:
        """One request's out-of-distribution score (max standardized
        mean shift vs the sidecar baseline), or None when unscoreable
        (CSV-path payloads, artifacts without stats). Host-side numpy,
        on an executor thread."""
        kind, value = payload
        if kind != "columns":
            return None
        ref = self._drift_ref(key)
        if ref is None:
            return None
        from tpuflow.online.drift import admission_score

        return admission_score(ref, value)

    # ---- request pipeline ----

    async def _predict(
        self, spec: dict, headers: dict
    ) -> tuple[int, dict, dict]:
        from tpuflow.obs import current_trace_id

        svc = self.service
        loop = asyncio.get_running_loop()
        t0 = time.perf_counter()
        trace_id = current_trace_id()
        out_headers: dict = {}
        deadline_ms = spec.pop("deadlineMs", None)
        if deadline_ms is None:
            deadline_ms = headers.get("x-deadline-ms") or self.deadline_ms
        try:
            deadline_ms = float(deadline_ms)
        except (TypeError, ValueError):
            return 400, {
                "error": f"deadlineMs={deadline_ms!r} is not a number",
                "trace_id": trace_id,
            }, out_headers
        deadline = (
            time.monotonic() + deadline_ms / 1000.0 if deadline_ms > 0
            else None
        )
        try:
            key, pred, payload = await loop.run_in_executor(
                self._pool, svc.begin_request, spec
            )
            if self.drift_admission != "off" and payload[0] == "columns":
                # Front-line drift defense: a request whose features
                # sit far outside what the artifact was trained on is
                # flagged (header + counter) or shed 429 HERE — it
                # never occupies a dispatch slot, and in-distribution
                # traffic never pays more than one numpy mean per
                # column (executor thread, host-side). Unscoreable
                # payloads (CSV path) skip even the executor hop.
                score = await loop.run_in_executor(
                    self._pool, self._drift_score, key, payload
                )
                if score is not None:
                    out_headers["X-Drift-Score"] = f"{score:.4f}"
                    if score > self.drift_threshold:
                        if self.drift_admission == "shed":
                            self._drift_admissions.inc(action="shed")
                            self.admission.shed_drift()
                            return 429, {
                                "error": (
                                    f"request features score {score:.2f} "
                                    "standardized shifts outside the "
                                    "artifact's training distribution "
                                    f"(threshold {self.drift_threshold:g})"
                                    "; shed at admission"
                                ),
                                "shed": "drift",
                                "drift_score": round(score, 4),
                                "trace_id": trace_id,
                            }, out_headers
                        self._drift_admissions.inc(action="flagged")
            if svc.batcher is None or not svc.coalescable(pred):
                # Degraded (Gilbert) answers and batching-off configs
                # take the per-request path on an executor thread. The
                # deadline contract holds here too: a request whose
                # (possibly seconds-long cold) artifact resolve already
                # blew its deadline sheds 504 instead of running.
                if deadline is not None and time.monotonic() > deadline:
                    raise DeadlineExpired(
                        f"request deadline ({deadline_ms:g}ms) expired "
                        "during prepare"
                    )
                y = await loop.run_in_executor(
                    self._pool, svc.answer_unbatched, pred, payload
                )
            else:
                x = await loop.run_in_executor(
                    self._pool, svc.transform_request, pred, payload
                )
                if deadline is not None and time.monotonic() > deadline:
                    # Expired during prepare: shed before it can occupy
                    # a dispatch slot (the batcher would shed it at
                    # drain time anyway; this is just sooner).
                    raise DeadlineExpired(
                        f"request deadline ({deadline_ms:g}ms) expired "
                        "during prepare"
                    )
                if len(x) == 0:
                    y = await loop.run_in_executor(
                        self._pool, pred.forward_prepared, x
                    )
                elif hasattr(svc.batcher, "enqueue"):
                    # The lane decision (a ReplicaSet resolves to its
                    # least-loaded replica lane — join-shortest-queue;
                    # a plain predictor keeps its artifact lane).
                    lane_key, lane_pred = svc.select_lane(key, pred)
                    y = await self._forward_coalesced(
                        lane_key, lane_pred, x, deadline
                    )
                else:
                    # Injected micro-engine service (the embedding
                    # path): blocking submit on an executor thread —
                    # still coalesced; no drain-time deadline shedding
                    # (the micro engine has no deadline hook; the
                    # pre-enqueue expiry check above still applies).
                    y = await loop.run_in_executor(
                        self._pool, svc.batcher.submit, key, pred, x
                    )

            def shape_response():
                # Response shaping is O(rows) numpy→list conversion plus
                # the JSON encode — blocking work like any other, so it
                # runs on the executor, not the loop (a 64MB-body batch
                # must not stall every other connection). _respond
                # passes bytes through verbatim.
                out = svc.finish_response(pred, y)
                out["trace_id"] = trace_id
                return json.dumps(out).encode()

            return 200, await loop.run_in_executor(
                self._pool, shape_response
            ), out_headers
        except DeadlineExpired as e:
            self.admission.shed_deadline()
            return 504, {
                "error": str(e), "shed": "deadline", "trace_id": trace_id,
            }, out_headers
        except ValueError as e:
            return 400, {"error": str(e), "trace_id": trace_id}, out_headers
        except QueueFull as e:
            # The batcher's bounded queue/lanes: capacity, not caller
            # error — 503 with retry semantics, counted as shed.
            self.admission.shed_queue()
            return 503, {
                "error": str(e), "shed": "queue", "trace_id": trace_id,
            }, out_headers
        except Exception as e:  # missing artifact, bad columns
            return 500, {
                "error": f"{type(e).__name__}: {e}", "trace_id": trace_id,
            }, out_headers
        finally:
            svc.record_latency(time.perf_counter() - t0)

    async def _forward_coalesced(self, key, pred, x, deadline):
        """Enqueue into the continuous batcher and await the scatter —
        the event loop parks a Future, not a thread. With ``hedge_ms``
        set, a dispatch that hasn't answered inside the window enqueues
        a duplicate and the first completion wins."""
        loop = asyncio.get_running_loop()
        fut = self._enqueue(loop, key, pred, x, deadline)
        if self.hedge_ms <= 0:
            return await self._await_entry(fut)
        try:
            done = await asyncio.wait_for(
                asyncio.shield(fut), timeout=self.hedge_ms / 1000.0
            )
            if done.error is not None:
                raise done.error
            return done.result
        except asyncio.TimeoutError:
            pass
        # Hedge: duplicate forward OUTSIDE the lane — the lane's single
        # thread is busy running the straggler itself, so a hedge
        # queued behind it could never win. An executor thread races
        # the original with the same predictor instance and rows (the
        # stale-scatter contract holds: the answer comes from exactly
        # the params this request resolved).
        self._hedges.inc()
        hedge_fut = loop.run_in_executor(
            self._pool, self.service._run_forward, pred, x
        )
        futs = {fut, hedge_fut}
        while futs:
            finished, futs = await asyncio.wait(
                futs, return_when=asyncio.FIRST_COMPLETED,
                timeout=self._wedge_timeout(),
            )
            if not finished:
                raise self._wedged_error()
            for f in finished:
                if f is hedge_fut:
                    try:
                        y = f.result()
                    except Exception:
                        continue  # hedge failed; the original may answer
                    self._hedge_wins.inc()
                    return y
                e = f.result()
                if e.error is None:
                    return e.result
                if isinstance(e.error, DeadlineExpired):
                    # The request is DEAD — a hedge rescuing it would
                    # return a 200 past the declared deadline and spend
                    # a full duplicate forward on it. Shed now; the
                    # in-flight hedge's result is discarded.
                    raise e.error
                # Original failed (non-deadline); the hedge may answer.
        # Both failed: surface the ORIGINAL's error (the hedge's is a
        # duplicate of the same dispatch conditions).
        raise fut.result().error

    def _wedge_timeout(self) -> float:
        return float(getattr(self.service.batcher, "submit_timeout", 60.0))

    def _wedged_error(self) -> RuntimeError:
        return RuntimeError(
            f"predict batch dispatch timed out after "
            f"{self._wedge_timeout():g}s (dispatcher wedged?)"
        )

    async def _await_entry(self, fut):
        """Await one batcher entry and unwrap it — the result, or the
        dispatch group's error — with the threaded path's wedge guard
        (``_Pending.wait(submit_timeout)``): a dispatch that answers
        nothing inside the window raises instead of parking this
        request — and its admission slot — forever. Shielded: a timeout
        must not cancel the entry a lane thread will still signal."""
        try:
            done = await asyncio.wait_for(
                asyncio.shield(fut), timeout=self._wedge_timeout()
            )
        except asyncio.TimeoutError:
            raise self._wedged_error() from None
        if done.error is not None:
            raise done.error
        return done.result

    def _enqueue(self, loop, key, pred, x, deadline):
        """Enqueue one batcher entry; returns a Future resolving to the
        completed entry (the on_done → call_soon_threadsafe bridge: the
        lane thread signals, the event loop wakes)."""
        fut = loop.create_future()

        def bridge(entry, fut=fut, loop=loop):
            loop.call_soon_threadsafe(_resolve, fut, entry)

        self.service.batcher.enqueue(
            key, pred, x, deadline=deadline, on_done=bridge
        )
        return fut

    # ---- HTTP layer ----

    async def _handle_conn(self, reader, writer):
        try:
            while True:
                req = await self._read_request(reader)
                if req is None:
                    break
                method, path, headers, body = req
                keep = (
                    headers.get("connection", "keep-alive").lower()
                    != "close"
                )
                res = await self._route(
                    method, path, headers, body, writer
                )
                status, payload, ctype = res[:3]
                # Trailing elements: dicts extend the response headers
                # (X-Drift-Score rides here); callables run post-respond
                # (admission release rides here).
                extra_headers: dict = {}
                hooks = []
                for item in res[3:]:
                    if isinstance(item, dict):
                        extra_headers.update(item)
                    else:
                        hooks.append(item)
                try:
                    await self._respond(
                        writer, status, payload, ctype, keep,
                        extra_headers=extra_headers,
                    )
                finally:
                    # Post-respond hooks (admission release rides here):
                    # the in-flight bound covers the response WRITE too,
                    # so slow readers holding big serialized bodies
                    # still count against max_inflight.
                    for hook in hooks:
                        hook()
                if not keep:
                    break
        except _RequestError as e:
            # HTTP-layer rejection: answer with the status (best
            # effort — the writer may already be torn), then close.
            try:
                await self._respond(
                    writer, e.status, {"error": str(e)},
                    "application/json", keep=False,
                )
            except Exception:
                pass
        except (
            ConnectionError, asyncio.IncompleteReadError,
            asyncio.LimitOverrunError, ValueError,
        ):
            pass  # torn/malformed connection: drop it, stay serving
        finally:
            try:
                writer.close()
                await writer.wait_closed()
            except Exception:
                pass

    @staticmethod
    async def _read_request(reader):
        line = await reader.readline()
        if not line:
            return None
        parts = line.decode("latin-1").rstrip("\r\n").split(" ")
        if len(parts) != 3:
            raise _RequestError(400, f"malformed request line {line!r}")
        method, path, _version = parts
        headers: dict[str, str] = {}
        for i in range(_MAX_HEADERS + 1):
            hline = await reader.readline()
            if hline in (b"\r\n", b"\n", b""):
                break
            if i >= _MAX_HEADERS:  # the cap is inclusive: 64 headers OK
                raise _RequestError(
                    400, f"too many headers (max {_MAX_HEADERS})"
                )
            name, _, value = hline.decode("latin-1").partition(":")
            headers[name.strip().lower()] = value.strip()
        if "chunked" in headers.get("transfer-encoding", "").lower():
            # The hand-rolled parser is Content-Length-only; reading a
            # chunked body as length-0 would desynchronize the
            # keep-alive stream (the chunk sizes parse as the next
            # request line). Fail actionably instead.
            raise _RequestError(
                501, "Transfer-Encoding: chunked is not supported; "
                "send Content-Length",
            )
        raw_length = headers.get("content-length", 0) or 0
        try:
            length = int(raw_length)
        except ValueError:
            raise _RequestError(
                400, f"bad Content-Length {raw_length!r}"
            ) from None
        if length < 0:
            raise _RequestError(400, f"bad Content-Length {length}")
        if length > _MAX_BODY:
            raise _RequestError(
                413, f"body of {length} bytes exceeds the "
                f"{_MAX_BODY}-byte cap"
            )
        body = await reader.readexactly(length) if length else b""
        return method, path, headers, body

    async def _respond(
        self, writer, status, payload, ctype, keep, extra_headers=None,
    ):
        body = (
            payload if isinstance(payload, (bytes, bytearray))
            else json.dumps(payload).encode()
        )
        extras = "".join(
            f"{name}: {value}\r\n"
            for name, value in (extra_headers or {}).items()
        )
        head = (
            f"HTTP/1.1 {status} {_STATUS_TEXT.get(status, 'OK')}\r\n"
            f"Content-Type: {ctype}\r\n"
            f"Content-Length: {len(body)}\r\n"
            f"{extras}"
            f"Connection: {'keep-alive' if keep else 'close'}\r\n\r\n"
        )
        writer.write(head.encode("latin-1") + body)
        await writer.drain()

    async def _route(self, method, path, headers, body, writer):
        from urllib.parse import parse_qs, urlsplit

        from tpuflow.obs import use_trace

        split = urlsplit(path)
        route = split.path.rstrip("/")
        json_ct = "application/json"
        if method == "GET":
            if route in ("", "/health", "/healthz"):
                deg = self.service.degraded()
                return 200, {
                    "status": "degraded" if deg else "ok",
                    "degraded": bool(deg),
                    "degraded_artifacts": deg,
                }, json_ct
            if route == "/metrics":
                fmt = parse_qs(split.query).get("format", [""])[0]
                if fmt == "prometheus":
                    from tpuflow.obs import (
                        default_registry,
                        render_prometheus,
                    )

                    # Refresh the SLO gauges first: the exposition's
                    # slo_* families must reflect THIS scrape's counter
                    # state, not the previous JSON view's.
                    self.slo.evaluate_registry(self.registry)
                    text = render_prometheus(
                        self.registry, default_registry()
                    )
                    return 200, text.encode(), (
                        "text/plain; version=0.0.4; charset=utf-8"
                    )
                return 200, self.metrics(), json_ct
            if route == "/jobs" and self.runner is not None:
                return 200, self.runner.list(), json_ct
            parts = route.split("/")
            if (
                len(parts) == 3 and parts[1] == "jobs"
                and self.runner is not None
            ):
                rec = self.runner.get(parts[2])
                if rec is None:
                    return 404, {"error": f"no job {parts[2]!r}"}, json_ct
                return 200, rec, json_ct
            return 404, {"error": f"no route {path!r}"}, json_ct
        if method == "POST" and route == "/predict":
            if self._draining.is_set():
                # Mid-drain: refuse NEW work before admission touches
                # its counters, while already-admitted requests keep
                # running to completion — the zero-500s drain contract.
                self.admission.shed_draining()
                return 503, {
                    "error": "server draining for shutdown; retry "
                    "another replica", "shed": "draining",
                }, json_ct
            client = headers.get("x-client-id") or (
                (writer.get_extra_info("peername") or ("?",))[0]
            )
            shed = self.admission.try_admit(str(client))
            if shed == 429:
                return 429, {
                    "error": "per-client quota exceeded; retry after "
                    "your token bucket refills", "shed": "quota",
                }, json_ct
            if shed == 503:
                return 503, {
                    "error": f"admission queue full "
                    f"({self.admission.max_inflight} in flight); "
                    "retry with backoff", "shed": "admission",
                }, json_ct
            try:
                try:
                    spec = await self._parse_body(body)
                except (ValueError, json.JSONDecodeError) as e:
                    return 400, {"error": str(e)}, json_ct, \
                        self.admission.release
                with use_trace(
                    _clean_trace_id(headers.get("x-trace-id"))
                ):
                    status, payload, extra = await self._predict(
                        spec, headers
                    )
                # The slot is released AFTER the response is written
                # (the caller runs trailing hooks post-_respond): the
                # in-flight bound must also cover a serialized body
                # parked behind a slow reader.
                return (
                    status, payload, json_ct, extra,
                    self.admission.release,
                )
            except BaseException:
                self.admission.release()
                raise
        if method == "POST" and route == "/artifacts/reload":
            # The online loop's swap signal (tpuflow/online): drop the
            # cached predictor; the next request loads the promoted
            # artifact. In-flight entries drain against the predictor
            # INSTANCE they enqueued with (the batcher contract), so a
            # reload never drops a request. On the executor: invalidate
            # takes the service lock and retires a dispatch lane.
            try:
                spec = await self._parse_body(body)
            except (ValueError, json.JSONDecodeError) as e:
                return 400, {"error": str(e)}, json_ct
            storage = spec.get("storagePath") or spec.get("storage_path")
            name = spec.get("model") or spec.get("name")
            if not storage or not name:
                return 400, {
                    "error": "reload needs storagePath and model"
                }, json_ct
            loop = asyncio.get_running_loop()
            # The online loop's lifecycle trace rides the nudge as
            # X-Trace-Id: bound here, the reload record (ring + trail)
            # carries it — the drift -> retrain -> swap -> reload chain
            # stays ONE trace across the process boundary.
            with use_trace(
                _clean_trace_id(headers.get("x-trace-id"))
            ) as tid:
                # Drops the cached predictor AND the drift baseline
                # (the swapped artifact carries its own reference
                # stats) — the same helper the job path's
                # artifact-change hook calls.
                await loop.run_in_executor(
                    self._pool, self._invalidate_artifact, storage, name
                )
                self._record_reload(storage, name)
            return 200, {
                "reloaded": True, "storage_path": storage, "model": name,
                "trace_id": tid,
            }, json_ct
        if method == "POST" and route == "/jobs" and self.runner is not None:
            import queue as _queue

            loop = asyncio.get_running_loop()
            try:
                spec = await self._parse_body(body)
                # Executor: submit() flushes the journal (disk I/O) —
                # a stalled journal filesystem must not stall the loop.
                res = await loop.run_in_executor(
                    self._pool, self.runner.submit, spec
                )
                return 202, res, json_ct
            except _queue.Full:
                return 429, {
                    "error": f"job queue full (max "
                    f"{self.runner.max_queued}); retry after a job "
                    "finishes"
                }, json_ct
            except (ValueError, TypeError, json.JSONDecodeError) as e:
                return 400, {"error": str(e)}, json_ct
        if method == "DELETE" and self.runner is not None:
            parts = route.split("/")
            if len(parts) == 3 and parts[1] == "jobs":
                loop = asyncio.get_running_loop()
                res = await loop.run_in_executor(
                    self._pool, self.runner.cancel, parts[2]
                )
                if res is None:
                    return 404, {"error": f"no job {parts[2]!r}"}, json_ct
                if res.pop("conflict", False):
                    return 409, {
                        **res, "error": f"job already {res['status']}",
                    }, json_ct
                return 200, res, json_ct
        return 404, {"error": f"no route {path!r}"}, json_ct

    async def _parse_body(self, body: bytes) -> dict:
        """Parse a JSON request body — on the executor past a size
        threshold: json.loads of a body near the 64MB cap takes loop-
        stalling time, and inbound parse deserves the same discipline
        the outbound ``shape_response`` already follows. Small bodies
        (the overwhelmingly common case) parse inline; the executor
        hop would cost more than it saves."""
        def parse():
            spec = json.loads(body or b"{}")
            if not isinstance(spec, dict):
                raise ValueError("request body must be a JSON object")
            return spec

        if len(body) < 64 * 1024:
            return parse()
        return await asyncio.get_running_loop().run_in_executor(
            self._pool, parse
        )

    # ---- autoscaler control seams ----
    #
    # Each setter is a single GIL-atomic store into state the request
    # path reads per-request (the documented cross-thread tolerance of
    # `drain` and the inflight gauge): no lock, no torn read, effective
    # on the very next admission/dispatch. Single writer — the
    # ObservingController's control thread.

    def set_max_inflight(self, n: int) -> int:
        """Resize the admission bound at runtime (floor 1)."""
        n = max(1, int(n))
        self.admission.max_inflight = n
        return n

    def set_hedge_ms(self, ms: float) -> float:
        """Retune the hedged re-dispatch window (0 = off)."""
        ms = max(0.0, float(ms))
        self.hedge_ms = ms
        return ms

    def set_drift_threshold(self, z: float) -> float:
        """Retune the drift-admission shed threshold (> 0)."""
        z = max(1e-9, float(z))
        self.drift_threshold = z
        return z

    def set_replicas(self, n: int) -> int:
        """Resize the replica data plane (delegates to the service's
        :meth:`~tpuflow.serve.PredictService.set_replicas`; raises the
        same diagnostics on an unplaceable count)."""
        return self.service.set_replicas(n)

    def metrics(self) -> dict:
        """The /metrics JSON view: the threaded daemon's schema plus the
        ``serving`` section (admission + shed + hedge counters). Keys
        are drift-tested against docs/serving.md's marker block."""
        out = {
            "jobs": self.runner.metrics() if self.runner is not None else {},
            "predict": self.service.metrics(),
            "serving": {
                **self.admission.metrics(),
                "hedges": int(self._hedges.value()),
                "hedge_wins": int(self._hedge_wins.value()),
                "deadline_ms": self.deadline_ms,
                "hedge_ms": self.hedge_ms,
                "drift_admission": self.drift_admission,
                "drift_threshold": self.drift_threshold,
                "drift_flagged": int(
                    self._drift_admissions.value(action="flagged")
                ),
                "drift_shed": int(
                    self._drift_admissions.value(action="shed")
                ),
            },
            "replicas": (
                self.service.replica_metrics()
                if hasattr(self.service, "replica_metrics")
                else {}
            ),
            # The SLO section (tpuflow/obs/slo.py): objectives scored
            # against this daemon's own counters at scrape time — the
            # same verdicts the Prometheus view carries as slo_* gauges.
            "slo": self.slo.evaluate_registry(self.registry),
            # Alert states as last evaluated by the history tick — a
            # scrape reports, it never advances hold-down clocks.
            "alerts": self.alerts.summary(),
            "uptime_s": round(time.monotonic() - self._started, 1),
        }
        if self.autoscaler is not None:
            out["autoscale"] = self.autoscaler.summary()
        return out

    # ---- lifecycle ----

    async def _amain(self):
        self._aserver = await asyncio.start_server(
            self._handle_conn, self.host, self.port, limit=1 << 16,
            backlog=512,
        )
        self.port = self._aserver.sockets[0].getsockname()[1]
        if self._announce:
            # Post-bind, so --port 0 prints the REAL ephemeral port and
            # a failed bind never prints a success line.
            print(
                f"tpuflow async serving on http://{self.host}:{self.port}",
                flush=True,
            )
        self._ready.set()
        # Both entry points (start() and serve_forever()) pass through
        # here, so the sampler and the autoscaler start exactly once,
        # post-bind — never for a daemon that failed to boot.
        self.history.start()
        if self.profiler is not None:
            self.profiler.start()
        if self.autoscaler is not None:
            self.autoscaler.start()
        async with self._aserver:
            await self._aserver.serve_forever()

    def _run_loop(self):
        self._loop = asyncio.new_event_loop()
        asyncio.set_event_loop(self._loop)
        try:
            self._loop.run_until_complete(self._amain())
        except asyncio.CancelledError:
            pass
        except BaseException as e:
            # Pre-bind failure (EADDRINUSE, EACCES): hand the REAL
            # error to the thread parked in start() instead of letting
            # it wait out the 30s and raise something generic.
            self._boot_error = e
            self._ready.set()
            raise
        finally:
            try:
                self._loop.run_until_complete(
                    self._loop.shutdown_asyncgens()
                )
            finally:
                self._loop.close()

    def start(self) -> "AsyncServer":
        """Serve on a background thread; returns once the socket is
        bound (``self.port`` is then the real ephemeral port). A bind
        failure re-raises here with its real cause."""
        self._thread = threading.Thread(
            target=self._run_loop, name="tpuflow-serve-async", daemon=True
        )
        self._thread.start()
        if not self._ready.wait(timeout=30):
            raise RuntimeError("async server failed to bind within 30s")
        if self._boot_error is not None:
            raise RuntimeError(
                f"async server failed to start: {self._boot_error}"
            ) from self._boot_error
        return self

    def serve_forever(self) -> None:
        """Foreground serving (``main()``): blocks until ``shutdown``."""
        self._run_loop()

    def drain(self, timeout: float = 10.0) -> bool:
        """Stop admitting NEW /predict work (503 "draining" sheds) and
        wait for every in-flight request to finish; returns True when
        the server is empty, False on timeout (in-flight work still
        running — the caller decides whether to abandon it).

        The listener deliberately stays OPEN: closing it would end the
        serve task, tear down the event loop, and cancel the very
        in-flight handlers a drain exists to protect (and health checks
        keep answering mid-drain, so an orchestrator can watch the
        drain instead of flying blind). Call ``shutdown()`` after.
        ``inflight`` is read cross-thread here — a GIL-atomic int load,
        the same documented tolerance as the gauge callback's.
        """
        self._draining.set()
        deadline = time.monotonic() + max(timeout, 0.0)
        while self.admission.inflight > 0 and time.monotonic() < deadline:
            time.sleep(0.02)
        return self.admission.inflight <= 0

    def shutdown(self) -> None:
        """Stop accepting, cancel the serve task, close the batcher and
        executor. Idempotent; callable from any thread."""
        # Control loops first: the autoscaler must not resize a daemon
        # that is tearing down, and the sampler's spill closes cleanly.
        if self.autoscaler is not None:
            self.autoscaler.stop()
        if self.profiler is not None:
            self.profiler.stop()
        self.history.stop()
        loop = self._loop
        if loop is not None and not loop.is_closed():

            def _stop():
                if self._aserver is not None:
                    self._aserver.close()
                for task in asyncio.all_tasks(loop):
                    task.cancel()

            try:
                loop.call_soon_threadsafe(_stop)
            except RuntimeError:
                pass
        if self._thread is not None:
            self._thread.join(timeout=10)
        self.service.close()
        self._pool.shutdown(wait=False)


def _resolve(fut, entry) -> None:
    if not fut.done():
        fut.set_result(entry)


def make_async_server(host: str = "127.0.0.1", port: int = 0, **kwargs):
    """Build-and-start convenience for tests/benchmarks: returns a
    RUNNING AsyncServer with ``.port`` resolved (the ``make_server`` +
    ``serve_forever``-thread idiom, one call)."""
    return AsyncServer(host, port, **kwargs).start()


def main(argv=None) -> int:
    import argparse
    import signal
    import sys

    p = argparse.ArgumentParser(
        prog="tpuflow.serve_async",
        description="tpuflow async serving control plane (asyncio front "
        "end + continuous batching + admission control)",
    )
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8700)
    p.add_argument(
        "--max-inflight", type=int, default=None, metavar="N",
        help="admission bound: requests in flight past admission "
        "(default 256; also TPUFLOW_SERVE_ADMIT_MAX); past it /predict "
        "sheds 503",
    )
    p.add_argument(
        "--quota-rps", type=float, default=None, metavar="R",
        help="per-client token-bucket refill rate, requests/sec "
        "(default 0 = off; also TPUFLOW_SERVE_QUOTA_RPS); past it the "
        "client sheds 429",
    )
    p.add_argument(
        "--quota-burst", type=float, default=None, metavar="B",
        help="per-client token-bucket size (default 16; also "
        "TPUFLOW_SERVE_QUOTA_BURST)",
    )
    p.add_argument(
        "--deadline-ms", type=float, default=None, metavar="MS",
        help="default per-request deadline (0 = off; also "
        "TPUFLOW_SERVE_DEADLINE_MS; per-request deadlineMs/X-Deadline-Ms "
        "override); an expired request sheds 504 and never occupies a "
        "dispatch slot",
    )
    p.add_argument(
        "--hedge-ms", type=float, default=None, metavar="MS",
        help="hedged re-dispatch window (0 = off; also "
        "TPUFLOW_SERVE_HEDGE_MS): a coalesced forward slower than this "
        "enqueues a duplicate and the first completion wins",
    )
    p.add_argument(
        "--prep-workers", type=int, default=None, metavar="N",
        help="executor threads for blocking work (artifact loads, "
        "feature transforms; default 4; also TPUFLOW_SERVE_PREP_WORKERS)",
    )
    p.add_argument(
        "--batch-max-rows", type=int, default=None, metavar="N",
        help="max rows per coalesced dispatch (default 256; also "
        "TPUFLOW_SERVE_MAX_BATCH)",
    )
    p.add_argument(
        "--no-batch-predicts", action="store_const", const=False,
        dest="batch_predicts", default=None,
        help="disable continuous batching (every request runs its own "
        "forward on an executor thread; default on, also "
        "TPUFLOW_SERVE_BATCH)",
    )
    p.add_argument(
        "--warmup-buckets", type=int, default=None, metavar="K",
        help="pre-compile the K largest pow-2 forward buckets at "
        "artifact load (default 0; also TPUFLOW_SERVE_WARMUP)",
    )
    p.add_argument(
        "--donate-forward", action="store_true", default=None,
        help="donate the input batch buffer to the jitted forward "
        "(also TPUFLOW_SERVE_DONATE=1)",
    )
    p.add_argument(
        "--max-resident", type=int, default=None, metavar="N",
        help="artifact placement bound: predictors resident before LRU "
        "spill (default 0 = unbounded; also TPUFLOW_SERVE_RESIDENT)",
    )
    p.add_argument(
        "--replicas", type=int, default=None, metavar="R",
        help="predictor replicas per artifact, one per device with its "
        "own dispatch lane, join-shortest-queue at enqueue (default 1; "
        "also TPUFLOW_SERVE_REPLICAS; host-side devices via "
        "XLA_FLAGS=--xla_force_host_platform_device_count=R)",
    )
    p.add_argument(
        "--drift-admission", choices=("off", "flag", "shed"),
        default=None,
        help="score request features against the artifact sidecar's "
        "reference stats at admission (default off; also "
        "TPUFLOW_SERVE_DRIFT_ADMISSION): flag = X-Drift-Score header + "
        "counter on far-out-of-distribution requests, shed = answer "
        "them 429 before they occupy a dispatch slot",
    )
    p.add_argument(
        "--drift-threshold", type=float, default=None, metavar="Z",
        help="standardized-shift score past which a request counts as "
        "out-of-distribution (default 6.0; also "
        "TPUFLOW_SERVE_DRIFT_THRESHOLD)",
    )
    p.add_argument(
        "--no-jobs", action="store_false", dest="enable_jobs", default=True,
        help="serve /predict only (no job queue)",
    )
    p.add_argument("--max-queued", type=int, default=64)
    p.add_argument("--default-timeout", type=float, default=None)
    p.add_argument("--journal", default=None, metavar="PATH")
    p.add_argument(
        "--trail", default=None, metavar="PATH",
        help="append lifecycle events (startup, trace-stamped "
        "/artifacts/reload records) as JSONL here — this daemon's lane "
        "in `python -m tpuflow.obs fleet` (also TPUFLOW_SERVE_TRAIL)",
    )
    p.add_argument(
        "--autoscale", action=argparse.BooleanOptionalAction,
        default=None,
        help="run the SLO-driven autoscaler (tpuflow/serve_autoscale): "
        "hill-climbs replicas / max-inflight / hedge / drift threshold "
        "against the live slo_burn_rate history, with hysteresis and a "
        "hard availability floor (default off; also "
        "TPUFLOW_SERVE_AUTOSCALE=1; --no-autoscale overrides the env; "
        "knobs via TPUFLOW_SERVE_AUTOSCALE_<KEY>)",
    )
    args = p.parse_args(argv)

    if args.replicas is not None:
        # Preflight the replica count against the hardware BEFORE
        # constructing anything: the diagnostic names the device count
        # and the host-side recipe (analysis pass; the service performs
        # the same check at construction for the env-var path).
        from tpuflow.analysis.plan import check_serve_plan

        diags = check_serve_plan(args.replicas)
        if diags:
            for d in diags:
                print(d.render(), file=sys.stderr)
            return 2

    try:
        server = AsyncServer(
            args.host, args.port,
            max_inflight=args.max_inflight,
            quota_rps=args.quota_rps,
            quota_burst=args.quota_burst,
            deadline_ms=args.deadline_ms,
            hedge_ms=args.hedge_ms,
            prep_workers=args.prep_workers,
            batch_predicts=args.batch_predicts,
            batch_max_rows=args.batch_max_rows,
            warmup_buckets=args.warmup_buckets,
            donate_forward=args.donate_forward,
            max_resident=args.max_resident,
            replicas=args.replicas,
            drift_admission=args.drift_admission,
            drift_threshold=args.drift_threshold,
            enable_jobs=args.enable_jobs,
            max_queued=args.max_queued,
            default_timeout=args.default_timeout,
            journal_path=args.journal,
            trail_path=args.trail,
            autoscale=args.autoscale,
        )
    except ValueError as e:
        # Configuration-shaped failure (malformed env knob, replica
        # count the devices cannot place): a message, not a traceback.
        print(f"tpuflow.serve_async: {e}", file=sys.stderr)
        return 2

    def _stop(signum, frame):
        threading.Thread(
            target=server.shutdown, name="tpuflow-serve-shutdown", daemon=True,
        ).start()

    signal.signal(signal.SIGTERM, _stop)
    signal.signal(signal.SIGINT, _stop)
    server._announce = True
    server.serve_forever()
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
