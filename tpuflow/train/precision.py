"""The mixed-precision training policy: bf16 compute, f32 masters.

One knob — ``TrainJobConfig.precision`` (``"f32"`` default | ``"bf16"``)
— installs one policy across the whole train path:

- **Master params and optimizer state stay float32.** ``create_state``
  enforces it (``ensure_f32_masters``); checkpoints, serving artifacts,
  warm starts, elastic averaging, and the online loop therefore never
  see a bf16 leaf and need no changes.
- **Compute runs in the compute dtype.** ``train()`` injects the
  resolved dtype into ``model_kwargs`` (every model family takes a
  ``dtype`` knob and casts params + activations per layer, flax-style:
  the cast sits INSIDE the differentiated graph, so gradients come back
  f32 against the f32 masters) and the jitted steps cast the input
  batch at step entry (``tpuflow/train/steps.py``).
- **Loss/grad reduction and the optimizer update stay f32.** Models
  return f32 predictions, the steps promote predictions at the loss
  site and cast the loss/grad_norm aux to f32, so the numerics
  watchdog's EWMA spike threshold never silently widens to bf16
  resolution, and ``apply_gradients`` updates f32 masters with f32
  grads.

Why bf16 at all: the LSTM-64 train step is HBM-BOUND on v5e (round 5:
13.6% MFU at 63% HBM util), and activation traffic dominates its byte
budget — halving the itemsize halves ``hbm_bytes_per_sample`` on the
binding resource (``tpuflow/utils/roofline.py`` accounts for it).
SparkNet-era CPU systems (PAPERS.md) could not express this
compute/accumulate split; the MXU is built for it.

Import-light: no jax at module import (preflight validates the knob
without touching a device); dtypes resolve lazily.
"""

from __future__ import annotations

# The knob's vocabulary — validated by the preflight spec pass
# (tpuflow/analysis/spec.py) so a typo'd precision dies at submission,
# naming these choices.
PRECISIONS = ("f32", "bf16")

# HBM itemsize of the compute dtype: the roofline's bytes-per-sample
# accounting must follow the dtype the activations actually travel in.
# Canonical map lives with the roofline (tpuflow/utils/roofline.py);
# re-exported here so policy callers need one import.
from tpuflow.utils.roofline import PRECISION_ITEMSIZE  # noqa: E402,F401

_DTYPE_NAMES = {"f32": "float32", "bf16": "bfloat16"}

# The documented bf16-vs-f32 parity tolerance for the fixed-seed LSTM
# fit gate: final losses within 5% relative, or the speedup is
# disqualified as a numerics regression. ONE definition — the tier-1
# drill (tests/test_precision.py) and the committed A/B artifact's gate
# (benchmarks/bench_lstm64.py --ab) both import it, so the two can
# never enforce contradictory verdicts. Measured slack on the reference
# drill is <1e-3 relative; 5% is the never-flaky bound that still fails
# a real numerics break (docs/performance.md "Mixed precision").
PARITY_RTOL = 0.05


def check_precision(precision: str) -> str:
    """Validate and return the precision token; raises naming choices."""
    if precision not in PRECISIONS:
        raise ValueError(
            f"unknown precision {precision!r}; "
            f"valid: {', '.join(PRECISIONS)}"
        )
    return precision


def compute_dtype(precision: str):
    """The jnp dtype activations/matmul operands run in under the policy."""
    import jax.numpy as jnp

    return jnp.dtype(_DTYPE_NAMES[check_precision(precision)]).type


def precision_itemsize(precision: str) -> int:
    """HBM bytes per activation element under the policy."""
    return PRECISION_ITEMSIZE[check_precision(precision)]


def model_accepts_dtype(model: str) -> bool:
    """Whether a registry model family takes the ``dtype`` compute knob.

    Every built-in family does (the policy's model leg); this exists so
    ``train()`` degrades gracefully — precision still casts the batch at
    step entry — if an external registry entry lacks the knob.
    """
    import inspect

    from tpuflow.models import MODELS

    if model not in MODELS:
        return False
    try:
        module = MODELS[model]()
    except TypeError:
        return False
    return "dtype" in {f.name for f in module.__dataclass_fields__.values()} \
        if hasattr(module, "__dataclass_fields__") else False


def inject_model_dtype(model: str, model_kwargs: dict, precision: str) -> dict:
    """Return ``model_kwargs`` with the policy's compute dtype injected
    — THE one injection rule, shared by ``train()`` and the preflight
    shape dry-run (``analysis/shapes.py``) so the graph preflight traces
    is the graph training runs. An explicit user ``dtype`` wins (the
    knob is a default, not a clamp); f32 injects nothing (the models'
    own default); families without the knob are left untouched (the
    step-entry cast still applies).
    """
    if (
        precision in PRECISIONS
        and precision != "f32"
        and "dtype" not in model_kwargs
        and model_accepts_dtype(model)
    ):
        return {**model_kwargs, "dtype": compute_dtype(precision)}
    return dict(model_kwargs)


def cast_floating(tree, dtype):
    """Cast every inexact (floating) leaf of a pytree to ``dtype``,
    leaving integer leaves (step counters, routing indices) untouched.
    Used at step entry for the batch and by ``ensure_f32_masters``."""
    import jax
    import jax.numpy as jnp

    def _cast(leaf):
        arr = jnp.asarray(leaf)
        if jnp.issubdtype(arr.dtype, jnp.inexact) and arr.dtype != dtype:
            return arr.astype(dtype)
        return leaf

    return jax.tree_util.tree_map(_cast, tree)
