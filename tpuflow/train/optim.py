"""Optimizers.

The reference's optimizer (SURVEY.md C11, reference cnn.py:117-118):
``SGD(lr=0.001, momentum=0.99, decay=1e-6, nesterov=True)``. Keras-era
``decay`` is a per-update learning-rate decay ``lr_t = lr / (1 + decay*t)``
— reproduced here as an optax schedule.
"""

from __future__ import annotations

import optax


def keras_sgd(
    learning_rate: float = 1e-3,
    momentum: float = 0.99,
    decay: float = 1e-6,
    nesterov: bool = True,
) -> optax.GradientTransformation:
    """SGD with Keras-style inverse-time lr decay (reference defaults)."""

    def schedule(step):
        return learning_rate / (1.0 + decay * step)

    return optax.sgd(schedule, momentum=momentum, nesterov=nesterov)


def build_optimizer(name: str = "keras_sgd", **kwargs) -> optax.GradientTransformation:
    if name == "keras_sgd":
        return keras_sgd(**kwargs)
    if name == "adam":
        return optax.adam(kwargs.pop("learning_rate", 1e-3), **kwargs)
    if name == "adamw":
        return optax.adamw(kwargs.pop("learning_rate", 1e-3), **kwargs)
    raise ValueError(f"unknown optimizer {name!r}")
