"""Optimizers.

The reference's optimizer (SURVEY.md C11, reference cnn.py:117-118):
``SGD(lr=0.001, momentum=0.99, decay=1e-6, nesterov=True)``. Keras-era
``decay`` is a per-update learning-rate decay ``lr_t = lr / (1 + decay*t)``
— reproduced here as an optax schedule.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import optax


class LrScaleState(NamedTuple):
    """State of :func:`with_lr_scale`: the wrapped optimizer's state plus
    a multiplicative LR scale as a REAL pytree leaf — host code can
    replace it between epochs (the numerics watchdog's ``halve_lr``
    policy) without retracing the jitted step, because it is data, not
    a static closure constant."""

    inner: Any
    lr_scale: Any


def with_lr_scale(tx: optax.GradientTransformation) -> optax.GradientTransformation:
    """Wrap ``tx`` so its final updates are multiplied by a runtime LR
    scale carried in the optimizer state (initially 1.0 — a no-op).

    Outermost by construction in :func:`wrap_optimizer`: the scale
    applies to whatever update the clip/accumulate/base chain produced,
    so halving the scale halves the effective learning rate exactly.
    """
    import jax
    import jax.numpy as jnp

    def init(params):
        return LrScaleState(
            inner=tx.init(params), lr_scale=jnp.asarray(1.0, jnp.float32)
        )

    def update(grads, state, params=None):
        updates, inner = tx.update(grads, state.inner, params)
        scaled = jax.tree_util.tree_map(
            lambda u: u * state.lr_scale.astype(u.dtype), updates
        )
        return scaled, LrScaleState(inner=inner, lr_scale=state.lr_scale)

    return optax.GradientTransformation(init, update)


def scale_lr_in_state(state, factor: float):
    """Multiply the ``lr_scale`` leaf inside a TrainState's optimizer
    state by ``factor``; returns the new state, or None when the
    optimizer was not built through :func:`wrap_optimizer` (no
    :class:`LrScaleState` anywhere — e.g. a hand-rolled optax chain).
    Pure host-side pytree surgery: same leaf shapes/dtypes, so the next
    jitted step reuses its compiled executable."""
    found = [False]

    def visit(node):
        if isinstance(node, LrScaleState):
            found[0] = True
            return LrScaleState(
                inner=visit(node.inner),
                lr_scale=node.lr_scale * factor,
            )
        if isinstance(node, tuple):
            rebuilt = [visit(c) for c in node]
            return (
                type(node)(*rebuilt) if hasattr(node, "_fields")
                else tuple(rebuilt)
            )
        if isinstance(node, list):
            return [visit(c) for c in node]
        if isinstance(node, dict):
            return {k: visit(v) for k, v in node.items()}
        return node

    new_opt_state = visit(state.opt_state)
    if not found[0]:
        return None
    return state.replace(opt_state=new_opt_state)


def reset_opt_state(state):
    """Re-initialize the optimizer state for the CURRENT params — the
    ``opt_policy="reset"`` half of elastic adoption (docs/elastic.md):
    after adopting a gang average, locally-accumulated momentum points
    along a trajectory the averaged params are no longer on.

    Only the floating leaves (momenta, EMAs) are reset; non-floating
    leaves (step counters) are kept from the old state — zeroing the
    count would restart ``keras_sgd``'s inverse-time decay schedule at
    its hottest learning rate mid-run. The runtime ``lr_scale`` leaf is
    likewise carried: the numerics watchdog's halvings are a property
    of this worker's run, not of the momentum trajectory. Pure
    host-side surgery, same shapes/dtypes — no retrace."""
    import jax
    import jax.numpy as jnp

    fresh = state.tx.init(state.params)
    old_leaves, old_def = jax.tree_util.tree_flatten(state.opt_state)
    new_leaves, new_def = jax.tree_util.tree_flatten(fresh)
    if old_def != new_def:
        # A structurally different state (restored from an older
        # optimizer config) cannot be leaf-merged; fresh is the only
        # coherent choice.
        return state.replace(opt_state=fresh)
    merged = [
        new if jnp.issubdtype(jnp.asarray(new).dtype, jnp.floating)
        else old
        for old, new in zip(old_leaves, new_leaves)
    ]
    out = jax.tree_util.tree_unflatten(new_def, merged)
    if isinstance(out, LrScaleState) and isinstance(
        state.opt_state, LrScaleState
    ):
        out = LrScaleState(inner=out.inner, lr_scale=state.opt_state.lr_scale)
    return state.replace(opt_state=out)


def keras_sgd(
    learning_rate: float = 1e-3,
    momentum: float = 0.99,
    decay: float = 1e-6,
    nesterov: bool = True,
) -> optax.GradientTransformation:
    """SGD with Keras-style inverse-time lr decay (reference defaults)."""

    def schedule(step):
        return learning_rate / (1.0 + decay * step)

    return optax.sgd(schedule, momentum=momentum, nesterov=nesterov)


def _adam(**kwargs) -> optax.GradientTransformation:
    return optax.adam(kwargs.pop("learning_rate", 1e-3), **kwargs)


def _adamw(**kwargs) -> optax.GradientTransformation:
    return optax.adamw(kwargs.pop("learning_rate", 1e-3), **kwargs)


# name -> builder: the registry preflight (tpuflow/analysis) validates
# TrainJobConfig.optimizer against, same shape as models.MODELS and
# core.losses.LOSSES.
OPTIMIZERS = {
    "keras_sgd": keras_sgd,
    "adam": _adam,
    "adamw": _adamw,
}


def build_optimizer(name: str = "keras_sgd", **kwargs) -> optax.GradientTransformation:
    if name not in OPTIMIZERS:
        raise ValueError(
            f"unknown optimizer {name!r}; known: {sorted(OPTIMIZERS)}"
        )
    return OPTIMIZERS[name](**kwargs)


def wrap_optimizer(
    tx: optax.GradientTransformation,
    clip_norm: float = 0.0,
    accumulate_steps: int = 1,
) -> optax.GradientTransformation:
    """Optional global-norm gradient clipping and gradient accumulation
    around any base optimizer.

    Accumulation (``optax.MultiSteps``) averages ``accumulate_steps``
    micro-batch gradients and applies ONE update — the standard recipe
    for effective batches larger than device memory. Parameters change
    only on the k-th micro-step, so size epochs to a multiple of k:
    a trailing partial window's gradients stay in the accumulator (and
    are discarded if training ends there). Clipping wraps OUTSIDE the
    accumulator, so each micro-batch gradient is clipped before it
    enters the average — one spiky micro-batch can't dominate the
    window.

    The whole chain is wrapped OUTERMOST in :func:`with_lr_scale`, a
    runtime LR multiplier (1.0 until touched) living in the optimizer
    state — the seam the numerics watchdog's ``halve_lr`` policy turns
    without recompiling the step.
    """
    if clip_norm < 0:
        # A negative max_norm would sign-flip every update in
        # optax.clip_by_global_norm (scale = max_norm/g_norm < 0) —
        # silent gradient ascent.
        raise ValueError(f"clip_norm must be >= 0, got {clip_norm}")
    if accumulate_steps < 1:
        raise ValueError(
            f"accumulate_steps must be >= 1, got {accumulate_steps}"
        )
    if accumulate_steps > 1:
        tx = optax.MultiSteps(tx, accumulate_steps).gradient_transformation()
    if clip_norm:
        tx = optax.chain(optax.clip_by_global_norm(clip_norm), tx)
    return with_lr_scale(tx)
