"""Optimizers.

The reference's optimizer (SURVEY.md C11, reference cnn.py:117-118):
``SGD(lr=0.001, momentum=0.99, decay=1e-6, nesterov=True)``. Keras-era
``decay`` is a per-update learning-rate decay ``lr_t = lr / (1 + decay*t)``
— reproduced here as an optax schedule.
"""

from __future__ import annotations

import optax


def keras_sgd(
    learning_rate: float = 1e-3,
    momentum: float = 0.99,
    decay: float = 1e-6,
    nesterov: bool = True,
) -> optax.GradientTransformation:
    """SGD with Keras-style inverse-time lr decay (reference defaults)."""

    def schedule(step):
        return learning_rate / (1.0 + decay * step)

    return optax.sgd(schedule, momentum=momentum, nesterov=nesterov)


def _adam(**kwargs) -> optax.GradientTransformation:
    return optax.adam(kwargs.pop("learning_rate", 1e-3), **kwargs)


def _adamw(**kwargs) -> optax.GradientTransformation:
    return optax.adamw(kwargs.pop("learning_rate", 1e-3), **kwargs)


# name -> builder: the registry preflight (tpuflow/analysis) validates
# TrainJobConfig.optimizer against, same shape as models.MODELS and
# core.losses.LOSSES.
OPTIMIZERS = {
    "keras_sgd": keras_sgd,
    "adam": _adam,
    "adamw": _adamw,
}


def build_optimizer(name: str = "keras_sgd", **kwargs) -> optax.GradientTransformation:
    if name not in OPTIMIZERS:
        raise ValueError(
            f"unknown optimizer {name!r}; known: {sorted(OPTIMIZERS)}"
        )
    return OPTIMIZERS[name](**kwargs)


def wrap_optimizer(
    tx: optax.GradientTransformation,
    clip_norm: float = 0.0,
    accumulate_steps: int = 1,
) -> optax.GradientTransformation:
    """Optional global-norm gradient clipping and gradient accumulation
    around any base optimizer.

    Accumulation (``optax.MultiSteps``) averages ``accumulate_steps``
    micro-batch gradients and applies ONE update — the standard recipe
    for effective batches larger than device memory. Parameters change
    only on the k-th micro-step, so size epochs to a multiple of k:
    a trailing partial window's gradients stay in the accumulator (and
    are discarded if training ends there). Clipping wraps OUTSIDE the
    accumulator, so each micro-batch gradient is clipped before it
    enters the average — one spiky micro-batch can't dominate the
    window.
    """
    if clip_norm < 0:
        # A negative max_norm would sign-flip every update in
        # optax.clip_by_global_norm (scale = max_norm/g_norm < 0) —
        # silent gradient ascent.
        raise ValueError(f"clip_norm must be >= 0, got {clip_norm}")
    if accumulate_steps < 1:
        raise ValueError(
            f"accumulate_steps must be >= 1, got {accumulate_steps}"
        )
    if accumulate_steps > 1:
        tx = optax.MultiSteps(tx, accumulate_steps).gradient_transformation()
    if clip_norm:
        tx = optax.chain(optax.clip_by_global_norm(clip_norm), tx)
    return tx
