"""Resumable training: periodic full-state checkpoints + deterministic resume.

SURVEY.md §5.3: the reference's fault-tolerance story is Spark task retry
at the cluster layer (reference Readme.md:3); it saves only the *best
params* with no way to continue a run (cnn.py:122). The TPU-native
equivalent is deterministic resumability: every N epochs the FULL training
state — params, optimizer state, step counter, early-stopping state, epoch
— is checkpointed via Orbax; after preemption, ``fit(..., resume=True)``
restores the latest and continues the exact same trajectory (batch
shuffling is seeded per-epoch and dropout keys fold the step counter, so a
resumed run is bit-identical to an uninterrupted one at epoch
granularity).
"""

from __future__ import annotations

from typing import Any

import jax
import orbax.checkpoint as ocp

from tpuflow.resilience import fault_point, io_policy, retry_call
from tpuflow.utils.paths import join_path


def _leaf_paths(tree) -> list[str]:
    """Human-readable key paths of every leaf, in flatten order."""
    leaves, _ = jax.tree_util.tree_flatten_with_path(tree)
    return [jax.tree_util.keystr(path) for path, _ in leaves]


def check_params_match(live_params, incoming) -> None:
    """Raise a ValueError naming the first mismatching leaf PATHS when
    ``incoming`` cannot overlay ``live_params`` (different tree
    structure, or a leaf with a different shape).

    ``incoming``'s leaves only need a ``.shape`` — real arrays and
    checkpoint METADATA leaves (``BestCheckpointer.best_structure``)
    both qualify, so a warm start can fail with a readable diagnosis
    BEFORE paying for the restore. Where BOTH sides expose a ``.dtype``,
    it is checked too: live states hold f32 MASTER params (the
    mixed-precision contract, ``train/state.py::ensure_f32_masters``),
    so an artifact whose checkpoint drifted to bf16 (or f64) fails here
    with the leaf path named, before any compile or restore — not as a
    silent widening inside the overlay.
    """
    treedef = jax.tree_util.tree_structure(live_params)
    new_def = jax.tree_util.tree_structure(incoming)
    if treedef != new_def:
        want = _leaf_paths(live_params)
        got = _leaf_paths(incoming)
        missing = sorted(set(want) - set(got))
        unexpected = sorted(set(got) - set(want))
        details = []
        if missing:
            head = ", ".join(missing[:3])
            more = f" (+{len(missing) - 3} more)" if len(missing) > 3 else ""
            details.append(f"missing from the incoming tree: {head}{more}")
        if unexpected:
            head = ", ".join(unexpected[:3])
            more = (
                f" (+{len(unexpected) - 3} more)"
                if len(unexpected) > 3 else ""
            )
            details.append(f"unexpected in the incoming tree: {head}{more}")
        if not details:
            # Same leaf-path SET but different structure (e.g. a list
            # where a tuple lives): the treedefs are all there is to show.
            details.append(f"incoming {new_def} vs live {treedef}")
        raise ValueError(
            "warm-start params tree structure does not match the live "
            f"state's — different model/config? {'; '.join(details)}"
        )
    want_leaves, _ = jax.tree_util.tree_flatten_with_path(live_params)
    got_leaves, _ = jax.tree_util.tree_flatten_with_path(incoming)
    for (path, got), (_, want) in zip(got_leaves, want_leaves):
        if tuple(got.shape) != tuple(want.shape):
            raise ValueError(
                f"warm-start params leaf "
                f"{jax.tree_util.keystr(path)} has shape "
                f"{tuple(got.shape)} but the live state's is "
                f"{tuple(want.shape)} — different model/config?"
            )
        got_dt = getattr(got, "dtype", None)
        want_dt = getattr(want, "dtype", None)
        if got_dt is not None and want_dt is not None and got_dt != want_dt:
            raise ValueError(
                f"warm-start params leaf "
                f"{jax.tree_util.keystr(path)} has dtype {got_dt} but the "
                f"live state's is {want_dt} — checkpoints must stay f32 "
                "masters whatever the compute precision "
                "(tpuflow/train/precision.py)"
            )


def apply_params(state, params):
    """Overlay externally-sourced params onto a live TrainState — the
    warm-start half of resumability that needs no Orbax tree on disk.

    Two subsystems ride it: the elastic runner (tpuflow/elastic — a late
    joiner adopts the gang's latest published average, every synced
    worker adopts each round's rebroadcast) and the online loop
    (tpuflow/online — each retrain resumes from the SERVING artifact's
    params). Structure is checked leaf-for-leaf against the live state:
    overlaying a differently-shaped model must fail loudly, never
    mis-assign weights — and because a mismatched warm start is the
    online loop's most likely user-facing failure (a stale artifact, a
    changed model_kwargs), the error names the first mismatching leaf
    PATHS, not just the opaque treedefs. Optimizer state and step
    counter are deliberately kept — SparkNet-style averaging replaces
    the *parameters* mid-trajectory, not the trajectory's bookkeeping.
    """
    check_params_match(state.params, params)
    return state.replace(params=params)


class RunCheckpointer:
    """Full-run state checkpoints under ``{storage_path}/runs/{name}``.

    Distinct from ``BestCheckpointer`` (best *params* by val_loss, the
    deployment artifact — reference cnn.py:122 contract): this one is the
    fault-tolerance artifact, keeping the latest few full states.
    """

    def __init__(
        self,
        storage_path: str,
        name: str = "model",
        keep: int = 2,
        async_save: bool = True,
    ):
        self.directory = join_path(storage_path, "runs", name)
        self._async = async_save
        self._mngr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=keep, enable_async_checkpointing=async_save
            ),
        )

    def save(self, epoch: int, state: Any, loop: dict) -> None:
        """Checkpoint the TrainState's arrays plus loop metadata.

        ``loop`` must be JSON-serializable (epoch, early-stop counters,
        best val loss, ...). ``apply_fn``/``tx`` are code, not state — they
        are reconstructed by the caller on restore. With async_save the
        write overlaps the next epoch's compute; read paths wait.
        """
        tree = {"params": state.params, "opt_state": state.opt_state,
                "step": state.step}

        def _save():
            # Shared ``checkpoint.save`` fault site + transient-I/O retry
            # (Orbax's atomic commit makes a retried save safe). As in
            # BestCheckpointer.maybe_save: sync saves are fully covered;
            # async saves cover the enqueue, and a background-write
            # failure surfaces at the next wait with the previous
            # checkpoint still intact.
            fault_point("checkpoint.save", index=epoch)
            self._mngr.save(
                epoch,
                args=ocp.args.Composite(
                    state=ocp.args.StandardSave(tree),
                    loop=ocp.args.JsonSave(loop),
                ),
            )

        retry_call(io_policy(), _save)
        if not self._async:
            self._mngr.wait_until_finished()

    @property
    def latest_epoch(self) -> int | None:
        self._mngr.wait_until_finished()
        return self._mngr.latest_step()

    def restore(self, state_template: Any) -> tuple[Any, dict] | None:
        """Restore the latest checkpoint into a freshly-built TrainState.

        Returns (state, loop_metadata), or None if no checkpoint exists.
        """
        self._mngr.wait_until_finished()
        epoch = self._mngr.latest_step()
        if epoch is None:
            return None
        tree = {
            "params": state_template.params,
            "opt_state": state_template.opt_state,
            "step": state_template.step,
        }

        def _restore_with(target_tree):
            abstract = jax.tree_util.tree_map(
                ocp.utils.to_shape_dtype_struct, target_tree
            )

            def _restore():
                fault_point("checkpoint.restore", index=epoch)
                return self._mngr.restore(
                    epoch,
                    args=ocp.args.Composite(
                        state=ocp.args.StandardRestore(abstract),
                        loop=ocp.args.JsonRestore(),
                    ),
                )

            return retry_call(io_policy(), _restore)

        try:
            out = _restore_with(tree)
            opt_state = out["state"]["opt_state"]
        except (ValueError, KeyError, TypeError) as primary:
            # Pre-LR-scale checkpoint compat: wrap_optimizer now always
            # installs the with_lr_scale leaf, so a checkpoint written
            # before that change carries the UNWRAPPED opt_state
            # structure. Retry the restore against the inner template
            # and rewrap with the template's fresh scale (1.0 — an old
            # run never touched it). If the legacy attempt ALSO fails,
            # the checkpoint's problem was never the wrapper — re-raise
            # the PRIMARY error (a corrupt new-format checkpoint must
            # report its own corruption, not the fallback's structure
            # complaint).
            from tpuflow.train.optim import LrScaleState

            if not isinstance(state_template.opt_state, LrScaleState):
                raise
            try:
                out = _restore_with(
                    dict(tree, opt_state=state_template.opt_state.inner)
                )
            except Exception:
                raise primary from None
            opt_state = LrScaleState(
                inner=out["state"]["opt_state"],
                lr_scale=state_template.opt_state.lr_scale,
            )
        state = state_template.replace(
            params=out["state"]["params"],
            opt_state=opt_state,
            step=out["state"]["step"],
        )
        return state, dict(out["loop"])

    def close(self) -> None:
        self._mngr.close()
