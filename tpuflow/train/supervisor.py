"""Failure-detecting training supervisor: detect crashes, restart, resume.

SURVEY.md §5.3: the reference's fault-tolerance story is Spark task retry
at the cluster layer (reference Readme.md:3) — a worker dies, the
scheduler notices and reruns the task. ``RunCheckpointer`` +
``resume=True`` (tpuflow/train/resume.py) give tpuflow the deterministic
state half of that story; this module adds the *detection and restart*
half: the training job runs in a child process, the supervisor watches
its exit status AND its liveness, and any abnormal death (segfault, OOM
kill, TPU-backend crash, preemption) is answered by relaunching the job
with ``resume=True`` so it continues from the latest full-state
checkpoint. Together they are the TPU-native equivalent of Spark's retry
loop — hardened three ways beyond rerun-on-death:

- **Restart backoff.** Attempts are separated by exponential backoff
  with jitter (``backoff_base``/``backoff_max``/``backoff_jitter``): a
  dying dependency gets time to recover instead of a restart storm.
- **Crash-loop classification.** The child writes a progress file after
  every completed epoch (``FitConfig.progress_path``; the supervisor
  injects the path). When ``crash_loop_threshold`` consecutive attempts
  die at the SAME progress epoch, the failure is deterministic — a bug,
  not bad luck — and the supervisor aborts early with
  :class:`CrashLoopError` naming the epoch, instead of burning the
  remaining restarts on a foregone conclusion.
- **Stall watchdog.** ``stall_timeout`` bounds the time between progress
  updates (not the whole attempt): a child making steady progress can
  run for hours, while one wedged inside an epoch — a hung collective, a
  dead storage backend — is killed and restarted. The whole-attempt
  ``timeout`` cannot make that distinction; both remain available.

Fault drills (tpuflow/resilience): spec-armed faults (``"faults": [...]``
in the job spec) run on the FIRST attempt only — the supervisor drops
them (and ``fault_epoch``) from restart specs, so one injection means
one failure and the recovery runs clean. Faults armed via the
``TPUFLOW_FAULTS`` environment variable are inherited by every child
attempt — the deterministic-crash simulation the crash-loop classifier
is drilled with.

The job is described by the same JSON spec the job-runner service accepts
(``tpuflow.serve.spec_to_config`` — camelCase or snake_case fields), so a
spec can move between ``POST /jobs`` and ``supervise()`` unchanged. The
spec must set ``storagePath`` and ``save_every >= 1``; without run
checkpoints a "restart" would silently start over, which the supervisor
refuses to do.

Run from a shell::

    python -m tpuflow.train.supervisor spec.json --max-restarts 3 \
        --stall-timeout 900

or from Python::

    result = supervise({"model": "lstm", "epochs": 40, "save_every": 1,
                        "storagePath": "/data/artifacts"})
    result.report["epochs_ran"], result.attempts
"""

from __future__ import annotations

import json
import os
import random
import subprocess
import sys
import tempfile
import time
from dataclasses import dataclass, field

from tpuflow.obs.health import NumericsDivergence
from tpuflow.resilience.retry import RetryPolicy
from tpuflow.storage import read_json, write_json

# The child's exit code when the numerics watchdog aborts a diverging
# run (policy="abort"). A dedicated code because the parent must CLASSIFY
# it: a diverged optimizer replays deterministically from the checkpoint,
# so restart-backoff would burn the whole budget re-diverging — the
# supervisor raises NumericsDivergence immediately instead (terminal,
# like CrashLoopError but without needing N deaths to prove itself).
NUMERICS_EXIT_CODE = 86


class CrashLoopError(RuntimeError):
    """The same epoch died ``threshold`` consecutive times: the failure
    is deterministic, restarts cannot fix it. ``epoch`` is the last
    completed epoch at each death (None = died before the first)."""

    def __init__(self, message: str, epoch: int | None, failures: list):
        super().__init__(message)
        self.epoch = epoch
        self.failures = failures


@dataclass
class SupervisedRun:
    """Outcome of a supervised job: the final report plus the crash log."""

    report: dict
    attempts: int  # total child launches (1 = no failures)
    # {rc, stderr_tail, kind: crash|stall|timeout, progress_epoch}
    failures: list[dict] = field(default_factory=list)
    backoffs: list[float] = field(default_factory=list)  # restart delays


def _validate(spec: dict) -> None:
    storage = spec.get("storagePath") or spec.get("storage_path")
    if not storage:
        raise ValueError(
            "supervise() needs storagePath in the spec — without run "
            "checkpoints a restart would silently lose all progress"
        )
    if int(spec.get("save_every", 0)) < 1:
        raise ValueError(
            "supervise() needs save_every >= 1 in the spec — restart "
            "recovery resumes from the periodic full-state checkpoints"
        )
    # Fail-fast preflight (spec pass only): a malformed job must die at
    # submission in THIS process, not after a child launch + jax startup
    # per restart attempt — a deterministic spec error would otherwise
    # burn the whole restart budget before surfacing. The spec pass
    # touches no accelerator state, so the supervisor parent stays off
    # the chip; plan/shape run inside the child's own train() preflight.
    from tpuflow.analysis import ensure_preflight
    from tpuflow.serve import spec_to_config

    ensure_preflight(spec_to_config(spec), passes=("spec",))


def _read_progress(path: str):
    """The child's last progress record, or None (no epoch completed /
    torn write — the write side is atomic, so torn means 'not yet')."""
    try:
        with open(path, encoding="utf-8") as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


def _tail(text: str, n: int = 5) -> str:
    return "\n".join((text or "").strip().splitlines()[-n:])


def terminate_gracefully(proc, term_grace: float) -> str:
    """SIGTERM, a bounded grace period, then SIGKILL.

    An immediate SIGKILL would deny a stalled-but-salvageable child its
    exit path — the forensics ring dump, Orbax's async-checkpoint
    commit, the elastic goodbye heartbeat all run on teardown. SIGTERM
    first gives Python's default handler (and any atexit/finally
    machinery) ``term_grace`` seconds to flush; only a child that
    ignores it gets the axe. Returns which signal actually ended it
    ("sigterm" | "sigkill"; "sigkill" directly when term_grace <= 0) so
    the failure record says whether teardown ran.

    Public: the runtime supervisor (tpuflow/runtime/) reuses this exact
    escalation for its process-backed services — one teardown contract
    for every child this codebase spawns.
    """
    if term_grace > 0:
        proc.terminate()
        try:
            proc.wait(timeout=term_grace)
            return "sigterm"
        except subprocess.TimeoutExpired:
            pass
    proc.kill()
    proc.wait()
    return "sigkill"


_terminate_gracefully = terminate_gracefully  # pre-rename internal alias


def _run_attempt(
    cmd: list[str],
    out_dir: str,
    progress_path: str,
    timeout: float | None,
    stall_timeout: float | None,
    poll_interval: float,
    term_grace: float = 5.0,
    child_env: dict | None = None,
) -> tuple[int | None, str, str, str | None]:
    """One child attempt under the watchdog.

    Returns ``(returncode, stderr_text, kind, killed_by)`` where kind is
    "" for a natural exit, "timeout" for the whole-attempt cap, "stall"
    for a progress watchdog kill; ``killed_by`` records which signal a
    watchdog kill took ("sigterm" after a graceful exit within
    ``term_grace`` seconds, "sigkill" for a child that ignored it; None
    for natural exits). Child stdout/stderr go to files (a pipe the
    supervisor isn't draining would block a chatty child at the 64KB
    buffer — the watchdog must never cause the hang it watches for).
    """
    stdout_path = os.path.join(out_dir, "stdout.log")
    stderr_path = os.path.join(out_dir, "stderr.log")
    start = time.monotonic()
    with open(stdout_path, "w") as out_f, open(stderr_path, "w") as err_f:
        proc = subprocess.Popen(
            cmd, stdout=out_f, stderr=err_f, cwd=os.getcwd(),
            env=child_env,
        )
        kind = ""
        killed_by = None
        if timeout is None and stall_timeout is None:
            # Nothing to watch for: block like subprocess.run would,
            # instead of spinning an hours-long training at
            # poll_interval.
            rc = proc.wait()
        else:
            # Stall clock: starts at launch (compile time counts — pick
            # a stall_timeout above the first-epoch compile) and resets
            # on every progress-file change, INCLUDING content inherited
            # from the previous attempt (we track change, not absolute
            # epoch). The file is only read when a stall watchdog is
            # armed.
            last_progress = _read_progress(progress_path)
            last_change = start
            while True:
                rc = proc.poll()
                if rc is not None:
                    break
                now = time.monotonic()
                if timeout is not None and now - start > timeout:
                    kind = "timeout"
                elif stall_timeout is not None:
                    cur = _read_progress(progress_path)
                    if cur != last_progress:
                        last_progress, last_change = cur, now
                    elif now - last_change > stall_timeout:
                        kind = "stall"
                if kind:
                    killed_by = _terminate_gracefully(proc, term_grace)
                    rc = None  # killed by the supervisor, not a child exit
                    break
                time.sleep(poll_interval)
    with open(stderr_path, encoding="utf-8") as f:
        stderr_text = f.read()
    return rc, stderr_text, kind, killed_by


def supervise(
    spec: dict,
    *,
    max_restarts: int = 3,
    timeout: float | None = None,
    stall_timeout: float | None = None,
    backoff_base: float = 1.0,
    backoff_max: float = 60.0,
    backoff_jitter: float = 0.25,
    backoff_seed: int | None = None,
    crash_loop_threshold: int = 3,
    poll_interval: float = 0.05,
    term_grace: float = 5.0,
    python: str = sys.executable,
    verbose: bool = True,
    sleep=time.sleep,
) -> SupervisedRun:
    """Run the training job described by ``spec``, restarting on crashes.

    Each attempt is a fresh child process; attempts after the first run
    with ``resume=True`` so they continue from the latest run checkpoint,
    after an exponential-backoff delay. Returns once an attempt exits
    cleanly. Raises :class:`CrashLoopError` when ``crash_loop_threshold``
    consecutive attempts die at the same progress epoch (deterministic
    failure — restarts are futile), :class:`NumericsDivergence` the
    moment a child exits with ``NUMERICS_EXIT_CODE`` (the numerics
    watchdog's abort — terminal on the first death, no restart churn),
    or ``RuntimeError`` after ``max_restarts`` restarts all die. ``stall_timeout`` kills an attempt
    whose progress file stops changing for that many seconds; ``timeout``
    caps the whole attempt. Watchdog kills are graceful: SIGTERM, then
    ``term_grace`` seconds for the child to flush (forensics, async
    checkpoint commits), then SIGKILL — the failure record's
    ``killed_by`` says which it took. ``sleep`` is injectable for tests.
    """
    _validate(spec)
    from tpuflow.obs import (
        current_trace_id,
        default_registry,
        dump_forensics,
        new_trace_id,
        record_event,
        trace_from_env,
    )
    from tpuflow.obs.tracing import TRACE_ENV

    # ONE trace for the whole supervised job, every attempt included: a
    # restart that minted a fresh run trace would orphan the pre-crash
    # spans from the recovery's — the one trail a crash investigation
    # needs stitched. Precedence: an already-bound trace (the online
    # loop supervising a retrain) > the validated TPUFLOW_TRACE_ID this
    # supervisor itself inherited > fresh. Children get it via the env,
    # the one channel that survives a process boundary; train() binds it
    # below any explicitly-bound trace, so every attempt's spans carry
    # the same id.
    job_trace = current_trace_id() or trace_from_env() or new_trace_id()
    child_env = {**os.environ, TRACE_ENV: job_trace}

    _reg = default_registry()
    _restarts = _reg.counter(
        "supervisor_restarts_total", "child attempts relaunched after death"
    )
    _crash_loops = _reg.counter(
        "supervisor_crash_loops_total",
        "runs aborted by crash-loop classification",
    )
    _numerics_aborts = _reg.counter(
        "supervisor_numerics_aborts_total",
        "runs classified terminal after a numerics-watchdog abort",
    )
    storage = spec.get("storagePath") or spec.get("storage_path")

    def _dump(reason: str) -> None:
        # Crash forensics next to the artifacts: the attempt trail
        # (deaths, kinds, progress epochs, backoffs) survives the
        # supervisor's TemporaryDirectory. A DISTINCT filename: each
        # crashed child's train() already dumped its own (richer) ring
        # to forensics.jsonl at the same storage path, and overwriting
        # it here would erase the child's last-moments trail at the
        # exact moment it's needed. Best-effort by contract.
        if storage:
            dump_forensics(
                os.path.join(storage, "forensics-supervisor.jsonl"),
                reason=reason,
            )

    failures: list[dict] = []
    backoffs: list[float] = []
    rng = random.Random(backoff_seed) if backoff_seed is not None else random
    backoff_policy = RetryPolicy(
        base_delay=backoff_base, max_delay=backoff_max,
        jitter=backoff_jitter,
    )
    with tempfile.TemporaryDirectory() as run_dir:
        # ONE progress file across attempts: crash-loop classification
        # compares the last-completed epoch at consecutive deaths, and a
        # resumed attempt that dies before completing anything must read
        # as "same epoch again", not "no progress file".
        progress_path = os.path.join(run_dir, "progress.json")
        # The fault-cursor sentinel: TPUFLOW_FAULTS_CURSOR=auto means
        # "persist env-fault firing state next to my progress file" —
        # resolved here because only the supervisor owns a run
        # directory. Opt-in on purpose: the crash-loop drills DEPEND on
        # an env fault re-firing in every attempt, so the default
        # (unset) keeps env faults stateless across restarts.
        if child_env.get("TPUFLOW_FAULTS_CURSOR") == "auto":
            child_env["TPUFLOW_FAULTS_CURSOR"] = os.path.join(
                run_dir, "faults-cursor.json"
            )
        for attempt in range(1, max_restarts + 2):
            attempt_spec = dict(spec)
            attempt_spec["progress_path"] = progress_path
            if attempt > 1:
                attempt_spec["resume"] = True
                # Spec-armed fault drills are one-shot by design: the
                # restart is the recovery, and it runs clean. Faults that
                # must persist across restarts (the deterministic-crash
                # simulation) go through TPUFLOW_FAULTS, which children
                # inherit from the environment.
                attempt_spec.pop("fault_epoch", None)
                attempt_spec.pop("faults", None)
            attempt_dir = os.path.join(run_dir, f"attempt{attempt}")
            os.makedirs(attempt_dir, exist_ok=True)
            spec_path = os.path.join(attempt_dir, "spec.json")
            out_path = os.path.join(attempt_dir, "report.json")
            # Atomic spec handoff through the storage seam: the child
            # must never race a half-written spec.
            write_json(spec_path, attempt_spec)
            rc, stderr_text, kind, killed_by = _run_attempt(
                [python, "-m", "tpuflow.train.supervisor",
                 "--child", spec_path, out_path],
                attempt_dir,
                progress_path,
                timeout,
                stall_timeout,
                poll_interval,
                term_grace,
                child_env=child_env,
            )
            if rc == 0:
                report = read_json(out_path)
                return SupervisedRun(
                    report=report, attempts=attempt, failures=failures,
                    backoffs=backoffs,
                )
            progress = _read_progress(progress_path)
            progress_epoch = progress["epoch"] if progress else None
            if rc == NUMERICS_EXIT_CODE:
                # The watchdog's abort is a CLASSIFICATION, not a crash:
                # the child examined its own numerics and declared the
                # run doomed. Terminal on the FIRST death — no
                # restart-backoff churn, no N-deaths crash-loop proof.
                _numerics_aborts.inc()
                record_event(
                    "supervisor_numerics_divergence", attempt=attempt,
                    progress_epoch=progress_epoch, trace_id=job_trace,
                )
                _dump(
                    f"numerics divergence at epoch {progress_epoch} "
                    "(watchdog abort; terminal)"
                )
                failures.append({
                    "rc": rc,
                    "kind": "numerics",
                    "killed_by": killed_by,
                    "stderr_tail": _tail(stderr_text),
                    "progress_epoch": progress_epoch,
                })
                err = NumericsDivergence(
                    "numerics watchdog aborted the run (policy=abort): "
                    "a diverged run replays deterministically — "
                    "restarting would burn the backoff budget "
                    "re-diverging; last stderr: "
                    f"{_tail(stderr_text)}",
                    epoch=progress_epoch,
                )
                # The attempt trail rides terminal classifications (as
                # on CrashLoopError / budget exhaustion), so callers
                # supervising many jobs keep the diagnostics.
                err.failures = failures
                raise err
            record_event(
                "supervisor_attempt_died", attempt=attempt, rc=rc,
                kind=kind or "crash", progress_epoch=progress_epoch,
                killed_by=killed_by, trace_id=job_trace,
            )
            failures.append({
                "rc": rc,
                "kind": kind or "crash",
                # Which signal a watchdog kill took: "sigterm" = the
                # child flushed its teardown within term_grace;
                # "sigkill" = it ignored the grace period. None for
                # natural exits.
                "killed_by": killed_by,
                "stderr_tail": (
                    "timed out" if kind == "timeout"
                    else f"stalled: no progress for {stall_timeout:g}s"
                    if kind == "stall"
                    else _tail(stderr_text)
                ),
                "progress_epoch": progress_epoch,
            })
            # Crash-loop: the SAME last-completed epoch at N consecutive
            # deaths means the failure replays deterministically; more
            # restarts only burn the budget. Classified and aborted with
            # a labeled reason instead.
            recent = failures[-crash_loop_threshold:]
            if (
                len(recent) == crash_loop_threshold
                and len({f["progress_epoch"] for f in recent}) == 1
                and all(f["kind"] == "crash" for f in recent)
            ):
                where = (
                    f"after epoch {progress_epoch}"
                    if progress_epoch is not None
                    else "before the first epoch completed"
                )
                _crash_loops.inc()
                _dump(
                    f"crash-loop classified at epoch {progress_epoch}"
                )
                raise CrashLoopError(
                    f"crash-loop: {crash_loop_threshold} consecutive "
                    f"attempts died {where} (deterministic failure — "
                    f"aborting instead of burning restarts); last "
                    f"stderr: {failures[-1]['stderr_tail']}",
                    progress_epoch,
                    failures,
                )
            if verbose:
                print(
                    f"supervisor: attempt {attempt} died "
                    f"rc={failures[-1]['rc']} "
                    f"kind={failures[-1]['kind']}; "
                    + (
                        "restarting with resume=True"
                        if attempt <= max_restarts
                        else "giving up"
                    ),
                    file=sys.stderr,
                )
            if attempt <= max_restarts:
                # The ONE backoff formula (resilience/retry.py): restart
                # delays and I/O retry delays share exponential growth +
                # proportional jitter by construction.
                delay = backoff_policy.delay(attempt, rng)
                backoffs.append(delay)
                _restarts.inc()
                sleep(delay)
    _dump(f"restart budget exhausted after {len(failures)} deaths")
    err = RuntimeError(
        f"job died {len(failures)} times (last rc="
        f"{failures[-1]['rc']}): {failures[-1]['stderr_tail']}"
    )
    # The attempt trail rides the exception (as on CrashLoopError):
    # callers that supervise many jobs (the elastic runner) keep the
    # per-attempt diagnostics even when the budget is exhausted.
    err.failures = failures
    raise err


def _child(spec_path: str, out_path: str) -> None:
    """One attempt: run train() from the spec, write the report JSON.

    A :class:`NumericsDivergence` escaping train() exits with the
    dedicated ``NUMERICS_EXIT_CODE`` so the parent can classify the
    death as terminal instead of restart-worthy — the message rides
    stderr like any other failure (the parent's ``stderr_tail``).
    """
    import signal

    # The graceful-kill contract (SIGTERM -> term_grace -> SIGKILL) is
    # only worth anything if SIGTERM actually runs teardown: Python's
    # DEFAULT disposition terminates with no finally/atexit, i.e. the
    # same data loss as SIGKILL. Raise SystemExit instead, so the
    # watchdog's SIGTERM drains checkpoint writes, dumps the forensics
    # ring, and sends the elastic goodbye heartbeat on the way out. A
    # child wedged inside C code never delivers the signal — that is
    # exactly what the SIGKILL after term_grace is for.
    signal.signal(signal.SIGTERM, lambda signum, frame: sys.exit(143))
    from tpuflow.api import train
    from tpuflow.serve import report_to_dict, spec_to_config

    spec = read_json(spec_path)
    config = spec_to_config(spec)
    try:
        report = train(config)
    except NumericsDivergence as e:
        print(f"NumericsDivergence: {e}", file=sys.stderr)
        sys.exit(NUMERICS_EXIT_CODE)
    # Atomic report publish: the parent reads this the instant rc==0.
    write_json(out_path, report_to_dict(report))


def main(argv: list[str] | None = None) -> None:
    argv = sys.argv[1:] if argv is None else argv
    if argv and argv[0] == "--child":
        _child(argv[1], argv[2])
        return
    import argparse

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("spec", help="JSON job-spec file (serve.py contract)")
    ap.add_argument("--max-restarts", type=int, default=3)
    ap.add_argument("--timeout", type=float, default=None,
                    help="per-attempt seconds (whole attempt)")
    ap.add_argument("--stall-timeout", type=float, default=None,
                    help="seconds without progress before an attempt is "
                    "killed as stalled (must exceed first-epoch compile)")
    ap.add_argument("--backoff-base", type=float, default=1.0,
                    help="first restart delay, seconds (doubles per "
                    "restart up to --backoff-max)")
    ap.add_argument("--backoff-max", type=float, default=60.0)
    ap.add_argument("--crash-loop-threshold", type=int, default=3,
                    help="same-epoch consecutive deaths before aborting "
                    "as a deterministic crash loop")
    ap.add_argument("--term-grace", type=float, default=5.0,
                    help="seconds between a watchdog's SIGTERM and the "
                    "SIGKILL for a child that ignores it (0 = immediate "
                    "SIGKILL)")
    args = ap.parse_args(argv)
    spec = read_json(args.spec)
    run = supervise(
        spec,
        max_restarts=args.max_restarts,
        timeout=args.timeout,
        stall_timeout=args.stall_timeout,
        backoff_base=args.backoff_base,
        backoff_max=args.backoff_max,
        crash_loop_threshold=args.crash_loop_threshold,
        term_grace=args.term_grace,
    )
    print(json.dumps({"attempts": run.attempts, **run.report}))


if __name__ == "__main__":
    main()
