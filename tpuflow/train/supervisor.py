"""Failure-detecting training supervisor: detect crashes, restart, resume.

SURVEY.md §5.3: the reference's fault-tolerance story is Spark task retry
at the cluster layer (reference Readme.md:3) — a worker dies, the
scheduler notices and reruns the task. ``RunCheckpointer`` +
``resume=True`` (tpuflow/train/resume.py) give tpuflow the deterministic
state half of that story; this module adds the *detection and restart*
half: the training job runs in a child process, the supervisor watches
its exit status, and any abnormal death (segfault, OOM kill, TPU-backend
crash, preemption) is answered by relaunching the job with
``resume=True`` so it continues from the latest full-state checkpoint.
Together they are the TPU-native equivalent of Spark's retry loop.

The job is described by the same JSON spec the job-runner service accepts
(``tpuflow.serve.spec_to_config`` — camelCase or snake_case fields), so a
spec can move between ``POST /jobs`` and ``supervise()`` unchanged. The
spec must set ``storagePath`` and ``save_every >= 1``; without run
checkpoints a "restart" would silently start over, which the supervisor
refuses to do.

Run from a shell::

    python -m tpuflow.train.supervisor spec.json --max-restarts 3

or from Python::

    result = supervise({"model": "lstm", "epochs": 40, "save_every": 1,
                        "storagePath": "/data/artifacts"})
    result.report["epochs_ran"], result.attempts
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
from dataclasses import dataclass, field


@dataclass
class SupervisedRun:
    """Outcome of a supervised job: the final report plus the crash log."""

    report: dict
    attempts: int  # total child launches (1 = no failures)
    failures: list[dict] = field(default_factory=list)  # {rc, stderr_tail}


def _validate(spec: dict) -> None:
    storage = spec.get("storagePath") or spec.get("storage_path")
    if not storage:
        raise ValueError(
            "supervise() needs storagePath in the spec — without run "
            "checkpoints a restart would silently lose all progress"
        )
    if int(spec.get("save_every", 0)) < 1:
        raise ValueError(
            "supervise() needs save_every >= 1 in the spec — restart "
            "recovery resumes from the periodic full-state checkpoints"
        )


def supervise(
    spec: dict,
    *,
    max_restarts: int = 3,
    timeout: float | None = None,
    python: str = sys.executable,
    verbose: bool = True,
) -> SupervisedRun:
    """Run the training job described by ``spec``, restarting on crashes.

    Each attempt is a fresh child process; attempts after the first run
    with ``resume=True`` so they continue from the latest run checkpoint.
    Returns once an attempt exits cleanly; raises ``RuntimeError`` after
    ``max_restarts`` restarts all die.
    """
    _validate(spec)
    failures: list[dict] = []
    for attempt in range(1, max_restarts + 2):
        attempt_spec = dict(spec)
        if attempt > 1:
            attempt_spec["resume"] = True
            # An injected fault is one-shot by construction (the resumed
            # run starts past it); leaving it in the spec is harmless but
            # dropping it keeps restart specs describing only real work.
            attempt_spec.pop("fault_epoch", None)
        with tempfile.TemporaryDirectory() as td:
            spec_path = os.path.join(td, "spec.json")
            out_path = os.path.join(td, "report.json")
            with open(spec_path, "w", encoding="utf-8") as f:
                json.dump(attempt_spec, f)
            try:
                proc = subprocess.run(
                    [python, "-m", "tpuflow.train.supervisor",
                     "--child", spec_path, out_path],
                    capture_output=True,
                    text=True,
                    timeout=timeout,
                    cwd=os.getcwd(),
                )
            except subprocess.TimeoutExpired:
                # A hang (e.g. a dead TPU relay) is a failure mode too —
                # subprocess.run killed the child; restart like a crash.
                failures.append({"rc": None, "stderr_tail": "timed out"})
                proc = None
            if proc is not None and proc.returncode == 0:
                with open(out_path, encoding="utf-8") as f:
                    report = json.load(f)
                return SupervisedRun(
                    report=report, attempts=attempt, failures=failures
                )
        if proc is not None:
            tail = "\n".join((proc.stderr or "").strip().splitlines()[-5:])
            failures.append({"rc": proc.returncode, "stderr_tail": tail})
        if verbose:
            print(
                f"supervisor: attempt {attempt} died "
                f"rc={failures[-1]['rc']}; "
                + (
                    "restarting with resume=True"
                    if attempt <= max_restarts
                    else "giving up"
                ),
                file=sys.stderr,
            )
    raise RuntimeError(
        f"job died {len(failures)} times (last rc="
        f"{failures[-1]['rc']}): {failures[-1]['stderr_tail']}"
    )


def _child(spec_path: str, out_path: str) -> None:
    """One attempt: run train() from the spec, write the report JSON."""
    from tpuflow.api import train
    from tpuflow.serve import report_to_dict, spec_to_config

    with open(spec_path, encoding="utf-8") as f:
        spec = json.load(f)
    config = spec_to_config(spec)
    report = train(config)
    rep = report_to_dict(report)
    with open(out_path, "w", encoding="utf-8") as f:
        json.dump(rep, f)


def main(argv: list[str] | None = None) -> None:
    argv = sys.argv[1:] if argv is None else argv
    if argv and argv[0] == "--child":
        _child(argv[1], argv[2])
        return
    import argparse

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("spec", help="JSON job-spec file (serve.py contract)")
    ap.add_argument("--max-restarts", type=int, default=3)
    ap.add_argument("--timeout", type=float, default=None,
                    help="per-attempt seconds")
    args = ap.parse_args(argv)
    with open(args.spec, encoding="utf-8") as f:
        spec = json.load(f)
    run = supervise(
        spec, max_restarts=args.max_restarts, timeout=args.timeout
    )
    print(json.dumps({"attempts": run.attempts, **run.report}))


if __name__ == "__main__":
    main()
