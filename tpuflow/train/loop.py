"""The fit loop: epochs, early stopping, save-best, timing, final report.

Behavioral parity with the reference's training driver (reference
cnn.py:121-134): up to 1000 epochs of minibatch SGD (batch 20), early
stopping on val_loss with patience 10, best-model checkpointing, wall-clock
timing around fit, and a final elapsed-time + test-loss report — minus its
[BUG]s (the Spark-DataFrame seam C14 and the py2 print C15) and plus
structured metrics (samples/sec/chip, grad norm).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable

import jax
import numpy as np

from tpuflow.core.losses import mae_clip
from tpuflow.data.pipeline import ArrayDataset, batches
from tpuflow.train.callbacks import EarlyStopping
from tpuflow.train.checkpoint import BestCheckpointer
from tpuflow.train.steps import make_eval_step, make_train_step


@dataclass
class FitConfig:
    # Reference defaults: cnn.py:121 (patience), cnn.py:128 (epochs, batch).
    max_epochs: int = 1000
    batch_size: int = 20
    patience: int = 10
    seed: int = 0
    loss: Callable = mae_clip
    storage_path: str | None = None  # enables save-best checkpointing
    model_name: str = "model"
    verbose: bool = True
    log_every: int = 1  # epochs between log lines


@dataclass
class FitResult:
    state: object
    history: list = field(default_factory=list)
    time_elapsed: float = 0.0
    test_loss: float | None = None
    test_mae: float | None = None
    best_val_loss: float = float("inf")
    epochs_ran: int = 0
    samples_per_sec: float = 0.0

    def report(self) -> str:
        """The reference's final report (cnn.py:133-134), working and extended."""
        lines = [
            f"Time elapsed: {self.time_elapsed:.2f}s",
            f"Testing set loss: {self.test_loss}",
            f"Throughput: {self.samples_per_sec:.0f} samples/sec/chip",
        ]
        return "\n".join(lines)


def fit(
    state,
    train_ds: ArrayDataset,
    val_ds: ArrayDataset,
    config: FitConfig = FitConfig(),
    train_step=None,
    eval_step=None,
) -> FitResult:
    """Train with early stopping and optional save-best checkpointing.

    ``train_step``/``eval_step`` may be injected (e.g. the data-parallel
    sharded steps from ``tpuflow.parallel``); defaults are the single-chip
    jitted steps.
    """
    train_step = train_step or make_train_step(config.loss)
    eval_step = eval_step or make_eval_step(config.loss)
    rng = jax.random.PRNGKey(config.seed)

    stopper = EarlyStopping(patience=config.patience)
    ckpt = (
        BestCheckpointer(config.storage_path, config.model_name)
        if config.storage_path
        else None
    )
    result = FitResult(state=state)
    samples_seen = 0
    t0 = time.time()

    for epoch in range(1, config.max_epochs + 1):
        te = time.time()
        train_losses = []
        for x, y in batches(
            train_ds, config.batch_size, seed=config.seed + epoch
        ):
            state, metrics = train_step(state, x, y, rng)
            train_losses.append(metrics["loss"])
            samples_seen += len(x)

        val = _eval_dataset(eval_step, state, val_ds, config.batch_size)
        train_loss = float(np.mean([float(l) for l in train_losses]))
        epoch_time = time.time() - te
        result.history.append(
            {"epoch": epoch, "loss": train_loss, "val_loss": val["loss"],
             "val_mae": val["mae"], "time": epoch_time}
        )
        if config.verbose and epoch % config.log_every == 0:
            print(
                f"Epoch {epoch}/{config.max_epochs} - {epoch_time:.2f}s"
                f" - loss: {train_loss:.4f} - val_loss: {val['loss']:.4f}"
            )

        if val["loss"] < result.best_val_loss:
            result.best_val_loss = val["loss"]
        should_stop = stopper.update(val["loss"])
        if ckpt is not None and stopper.improved:
            ckpt.maybe_save(epoch, state.params, val["loss"])
        result.epochs_ran = epoch
        if should_stop:
            break

    result.time_elapsed = time.time() - t0
    result.samples_per_sec = samples_seen / max(result.time_elapsed, 1e-9)
    result.state = state
    if ckpt is not None:
        ckpt.close()
    return result


def evaluate(state, ds: ArrayDataset, batch_size: int = 256, eval_step=None, loss=mae_clip):
    """Full-dataset eval: mean loss/MAE over fixed-size batches."""
    eval_step = eval_step or make_eval_step(loss)
    return _eval_dataset(eval_step, state, ds, batch_size)


def _eval_dataset(eval_step, state, ds: ArrayDataset, batch_size: int):
    loss_sum = mae_sum = count = 0.0
    for x, y in batches(ds, batch_size, seed=None, drop_remainder=False):
        # Pad the tail batch to the fixed shape (one XLA compile), mask the
        # pad rows out of the aggregation (exact dataset metrics).
        n = len(x)
        mask = np.ones(batch_size, dtype=np.float32)
        if n < batch_size:
            pad = batch_size - n
            x = np.concatenate([x, np.repeat(x[-1:], pad, axis=0)])
            y = np.concatenate([y, np.repeat(y[-1:], pad, axis=0)])
            mask[n:] = 0.0
        m = eval_step(state, x, y, mask)
        loss_sum += float(m["loss_sum"])
        mae_sum += float(m["mae_sum"])
        count += float(m["count"])
    return {"loss": loss_sum / count, "mae": mae_sum / count}
