"""The fit loop: epochs, early stopping, save-best, timing, final report.

Behavioral parity with the reference's training driver (reference
cnn.py:121-134): up to 1000 epochs of minibatch SGD (batch 20), early
stopping on val_loss with patience 10, best-model checkpointing, wall-clock
timing around fit, and a final elapsed-time + test-loss report — minus its
[BUG]s (the Spark-DataFrame seam C14 and the py2 print C15) and plus
structured metrics (samples/sec/chip, grad norm).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable

import jax
import numpy as np

from tpuflow.core.losses import mae_clip
from tpuflow.data.pipeline import ArrayDataset, batches
from tpuflow.resilience import fault_point
from tpuflow.train.callbacks import EarlyStopping
from tpuflow.train.checkpoint import make_checkpointer
from tpuflow.train.steps import make_eval_step, make_train_step


class TrainingInterrupted(RuntimeError):
    """Raised between epochs when ``FitConfig.stop_fn`` requests a stop.

    ``reason`` is the stop_fn's string ("cancelled", "timeout after 60s",
    ...). Checkpoints already written stay on disk (the fit loop's finally
    block drains async writes), so an interrupted job's partial artifact is
    durable — the job-runner uses this for cancellation and per-job
    timeouts (SURVEY.md §3.2's web-trigger layer, hardened).
    """

    def __init__(self, reason: str):
        super().__init__(reason)
        self.reason = reason


class StreamingSource:
    """Out-of-core train source for ``fit``: a factory of per-epoch batch
    iterators instead of in-memory arrays.

    ``factory(epoch)`` must yield ``(x, y)`` numpy batches of a fixed
    batch size (drop_remainder — one XLA shape for the run); each epoch
    gets a fresh pass so windowed-shuffle order differs per epoch. Memory
    stays bounded by the stream's chunk/shuffle buffers no matter the file
    size (the reference's cluster-resident-data story, Readme.md:3, done
    host-side).
    """

    def __init__(self, factory: Callable):
        self.factory = factory

    def epoch_batches(self, epoch: int):
        return self.factory(epoch)


@dataclass
class FitConfig:
    # Reference defaults: cnn.py:121 (patience), cnn.py:128 (epochs, batch).
    max_epochs: int = 1000
    batch_size: int = 20
    patience: int = 10
    seed: int = 0
    loss: Callable = mae_clip
    storage_path: str | None = None  # enables save-best checkpointing
    model_name: str = "model"
    verbose: bool = True
    log_every: int = 1  # epochs between log lines
    # Fault tolerance (SURVEY.md §5.3): full-state checkpoint cadence and
    # resume-from-latest. Requires storage_path.
    save_every: int = 0  # epochs between run-state checkpoints (0 = off)
    resume: bool = False
    # Observability: jax.profiler trace of the first profiled epoch.
    trace_dir: str | None = None
    # Host→device overlap: batches move to the device in a background
    # thread, ahead of the step that consumes them.
    prefetch: int = 2  # buffered batches (0 = synchronous feed)
    # Compile the whole epoch into one XLA program (lax.scan over batches).
    # Removes per-step Python dispatch — the throughput path for small
    # models at the reference's batch size of 20. Defaults to the
    # single-chip epoch program; inject fit(epoch_step=...) (e.g.
    # parallel.make_dp_epoch_step) for data-parallel epochs. Dropout
    # streams differ from the per-batch path (per-batch-index vs per-step
    # rng folding).
    jit_epoch: bool = False
    # Structured metrics: append per-epoch JSONL records here (SURVEY §5.5).
    metrics_path: str | None = None
    # Fault injection (SURVEY §5.3): simulate a preemption by killing the
    # PROCESS (os._exit — no Python cleanup, like the real thing) right
    # after this epoch's bookkeeping. A resumed run never re-fires it
    # (arming requires resume=False), so one injection means one
    # preemption however the retry is driven. Now a thin alias over the
    # resilience fault registry: fit() arms it as an exit fault at the
    # ``train.epoch_end`` site (tpuflow/resilience/faults.py), the same
    # machinery every TPUFLOW_FAULTS / config.faults drill rides.
    fault_epoch: int | None = None
    # ckpt_async: background (async) checkpoint writes, the default.
    # False = synchronous saves: every process completes the write (and
    # any cross-process Orbax barrier) INSIDE the epoch — required for
    # multi-process fault drills, where an async save's barrier racing
    # an asymmetric fault can wedge the coordination service (see
    # tests/mp_worker.py), and a legitimate choice when save latency
    # matters less than determinism.
    ckpt_async: bool = True
    # fault_hard: exit WITHOUT committing in-flight async checkpoint
    # writes — the truthful preemption (the tail write may be lost;
    # Orbax's atomic commit surfaces the previous checkpoint). The soft
    # default commits first so single-process resume tests are
    # epoch-deterministic; hard is REQUIRED for multi-process fault
    # tests, where the commit's cross-process barrier would deadlock
    # against surviving processes stuck in a training collective.
    fault_hard: bool = False
    # Cooperative cancellation/timeout: called at the top of every epoch;
    # a non-None string stops the run by raising
    # ``TrainingInterrupted(reason)``. Between-epoch granularity: a single
    # enormous epoch (or a long XLA compile) is not interruptible — the
    # job-runner documents the same.
    stop_fn: Callable[[], str | None] | None = None
    # Liveness: overwrite this file with {"epoch": N, "time": ...} after
    # every completed epoch. The supervisor's stall watchdog reads it —
    # a child whose progress file stops changing is killed and restarted,
    # which a whole-attempt timeout cannot distinguish from slow-but-
    # alive. Best-effort: a failing write logs once and never kills
    # training.
    progress_path: str | None = None
    # Numerics watchdog (tpuflow/obs/health.py): per-epoch host-side
    # NaN/Inf + EWMA-spike checks over the loss/grad_norm aux the steps
    # already return, read POST-epoch (never per-step inside the scanned
    # body — TPF006). "warn" | "halve_lr" | "abort"; None/"off" disables.
    health: str | None = "warn"
    # Fleet identity for crash artifacts (an elastic worker id like
    # "w0"): forensics dumps under a SHARED storage root are suffixed
    # with it so sibling processes never clobber each other's trail
    # (tpuflow/obs/forensics.py::forensics_path). None = plain run.
    run_identity: str | None = None
    # Live roofline context: {"flops_per_sample", "bytes_per_sample",
    # "n_chips"} for the model being trained (tpuflow/utils/roofline.py
    # model_cost_per_sample), plus optional "compute_dtype" ("f32" |
    # "bf16") so the MFU verdict is judged against the right peak. When
    # set, every epoch publishes train_mfu / train_hbm_util /
    # train_bound gauges and a "roofline" JSONL record.
    roofline: dict | None = None
    # Mixed-precision compute dtype (tpuflow/train/precision.py): when
    # set, the DEFAULT train/eval/epoch steps cast the batch at step
    # entry and keep loss/grad aux f32. Injected steps own their own
    # precision (the model's dtype knob still applies either way).
    compute_dtype: object = None
    # Recompile detection: wrap the step fns in a data-arg signature
    # check; steady-state signature churn (recompiles after the first
    # epoch) is surfaced as xla.compile spans, the train_recompiles
    # gauge, and a diagnostic in FitResult.recompiles.
    detect_recompiles: bool = True
    # Elastic parameter-sync hook (tpuflow/elastic): called with
    # (epoch, state) after each epoch's bookkeeping — BEFORE the
    # run-state checkpoint, so a checkpoint captures the post-averaging
    # state and a restarted worker resumes already synced. Returns the
    # state to continue with (the worker client swaps in the gang's
    # averaged params on sync rounds).
    sync_fn: Callable | None = None
    # Online occupancy autotuner (tpuflow/train/autotune.py): a
    # constructed OccupancyAutotuner, or None. Post-epoch (NumericsWatchdog
    # mold) it hill-climbs microbatch size / remat / epoch program from
    # measured throughput under a recompile budget; the loop applies its
    # moves between epochs. Requires the DEFAULT single-chip steps
    # (injected train/epoch steps, batch sharding, and streaming sources
    # are rejected loudly) and detect_recompiles=True (the budget is
    # charged through the detector).
    autotune: object | None = None


@dataclass
class FitResult:
    state: object
    history: list = field(default_factory=list)
    time_elapsed: float = 0.0
    test_loss: float | None = None
    test_mae: float | None = None
    best_val_loss: float = float("inf")
    epochs_ran: int = 0
    samples_per_sec: float = 0.0
    # Health monitor outcomes (tpuflow/obs/health.py): the watchdog's
    # anomaly trail ({"epoch","kind","value"} dicts; empty = healthy)
    # and the recompile detector's summary (None = no recompiles).
    anomalies: list = field(default_factory=list)
    recompiles: dict | None = None
    # Occupancy-autotuner summary (train/autotune.py; None = not tuned):
    # start/best points, freeze state, recompiles charged, and the
    # decision trail.
    autotune: dict | None = None

    def report(self) -> str:
        """The reference's final report (cnn.py:133-134), working and extended."""
        lines = [
            f"Time elapsed: {self.time_elapsed:.2f}s",
            f"Testing set loss: {self.test_loss}",
            f"Throughput: {self.samples_per_sec:.0f} samples/sec/chip",
        ]
        return "\n".join(lines)


def fit(
    state,
    train_ds: ArrayDataset,
    val_ds: ArrayDataset,
    config: FitConfig = FitConfig(),
    train_step=None,
    eval_step=None,
    batch_sharding=None,
    epoch_step=None,
) -> FitResult:
    """Train with early stopping and optional save-best checkpointing.

    ``train_step``/``eval_step`` may be injected (e.g. the data-parallel
    sharded steps from ``tpuflow.parallel``); defaults are the single-chip
    jitted steps. ``batch_sharding`` (a ``NamedSharding``) makes the
    prefetcher land batches pre-sharded over the mesh instead of on the
    default device — pass ``data_sharding(mesh)`` alongside DP steps.
    ``epoch_step`` (with ``config.jit_epoch``) injects a whole-epoch
    scanned program — e.g. ``parallel.make_dp_epoch_step`` so DP runs get
    the same K-steps-per-dispatch path as single-chip ``jit_epoch``.
    """
    if config.jit_epoch and epoch_step is None and (
        train_step is not None or batch_sharding is not None
    ):
        raise ValueError(
            "jit_epoch's default epoch program is single-chip and would "
            "silently ignore the injected train_step/batch_sharding; inject "
            "epoch_step (parallel.make_dp_epoch_step) for data-parallel runs"
        )
    if config.jit_epoch and isinstance(train_ds, StreamingSource):
        raise ValueError(
            "jit_epoch stacks the whole epoch into device arrays and would "
            "defeat the bounded-memory stream; use per-batch stepping for "
            "streaming runs"
        )
    if (config.resume or config.save_every) and not config.storage_path:
        raise ValueError(
            "resume/save_every need storage_path — without it no run "
            "checkpoints exist and a 'resumed' run would silently restart"
        )
    tuner = config.autotune
    if tuner is not None:
        if (
            train_step is not None or eval_step is not None
            or epoch_step is not None or batch_sharding is not None
        ):
            raise ValueError(
                "autotune drives the DEFAULT single-chip steps; injected "
                "train/eval/epoch steps or batch sharding would be "
                "silently swapped out mid-run — tune those paths offline"
            )
        if isinstance(train_ds, StreamingSource):
            raise ValueError(
                "autotune resizes the microbatch between epochs; a "
                "streaming source bakes its batch size into the stream "
                "(tune streaming jobs offline)"
            )
        if not config.detect_recompiles:
            raise ValueError(
                "autotune charges its moves against the RecompileDetector;"
                " detect_recompiles=False would leave the budget blind"
            )
    _start_remat = bool(tuner.current.remat) if tuner is not None else False
    train_step = train_step or make_train_step(
        config.loss, compute_dtype=config.compute_dtype,
        remat=_start_remat,
    )
    eval_step = eval_step or make_eval_step(
        config.loss, compute_dtype=config.compute_dtype
    )
    rng = jax.random.PRNGKey(config.seed)

    stopper = EarlyStopping(patience=config.patience)
    ckpt = (
        make_checkpointer(
            config.storage_path, config.model_name,
            async_save=config.ckpt_async,
        )
        if config.storage_path
        else None
    )
    run_ckpt = None
    start_epoch = 1
    result = FitResult(state=state)
    if config.storage_path and (config.save_every or config.resume):
        from tpuflow.train.resume import RunCheckpointer

        run_ckpt = RunCheckpointer(
            config.storage_path, config.model_name,
            async_save=config.ckpt_async,
        )
        if config.resume:
            restored = run_ckpt.restore(state)
            if restored is not None:
                state, loop_meta = restored
                start_epoch = int(loop_meta["epoch"]) + 1
                stopper.best = float(loop_meta["stopper_best"])
                stopper.bad_epochs = int(loop_meta["stopper_bad_epochs"])
                result.best_val_loss = float(loop_meta["best_val_loss"])
                if config.verbose:
                    print(f"Resuming from epoch {loop_meta['epoch']}")
    samples_seen = 0
    samples_counted = 0  # high-water mark already added to the registry
    # Monotonic, not wall-clock: the run's elapsed/throughput numbers
    # must survive an NTP step mid-run (TPF015 — durations never come
    # from time.time() deltas).
    t0 = time.monotonic()

    use_scan = bool(config.jit_epoch)
    if use_scan:
        if epoch_step is None:
            from tpuflow.train.steps import make_epoch_step

            epoch_step = make_epoch_step(
                config.loss, compute_dtype=config.compute_dtype,
                remat=_start_remat,
            )
    else:
        epoch_step = None

    mlog = None
    if config.metrics_path:
        from tpuflow.utils.logging import MetricsLogger

        mlog = MetricsLogger(config.metrics_path)

    # Registry-backed throughput signals (process-wide; tpuflow/obs) +
    # span events for where each epoch's time went. Recording happens
    # OUTSIDE the jitted step/epoch programs — values observed are
    # already host floats (TPF005's contract).
    from tpuflow.obs import (
        NumericsWatchdog,
        RecompileDetector,
        default_registry,
        install_compile_listener,
        publish_roofline,
        record_span,
    )
    from tpuflow.obs.health import HEALTH_OFF

    _reg = default_registry()
    _epochs_total = _reg.counter(
        "train_epochs_total", "training epochs completed"
    )
    _samples_total = _reg.counter(
        "train_samples_total", "training samples consumed"
    )
    _epoch_seconds = _reg.histogram(
        "train_epoch_seconds", "wall-clock per completed epoch"
    )

    # --- the health monitor (tpuflow/obs/health.py) ---
    watchdog = None
    if config.health not in HEALTH_OFF:
        watchdog = NumericsWatchdog(
            config.health,
            storage_path=config.storage_path,
            model_name=config.model_name,
            logger=mlog,
            verbose=config.verbose,
            dump_identity=config.run_identity,
        )
    detector = None
    if config.detect_recompiles:
        install_compile_listener()  # process-wide count, best-effort
        detector = RecompileDetector(logger=mlog)
        # Variant-aware names: a run that STARTS remat (a resumed tuned
        # point) must not share a signature set with the remat-off
        # variant _live_step builds later — a shared name would swallow
        # that variant's first compile (seen-signature fast path) and
        # leak the armed expect() tag onto a later unrelated recompile.
        _sfx = "@remat" if _start_remat else ""
        train_step = detector.wrap(train_step, f"train_step{_sfx}")
        eval_step = detector.wrap(eval_step, "eval_step")
        epoch_step = detector.wrap(epoch_step, f"epoch_step{_sfx}")
    # --- the occupancy autotuner's live knobs (train/autotune.py) ---
    # live_batch is the microbatch the TRAIN loop uses this epoch (eval
    # keeps config.batch_size — one fixed eval shape for the run);
    # use_scan picks the epoch program. Both move only when the tuner
    # hands back a decision, applied between epochs.
    live_batch = config.batch_size
    _step_cache: dict = {}
    if tuner is not None:
        tuner.bind(detector=detector, registry=_reg, logger=mlog)
        _step_cache[("train", _start_remat)] = train_step
        if epoch_step is not None:
            _step_cache[("epoch", _start_remat)] = epoch_step

    def _live_step(kind: str, remat: bool):
        """Detector-wrapped step variants for the tuner's moves,
        memoized by (kind, remat): revisiting a variant reuses the same
        wrapped callable, so jit serves the cached executable and a
        revert costs zero recompiles. Variants built mid-run are
        wrapped with count_first=True — their first compile is a
        recompile OF THE RUN, charged against the budget and visible as
        an xla.compile span (building the variant here is lazy: jit
        compiles nothing until the first call)."""
        key = (kind, remat)
        if key not in _step_cache:
            from tpuflow.train.steps import make_epoch_step

            factory = (
                make_train_step if kind == "train" else make_epoch_step
            )
            fn = factory(
                config.loss, compute_dtype=config.compute_dtype,
                remat=remat,
            )
            if detector is not None:
                suffix = "@remat" if remat else ""
                fn = detector.wrap(
                    fn, f"{kind}_step{suffix}", count_first=True
                )
            _step_cache[key] = fn
        return _step_cache[key]
    # Live MFU context: the chip this run dispatches to (roofline peaks
    # are keyed by device_kind; "cpu" reports honestly as unknown).
    if config.roofline:
        from tpuflow.parallel.placement import device_kind

        _device_kind = device_kind()
    else:
        _device_kind = None

    # The legacy fault_epoch knob, re-expressed as a registry drill: an
    # exit fault at the train.epoch_end site. Soft (default) commits
    # in-flight async checkpoint writes first so single-process resume
    # drills are epoch-deterministic; fault_hard skips the commit — the
    # truthful preemption (see the FitConfig comments). Never armed on a
    # resumed run (the recovery is not the victim), and armed LAST,
    # immediately before the try whose finally disarms it: a setup
    # failure in between would leak a process-global exit fault into a
    # later job in the same process.
    armed_faults = []
    if config.fault_epoch is not None and not config.resume:
        from tpuflow.resilience import FaultSpec, arm

        def _commit_before_exit():
            if not config.fault_hard:
                if run_ckpt is not None:
                    run_ckpt.close()
                if ckpt is not None:
                    ckpt.close()

        armed_faults.append(
            arm(
                FaultSpec(
                    site="train.epoch_end",
                    at=config.fault_epoch,
                    mode="exit",
                    code=42,
                    on_fire=_commit_before_exit,
                )
            )
        )
    try:
        for epoch in range(start_epoch, config.max_epochs + 1):
            if config.stop_fn is not None:
                reason = config.stop_fn()
                if reason:
                    raise TrainingInterrupted(reason)
            # Before any work: a crash armed here REPLAYS this epoch
            # after resume — the deterministic same-epoch crash-loop the
            # supervisor classifies (vs train.epoch_end, whose crash is
            # survived by this epoch's checkpoint).
            fault_point("train.epoch_start", index=epoch)
            if detector is not None:
                detector.epoch = epoch
            te = time.monotonic()
            tracing = config.trace_dir is not None and epoch == start_epoch
            if tracing:
                jax.profiler.start_trace(config.trace_dir)

            if use_scan:
                # Whole epoch in one compiled call (scan over batches).
                xs, ys = _stacked_epoch(
                    train_ds, live_batch, config.seed + epoch
                )
                state, epoch_loss = epoch_step(
                    state, xs, ys, jax.random.fold_in(rng, epoch)
                )
                train_loss = float(epoch_loss)
                epoch_losses_host = [train_loss]
                epoch_grads_host = []  # the scanned program returns no aux
                samples_seen += xs.shape[0] * xs.shape[1]
                last_device_value = epoch_loss
            else:
                train_losses = []
                grad_norms = []
                if isinstance(train_ds, StreamingSource):
                    epoch_batches = train_ds.epoch_batches(epoch)
                else:
                    epoch_batches = batches(
                        train_ds, live_batch, seed=config.seed + epoch
                    )
                if config.prefetch:
                    from tpuflow.data.prefetch import device_prefetch

                    epoch_batches = device_prefetch(
                        epoch_batches,
                        buffer_size=config.prefetch,
                        sharding=batch_sharding,
                    )
                for x, y in epoch_batches:
                    # Device references only inside the batch loop: a
                    # float() here would sync the device once per step
                    # and serialize the dispatch pipeline — host
                    # conversion happens ONCE, post-epoch (TPF006).
                    state, metrics = train_step(state, x, y, rng)
                    train_losses.append(metrics["loss"])
                    g = metrics.get("grad_norm")
                    if g is not None:
                        grad_norms.append(g)
                    samples_seen += len(x)
                if not train_losses:
                    if tracing:  # don't leave the profiler trace open
                        jax.profiler.stop_trace()
                    raise ValueError(
                        f"epoch {epoch} yielded zero batch_size="
                        f"{live_batch} batches — training would be a "
                        "silent no-op reporting NaN loss (dataset/stream split "
                        "smaller than one batch?)"
                    )
                epoch_losses_host = [float(l) for l in train_losses]
                epoch_grads_host = [float(g) for g in grad_norms]
                train_loss = float(np.mean(epoch_losses_host))
                last_device_value = train_losses[-1]
            if tracing:
                # device_get: block_until_ready is not a reliable sync
                # point on the relay backend (benchmarks/common.py::drain).
                jax.device_get(last_device_value)
                jax.profiler.stop_trace()
            if watchdog is not None:
                # Post-epoch, host floats only — and strictly AFTER the
                # profiler stop above: an abort raised mid-trace would
                # leak the open trace into the next fit() in this
                # process. abort raises the typed NumericsDivergence out
                # of the loop (the finally still drains checkpoints);
                # halve_lr hands back a state whose optimizer LR-scale
                # leaf was halved — same pytree structure, no recompile.
                state = watchdog.observe_epoch(
                    epoch, epoch_losses_host, epoch_grads_host, state
                )
                result.anomalies = watchdog.anomalies

            # The "step" span: this epoch's training phase (all batches),
            # measured before validation starts — with the eval span
            # below it answers "train or eval?" for a slow epoch. The
            # same duration feeds the live-MFU math below: the roofline
            # divides TRAIN samples, so it must divide train time, not
            # train+eval (an inflated denominator would understate MFU
            # against the bench.py numbers it is documented to match).
            train_time = time.monotonic() - te
            record_span("step", train_time, logger=mlog, epoch=epoch)
            t_eval = time.perf_counter()
            val = _eval_dataset(eval_step, state, val_ds, config.batch_size)
            record_span(
                "eval", time.perf_counter() - t_eval, logger=mlog,
                epoch=epoch,
            )
            epoch_time = time.monotonic() - te
            result.history.append(
                {"epoch": epoch, "loss": train_loss, "val_loss": val["loss"],
                 "val_mae": val["mae"], "time": epoch_time}
            )
            if mlog is not None:
                rec = dict(result.history[-1])
                # 'time' would shadow the logger's wall-clock timestamp field.
                rec["epoch_time"] = rec.pop("time")
                mlog.write("epoch", model=config.model_name, **rec)
            if config.verbose and epoch % config.log_every == 0:
                print(
                    f"Epoch {epoch}/{config.max_epochs} - {epoch_time:.2f}s"
                    f" - loss: {train_loss:.4f} - val_loss: {val['loss']:.4f}"
                )

            if val["loss"] < result.best_val_loss:
                result.best_val_loss = val["loss"]
            should_stop = stopper.update(val["loss"])
            if ckpt is not None and stopper.improved:
                t_ckpt = time.perf_counter()
                ckpt.maybe_save(epoch, state.params, val["loss"])
                record_span(
                    "checkpoint", time.perf_counter() - t_ckpt,
                    logger=mlog, epoch=epoch, kind="best",
                )
            if config.sync_fn is not None:
                # Elastic averaging round (tpuflow/elastic): push local
                # params, adopt the gang average. Before the run-state
                # save below, so checkpoints hold the synced state.
                t_sync = time.perf_counter()
                state = config.sync_fn(epoch, state)
                record_span(
                    "elastic.sync", time.perf_counter() - t_sync,
                    logger=mlog, epoch=epoch,
                )
            if (
                run_ckpt is not None
                and config.save_every
                and epoch % config.save_every == 0
            ):
                t_ckpt = time.perf_counter()
                run_ckpt.save(
                    epoch,
                    state,
                    {
                        "epoch": epoch,
                        "stopper_best": stopper.best,
                        "stopper_bad_epochs": stopper.bad_epochs,
                        "best_val_loss": result.best_val_loss,
                    },
                )
                record_span(
                    "checkpoint", time.perf_counter() - t_ckpt,
                    logger=mlog, epoch=epoch, kind="run_state",
                )
            result.epochs_ran = epoch
            _epochs_total.inc()
            epoch_samples = samples_seen - samples_counted
            if config.roofline:
                # Live MFU: this epoch's measured samples/sec/chip
                # against the model's FLOPs/bytes cost — the roofline
                # math bench.py runs offline, published mid-run through
                # the registry (GET /metrics?format=prometheus) and the
                # run's metrics JSONL.
                publish_roofline(
                    epoch_samples
                    / max(train_time, 1e-9)
                    / max(int(config.roofline.get("n_chips", 1)), 1),
                    config.roofline["flops_per_sample"],
                    config.roofline["bytes_per_sample"],
                    _device_kind,
                    compute_dtype=config.roofline.get("compute_dtype"),
                    logger=mlog,
                    epoch=epoch,
                )
            # Per-epoch delta, not a bulk add at fit end: a scrape
            # mid-run must see live throughput, and a crashed run must
            # still have counted the samples it consumed.
            _samples_total.inc(epoch_samples)
            samples_counted = samples_seen
            _epoch_seconds.observe(epoch_time)
            if tuner is not None:
                # One controller step per epoch, AFTER the roofline
                # publish (the tuner reads the gauges this epoch just
                # set) and strictly host-side: samples and train_time
                # are already host floats. A returned point is applied
                # before the next epoch begins.
                decision = tuner.observe_epoch(
                    epoch, samples=epoch_samples, train_time=train_time
                )
                if decision is not None:
                    live_batch = decision.batch_size
                    use_scan = decision.jit_epoch
                    train_step = _live_step("train", decision.remat)
                    if use_scan:
                        epoch_step = _live_step("epoch", decision.remat)
            if config.progress_path:
                _write_progress(config.progress_path, epoch)
            # The legacy fault_epoch fires here (armed above as an exit
            # spec); env/spec drills at this site ride the same call.
            fault_point("train.epoch_end", index=epoch)
            if should_stop:
                break

        result.time_elapsed = time.monotonic() - t0
        result.samples_per_sec = samples_seen / max(result.time_elapsed, 1e-9)
        result.state = state
        if detector is not None:
            # Steady state starts after the run's first epoch: its
            # compiles are the price of admission; recompiles beyond it
            # are shape churn (the run-summary diagnostic).
            result.recompiles = detector.summary(steady_after=start_epoch)
        if tuner is not None:
            tuner.finalize(result.epochs_ran)
            result.autotune = tuner.summary()
        if mlog is not None:
            mlog.write(
                "fit_done",
                model=config.model_name,
                epochs=result.epochs_ran,
                best_val_loss=result.best_val_loss,
                time_elapsed=result.time_elapsed,
                samples_per_sec=result.samples_per_sec,
            )
    finally:
        # Always drain + commit in-flight ASYNC checkpoint writes —
        # an exception mid-epoch must not lose a save the loop
        # already reported (close() waits before releasing).
        if ckpt is not None:
            ckpt.close()
        if run_ckpt is not None:
            run_ckpt.close()
        if mlog is not None:
            mlog.close()
        # An unfired fault_epoch spec (early stop before the fault, or a
        # max_epochs below it) must not leak into a later fit() in this
        # process.
        if armed_faults:
            from tpuflow.resilience import disarm

            for spec in armed_faults:
                disarm(spec)
    return result


def _write_progress(path: str, epoch: int, **extra) -> None:
    """Overwrite the liveness file with this epoch's progress record —
    atomically (tmp + rename), so the supervisor's watchdog never reads
    a torn write. Best-effort: progress is observability, and an
    unwritable progress file must not kill a healthy training run.
    ``extra`` fields ride along (the elastic sync wait pings liveness
    through THIS writer — one owner of the record the supervisor
    parses); ``epoch`` must stay the last COMPLETED epoch."""
    from tpuflow.utils.paths import atomic_write_json

    try:
        atomic_write_json(
            path, {"epoch": epoch, "time": time.time(), **extra}
        )
    except OSError as e:
        import sys

        print(
            f"tpuflow.train: progress write to {path!r} failed "
            f"({type(e).__name__}: {e}); continuing without liveness",
            file=sys.stderr,
        )


def _stacked_epoch(ds: ArrayDataset, batch_size: int, seed: int):
    """Shuffle + drop-remainder + stack into [n_batches, B, ...] arrays —
    the same batch composition as ``batches(..., seed)``, shaped for the
    jitted epoch scan."""
    order = np.random.default_rng(seed).permutation(ds.n)
    nb = ds.n // batch_size
    if nb == 0:
        raise ValueError(
            f"jit_epoch: dataset of {ds.n} rows yields zero "
            f"batch_size={batch_size} batches — the epoch scan would train "
            "on nothing and report NaN loss"
        )
    idx = order[: nb * batch_size].reshape(nb, batch_size)
    return ds.x[idx], ds.y[idx]


def evaluate(state, ds: ArrayDataset, batch_size: int = 256, eval_step=None, loss=mae_clip):
    """Full-dataset eval: mean loss/MAE over fixed-size batches."""
    eval_step = eval_step or make_eval_step(loss)
    return _eval_dataset(eval_step, state, ds, batch_size)


def _eval_dataset(eval_step, state, ds: ArrayDataset, batch_size: int):
    loss_sum = mae_sum = count = 0.0
    for x, y in batches(ds, batch_size, seed=None, drop_remainder=False):
        # Pad the tail batch to the fixed shape (one XLA compile), mask the
        # pad rows out of the aggregation (exact dataset metrics).
        n = len(x)
        mask = np.ones(batch_size, dtype=np.float32)
        if n < batch_size:
            pad = batch_size - n
            x = np.concatenate([x, np.repeat(x[-1:], pad, axis=0)])
            y = np.concatenate([y, np.repeat(y[-1:], pad, axis=0)])
            mask[n:] = 0.0
        m = eval_step(state, x, y, mask)
        loss_sum += float(m["loss_sum"])
        mae_sum += float(m["mae_sum"])
        count += float(m["count"])
    return {"loss": loss_sum / count, "mae": mae_sum / count}
