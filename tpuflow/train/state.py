"""Train state construction."""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax
from flax.training import train_state

from tpuflow.train.optim import keras_sgd


def create_state(
    model: nn.Module,
    rng: jax.Array,
    sample_x: jnp.ndarray,
    tx: optax.GradientTransformation | None = None,
) -> train_state.TrainState:
    """Initialize params from a sample batch and wrap them in a TrainState."""
    params = model.init(rng, jnp.asarray(sample_x))["params"]
    return train_state.TrainState.create(
        apply_fn=model.apply, params=params, tx=tx or keras_sgd()
    )
