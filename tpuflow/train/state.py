"""Train state construction."""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax
from flax.training import train_state

from tpuflow.train.optim import keras_sgd


def ensure_f32_masters(params):
    """Cast any floating leaf to float32 — the MASTER-weights contract
    of the mixed-precision policy (tpuflow/train/precision.py).

    Flax keeps ``param_dtype`` f32 even when a model computes in bf16,
    so this is normally a no-op; it exists so the contract is enforced
    at the one place states are born rather than assumed: whatever a
    model's initializers did, checkpoints, serving artifacts, warm
    starts, and the optimizer update all see f32 leaves.
    """
    from tpuflow.train.precision import cast_floating

    return cast_floating(params, jnp.float32)


def create_state(
    model: nn.Module,
    rng: jax.Array,
    sample_x: jnp.ndarray,
    tx: optax.GradientTransformation | None = None,
) -> train_state.TrainState:
    """Initialize params from a sample batch and wrap them in a TrainState.

    Params are forced to f32 masters regardless of the model's compute
    dtype (``ensure_f32_masters``): the optimizer accumulates in f32 and
    every artifact consumer reads f32, whatever precision the train
    steps run at.
    """
    params = ensure_f32_masters(
        model.init(rng, jnp.asarray(sample_x))["params"]
    )
    return train_state.TrainState.create(
        apply_fn=model.apply, params=params, tx=tx or keras_sgd()
    )
