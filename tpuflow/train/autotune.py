"""Epoch-program auto-selection: measured sweep data over static defaults.

The fit loop has two epoch programs (tpuflow/train/loop.py): per-batch
stepping (one XLA dispatch per minibatch) and ``jit_epoch`` (the whole
epoch scanned into one compiled program). Which one is faster is a
per-backend measurement, not a guess: on the relay-attached TPU a single
dispatch costs ~700us of round-trip, so the scanned program wins at
EVERY batch measured (round 5, transfer-drained timing: 9.36M samples/s
scanned vs 1.47M per-batch at B=1024 — round 3's contrary 17.7M
per-batch reading was a sync artifact of ``block_until_ready`` on the
relay backend, see BENCHLOG.md). On other backends the ordering can
differ, so ``train(config)`` resolves ``jit_epoch=None`` ("auto")
through :func:`choose_epoch_program` from recorded sweeps instead of a
static default.

The decision source, in order:

1. **Constraints** — streaming ingest, tensor parallelism, and multi-host
   runs require per-batch stepping (the scanned program would defeat
   bounded-memory streaming / isn't wired for the TP GSPMD step).
2. **Measured sweep** — ``benchmarks/sweep_epoch_program.py`` races both
   programs over a batch-size grid on the CURRENT backend and records
   the crossover to ``benchmarks/program_sweep.json``; when that file
   exists and matches the running device kind, its crossover decides.
   (Override the location with ``TPUFLOW_PROGRAM_SWEEP``.)
3. **Heuristic fallback** — no measurement for this device: scan the
   epoch when ``batch_size < 256`` (the dispatch-bound regime on every
   backend measured so far), step per-batch otherwise.

The choice is reported on ``TrainReport.epoch_program`` so a job's
program is observable, and tested by ``tests/test_autotune.py``.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass

# Batch sizes below this are dispatch-bound: the scanned epoch program
# wins. The default is the unmeasured-device fallback; a measured sweep
# (benchmarks/sweep_epoch_program.py) replaces it per device kind.
HEURISTIC_CROSSOVER_BATCH = 256


@dataclass(frozen=True)
class ProgramChoice:
    """The resolved epoch program and why it was chosen."""

    jit_epoch: bool
    reason: str
    # "constraint" | "measured" | "heuristic" from choose_epoch_program;
    # "explicit" when train() honors a caller-set jit_epoch instead.
    source: str

    @property
    def name(self) -> str:
        return "jit_epoch" if self.jit_epoch else "per_batch"


def _sweep_path() -> str:
    env = os.environ.get("TPUFLOW_PROGRAM_SWEEP")
    if env:
        return env
    # Repo-relative default: tpuflow/train/autotune.py -> repo root.
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)
    )))
    return os.path.join(root, "benchmarks", "program_sweep.json")


def load_measured_crossover(
    device_kind: str, compute_dtype: str | None = None
) -> tuple[float, str] | None:
    """The measured crossover batch for ``device_kind`` (and, when
    given, ``compute_dtype`` — the precision token "f32"/"bf16"), if a
    matching sweep has been recorded; ``(crossover, source_desc)``.
    ``inf`` means the sweep measured the scanned program faster at every
    batch (``scan_always``).

    Dtype matching: sweeps are keyed ``"<device_kind>@<dtype>"`` (the
    exact match, tried first) or plain ``"<device_kind>"`` whose record
    carries a ``compute_dtype`` field — a crossover measured under one
    compute dtype must never silently decide runs under another (the
    HBM working set halves under bf16, which is what moves the knee).
    A plain record WITHOUT the field matches any request (pre-policy
    files), and ``compute_dtype=None`` requests match any record.
    """
    path = _sweep_path()
    try:
        with open(path, encoding="utf-8") as f:
            sweep = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    if not isinstance(sweep, dict):
        return None
    candidates = []
    if compute_dtype:
        candidates.append((f"{device_kind}@{compute_dtype}", True))
    candidates.append((device_kind, False))
    for key, exact in candidates:
        rec = sweep.get(key)
        if not isinstance(rec, dict):
            continue
        if not exact and compute_dtype:
            recorded = rec.get("compute_dtype")
            if recorded is not None and recorded != compute_dtype:
                continue
        if rec.get("scan_always") is True:
            return float("inf"), f"{path} [{key}]"
        crossover = rec.get("crossover_batch")
        if not isinstance(crossover, (int, float)) or crossover <= 0:
            continue
        return float(crossover), f"{path} [{key}]"
    return None


def choose_epoch_program(
    batch_size: int,
    *,
    stream: bool = False,
    tp: int = 1,
    pp: int = 1,
    ep: int = 1,
    multi_host: bool = False,
    device_kind: str | None = None,
    compute_dtype: str | None = None,
) -> ProgramChoice:
    """Resolve ``jit_epoch=None`` ("auto") for one training job."""
    if stream:
        return ProgramChoice(
            False, "streaming ingest requires per-batch stepping "
            "(bounded memory)", "constraint",
        )
    if tp > 1:
        return ProgramChoice(
            False, "tensor parallelism trains through the per-batch "
            "GSPMD step", "constraint",
        )
    if pp > 1:
        return ProgramChoice(
            False, "pipeline parallelism trains through the per-batch "
            "GPipe step", "constraint",
        )
    if ep > 1:
        return ProgramChoice(
            False, "expert parallelism trains through the per-batch "
            "routed step", "constraint",
        )
    if multi_host:
        # The multi-host scanned path exists (fit(epoch_step=...)), but
        # auto never picks a program that depends on every host slicing
        # identically — explicit jit_epoch=True opts in.
        return ProgramChoice(
            False, "multi-host runs default to per-batch stepping; pass "
            "jit_epoch=True to opt in to the scanned program",
            "constraint",
        )
    if device_kind is None:
        import jax

        from tpuflow.parallel.placement import (
            device_kind as _placed_kind,
        )

        device_kind = _placed_kind(default=jax.default_backend())
    measured = load_measured_crossover(device_kind, compute_dtype)
    dtype_tag = f" [{compute_dtype}]" if compute_dtype else ""
    if measured is not None:
        crossover, source = measured
        jit = batch_size < crossover
        if crossover == float("inf"):
            desc = (
                f"scanned program measured faster at every swept batch "
                f"on {device_kind!r}{dtype_tag}"
            )
        else:
            desc = (
                f"batch_size {batch_size} {'<' if jit else '>='} measured "
                f"crossover {int(crossover)} for {device_kind!r}{dtype_tag}"
            )
        return ProgramChoice(jit, desc, "measured")
    jit = batch_size < HEURISTIC_CROSSOVER_BATCH
    return ProgramChoice(
        jit,
        f"batch_size {batch_size} {'<' if jit else '>='} heuristic "
        f"crossover {HEURISTIC_CROSSOVER_BATCH} (no sweep recorded for "
        f"{device_kind!r}{dtype_tag}; run "
        "benchmarks/sweep_epoch_program.py)",
        "heuristic",
    )
