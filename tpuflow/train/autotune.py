"""Occupancy autotuning: offline program selection + the online tuner.

Two layers share this module:

**Offline prior** — the fit loop has two epoch programs
(tpuflow/train/loop.py): per-batch stepping (one XLA dispatch per
minibatch) and ``jit_epoch`` (the whole epoch scanned into one compiled
program). Which one is faster is a per-backend measurement, not a
guess: on the relay-attached TPU a single dispatch costs ~700us of
round-trip, so the scanned program wins at EVERY batch measured (round
5, transfer-drained timing: 9.36M samples/s scanned vs 1.47M per-batch
at B=1024 — round 3's contrary 17.7M per-batch reading was a sync
artifact of ``block_until_ready`` on the relay backend, see
BENCHLOG.md). ``train(config)`` resolves ``jit_epoch=None`` ("auto")
through :func:`choose_epoch_program` from recorded sweeps
(``benchmarks/program_sweep.json``) with constraint and heuristic
fallbacks; the choice is reported on ``TrainReport.epoch_program``.

**Online controller** — :class:`OccupancyAutotuner` closes the loop
*during* a run (ROADMAP item 2): a post-epoch, host-side controller in
the NumericsWatchdog mold that reads each epoch's wall-time/throughput
plus the live ``train_mfu``/``train_hbm_util``/``train_bound`` gauges,
and hill-climbs the knobs that move them — microbatch size (a pow-2
ladder around the starting batch), remat on/off (``jax.checkpoint`` on
the step's apply — trade recompute FLOPs for HBM residency), and the
scan-vs-per-batch epoch program. Every move is a known XLA recompile,
charged against an explicit **recompile budget** through the
RecompileDetector (``tpuflow/obs/health.py``); when the budget is
spent the tuner FREEZES on the best-seen configuration — it converges
instead of churning compiles. Adoption requires a hysteresis margin so
noisy gauges never flip-flop the config, a regressing move is reverted
(reverts revisit already-compiled programs, so they cost zero
recompiles), and the winning point is persisted next to the serving
sidecar (``{storage}/meta/{model}.autotune.json``, keyed by
``device_kind@precision`` — bf16 and f32 runs tune independently) so
warm-started and supervised-restart runs resume tuned. The offline
measured crossover above is the controller's *prior* (it seeds the
starting program), not its verdict.

Configured by the spec-validated ``TrainJobConfig.autotune`` block
(CLI ``--autotune``; every knob has a ``TPUFLOW_AUTOTUNE_*`` env
spelling validated through ``tpuflow/utils/env.py``). Tested by
``tests/test_autotune.py``.
"""

from __future__ import annotations

import json
import os
import statistics
from dataclasses import dataclass

# Batch sizes below this are dispatch-bound: the scanned epoch program
# wins. The default is the unmeasured-device fallback; a measured sweep
# (benchmarks/sweep_epoch_program.py) replaces it per device kind.
HEURISTIC_CROSSOVER_BATCH = 256


@dataclass(frozen=True)
class ProgramChoice:
    """The resolved epoch program and why it was chosen."""

    jit_epoch: bool
    reason: str
    # "constraint" | "measured" | "heuristic" from choose_epoch_program;
    # "explicit" when train() honors a caller-set jit_epoch instead.
    source: str

    @property
    def name(self) -> str:
        return "jit_epoch" if self.jit_epoch else "per_batch"


def _sweep_path() -> str:
    env = os.environ.get("TPUFLOW_PROGRAM_SWEEP")
    if env:
        return env
    # Repo-relative default: tpuflow/train/autotune.py -> repo root.
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)
    )))
    return os.path.join(root, "benchmarks", "program_sweep.json")


def load_measured_crossover(
    device_kind: str, compute_dtype: str | None = None
) -> tuple[float, str] | None:
    """The measured crossover batch for ``device_kind`` (and, when
    given, ``compute_dtype`` — the precision token "f32"/"bf16"), if a
    matching sweep has been recorded; ``(crossover, source_desc)``.
    ``inf`` means the sweep measured the scanned program faster at every
    batch (``scan_always``).

    Dtype matching: sweeps are keyed ``"<device_kind>@<dtype>"`` (the
    exact match, tried first) or plain ``"<device_kind>"`` whose record
    carries a ``compute_dtype`` field — a crossover measured under one
    compute dtype must never silently decide runs under another (the
    HBM working set halves under bf16, which is what moves the knee).
    A plain record WITHOUT the field matches any request (pre-policy
    files), and ``compute_dtype=None`` requests match any record.
    """
    from tpuflow.storage import read_json

    path = _sweep_path()
    try:
        sweep = read_json(path)
    except (OSError, ValueError):
        return None
    if not isinstance(sweep, dict):
        return None
    candidates = []
    if compute_dtype:
        candidates.append((f"{device_kind}@{compute_dtype}", True))
    candidates.append((device_kind, False))
    for key, exact in candidates:
        rec = sweep.get(key)
        if not isinstance(rec, dict):
            continue
        if not exact and compute_dtype:
            recorded = rec.get("compute_dtype")
            if recorded is not None and recorded != compute_dtype:
                continue
        if rec.get("scan_always") is True:
            return float("inf"), f"{path} [{key}]"
        crossover = rec.get("crossover_batch")
        if not isinstance(crossover, (int, float)) or crossover <= 0:
            continue
        return float(crossover), f"{path} [{key}]"
    return None


def choose_epoch_program(
    batch_size: int,
    *,
    stream: bool = False,
    tp: int = 1,
    pp: int = 1,
    ep: int = 1,
    multi_host: bool = False,
    device_kind: str | None = None,
    compute_dtype: str | None = None,
) -> ProgramChoice:
    """Resolve ``jit_epoch=None`` ("auto") for one training job."""
    if stream:
        return ProgramChoice(
            False, "streaming ingest requires per-batch stepping "
            "(bounded memory)", "constraint",
        )
    if tp > 1:
        return ProgramChoice(
            False, "tensor parallelism trains through the per-batch "
            "GSPMD step", "constraint",
        )
    if pp > 1:
        return ProgramChoice(
            False, "pipeline parallelism trains through the per-batch "
            "GPipe step", "constraint",
        )
    if ep > 1:
        return ProgramChoice(
            False, "expert parallelism trains through the per-batch "
            "routed step", "constraint",
        )
    if multi_host:
        # The multi-host scanned path exists (fit(epoch_step=...)), but
        # auto never picks a program that depends on every host slicing
        # identically — explicit jit_epoch=True opts in.
        return ProgramChoice(
            False, "multi-host runs default to per-batch stepping; pass "
            "jit_epoch=True to opt in to the scanned program",
            "constraint",
        )
    if device_kind is None:
        import jax

        from tpuflow.parallel.placement import (
            device_kind as _placed_kind,
        )

        device_kind = _placed_kind(default=jax.default_backend())
    measured = load_measured_crossover(device_kind, compute_dtype)
    dtype_tag = f" [{compute_dtype}]" if compute_dtype else ""
    if measured is not None:
        crossover, source = measured
        jit = batch_size < crossover
        if crossover == float("inf"):
            desc = (
                f"scanned program measured faster at every swept batch "
                f"on {device_kind!r}{dtype_tag}"
            )
        else:
            desc = (
                f"batch_size {batch_size} {'<' if jit else '>='} measured "
                f"crossover {int(crossover)} for {device_kind!r}{dtype_tag}"
            )
        return ProgramChoice(jit, desc, "measured")
    jit = batch_size < HEURISTIC_CROSSOVER_BATCH
    return ProgramChoice(
        jit,
        f"batch_size {batch_size} {'<' if jit else '>='} heuristic "
        f"crossover {HEURISTIC_CROSSOVER_BATCH} (no sweep recorded for "
        f"{device_kind!r}{dtype_tag}; run "
        "benchmarks/sweep_epoch_program.py)",
        "heuristic",
    )


# --- the online occupancy autotuner --------------------------------------

# Per-knob defaults for the ``autotune`` config block. Kept import-light
# (no jax): the preflight spec pass validates blocks without touching a
# device. Every key has a ``TPUFLOW_AUTOTUNE_<KEY>`` env spelling that
# supplies the default when the block leaves it unset (the
# TPUFLOW_ELASTIC_* precedent); an explicit block value always wins.
AUTOTUNE_DEFAULTS: dict = {
    "interval": 1,          # epochs measured per config before a decision
    "warmup_epochs": 1,     # post-move epochs discarded (compile noise)
    "recompile_budget": 8,  # tuner-attributed recompiles before freeze
    "hysteresis": 0.05,     # relative throughput gain a move must clear
    "tune_batch": True,     # walk the pow-2 microbatch ladder
    "tune_remat": True,     # toggle remat (jax.checkpoint on the step)
    "tune_program": True,   # toggle scan-vs-per-batch epoch program
    "min_batch": 1,         # ladder floor (also clamped to n_devices)
    "max_batch": 4096,      # ladder ceiling (also clamped to n_train)
    "batch_ladder": 6,      # max pow-2 steps away from the start batch
    "persist": True,        # write the tuned point next to the sidecar
}

_AUTOTUNE_FLAG_KEYS = (
    "tune_batch", "tune_remat", "tune_program", "persist",
)
_AUTOTUNE_INT_KEYS = {
    # key -> minimum
    "interval": 1,
    "warmup_epochs": 0,
    "recompile_budget": 0,
    "min_batch": 1,
    "max_batch": 1,
    "batch_ladder": 0,
}


def validate_autotune_block(block) -> list[str]:
    """Every problem with an ``autotune`` config block, as messages
    (empty = valid). Never raises — the preflight spec pass reports all
    findings at once; :func:`resolve_autotune` turns them into the
    fail-loud raise for runtime callers."""
    if not isinstance(block, dict):
        return [
            f"autotune must be a dict config block (or {{}} for "
            f"defaults), got {type(block).__name__}"
        ]
    out = []
    unknown = sorted(set(block) - set(AUTOTUNE_DEFAULTS))
    if unknown:
        out.append(
            f"unknown autotune key(s) {unknown}; known: "
            f"{sorted(AUTOTUNE_DEFAULTS)}"
        )
    for key, minimum in _AUTOTUNE_INT_KEYS.items():
        if key not in block:
            continue
        value = block[key]
        if isinstance(value, bool) or not isinstance(value, int):
            out.append(
                f"autotune.{key} must be an integer >= {minimum}, got "
                f"{value!r}"
            )
        elif value < minimum:
            out.append(
                f"autotune.{key} must be >= {minimum}, got {value}"
            )
    if "hysteresis" in block:
        h = block["hysteresis"]
        if isinstance(h, bool) or not isinstance(h, (int, float)):
            out.append(
                f"autotune.hysteresis must be a number >= 0, got {h!r}"
            )
        elif not (0 <= float(h) < 1):
            out.append(
                f"autotune.hysteresis must be in [0, 1), got {h}"
            )
    for key in _AUTOTUNE_FLAG_KEYS:
        if key in block and not isinstance(block[key], bool):
            out.append(
                f"autotune.{key} must be a boolean, got {block[key]!r}"
            )
    lo = block.get("min_batch", AUTOTUNE_DEFAULTS["min_batch"])
    hi = block.get("max_batch", AUTOTUNE_DEFAULTS["max_batch"])
    if (
        isinstance(lo, int) and isinstance(hi, int)
        and not isinstance(lo, bool) and not isinstance(hi, bool)
        and lo > hi
    ):
        out.append(
            f"autotune.min_batch {lo} exceeds autotune.max_batch {hi}"
        )
    return out


def _env_knobs() -> dict:
    """The ``TPUFLOW_AUTOTUNE_*`` env family, validated at read time
    through tpuflow/utils/env.py (a malformed value raises naming the
    variable and the expected form). Returns only the keys the
    environment actually sets — spec-block values win over these."""
    from tpuflow.utils.env import env_flag, env_num

    out: dict = {}
    for key, minimum in _AUTOTUNE_INT_KEYS.items():
        var = f"TPUFLOW_AUTOTUNE_{key.upper()}"
        value = env_num(var, None, int, minimum=minimum)
        if value is not None:
            out[key] = int(value)
    hyst = env_num(
        "TPUFLOW_AUTOTUNE_HYSTERESIS", None, float, minimum=0,
        form="a number in [0, 1)",
    )
    if hyst is not None:
        if hyst >= 1:
            raise ValueError(
                f"invalid TPUFLOW_AUTOTUNE_HYSTERESIS={hyst!r}: "
                "expected a number in [0, 1)"
            )
        out["hysteresis"] = float(hyst)
    for key in _AUTOTUNE_FLAG_KEYS:
        var = f"TPUFLOW_AUTOTUNE_{key.upper()}"
        if os.environ.get(var, "").strip():
            out[key] = env_flag(var, AUTOTUNE_DEFAULTS[key])
    return out


def resolve_autotune(block: dict) -> dict:
    """One resolved knob dict: defaults <- env knobs <- explicit block.
    Raises ValueError naming every problem (the runtime spelling of
    :func:`validate_autotune_block`)."""
    problems = validate_autotune_block(block)
    if problems:
        raise ValueError(
            "invalid autotune config: " + "; ".join(problems)
        )
    resolved = {**AUTOTUNE_DEFAULTS, **_env_knobs(), **block}
    if resolved["min_batch"] > resolved["max_batch"]:
        raise ValueError(
            f"invalid autotune config: min_batch {resolved['min_batch']} "
            f"exceeds max_batch {resolved['max_batch']}"
        )
    return resolved


@dataclass(frozen=True)
class TuningPoint:
    """One point in the tuner's knob space."""

    batch_size: int
    remat: bool
    jit_epoch: bool

    @property
    def key(self) -> str:
        return (
            f"b{self.batch_size}"
            f"-{'remat' if self.remat else 'noremat'}"
            f"-{'scan' if self.jit_epoch else 'perbatch'}"
        )

    def to_dict(self) -> dict:
        return {
            "batch_size": self.batch_size,
            "remat": self.remat,
            "jit_epoch": self.jit_epoch,
        }


def tuned_config_path(storage_path: str, model_name: str) -> str:
    """The persisted tuned-config file, next to the serving sidecar."""
    from tpuflow.utils.paths import join_path

    return join_path(storage_path, "meta", f"{model_name}.autotune.json")


def load_tuned(
    storage_path: str, model_name: str, device_kind: str,
    compute_dtype: str,
) -> TuningPoint | None:
    """The persisted winning point for EXACTLY this device kind and
    compute dtype, if one was recorded — ``None`` otherwise. Exact-key
    only, no wildcard: a point tuned under bf16 halves the HBM working
    set and must never silently seed an f32 run (the
    ``program_sweep.json`` dtype discipline, PR 10)."""
    from tpuflow.utils.paths import open_file

    path = tuned_config_path(storage_path, model_name)
    try:
        with open_file(path, "r", encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    rec = doc.get(f"{device_kind}@{compute_dtype}") if isinstance(
        doc, dict
    ) else None
    if not isinstance(rec, dict):
        return None
    try:
        return TuningPoint(
            batch_size=int(rec["batch_size"]),
            remat=bool(rec["remat"]),
            jit_epoch=bool(rec["jit_epoch"]),
        )
    except (KeyError, TypeError, ValueError):
        return None


def save_tuned(
    storage_path: str, model_name: str, device_kind: str,
    compute_dtype: str, point: TuningPoint, *, throughput: float,
    frozen: bool, epoch: int,
) -> None:
    """Record the winning point under its ``device@dtype`` key (other
    keys preserved — a bf16 entry never clobbers the f32 one).
    Atomic write locally; URI storage (gs://, s3://) goes through
    ``open_file`` like the sidecar — object stores replace whole
    objects, which is the same no-torn-read guarantee the local
    tmp+rename gives. Best-effort is the CALLER's policy."""
    from tpuflow.utils.paths import atomic_write_json, is_uri, open_file

    path = tuned_config_path(storage_path, model_name)
    doc: dict = {}
    try:
        with open_file(path, "r", encoding="utf-8") as f:
            loaded = json.load(f)
        if isinstance(loaded, dict):
            doc = loaded
    except (OSError, json.JSONDecodeError):
        pass
    doc[f"{device_kind}@{compute_dtype}"] = {
        **point.to_dict(),
        "samples_per_sec": round(float(throughput), 3),
        "frozen": frozen,
        "epoch": epoch,
    }
    if is_uri(path):
        with open_file(path, "w", encoding="utf-8") as f:
            json.dump(doc, f)
    else:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        atomic_write_json(path, doc)


class OccupancyAutotuner:
    """Post-epoch hill-climb over (batch, remat, program) under a
    recompile budget.

    Strictly host-side and strictly post-epoch (the NumericsWatchdog
    mold): the fit loop calls :meth:`observe_epoch` once per epoch with
    the epoch's sample count and train wall-time — values it already
    holds as host floats — and applies the returned
    :class:`TuningPoint` (or None: stay) before the next epoch. The
    controller never touches a device.

    The state machine per decision:

    1. **Warmup** — ``warmup_epochs`` epochs after every move are
       discarded (the first one carries the move's XLA compile) and the
       move's actual recompile cost is charged from the
       RecompileDetector's event delta (floored at 1 — every move to an
       unseen point compiles by construction).
    2. **Measure** — ``interval`` epochs of samples/sec at the current
       point, reduced by median (one outlier epoch cannot fake a win).
    3. **Decide** — an explored neighbor is ADOPTED only if its median
       clears ``(1 + hysteresis) x`` the best-seen (no flip-flop on
       noisy gauges); otherwise it is REVERTED — back to the best-seen
       point, which is already compiled, so reverts are free. From the
       anchor, the next unvisited neighbor is explored: batch x2,
       batch /2 (pow-2 ladder, bounds- and divisibility-checked), remat
       toggle, program toggle. When the budget is spent (or no
       neighbors remain) the tuner FREEZES on the best-seen point: zero
       further moves, zero further recompiles.

    Every step is an ``autotune.step`` span (duration = the measured
    epoch's train time, so the tuner's timeline rides its own lane in
    ``obs timeline``) carrying the live MFU/HBM/bound gauge readings,
    and the ``train_autotune_*`` counters/gauges track the trajectory.
    """

    def __init__(
        self,
        cfg: dict,
        start: TuningPoint,
        *,
        n_train_rows: int,
        n_devices: int = 1,
        can_scan: bool = True,
        can_remat: bool = True,
        device_kind: str = "cpu",
        compute_dtype: str = "f32",
        storage_path: str | None = None,
        model_name: str = "model",
        prior: str | None = None,
        verbose: bool = True,
    ):
        self.cfg = {**AUTOTUNE_DEFAULTS, **cfg}
        self.n_train_rows = int(n_train_rows)
        self.n_devices = max(int(n_devices), 1)
        self.can_scan = can_scan
        self.can_remat = can_remat
        self.device_kind = device_kind
        self.compute_dtype = compute_dtype
        self.storage_path = storage_path
        self.model_name = model_name
        self.prior = prior
        self.verbose = verbose

        self.start = self._clamp(start)
        self.current = self.start
        self.best: TuningPoint = self.start
        self.best_sps: float | None = None
        self.measured: dict[TuningPoint, float] = {}
        self.frozen = False
        self.spent = 0
        self.reverts = 0
        self.trail: list[dict] = []
        self._window: list[float] = []
        self._cooldown = int(self.cfg["warmup_epochs"])
        self._await_charge = False
        self._detector_mark = 0
        self._persisted = False

        self._detector = None
        self._registry = None
        self._logger = None
        self._steps = None

    # --- wiring ---------------------------------------------------------

    def bind(self, *, detector=None, registry=None, logger=None) -> None:
        """Late wiring from inside fit(): the RecompileDetector the
        budget charges against, the registry the live gauges live in,
        and the run's metrics logger."""
        from tpuflow.obs.metrics import default_registry

        self._detector = detector
        self._registry = registry or default_registry()
        self._logger = logger
        reg = self._registry
        self._steps = reg.counter(
            "train_autotune_steps_total",
            "occupancy-autotuner decisions, by action",
        )
        self._recompiles_total = reg.counter(
            "train_autotune_recompiles_total",
            "XLA recompiles charged against the autotune budget",
        )
        self._reverts_total = reg.counter(
            "train_autotune_reverts_total",
            "autotuner moves reverted for missing the hysteresis bar",
        )
        self._freezes_total = reg.counter(
            "train_autotune_freezes_total",
            "autotuner freezes (budget spent or neighborhood exhausted)",
        )
        self._batch_gauge = reg.gauge(
            "train_autotune_batch_size",
            "microbatch size the autotuner is currently running",
        )
        self._frozen_gauge = reg.gauge(
            "train_autotune_frozen",
            "1 once the autotuner has frozen on its best-seen config",
        )
        self._budget_gauge = reg.gauge(
            "train_autotune_budget_remaining",
            "recompile budget the autotuner has left",
        )
        self._batch_gauge.set(float(self.current.batch_size))
        self._frozen_gauge.set(0.0)
        self._budget_gauge.set(float(self._budget_remaining()))
        if detector is not None:
            self._detector_mark = detector.count

    # --- geometry -------------------------------------------------------

    def _bounds(self) -> tuple[int, int]:
        lo = max(int(self.cfg["min_batch"]), self.n_devices)
        hi = min(int(self.cfg["max_batch"]), self.n_train_rows)
        return lo, max(hi, lo)

    def _clamp(self, point: TuningPoint) -> TuningPoint:
        lo, hi = self._bounds()
        b = min(max(point.batch_size, lo), hi)
        remat = point.remat and self.can_remat
        scan = point.jit_epoch and self.can_scan
        if (b, remat, scan) == (
            point.batch_size, point.remat, point.jit_epoch
        ):
            return point
        return TuningPoint(b, remat, scan)

    def _batch_ok(self, b: int) -> bool:
        lo, hi = self._bounds()
        if not (lo <= b <= hi) or b % self.n_devices:
            return False
        ladder = int(self.cfg["batch_ladder"])
        ref, steps = self.start.batch_size, 0
        big, small = max(b, ref), min(b, ref)
        while small < big:
            small *= 2
            steps += 1
        return small == big and steps <= ladder

    def _neighbors(self, point: TuningPoint) -> list[TuningPoint]:
        out = []
        if self.cfg["tune_batch"]:
            for b in (point.batch_size * 2, point.batch_size // 2):
                if b and self._batch_ok(b):
                    out.append(
                        TuningPoint(b, point.remat, point.jit_epoch)
                    )
        if self.cfg["tune_program"] and self.can_scan:
            out.append(TuningPoint(
                point.batch_size, point.remat, not point.jit_epoch
            ))
        if self.cfg["tune_remat"] and self.can_remat:
            out.append(TuningPoint(
                point.batch_size, not point.remat, point.jit_epoch
            ))
        return out

    def _propose(self) -> TuningPoint | None:
        for cand in self._neighbors(self.best):
            if cand not in self.measured and cand != self.current:
                return cand
        return None

    def _budget_remaining(self) -> int:
        return max(int(self.cfg["recompile_budget"]) - self.spent, 0)

    # --- the controller step -------------------------------------------

    def observe_epoch(
        self, epoch: int, *, samples: int, train_time: float
    ) -> TuningPoint | None:
        """One post-epoch controller step; returns the point to apply
        for the NEXT epoch when the tuner moves, None to stay."""
        sps = float(samples) / max(float(train_time), 1e-9)
        if self._await_charge:
            # The epoch just measured carried the move's compile(s):
            # charge the detector's event delta, floored at 1 — a move
            # to an unseen point compiles by construction even when the
            # detector cannot see it (a remat swap keeps data shapes).
            delta = 1
            if self._detector is not None:
                delta = max(self._detector.count - self._detector_mark, 1)
            self.spent += delta
            self._recompiles_total.inc(delta)
            self._budget_gauge.set(float(self._budget_remaining()))
            self._await_charge = False
        if self.frozen:
            self._record(epoch, "frozen", sps, train_time)
            return None
        if self._cooldown > 0:
            self._cooldown -= 1
            self._record(epoch, "warmup", sps, train_time)
            return None
        self._window.append(sps)
        if len(self._window) < int(self.cfg["interval"]):
            self._record(epoch, "measure", sps, train_time)
            return None
        med = statistics.median(self._window)
        self._window = []
        self.measured[self.current] = med

        if self.best_sps is None:
            self.best_sps = med
        elif self.current == self.best:
            # Re-measuring the anchor tracks drift: a regime change
            # lowers the bar neighbors must clear, so the climb resumes
            # from live truth rather than a stale record.
            self.best_sps = med
        elif med >= self.best_sps * (1.0 + float(self.cfg["hysteresis"])):
            self.best, self.best_sps = self.current, med
            self._record(epoch, "adopt", med, train_time)
            self._persist(epoch)
        else:
            # Missed the bar: revert to the best-seen point. Its
            # programs are already compiled (jit caches by signature),
            # so the move back is recompile-free.
            self.reverts += 1
            self._reverts_total.inc()
            self._record(epoch, "revert", med, train_time)
            self._event("autotune_revert", epoch=epoch,
                        from_config=self.current.key, to=self.best.key)
            return self._move(self.best, charge=False)

        if self._budget_remaining() <= 0:
            return self._freeze(epoch, "recompile budget spent")
        cand = self._propose()
        if cand is None:
            return self._freeze(epoch, "neighborhood exhausted")
        self._record(epoch, "explore", med, train_time, target=cand.key)
        return self._move(cand, charge=True)

    def _move(
        self, point: TuningPoint, *, charge: bool
    ) -> TuningPoint | None:
        if point == self.current:
            return None
        self.current = point
        # Warmup discards post-COMPILE noise; a revert/freeze revisits
        # an already-compiled point, so its next epoch measures clean —
        # no epochs wasted cooling down a move that cost nothing.
        self._cooldown = int(self.cfg["warmup_epochs"]) if charge else 0
        self._window = []
        if charge:
            self._await_charge = True
            if self._detector is not None:
                self._detector_mark = self._detector.count
                self._detector.expect("autotune")
        self._batch_gauge.set(float(point.batch_size))
        return point

    def _freeze(self, epoch: int, reason: str) -> TuningPoint | None:
        self.frozen = True
        self._freezes_total.inc()
        self._frozen_gauge.set(1.0)
        self._event(
            "autotune_freeze", epoch=epoch, reason=reason,
            config=self.best.key, recompiles=self.spent,
        )
        if self.verbose:
            import sys

            print(
                f"tpuflow.autotune: frozen on {self.best.key} at epoch "
                f"{epoch} ({reason}; {self.spent} recompile(s) charged "
                f"of budget {self.cfg['recompile_budget']})",
                file=sys.stderr,
            )
        self._persist(epoch)
        return self._move(self.best, charge=False)

    # --- recording ------------------------------------------------------

    def _gauge_readings(self) -> dict:
        """The live occupancy gauges, read without creating absent
        families (Registry.peek): on a chip without roofline peaks the
        gauges are honestly absent and so are these fields."""
        out: dict = {}
        reg = self._registry
        if reg is None:
            return out
        for field, metric in (
            ("mfu", "train_mfu"), ("hbm_util", "train_hbm_util"),
        ):
            fam = reg.peek(metric)
            if fam is not None and fam.labels_seen():
                out[field] = fam.value()
        bound = reg.peek("train_bound")
        if bound is not None:
            for b in ("hbm", "mxu"):
                if bound.value(bound=b) == 1.0:
                    out["bound"] = b
        return out

    def _record(
        self, epoch: int, action: str, sps: float, train_time: float,
        **extra,
    ) -> None:
        from tpuflow.obs.tracing import record_span

        rec = {
            "epoch": epoch,
            "action": action,
            "config": self.current.key,
            "batch_size": self.current.batch_size,
            "remat": self.current.remat,
            "scan": self.current.jit_epoch,
            "samples_per_sec": round(sps, 3),
            "budget_remaining": self._budget_remaining(),
            **self._gauge_readings(),
            **extra,
        }
        self.trail.append(rec)
        self._steps.inc(action=action)
        record_span(
            "autotune.step", float(train_time), logger=self._logger,
            **rec,
        )

    def _event(self, name: str, **fields) -> None:
        from tpuflow.obs.forensics import record_event

        record_event(name, **fields)
        if self._logger is not None:
            self._logger.write(name, **fields)

    def _persist(self, epoch: int) -> None:
        """Write the best-seen point on every adoption/freeze — not
        just at fit end — so a preempted run's next attempt still
        resumes tuned. Best-effort: persistence is an optimization and
        must never kill a healthy training run."""
        if not (self.cfg["persist"] and self.storage_path):
            return
        try:
            save_tuned(
                self.storage_path, self.model_name, self.device_kind,
                self.compute_dtype, self.best,
                throughput=self.best_sps or 0.0, frozen=self.frozen,
                epoch=epoch,
            )
            self._persisted = True
        except Exception as e:  # noqa: BLE001 — URI backends raise
            # non-OSError (gcsfs HttpError, botocore ClientError);
            # best-effort means NONE of them may kill a healthy run
            # (the train/resume.py precedent).
            if self.verbose:
                import sys

                print(
                    f"tpuflow.autotune: tuned-config write failed "
                    f"({type(e).__name__}: {e}); continuing untuned "
                    "next restart", file=sys.stderr,
                )

    def finalize(self, epoch: int | None = None) -> None:
        """End-of-fit bookkeeping: persist the best-seen point (a run
        that ended before freezing still hands its successor the best
        it found)."""
        if not self._persisted or not self.frozen:
            self._persist(epoch if epoch is not None else 0)

    def summary(self) -> dict:
        """The run-report record (``TrainReport.autotune``)."""
        return {
            "start": self.start.to_dict(),
            "best": self.best.to_dict(),
            "best_config": self.best.key,
            "best_samples_per_sec": (
                round(self.best_sps, 3) if self.best_sps else None
            ),
            "frozen": self.frozen,
            "recompiles_charged": self.spent,
            "recompile_budget": int(self.cfg["recompile_budget"]),
            "reverts": self.reverts,
            "decisions": len(self.trail),
            "configs_measured": sorted(p.key for p in self.measured),
            "prior": self.prior,
            # The DECISION trail: post-freeze epochs all record "frozen"
            # and would evict the interesting prefix from any tail-cap —
            # keep the decisions, count the frozen epochs.
            "trail": [
                r for r in self.trail if r["action"] != "frozen"
            ][:64],
            "frozen_epochs": sum(
                1 for r in self.trail if r["action"] == "frozen"
            ),
        }
