"""Training callbacks.

``EarlyStopping`` matches the reference's
``EarlyStopping(monitor='val_loss', patience=10)`` (reference cnn.py:121):
stop after ``patience`` epochs without val-loss improvement.
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass
class EarlyStopping:
    patience: int = 10
    min_delta: float = 0.0
    best: float = field(default=float("inf"), init=False)
    bad_epochs: int = field(default=0, init=False)

    def update(self, val_loss: float) -> bool:
        """Record an epoch's val loss; returns True if training should stop."""
        if val_loss < self.best - self.min_delta:
            self.best = val_loss
            self.bad_epochs = 0
        else:
            self.bad_epochs += 1
        return self.bad_epochs >= self.patience

    @property
    def improved(self) -> bool:
        return self.bad_epochs == 0
