"""Jitted train/eval steps.

Each step is traced once per (model, shape) and reused for the whole run —
the XLA contract SURVEY.md §7 calls out. Dropout randomness is derived by
folding the step counter into a base rng, so steps stay functional.

Mixed precision (tpuflow/train/precision.py): ``compute_dtype`` installs
the step half of the policy — the input batch is cast to the compute
dtype at step entry (the activations' HBM traffic halves under bf16
before the first matmul), while differentiation still runs against the
f32 MASTER params (the model's own per-layer ``dtype`` cast sits inside
the differentiated graph, so grads come back f32), predictions are
promoted to f32 at the loss site (reduction never happens in bf16), and
the loss/grad_norm aux is returned f32 so the numerics watchdog's EWMA
threshold keeps f32 resolution. ``compute_dtype=None`` (default) is the
all-f32 path, byte-identical to the pre-policy steps.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from flax.training.train_state import TrainState

from tpuflow.core.losses import mae_clip

LossFn = Callable[[jnp.ndarray, jnp.ndarray], jnp.ndarray]


def _cast_batch(x, compute_dtype):
    """Step-entry activation cast: the ONE sanctioned narrowing site.
    Params are deliberately NOT cast here — the model casts them inside
    the differentiated graph, which is what keeps grads f32 against the
    f32 masters (a step-entry param cast would hand bf16 grads to the
    f32 optimizer update)."""
    if compute_dtype is None:
        return x
    return jnp.asarray(x).astype(compute_dtype)


def _maybe_remat(apply, remat: bool):
    """The step half of the remat knob: ``jax.checkpoint`` on the
    model's forward, so backward recomputes activations instead of
    holding them in HBM — recompute FLOPs traded for residency on the
    HBM-bound path, for EVERY model family (the model-level ``remat``
    kwarg of the LSTM family remats only its gate scan). The occupancy
    autotuner toggles this per run from measured throughput."""
    return jax.checkpoint(apply) if remat else apply


def make_train_step(
    loss_fn: LossFn = mae_clip, donate: bool = True, compute_dtype=None,
    remat: bool = False,
):
    """Build a jitted step: (state, x, y, rng) -> (state, metrics)."""

    def step(state: TrainState, x, y, rng):
        dropout_rng = jax.random.fold_in(rng, state.step)
        x = _cast_batch(x, compute_dtype)

        def loss_of(params):
            apply = _maybe_remat(
                lambda p, xs: state.apply_fn(
                    {"params": p},
                    xs,
                    deterministic=False,
                    rngs={"dropout": dropout_rng},
                ),
                remat,
            )
            pred = apply(params, x)
            # Loss reduction stays f32 whatever the compute dtype: a
            # model that returns bf16 must not narrow the reduction
            # (models in this tree already emit f32; this is the
            # contract made executable).
            return loss_fn(y, pred.astype(jnp.float32))

        loss, grads = jax.value_and_grad(loss_of)(state.params)
        state = state.apply_gradients(grads=grads)
        gnorm = optax_global_norm(grads)
        # The aux CONTRACT: loss/grad_norm stay device values through
        # the epoch's batch loop and feed the numerics watchdog as host
        # floats only post-epoch (tpuflow/obs/health.py; lint TPF006) —
        # a float() per step here would serialize async dispatch. Both
        # are f32 regardless of precision (watchdog EWMA resolution).
        return state, {
            "loss": loss.astype(jnp.float32),
            "grad_norm": gnorm.astype(jnp.float32),
        }

    return jax.jit(step, donate_argnums=(0,) if donate else ())


def make_epoch_step(
    loss_fn: LossFn = mae_clip, donate: bool = True, compute_dtype=None,
    remat: bool = False,
):
    """Build a jitted WHOLE-EPOCH step: (state, xs, ys, rng) -> (state, loss).

    ``xs [n_batches, B, ...]`` / ``ys [n_batches, B, ...]`` are the epoch's
    pre-batched data; the batch loop is a ``lax.scan`` compiled into one
    XLA program, so per-step Python dispatch disappears. This is the
    throughput path for small models at the reference's tiny batch size
    (20, reference cnn.py:128) where dispatch otherwise dominates the MXU
    work. Returns the mean train loss over the epoch.
    """

    def batch_step(state, batch):
        x, y, rng = batch

        def loss_of(params):
            apply = _maybe_remat(
                lambda p, xs: state.apply_fn(
                    {"params": p},
                    xs,
                    deterministic=False,
                    rngs={"dropout": rng},
                ),
                remat,
            )
            pred = apply(params, x)
            return loss_fn(y, pred.astype(jnp.float32))

        loss, grads = jax.value_and_grad(loss_of)(state.params)
        state = state.apply_gradients(grads=grads)
        return state, loss.astype(jnp.float32)

    def epoch(state, xs, ys, rng):
        # One cast for the whole epoch's stacked batches: under bf16 the
        # scanned program's dominant HBM stream (the per-step batch
        # loads) moves half the bytes.
        xs = _cast_batch(xs, compute_dtype)
        rngs = jax.vmap(lambda i: jax.random.fold_in(rng, i))(
            jnp.arange(xs.shape[0])
        )
        state, losses = jax.lax.scan(batch_step, state, (xs, ys, rngs))
        return state, jnp.mean(losses)

    return jax.jit(epoch, donate_argnums=(0,) if donate else ())


def make_eval_step(loss_fn: LossFn = mae_clip, compute_dtype=None):
    """Build a jitted eval step returning masked per-example SUMS.

    Returning sums + a valid-row mask (instead of a batch mean) lets the
    caller pad the tail batch to the fixed XLA shape and still aggregate
    exact dataset-level metrics. Metrics aggregate in f32 whatever the
    compute dtype (the model promotes its output; y/mask stay f32).
    """

    def step(state: TrainState, x, y, mask):
        x = _cast_batch(x, compute_dtype)
        pred = state.apply_fn({"params": state.params}, x, deterministic=True)
        pred = pred.astype(jnp.float32)
        per_loss = jax.vmap(loss_fn)(y, pred)  # [B]: per-example mean loss
        per_mae = jnp.abs(y - pred).reshape(y.shape[0], -1).mean(axis=1)
        return {
            "loss_sum": jnp.sum(per_loss * mask),
            "mae_sum": jnp.sum(per_mae * mask),
            "count": jnp.sum(mask),
        }

    return jax.jit(step)


def make_predict(model_apply, donate_input: bool = False):
    """Jitted deterministic forward pass.

    ``donate_input=True`` donates the input batch's device buffer to the
    call (serving fast path: the padded batch is freshly built per
    dispatch and never reused, so XLA may overwrite it in place). Off by
    default — callers that reuse ``x`` after the call must not donate.

    No ``compute_dtype`` knob on purpose: serving rebuilds models from
    the sidecar, which records no compute dtype — artifacts serve f32
    (the precision policy's checkpoint/serving contract).
    """

    def predict(params, x):
        return model_apply({"params": params}, x, deterministic=True)

    if donate_input:
        return jax.jit(predict, donate_argnums=(1,))
    return jax.jit(predict)


def optax_global_norm(tree) -> jnp.ndarray:
    leaves = jax.tree_util.tree_leaves(tree)
    return jnp.sqrt(sum(jnp.sum(jnp.square(l)) for l in leaves))
