"""Training: optimizer/state, jitted steps, callbacks, fit loop, checkpoints.

TPU-native rebuild of the reference's Keras training layer (L3, reference
cnn.py:110-134): SGD with the reference's exact hyperparameters, early
stopping on val_loss (patience 10), save-best checkpointing, and the
elapsed-time + test-loss final report — plus what the reference lacked:
deterministic resume, structured per-step metrics, and samples/sec/chip
accounting.
"""

from tpuflow.train.optim import (  # noqa: F401
    build_optimizer,
    keras_sgd,
    wrap_optimizer,
)
from tpuflow.train.state import create_state  # noqa: F401
from tpuflow.train.steps import make_train_step, make_eval_step  # noqa: F401
from tpuflow.train.callbacks import EarlyStopping  # noqa: F401
from tpuflow.train.checkpoint import BestCheckpointer  # noqa: F401
from tpuflow.train.loop import (  # noqa: F401
    FitConfig,
    FitResult,
    StreamingSource,
    TrainingInterrupted,
    evaluate,
    fit,
)
from tpuflow.train.supervisor import SupervisedRun, supervise  # noqa: F401
