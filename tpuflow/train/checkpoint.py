"""Orbax checkpointing: save-best plus resume.

The reference saves only the best model by val_loss to shared storage —
``ModelCheckpoint(storagePath + "models/cnn.mdl", save_best_only=True)``
(reference cnn.py:122) — with **no** resume path. Here save-best is kept
(same contract: best-by-val-loss under ``{storage_path}/models/{name}``)
and resume is added: restoring the latest/best checkpoint is the TPU-native
answer to Spark's task-retry fault-tolerance story (SURVEY.md §5.3).
"""

from __future__ import annotations

from typing import Any

import jax
import orbax.checkpoint as ocp

from tpuflow.resilience import fault_point, io_policy, retry_call
from tpuflow.utils.paths import join_path


def make_checkpointer(
    storage_path: str, name: str = "model", async_save: bool = True
):
    """The best-checkpointer for a storage root: Orbax
    (:class:`BestCheckpointer`) for local trees and natively-supported
    URIs, the object-store seam's :class:`~tpuflow.storage.checkpoint
    .StoreCheckpointer` when the root resolves through
    ``tpuflow.storage`` (``fake://`` today) — same ``maybe_save`` /
    ``restore_best`` contract either way, so the train loop and the
    serving load path pick by root, not by code path."""
    from tpuflow.storage import is_store_uri

    if is_store_uri(storage_path):
        from tpuflow.storage.checkpoint import StoreCheckpointer

        return StoreCheckpointer(storage_path, name)
    return BestCheckpointer(storage_path, name, async_save=async_save)


class BestCheckpointer:
    """Save-best-by-val-loss checkpoint manager with restore support.

    ``async_save=True`` (default) writes in the background so the save
    overlaps the next epoch's device compute instead of stalling the fit
    loop — the TPU-idiomatic pattern. Every read path (``best_step``,
    ``restore_best``) and ``close()`` waits for in-flight writes first, so
    callers never observe a half-written checkpoint.
    """

    def __init__(
        self, storage_path: str, name: str = "model", async_save: bool = True
    ):
        # Same artifact layout as the reference: {storagePath}/models/{name}
        # (reference cnn.py:39,122 — MDL_NAME constant + path join).
        # URI-schemed storage (gs://...) passes through to Orbax intact.
        self.directory = join_path(storage_path, "models", name)
        self._async = async_save
        self._mngr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=1,
                best_fn=lambda metrics: metrics["val_loss"],
                best_mode="min",
                enable_async_checkpointing=async_save,
            ),
        )

    def maybe_save(self, step: int, params: Any, val_loss: float) -> bool:
        """Offer a checkpoint; the manager keeps it only if it's the best.

        The keep/drop decision is made synchronously from ``val_loss``;
        with async_save only the array write happens in the background.
        The shared I/O retry policy wraps the ``save`` call, so with
        ``async_save=False`` (where Orbax writes synchronously inside
        ``save``) transient storage errors are fully absorbed; with
        async saves only the enqueue is covered — a background-write
        failure surfaces at the next wait point (``best_step``/
        ``close``), where Orbax's atomic commit means the PREVIOUS
        checkpoint is still intact. ``checkpoint.save`` is a registered
        fault site keyed by the step.
        """

        def _save():
            fault_point("checkpoint.save", index=step)
            return self._mngr.save(
                step,
                args=ocp.args.StandardSave(params),
                metrics={"val_loss": float(val_loss)},
            )

        saved = retry_call(io_policy(), _save)
        if not self._async:
            self._mngr.wait_until_finished()
        return bool(saved)

    @property
    def best_step(self) -> int | None:
        self._mngr.wait_until_finished()
        return self._mngr.best_step()

    def best_structure(self):
        """The best checkpoint's tree of per-leaf METADATA (shapes and
        dtypes, no array data). Warm-start compatibility checks
        (``train/resume.py::check_params_match``) read this to fail with
        named leaf paths BEFORE paying for a restore — a structurally
        incompatible artifact would otherwise die inside Orbax's
        template matching as an opaque pytree error."""
        self._mngr.wait_until_finished()
        step = self._mngr.best_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoint under {self.directory}")
        # Read the step's item directly (shapes/dtypes only, no array
        # data): the manager's own item_metadata answers None — with a
        # handler-registry warning — on a manager freshly opened over an
        # existing tree, which is exactly the warm-start case.
        return ocp.StandardCheckpointer().metadata(
            join_path(self.directory, str(step), "default")
        )

    def restore_best(self, params_like: Any | None = None) -> Any:
        """Restore the best params (optionally into an example structure).

        Transient read errors retry under the shared I/O policy;
        ``checkpoint.restore`` is a registered fault site."""
        self._mngr.wait_until_finished()
        step = self._mngr.best_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoint under {self.directory}")

        def _restore():
            fault_point("checkpoint.restore", index=step)
            if params_like is not None:
                abstract = jax.tree_util.tree_map(
                    ocp.utils.to_shape_dtype_struct, params_like
                )
                return self._mngr.restore(
                    step, args=ocp.args.StandardRestore(abstract)
                )
            return self._mngr.restore(step)

        return retry_call(io_policy(), _restore)

    def close(self):
        self._mngr.close()
