"""Flax model family — the TPU-native rebuild of the reference's model suite.

The reference names four learned model types plus a physical baseline
(reference Readme.md:7-21): a static ANN, a dynamic ANN, a 1-D CNN (the one
surviving script, cnn.py:110-114), and an LSTM; BASELINE.json adds the
multi-well stacked-LSTM data-parallel config. Each is a ``flax.linen``
module here, shaped for the MXU: dense/conv compute in large batched
matmuls, recurrence via an on-chip scan.
"""

from tpuflow.models.attention import AttentionRegressor  # noqa: F401
from tpuflow.models.mlp import (  # noqa: F401
    DynamicMLP,
    GilbertResidualMLP,
    MoEMLP,
    PipelineMLP,
    StaticMLP,
)
from tpuflow.models.cnn import CNN1D  # noqa: F401
from tpuflow.models.lstm import GilbertResidualLSTM, LSTMRegressor  # noqa: F401
from tpuflow.models.registry import MODELS, build_model  # noqa: F401
