"""LSTM sequence regressors — the flagship, throughput-critical family.

The reference names an LSTM model for dynamic flow prediction (reference
Readme.md:21; SURVEY.md C19 — script absent from the snapshot) and the
north-star benchmark is "LSTM-64 single-well sequence model
(teacher-forced)" plus "multi-well stacked-LSTM, data-parallel"
(BASELINE.json configs) at ≥10k samples/sec/chip.

TPU-first design (SURVEY.md §3.4, §7 "hard parts"):

- **Input projections are hoisted out of the recurrence.** ``x_t @ W_x``
  for all timesteps is ONE large ``[B*T, F] x [F, 4H]`` matmul that tiles
  onto the MXU, instead of T skinny per-step matmuls.
- The remaining per-step work — ``h @ W_h`` plus the elementwise gate
  math — runs in a single ``lax.scan`` over the time axis, carrying
  ``(h, c)``. XLA fuses the gate elementwise ops into the recurrent
  matmul's epilogue.
- All four gates share one fused weight matrix ``[·, 4H]``; the forget
  gate gets the standard +1 bias at init.
- Optional bfloat16 compute (params stay float32) for MXU-native matmuls.

A Pallas fused-cell kernel can replace the scan body without changing this
module's interface (``tpuflow.kernels``).
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp
from jax import lax


def lstm_step(carry, xw_t, w_h, b):
    """One LSTM step (gate order i, f, g, o; the single source of the cell
    math — the scan path, the sequence-parallel path, and the Pallas
    kernels all implement/verify against this).

    ``carry = (h, c)``; ``xw_t`` is the pre-projected input ``x_t @ W_x``.
    """
    h, c = carry
    z = xw_t + h @ w_h + b
    i, f, g, o = jnp.split(z, 4, axis=-1)
    c = nn.sigmoid(f) * c + nn.sigmoid(i) * jnp.tanh(g)
    h = nn.sigmoid(o) * jnp.tanh(c)
    return (h, c), h


class LSTMLayer(nn.Module):
    """One LSTM layer: [B, T, F] -> [B, T, H], batch-major in/out.

    ``backend="xla"`` runs the recurrence as a ``lax.scan`` (XLA fuses the
    gate math into the recurrent matmul); ``backend="pallas"`` swaps in the
    fused Pallas kernel from ``tpuflow.kernels`` — same math, same
    parameters, interchangeable checkpoints.
    """

    hidden: int
    dtype: Any = jnp.float32
    backend: str = "xla"  # "xla" | "pallas"
    # lax.scan unroll factor for the XLA backend: unrolling K steps per
    # loop iteration amortizes loop overhead and lets XLA fuse across
    # steps — a real lever for small recurrences (H=64) where per-step
    # work barely covers the loop cost. Compile time grows with K; T must
    # not need to divide K (lax.scan handles the remainder).
    unroll: int = 1
    # Rematerialize the gate math in backward instead of storing it: the
    # train step measured HBM-BOUND on v5e (round 5: 13.6% MFU at 63% HBM
    # util), and the stored per-step gate activations are the bulk of the
    # residual traffic. jax.checkpoint on the scan body saves only the
    # (h, c) carry per step and recomputes z/gates from it in backward —
    # trading idle MXU FLOPs (~86% idle) for the saturated resource.
    remat: bool = False

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        B, T, F = x.shape
        H = self.hidden
        w_x = self.param("w_x", nn.initializers.lecun_normal(), (F, 4 * H))
        w_h = self.param("w_h", nn.initializers.orthogonal(), (H, 4 * H))
        # Forget-gate bias +1 (gate order: i, f, g, o).
        b = self.param(
            "b",
            lambda key, shape: jnp.concatenate(
                [jnp.zeros(H), jnp.ones(H), jnp.zeros(2 * H)]
            ).astype(jnp.float32),
            (4 * H,),
        )
        dt = self.dtype
        x = x.astype(dt)
        w_x, w_h, b = w_x.astype(dt), w_h.astype(dt), b.astype(dt)

        # Hoisted input projection: one MXU-sized matmul for all timesteps.
        xw = (x.reshape(B * T, F) @ w_x).reshape(B, T, 4 * H)
        xw = jnp.swapaxes(xw, 0, 1)  # time-major for the scan: [T, B, 4H]

        if self.backend == "pallas":
            from tpuflow.kernels import lstm_scan

            hs = lstm_scan(xw, w_h, b)
        else:
            import jax

            h0 = jnp.zeros((B, H), dtype=dt)
            step = lambda carry, xw_t: lstm_step(carry, xw_t, w_h, b)
            if self.remat:
                step = jax.checkpoint(step)
            (_, _), hs = lax.scan(step, (h0, h0), xw, unroll=self.unroll)
        return jnp.swapaxes(hs, 0, 1)  # back to batch-major [B, T, H]


class GilbertResidualLSTM(nn.Module):
    """Physics-informed sequence model: per-step Gilbert flow × learned
    sequence correction.

    The sequence counterpart of ``GilbertResidualMLP`` (reference
    Readme.md:7-21 pairs the physical model with every learned family):
    the RAW per-timestep Gilbert prediction rides as the LAST feature
    channel (appended by ``prepare_windowed(append_gilbert=True)``); the
    stacked LSTM reads the remaining standardized channels and emits a
    positive multiplicative correction per step, centred at 1 by a
    zero-init head. At init the output IS the standardized Gilbert
    prediction — training starts at the physical baseline and spends its
    capacity on the physics' error, which is why it reaches lower MAE than
    a from-scratch LSTM of the same size.

    ``target_mean``/``target_std`` standardize the raw physical output so
    training sees standardized targets (clip=6 discipline); the training
    pipeline injects the train-split stats.
    """

    hidden: int = 64
    num_layers: int = 1
    readout: str = "sequence"  # "sequence" | "last"
    dtype: Any = jnp.float32
    backend: str = "xla"  # "xla" | "pallas"
    unroll: int = 1  # lax.scan unroll for the XLA backend (see LSTMLayer)
    remat: bool = False  # rematerialize gate math in backward (see LSTMLayer)
    target_mean: float = 0.0
    target_std: float = 1.0

    @nn.compact
    def __call__(self, x: jnp.ndarray, *, deterministic: bool = True) -> jnp.ndarray:
        from tpuflow.models.mlp import SOFTPLUS_ONE

        gilbert_q = x[..., -1].astype(jnp.float32)  # [B, T] raw flow
        h = x[..., :-1]
        for layer in range(self.num_layers):
            h = LSTMLayer(
                self.hidden,
                dtype=self.dtype,
                backend=self.backend,
                unroll=self.unroll,
                remat=self.remat,
                name=f"lstm_{layer}",
            )(h)
        raw = nn.Dense(
            1, dtype=self.dtype, kernel_init=nn.initializers.zeros, name="head"
        )(h)[..., 0].astype(jnp.float32)
        correction = nn.softplus(raw + SOFTPLUS_ONE)
        y = (gilbert_q * correction - self.target_mean) / self.target_std
        if self.readout == "last":
            return y[:, -1]
        if self.readout == "sequence":
            return y
        raise ValueError(f"unknown readout {self.readout!r}")


class LSTMRegressor(nn.Module):
    """Stacked-LSTM flow regressor.

    ``num_layers=1, hidden=64`` is the BASELINE "LSTM-64" config;
    ``num_layers>=2`` is the "multi-well stacked-LSTM" config. With
    ``readout="sequence"`` the head emits a prediction per step ([B, T],
    teacher-forced training); ``readout="last"`` emits only the final step
    ([B]).
    """

    hidden: int = 64
    num_layers: int = 1
    readout: str = "sequence"  # "sequence" | "last"
    dtype: Any = jnp.float32
    backend: str = "xla"  # "xla" | "pallas"
    unroll: int = 1  # lax.scan unroll for the XLA backend (see LSTMLayer)
    remat: bool = False  # rematerialize gate math in backward (see LSTMLayer)

    @nn.compact
    def __call__(self, x: jnp.ndarray, *, deterministic: bool = True) -> jnp.ndarray:
        for layer in range(self.num_layers):
            x = LSTMLayer(
                self.hidden,
                dtype=self.dtype,
                backend=self.backend,
                unroll=self.unroll,
                remat=self.remat,
                name=f"lstm_{layer}",
            )(x)
        y = nn.Dense(1, dtype=self.dtype, name="head")(x)[..., 0]  # [B, T]
        y = y.astype(jnp.float32)
        if self.readout == "last":
            return y[:, -1]
        if self.readout == "sequence":
            return y
        raise ValueError(f"unknown readout {self.readout!r}")
