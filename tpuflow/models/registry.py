"""Model registry: name -> constructor, the L4 "one script per model type"
layer of the reference (SURVEY.md §1) collapsed into a single lookup."""

from __future__ import annotations

from typing import Callable

import flax.linen as nn

from tpuflow.models.attention import AttentionRegressor
from tpuflow.models.cnn import CNN1D
from tpuflow.models.lstm import GilbertResidualLSTM, LSTMRegressor
from tpuflow.models.mlp import (
    DynamicMLP,
    GilbertResidualMLP,
    MoEMLP,
    PipelineMLP,
    StaticMLP,
)

MODELS: dict[str, Callable[..., nn.Module]] = {
    # BASELINE config 1: "Static ANN: 3-layer MLP single-well regressor"
    "static_mlp": lambda **kw: StaticMLP(**kw),
    # BASELINE config 3: "Dynamic ANN: windowed MLP on 24-step well-logs"
    "dynamic_mlp": lambda **kw: DynamicMLP(**kw),
    # Reference cnn.py parity model
    "cnn1d": lambda **kw: CNN1D(**kw),
    # BASELINE config 4: "LSTM-64 single-well sequence model"
    "lstm": lambda **kw: LSTMRegressor(**{"hidden": 64, **kw}),
    # BASELINE config 5: "Multi-well stacked-LSTM"
    "stacked_lstm": lambda **kw: LSTMRegressor(
        **{"hidden": 64, "num_layers": 2, **kw}
    ),
    # Physics-informed extensions (Gilbert x learned correction)
    "gilbert_residual": lambda **kw: GilbertResidualMLP(**kw),
    "lstm_residual": lambda **kw: GilbertResidualLSTM(**{"hidden": 64, **kw}),
    # Long-context family: causal transformer whose scale-out path is
    # ring attention over the mesh (tpuflow.parallel.ring_attention)
    "attention": lambda **kw: AttentionRegressor(**kw),
    # Pipeline-parallel family: homogeneous stages trained as a GPipe
    # microbatch pipeline via TrainJobConfig(pp=N) (parallel/pp_train.py)
    "pipeline_mlp": lambda **kw: PipelineMLP(**kw),
    # Expert-parallel family: top-1 routed expert bank trained with
    # experts sharded over the model axis via TrainJobConfig(ep=N)
    # (parallel/ep_train.py)
    "moe_mlp": lambda **kw: MoEMLP(**kw),
}


def build_model(name: str, **kwargs) -> nn.Module:
    if name not in MODELS:
        raise ValueError(f"unknown model {name!r}; known: {sorted(MODELS)}")
    return MODELS[name](**kwargs)
