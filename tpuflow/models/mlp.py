"""MLP regressors: the static and dynamic ANN model families.

Static ANN (SURVEY.md C17; reference Readme.md:17, BASELINE "3-layer MLP
single-well regressor"): an MLP over the assembled tabular feature vector.

Dynamic ANN (SURVEY.md C18; reference Readme.md:19, BASELINE "windowed MLP
on 24-step well-log sequences"): the same MLP over a flattened trailing
window of time-varying features.

``GilbertResidualMLP`` goes beyond the reference: it predicts a
*multiplicative correction* to the Gilbert physical prediction — the
physics-informed variant the reference's pairing of a physical model with
learned regressors (Readme.md:7-21) gestures at but never builds.
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp

# softplus(SOFTPLUS_ONE) == 1: with a zero-init head the learned
# multiplicative correction starts exactly at 1, i.e. the model begins AS
# the physical baseline and learns deviations.
SOFTPLUS_ONE = 0.5413248546129181  # ln(e - 1)


class StaticMLP(nn.Module):
    """3-layer MLP over tabular features: [B, F] -> [B]."""

    hidden: Sequence[int] = (64, 64)
    dropout_rate: float = 0.0

    @nn.compact
    def __call__(self, x: jnp.ndarray, *, deterministic: bool = True) -> jnp.ndarray:
        for h in self.hidden:
            x = nn.relu(nn.Dense(h)(x))
            if self.dropout_rate > 0:
                x = nn.Dropout(self.dropout_rate, deterministic=deterministic)(x)
        return nn.Dense(1)(x)[..., 0]


class DynamicMLP(nn.Module):
    """Windowed MLP: [B, T, F] -> [B], flattening the trailing window."""

    hidden: Sequence[int] = (128, 64)
    dropout_rate: float = 0.0

    @nn.compact
    def __call__(self, x: jnp.ndarray, *, deterministic: bool = True) -> jnp.ndarray:
        x = x.reshape(x.shape[0], -1)
        for h in self.hidden:
            x = nn.relu(nn.Dense(h)(x))
            if self.dropout_rate > 0:
                x = nn.Dropout(self.dropout_rate, deterministic=deterministic)(x)
        return nn.Dense(1)(x)[..., 0]


class GilbertResidualMLP(nn.Module):
    """Physics-informed MLP: Gilbert flow × learned correction.

    Expects the Gilbert-equation prediction as the LAST feature column
    (un-standardized raw flow); the MLP maps the remaining features to a
    positive correction factor via softplus, centred at 1.

    ``target_mean``/``target_std`` standardize the raw physical output so
    the module trains against standardized targets like every other model
    (keeping the clip=6 loss meaningful and SGD gradients O(1) —
    raw-flow-unit losses blow up the reference's lr=1e-3/momentum=.99
    optimizer). The training pipeline injects the train-split stats; at
    init the output IS the standardized Gilbert prediction.
    """

    hidden: Sequence[int] = (64, 64)
    target_mean: float = 0.0
    target_std: float = 1.0

    @nn.compact
    def __call__(self, x: jnp.ndarray, *, deterministic: bool = True) -> jnp.ndarray:
        gilbert_q = x[..., -1]
        h = x[..., :-1]
        for width in self.hidden:
            h = nn.relu(nn.Dense(width)(h))
        # Zero-init head => raw=0 at init => softplus(SOFTPLUS_ONE) == 1:
        # training starts exactly at the physical model, learns deviations.
        raw = nn.Dense(1, kernel_init=nn.initializers.zeros)(h)[..., 0]
        correction = nn.softplus(raw + SOFTPLUS_ONE)
        return (gilbert_q * correction - self.target_mean) / self.target_std
