"""MLP regressors: the static and dynamic ANN model families.

Static ANN (SURVEY.md C17; reference Readme.md:17, BASELINE "3-layer MLP
single-well regressor"): an MLP over the assembled tabular feature vector.

Dynamic ANN (SURVEY.md C18; reference Readme.md:19, BASELINE "windowed MLP
on 24-step well-log sequences"): the same MLP over a flattened trailing
window of time-varying features.

``GilbertResidualMLP`` goes beyond the reference: it predicts a
*multiplicative correction* to the Gilbert physical prediction — the
physics-informed variant the reference's pairing of a physical model with
learned regressors (Readme.md:7-21) gestures at but never builds.
"""

from __future__ import annotations

from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp

# softplus(SOFTPLUS_ONE) == 1: with a zero-init head the learned
# multiplicative correction starts exactly at 1, i.e. the model begins AS
# the physical baseline and learns deviations.
SOFTPLUS_ONE = 0.5413248546129181  # ln(e - 1)


class StaticMLP(nn.Module):
    """3-layer MLP over tabular features: [B, F] -> [B].

    ``dtype`` is the COMPUTE dtype (mixed-precision policy,
    tpuflow/train/precision.py): params stay f32 masters (flax
    ``param_dtype``), activations/matmuls run in ``dtype``, and the
    output is promoted to f32 so loss reduction never narrows.
    """

    hidden: Sequence[int] = (64, 64)
    dropout_rate: float = 0.0
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray, *, deterministic: bool = True) -> jnp.ndarray:
        x = x.astype(self.dtype)
        for h in self.hidden:
            x = nn.relu(nn.Dense(h, dtype=self.dtype)(x))
            if self.dropout_rate > 0:
                x = nn.Dropout(self.dropout_rate, deterministic=deterministic)(x)
        return nn.Dense(1, dtype=self.dtype)(x)[..., 0].astype(jnp.float32)


class DynamicMLP(nn.Module):
    """Windowed MLP: [B, T, F] -> [B], flattening the trailing window."""

    hidden: Sequence[int] = (128, 64)
    dropout_rate: float = 0.0
    dtype: Any = jnp.float32  # compute dtype; params stay f32 (see StaticMLP)

    @nn.compact
    def __call__(self, x: jnp.ndarray, *, deterministic: bool = True) -> jnp.ndarray:
        x = x.reshape(x.shape[0], -1).astype(self.dtype)
        for h in self.hidden:
            x = nn.relu(nn.Dense(h, dtype=self.dtype)(x))
            if self.dropout_rate > 0:
                x = nn.Dropout(self.dropout_rate, deterministic=deterministic)(x)
        return nn.Dense(1, dtype=self.dtype)(x)[..., 0].astype(jnp.float32)


class GilbertResidualMLP(nn.Module):
    """Physics-informed MLP: Gilbert flow × learned correction.

    Expects the Gilbert-equation prediction as the LAST feature column
    (un-standardized raw flow); the MLP maps the remaining features to a
    positive correction factor via softplus, centred at 1.

    ``target_mean``/``target_std`` standardize the raw physical output so
    the module trains against standardized targets like every other model
    (keeping the clip=6 loss meaningful and SGD gradients O(1) —
    raw-flow-unit losses blow up the reference's lr=1e-3/momentum=.99
    optimizer). The training pipeline injects the train-split stats; at
    init the output IS the standardized Gilbert prediction.
    """

    hidden: Sequence[int] = (64, 64)
    target_mean: float = 0.0
    target_std: float = 1.0
    dtype: Any = jnp.float32  # compute dtype; params stay f32 (see StaticMLP)

    @nn.compact
    def __call__(self, x: jnp.ndarray, *, deterministic: bool = True) -> jnp.ndarray:
        # The physical channel and the correction arithmetic stay f32
        # whatever the compute dtype: raw flow spans orders of magnitude
        # bf16 cannot hold without quantization error in the OUTPUT.
        gilbert_q = x[..., -1].astype(jnp.float32)
        h = x[..., :-1].astype(self.dtype)
        for width in self.hidden:
            h = nn.relu(nn.Dense(width, dtype=self.dtype)(h))
        # Zero-init head => raw=0 at init => softplus(SOFTPLUS_ONE) == 1:
        # training starts exactly at the physical model, learns deviations.
        raw = nn.Dense(
            1, dtype=self.dtype, kernel_init=nn.initializers.zeros
        )(h)[..., 0].astype(jnp.float32)
        correction = nn.softplus(raw + SOFTPLUS_ONE)
        return (gilbert_q * correction - self.target_mean) / self.target_std


class PipelineMLP(nn.Module):
    """Homogeneous-stage MLP built for pipeline parallelism: [B, F] -> [B].

    ``embed`` Dense -> ``stages`` identical ``tanh(h @ W_s + b_s)``
    blocks whose params are STACKED on a leading stage dim (so a
    pipeline trainer shards them one-or-more-stages-per-device) -> a
    scalar ``head``. This single-device ``__call__`` applies the stages
    sequentially — it is the parity oracle for the GPipe trainer
    (tpuflow/parallel/pp_train.py) and the serving path (an artifact
    trained with a pipeline axis restores and predicts off-mesh like any
    other model). The reference has no PP (SURVEY.md §2: out of scope
    for parity); this family exists so the framework's pipeline axis is
    training-capable end to end, not just a block.
    """

    stages: int = 4
    hidden: int = 32
    dtype: Any = jnp.float32  # compute dtype; params stay f32 (see StaticMLP)

    @nn.compact
    def __call__(self, x: jnp.ndarray, *, deterministic: bool = True) -> jnp.ndarray:
        import jax.nn.initializers as init

        dt = self.dtype
        h = nn.relu(nn.Dense(self.hidden, dtype=dt, name="embed")(
            x.astype(dt)
        ))
        wk = self.param(
            "stage_kernels", init.lecun_normal(),
            (self.stages, self.hidden, self.hidden),
        ).astype(dt)
        bk = self.param(
            "stage_biases", init.zeros, (self.stages, self.hidden)
        ).astype(dt)
        for s in range(self.stages):
            h = jnp.tanh(h @ wk[s] + bk[s])
        return nn.Dense(1, dtype=dt, name="head")(h)[..., 0].astype(
            jnp.float32
        )


class MoEMLP(nn.Module):
    """Top-1 mixture-of-experts MLP built for expert parallelism:
    [B, F] -> [B].

    ``embed`` Dense -> a router (``gate``) picks one expert per token
    from a STACKED bank of per-expert FFNs (params stacked on a leading
    expert dim, so an expert-parallel trainer shards them
    experts-per-device) -> residual add -> scalar ``head``. This
    single-device ``__call__`` loops the experts densely — the parity
    oracle for the EP trainer (tpuflow/parallel/ep_train.py) and the
    serving path. The residual keeps the model trainable even when the
    router's early routing is poor. The reference has no MoE (SURVEY.md
    §2: out of scope for parity); this family exists so the expert axis
    is training-capable end to end.
    """

    experts: int = 4
    hidden: int = 32
    ffn: int = 64
    dtype: Any = jnp.float32  # compute dtype; params stay f32 (see StaticMLP)

    @nn.compact
    def __call__(self, x: jnp.ndarray, *, deterministic: bool = True) -> jnp.ndarray:
        import jax
        import jax.nn.initializers as init

        dt = self.dtype
        h = nn.relu(nn.Dense(self.hidden, dtype=dt, name="embed")(
            x.astype(dt)
        ))
        gate = self.param(
            "gate", init.lecun_normal(), (self.hidden, self.experts)
        ).astype(dt)
        w1 = self.param(
            "expert_w1", init.lecun_normal(),
            (self.experts, self.hidden, self.ffn),
        ).astype(dt)
        w2 = self.param(
            "expert_w2", init.lecun_normal(),
            (self.experts, self.ffn, self.hidden),
        ).astype(dt)
        # THE shared top-1 router (tpuflow.parallel.ep.top1_gate): this
        # dense __call__ is the EP trainer's parity oracle AND the
        # serving path, so a routing change must reach all of them at
        # once. (Lazy import: models must stay importable without the
        # parallel package's jax.sharding machinery.)
        from tpuflow.parallel.ep import top1_gate

        choice, weight = top1_gate(h, gate)
        moe = sum(
            ((choice == e).astype(h.dtype) * weight.astype(h.dtype))[:, None]
            * (nn.relu(h @ w1[e]) @ w2[e])
            for e in range(self.experts)
        )
        return nn.Dense(1, dtype=dt, name="head")(h + moe)[..., 0].astype(
            jnp.float32
        )
