"""1-D CNN regressor — parity with the reference's only coded model.

The reference model (cnn.py:110-114, Keras-0.x positional style) is:
``Convolution1D(input_dim=1, nb_filter=100, filter_length=13,
activation="relu")`` → ``Dropout(0.5)`` → ``Flatten`` → ``Dense``. Rebuilt
here as a Flax module over [B, T, F] windows: Conv(100 filters, width 13,
relu) → dropout 0.5 → flatten → dense head. The reference head's odd
``Dense(3600, 12)`` 12-unit output is part of its never-ran glue
(SURVEY.md C10/C14); the documented intent — a regression script whose
loss is clipped MAE against a scalar flow target — needs a scalar head,
so the head is Dense(1).
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp


class CNN1D(nn.Module):
    """[B, T, F] -> [B] via 1-D convolution over the time axis.

    ``dtype`` is the COMPUTE dtype (mixed-precision policy,
    tpuflow/train/precision.py): params stay f32, the conv/dense math
    runs in ``dtype``, the output is promoted back to f32.
    """

    filters: int = 100
    kernel_size: int = 13
    dropout_rate: float = 0.5
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray, *, deterministic: bool = True) -> jnp.ndarray:
        x = nn.relu(
            nn.Conv(
                features=self.filters,
                kernel_size=(self.kernel_size,),
                dtype=self.dtype,
            )(x.astype(self.dtype))
        )
        x = nn.Dropout(self.dropout_rate, deterministic=deterministic)(x)
        x = x.reshape(x.shape[0], -1)
        return nn.Dense(1, dtype=self.dtype)(x)[..., 0].astype(jnp.float32)
