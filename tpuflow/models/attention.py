"""Attention sequence regressor — the framework's long-context family.

The reference family stops at the LSTM (reference Readme.md:21); its
windows are 24 steps, comfortably on-chip. This model exists because the
framework treats long-context as first-class: a small pre-LN transformer
encoder whose attention runs **causal** (per-step predictions use only
past observations, matching the LSTM's teacher-forced semantics). With
``backend="ring"`` (+ a mesh) every block's attention runs blockwise over
the mesh ring (``tpuflow.parallel.ring_attention``): the quadratic
[T, T] score matrix never materializes and its compute shards across
devices — the flash/ring-attention memory story for long logs. The O(T)
linear activations stay replicated here; sharding those too is the
whole-model ``shard_map`` recipe, not this module's job.

TPU-first shape choices: one fused QKV projection per block ([D, 3D], a
single MXU matmul), heads folded into the batch dimension for the
blockwise attention primitive, bf16-friendly (dtype param like the LSTM
family), static shapes throughout.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp

from tpuflow.parallel.ring_attention import full_attention, ring_attention


def _split_heads(x: jnp.ndarray, heads: int) -> jnp.ndarray:
    """[B, T, D] -> [B*h, T, D/h] (heads folded into batch)."""
    B, T, D = x.shape
    x = x.reshape(B, T, heads, D // heads)
    return x.transpose(0, 2, 1, 3).reshape(B * heads, T, D // heads)


def _merge_heads(x: jnp.ndarray, heads: int) -> jnp.ndarray:
    """[B*h, T, D/h] -> [B, T, D]."""
    Bh, T, Dh = x.shape
    x = x.reshape(Bh // heads, heads, T, Dh)
    return x.transpose(0, 2, 1, 3).reshape(Bh // heads, T, heads * Dh)


class EncoderBlock(nn.Module):
    """Pre-LN block: causal MHA + MLP, residual connections.

    ``backend="full"`` materializes the [T, T] scores on-chip (right for
    the reference's 24-step windows); ``backend="flash"`` swaps in the
    fused Pallas flash-attention kernel (``tpuflow.kernels``) — scores
    stay blockwise in VMEM, single chip; ``backend="ring"`` runs the same
    exact attention blockwise over ``mesh``'s data-axis ring
    (``tpuflow.parallel.ring_attention``) — attention memory O(T/N) for
    logs longer than one chip. Same math, same params, interchangeable
    checkpoints (the LSTM family's xla/pallas backend pattern).
    """

    dim: int
    heads: int = 4
    mlp_ratio: int = 4
    dropout_rate: float = 0.0
    dtype: Any = jnp.float32
    backend: str = "full"  # "full" | "flash" | "ring"
    mesh: Any = None  # required for backend="ring"
    ring_impl: str = "jnp"  # ring block math: "jnp" | "flash" (composed)

    @nn.compact
    def __call__(self, x, *, deterministic: bool = True):
        h = nn.LayerNorm(dtype=self.dtype)(x)
        qkv = nn.Dense(3 * self.dim, dtype=self.dtype, name="qkv")(h)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q, k, v = (_split_heads(t, self.heads) for t in (q, k, v))
        if self.backend == "flash":
            from tpuflow.kernels import flash_attention

            att = flash_attention(q, k, v)
        elif self.backend == "ring":
            if self.mesh is None:
                raise ValueError('backend="ring" needs a mesh')
            att = ring_attention(
                self.mesh, q, k, v, causal=True, impl=self.ring_impl
            )
            # The quadratic [T, T] score matrix stayed blockwise inside
            # the ring; the O(T) output comes back replicated so the
            # surrounding Dense/LayerNorm grads have unambiguous
            # shardings. (Sharding the whole block over time is the
            # shard_map recipe in examples/, not this module's job.)
            from jax.sharding import NamedSharding, PartitionSpec

            from tpuflow.parallel.compat import reshard

            # NamedSharding (not a bare spec): the supplied mesh must be
            # sufficient on its own — a bare PartitionSpec would demand
            # an ambient set_mesh context on top of the parameter.
            att = reshard(
                att, NamedSharding(self.mesh, PartitionSpec())
            )
        else:
            att = full_attention(q, k, v, causal=True)
        att = _merge_heads(att, self.heads)
        att = nn.Dense(self.dim, dtype=self.dtype, name="proj")(att)
        if self.dropout_rate > 0:
            att = nn.Dropout(self.dropout_rate, deterministic=deterministic)(att)
        x = x + att
        h = nn.LayerNorm(dtype=self.dtype)(x)
        h = nn.Dense(self.mlp_ratio * self.dim, dtype=self.dtype)(h)
        h = nn.gelu(h)
        h = nn.Dense(self.dim, dtype=self.dtype)(h)
        if self.dropout_rate > 0:
            h = nn.Dropout(self.dropout_rate, deterministic=deterministic)(h)
        return x + h


class AttentionRegressor(nn.Module):
    """Causal transformer flow regressor: [B, T, F] -> [B, T] (or [B]).

    Same interface contract as ``LSTMRegressor`` (sequence/last readout,
    dtype, teacher-forced targets), so it drops into the same training
    loop, comparison runs, and serving artifacts. Positions enter via a
    learned embedding over the window (windows are fixed-length, so the
    embedding shape is static).
    """

    dim: int = 64
    num_layers: int = 2
    heads: int = 4
    readout: str = "sequence"  # "sequence" | "last"
    dropout_rate: float = 0.0
    dtype: Any = jnp.float32
    backend: str = "full"  # "full" | "flash" | "ring" (see EncoderBlock)
    mesh: Any = None  # required for backend="ring"; T must divide its ring
    ring_impl: str = "jnp"  # "flash" = Pallas round kernels inside the ring

    @nn.compact
    def __call__(self, x: jnp.ndarray, *, deterministic: bool = True) -> jnp.ndarray:
        B, T, F = x.shape
        h = nn.Dense(self.dim, dtype=self.dtype, name="embed")(x.astype(self.dtype))
        pos = self.param(
            "pos_embed", nn.initializers.normal(0.02), (T, self.dim)
        )
        h = h + pos.astype(self.dtype)[None]
        for i in range(self.num_layers):
            h = EncoderBlock(
                self.dim,
                heads=self.heads,
                dropout_rate=self.dropout_rate,
                dtype=self.dtype,
                backend=self.backend,
                mesh=self.mesh,
                ring_impl=self.ring_impl,
                name=f"block_{i}",
            )(h, deterministic=deterministic)
        h = nn.LayerNorm(dtype=self.dtype)(h)
        y = nn.Dense(1, dtype=self.dtype, name="head")(h)[..., 0]
        y = y.astype(jnp.float32)
        if self.readout == "last":
            return y[:, -1]
        if self.readout == "sequence":
            return y
        raise ValueError(f"unknown readout {self.readout!r}")
