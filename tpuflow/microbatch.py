"""Cross-request micro-batching for the serving fast path.

The reference lineage's throughput lever is batching: SparkNet and BigDL
(PAPERS.md) both win by amortizing fixed per-dispatch overhead across
many rows of work. The serving path had none of it — every ``POST
/predict`` made its own jitted device call against the same params, so
N concurrent callers paid N dispatch overheads (and, on first touch, N
chances at an XLA compile) for work one dispatch could carry.

``MicroBatcher`` is the coalescing seam: requests for the same artifact
key enqueue their ALREADY feature-transformed row arrays; a single
dispatcher thread drains a key's queue once ``max_wait_ms`` has passed
since its oldest entry (or sooner, when ``max_batch_rows`` accumulate),
concatenates the rows, runs ONE forward through the caller-supplied
``run_batch`` hook, and scatters the result rows back to the waiting
callers.

Correctness constraints the dispatcher enforces (docs/serving.md):

- **No stale scatter across a retrain.** Every entry carries the
  predictor INSTANCE it resolved at enqueue time; a drain is grouped by
  instance, never just by key. When a retrain invalidates the cache
  mid-flight, requests that resolved the old predictor and requests
  that resolved the new one land in SEPARATE dispatches — each caller
  gets predictions from exactly the params it resolved, exactly as the
  unbatched path would have answered it.
- **Errors scatter too.** A failing forward fails every request in its
  dispatch group (and only that group); the dispatcher thread survives.
- **Bounded queue.** Past ``max_queue_rows`` pending rows, ``submit``
  raises instead of accepting unbounded backlog (the JobRunner 429
  discipline, applied to predicts).

Degraded (Gilbert-fallback) answers must never be coalesced into model
batches — that gate lives in ``PredictService.predict``, which bypasses
this module entirely for degraded predictors.

``LatencyStats`` is the per-request latency accounting that rides along:
a bounded reservoir of recent request latencies, snapshotted into
p50/p99 for ``/metrics``.
"""

from __future__ import annotations

import threading
import time
from collections import deque

import numpy as np


class LatencyStats:
    """Bounded reservoir of recent request latencies (seconds in,
    milliseconds out). ``window`` bounds memory and keeps the
    percentiles describing RECENT traffic, not the whole process
    lifetime."""

    def __init__(self, window: int = 2048):
        self._lock = threading.Lock()
        self._samples: deque[float] = deque(maxlen=window)
        self._count = 0
        self._total = 0.0
        self._max = 0.0

    def record(self, seconds: float) -> None:
        with self._lock:
            self._samples.append(seconds)
            self._count += 1
            self._total += seconds
            if seconds > self._max:
                self._max = seconds

    @staticmethod
    def _percentiles_ms(samples: list[float]) -> dict[float, float | None]:
        """p50/p99 in milliseconds over one locked copy of the window —
        THE one percentile computation, shared by the JSON snapshot and
        the Prometheus summary so the two views cannot drift."""
        if not samples:
            return {0.5: None, 0.99: None}
        arr = np.asarray(samples, np.float64) * 1000.0
        return {
            0.5: round(float(np.percentile(arr, 50)), 3),
            0.99: round(float(np.percentile(arr, 99)), 3),
        }

    def snapshot(self) -> dict:
        """One consistent view: counters plus percentiles over the
        current window, all in milliseconds."""
        with self._lock:
            samples = list(self._samples)
            count, total, worst = self._count, self._total, self._max
        pcts = self._percentiles_ms(samples)
        return {
            "count": count,
            "window": len(samples),
            "p50_ms": pcts[0.5],
            "p99_ms": pcts[0.99],
            "mean_ms": round(total / count * 1000.0, 3) if count else None,
            "max_ms": round(worst * 1000.0, 3) if count else None,
        }

    def summary(self) -> dict:
        """The reservoir reshaped for a registry Summary (the Prometheus
        quantile exposition): window percentiles + lifetime sum/count —
        all from ONE lock acquisition, so the exported sum never
        includes a sample the count excludes."""
        with self._lock:
            samples = list(self._samples)
            count, total = self._count, self._total
        return {
            "quantiles": self._percentiles_ms(samples),
            "sum": round(total * 1000.0, 3),
            "count": count,
        }


class _Pending:
    """One waiting request: its transformed rows, the predictor instance
    it resolved (the anti-stale-scatter token), the trace ID bound when
    it was submitted (the dispatcher thread has no request context — the
    ID must ride the entry), and the rendezvous."""

    __slots__ = (
        "pred", "x", "event", "result", "error", "t_enqueued", "trace_id"
    )

    def __init__(self, pred, x):
        from tpuflow.obs import current_trace_id

        self.pred = pred
        self.x = x
        self.event = threading.Event()
        self.result = None
        self.error: BaseException | None = None
        self.t_enqueued = time.monotonic()
        self.trace_id = current_trace_id()


class MicroBatcher:
    """Coalesces concurrent ``submit`` calls per artifact key into shared
    forward dispatches. ``run_batch(pred, x)`` is the one hook: it must
    return one output row per input row (the service passes the
    predictor's denormalizing forward)."""

    def __init__(
        self,
        run_batch,
        max_batch_rows: int = 128,
        max_wait_ms: float = 2.0,
        max_queue_rows: int = 8192,
        submit_timeout: float = 60.0,
        registry=None,
    ):
        from tpuflow.obs import DEFAULT_COUNT_BUCKETS, Registry

        if max_batch_rows < 1:
            raise ValueError(f"max_batch_rows must be >= 1, got {max_batch_rows}")
        if max_wait_ms < 0:
            raise ValueError(f"max_wait_ms must be >= 0, got {max_wait_ms}")
        self._run_batch = run_batch
        self.max_batch_rows = max_batch_rows
        self.max_wait_ms = max_wait_ms
        self.max_queue_rows = max_queue_rows
        self.submit_timeout = submit_timeout
        self._cond = threading.Condition()
        self._pending: dict[tuple, list[_Pending]] = {}
        self._queued_rows = 0
        self._stop = False
        # Registry-backed counters (tpuflow/obs): dispatches = device
        # calls made; coalesced_dispatches = those carrying > 1 request;
        # the batch-size histogram is the observable proof coalescing
        # actually happens under load. Increments happen under
        # self._cond's lock exactly where the old dict writes did, so
        # metrics() keeps its one-consistent-view discipline; the same
        # registry renders straight into /metrics?format=prometheus.
        # Default: a private run-scoped Registry, so parallel batchers
        # (tests, benchmarks) never bleed counts into each other.
        self.registry = registry if registry is not None else Registry()
        self._counters = {
            name: self.registry.counter(
                f"predict_batch_{name}_total", help
            )
            for name, help in (
                ("requests", "requests entering the micro-batch queue"),
                ("rejected", "submissions refused on a full queue"),
                ("dispatches", "device dispatches made"),
                ("coalesced_dispatches", "dispatches carrying > 1 request"),
                ("rows_dispatched", "total rows sent to the device"),
            )
        }
        self._depth_gauge = self.registry.gauge(
            "predict_batch_queue_depth_rows",
            "rows currently waiting to be coalesced",
            fn=lambda: self._queued_rows,
        )
        self._max_depth_gauge = self.registry.gauge(
            "predict_batch_max_queue_depth_rows",
            "high-water mark of rows waiting to be coalesced",
        )
        self._max_depth = 0
        self._size_hist = self.registry.histogram(
            "predict_batch_size",
            "requests coalesced per dispatch",
            buckets=DEFAULT_COUNT_BUCKETS,
        )
        # Exact requests-per-dispatch tallies for the JSON view (the
        # fixed-bucket registry histogram backs the Prometheus one).
        self._hist: dict[int, int] = {}
        self._thread = threading.Thread(
            target=self._loop, name="tpuflow-microbatch", daemon=True
        )
        self._thread.start()

    # ---- caller side ----

    def submit(self, key: tuple, pred, x) -> np.ndarray:
        """Enqueue ``x`` (rows already feature-transformed for ``pred``)
        and block until the dispatcher scatters this request's slice
        back. Raises the dispatch group's exception if the forward
        failed, and RuntimeError on a full queue or a closed batcher."""
        entry = _Pending(pred, x)
        with self._cond:
            if self._stop:
                raise RuntimeError("predict micro-batcher is closed")
            if self._queued_rows + len(x) > self.max_queue_rows:
                self._counters["rejected"].inc()
                raise RuntimeError(
                    f"predict micro-batch queue full "
                    f"({self._queued_rows} rows pending, max "
                    f"{self.max_queue_rows}); retry shortly"
                )
            self._counters["requests"].inc()
            self._pending.setdefault(key, []).append(entry)
            self._queued_rows += len(x)
            if self._queued_rows > self._max_depth:
                self._max_depth = self._queued_rows
                self._max_depth_gauge.set(self._max_depth)
            self._cond.notify_all()
        if not entry.event.wait(timeout=self.submit_timeout):
            raise RuntimeError(
                f"predict micro-batch dispatch timed out after "
                f"{self.submit_timeout:g}s (dispatcher wedged?)"
            )
        if entry.error is not None:
            raise entry.error
        return entry.result

    def metrics(self) -> dict:
        """Counter snapshot under the lock — one consistent view, built
        from the registry counters (the JSON keys are unchanged; the
        Prometheus view renders the same registry)."""
        with self._cond:
            return {
                "enabled": True,
                **{
                    name: int(c.value())
                    for name, c in self._counters.items()
                },
                "max_queue_depth_rows": self._max_depth,
                "queue_depth_rows": self._queued_rows,
                "batch_size_hist": dict(sorted(self._hist.items())),
                "max_batch_rows": self.max_batch_rows,
                "max_wait_ms": self.max_wait_ms,
            }

    def close(self) -> None:
        """Stop the dispatcher; pending entries are drained first so no
        in-flight caller is abandoned mid-wait."""
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        self._thread.join(timeout=10)

    # ---- dispatcher side ----

    def _due_key_locked(self, now: float):
        """(key, seconds-until-next-deadline): among keys whose oldest
        entry has aged past max_wait_ms or whose rows hit max_batch_rows,
        the one whose oldest entry has waited LONGEST — dict order would
        starve every other artifact behind one hot key that is always
        due (it never fully drains, so it never loses its slot). If none
        is due: how long the dispatcher may sleep before one will."""
        due_key, due_age, next_due = None, -1.0, None
        for key, entries in self._pending.items():
            rows = sum(len(e.x) for e in entries)
            age = now - entries[0].t_enqueued
            if rows >= self.max_batch_rows or age * 1000.0 >= self.max_wait_ms:
                if age > due_age:
                    due_key, due_age = key, age
                continue
            remaining = self.max_wait_ms / 1000.0 - age
            if next_due is None or remaining < next_due:
                next_due = remaining
        if due_key is not None:
            return due_key, 0.0
        return None, next_due

    def _drain_locked(self, key: tuple) -> list[_Pending]:
        """Take entries for ``key`` up to max_batch_rows (leaving the
        rest queued with their original enqueue times)."""
        entries = self._pending[key]
        taken, rows = [], 0
        while entries and rows < self.max_batch_rows:
            taken.append(entries.pop(0))
            rows += len(taken[-1].x)
        if not entries:
            del self._pending[key]
        self._queued_rows -= rows
        return taken

    def _loop(self) -> None:
        while True:
            with self._cond:
                while not self._pending:
                    if self._stop:
                        return
                    self._cond.wait()
                key, wait_s = self._due_key_locked(time.monotonic())
                if key is None and self._stop:
                    # Closing: drain promptly, don't sit out max_wait_ms.
                    key = next(iter(self._pending))
                if key is None:
                    # Nothing due yet: sleep until the earliest deadline
                    # (or an arrival/notify), then re-scan.
                    self._cond.wait(timeout=wait_s)
                    continue
                taken = self._drain_locked(key)
            self._dispatch(taken)

    def _dispatch(self, taken: list[_Pending]) -> None:
        # Group by predictor INSTANCE: entries at one key can straddle a
        # cache invalidation (retrain mid-flight), and a single forward
        # mixing old and new params would scatter stale predictions to
        # whichever side didn't match the batch. One dispatch per
        # distinct instance, in arrival order.
        from tpuflow.obs import record_span

        groups: dict[int, list[_Pending]] = {}
        for e in taken:
            groups.setdefault(id(e.pred), []).append(e)
        for group in groups.values():
            rows = sum(len(e.x) for e in group)
            t0 = time.perf_counter()
            failed = False
            try:
                # Concatenate inside the try: even a pathological shape
                # mismatch must fail THIS group, never kill the
                # dispatcher thread and wedge every later caller.
                xs = [e.x for e in group]
                x = np.concatenate(xs, axis=0) if len(xs) > 1 else xs[0]
                y = np.asarray(self._run_batch(group[0].pred, x))
                if len(y) != len(x):
                    raise RuntimeError(
                        f"micro-batch forward returned {len(y)} rows "
                        f"for {len(x)} inputs"
                    )
                offset = 0
                for e in group:
                    n = len(e.x)
                    e.result = y[offset : offset + n]
                    offset += n
            except BaseException as exc:  # scatter the failure, stay alive
                failed = True
                for e in group:
                    e.error = exc
            finally:
                with self._cond:
                    self._counters["dispatches"].inc()
                    self._counters["rows_dispatched"].inc(rows)
                    if len(group) > 1:
                        self._counters["coalesced_dispatches"].inc()
                    self._size_hist.observe(len(group))
                    self._hist[len(group)] = self._hist.get(len(group), 0) + 1
                # The coalesced-dispatch span: every trace ID this device
                # call answered, so one caller's request is linkable to
                # the shared dispatch that served it (forensics ring +
                # any test reading obs.recent_events()).
                record_span(
                    "predict.dispatch",
                    time.perf_counter() - t0,
                    hot=True,  # per-dispatch rate: the forensics hot ring
                    requests=len(group),
                    rows=rows,
                    ok=not failed,
                    trace_ids=[
                        e.trace_id for e in group if e.trace_id
                    ],
                )
                for e in group:
                    e.event.set()
