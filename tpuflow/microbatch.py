"""Cross-request batching for the serving fast path: micro + continuous.

The reference lineage's throughput lever is batching: SparkNet and BigDL
(PAPERS.md) both win by amortizing fixed per-dispatch overhead across
many rows of work. The serving path had none of it — every ``POST
/predict`` made its own jitted device call against the same params, so
N concurrent callers paid N dispatch overheads (and, on first touch, N
chances at an XLA compile) for work one dispatch could carry.

Two batchers share one coalescing contract:

- ``MicroBatcher`` — the wait-then-dispatch original: a single
  dispatcher thread drains a key's queue once ``max_wait_ms`` has
  passed since its oldest entry (or sooner, when ``max_batch_rows``
  accumulate). Simple, but the timer is a latency floor: every request
  pays up to ``max_wait_ms`` of deliberate waiting even when the device
  is idle.
- ``ContinuousBatcher`` — the async control plane's dispatch engine
  (docs/serving.md): one dispatch **lane** (thread) per artifact key,
  double-buffered — while a dispatch is in flight on the device, new
  rows accumulate in the lane's queue, and the moment the dispatch
  returns the lane drains EVERYTHING that arrived meanwhile into the
  next one. No timer: an idle lane dispatches a lone request
  immediately; a busy lane coalesces exactly as much as the device's
  own latency allows. Entries may carry a **deadline** (monotonic
  seconds): a request whose deadline passed while queued is failed with
  :class:`DeadlineExpired` at drain time and NEVER occupies a dispatch
  slot — shed load must not also waste device time.

Correctness constraints both dispatchers enforce (docs/serving.md):

- **No stale scatter across a retrain.** Every entry carries the
  predictor INSTANCE it resolved at enqueue time; a drain is grouped by
  instance, never just by key. When a retrain invalidates the cache
  mid-flight, requests that resolved the old predictor and requests
  that resolved the new one land in SEPARATE dispatches — each caller
  gets predictions from exactly the params it resolved, exactly as the
  unbatched path would have answered it.
- **Errors scatter too.** A failing forward fails every request in its
  dispatch group (and only that group); the dispatcher thread survives.
- **Bounded queue.** Past ``max_queue_rows`` pending rows, ``submit``
  raises instead of accepting unbounded backlog (the JobRunner 429
  discipline, applied to predicts).

Degraded (Gilbert-fallback) answers must never be coalesced into model
batches — that gate lives in ``PredictService.predict``, which bypasses
this module entirely for degraded predictors.

``LatencyStats`` is the per-request latency accounting that rides along:
a bounded reservoir of recent request latencies, snapshotted into
p50/p99 for ``/metrics``.
"""

from __future__ import annotations

import threading
import time
from collections import deque

import numpy as np


class LatencyStats:
    """Bounded reservoir of recent request latencies (seconds in,
    milliseconds out). ``window`` bounds memory and keeps the
    percentiles describing RECENT traffic, not the whole process
    lifetime."""

    def __init__(self, window: int = 2048):
        self._lock = threading.Lock()
        self._samples: deque[float] = deque(maxlen=window)
        self._count = 0
        self._total = 0.0
        self._max = 0.0

    def record(self, seconds: float) -> None:
        with self._lock:
            self._samples.append(seconds)
            self._count += 1
            self._total += seconds
            if seconds > self._max:
                self._max = seconds

    @staticmethod
    def _percentiles_ms(samples: list[float]) -> dict[float, float | None]:
        """p50/p99 in milliseconds over one locked copy of the window —
        THE one percentile computation, shared by the JSON snapshot and
        the Prometheus summary so the two views cannot drift."""
        if not samples:
            return {0.5: None, 0.99: None}
        arr = np.asarray(samples, np.float64) * 1000.0
        return {
            0.5: round(float(np.percentile(arr, 50)), 3),
            0.99: round(float(np.percentile(arr, 99)), 3),
        }

    def snapshot(self) -> dict:
        """One consistent view: counters plus percentiles over the
        current window, all in milliseconds."""
        with self._lock:
            samples = list(self._samples)
            count, total, worst = self._count, self._total, self._max
        pcts = self._percentiles_ms(samples)
        return {
            "count": count,
            "window": len(samples),
            "p50_ms": pcts[0.5],
            "p99_ms": pcts[0.99],
            "mean_ms": round(total / count * 1000.0, 3) if count else None,
            "max_ms": round(worst * 1000.0, 3) if count else None,
        }

    def summary(self) -> dict:
        """The reservoir reshaped for a registry Summary (the Prometheus
        quantile exposition): window percentiles + lifetime sum/count —
        all from ONE lock acquisition, so the exported sum never
        includes a sample the count excludes."""
        with self._lock:
            samples = list(self._samples)
            count, total = self._count, self._total
        return {
            "quantiles": self._percentiles_ms(samples),
            "sum": round(total * 1000.0, 3),
            "count": count,
        }


class DeadlineExpired(RuntimeError):
    """A request's deadline passed before its dispatch began. Raised to
    the submitting caller; the request never occupied a dispatch slot."""


class QueueFull(RuntimeError):
    """A bounded-capacity rejection — the row queue or the lane table is
    full. Capacity shedding, not caller error: HTTP front ends map this
    to 503 retry-with-backoff semantics (a typed seam, so an unrelated
    error whose message happens to contain "full" is never misreported
    as a shed)."""


class _Pending:
    """One waiting request: its transformed rows, the predictor instance
    it resolved (the anti-stale-scatter token), the trace ID bound when
    it was submitted (the dispatcher thread has no request context — the
    ID must ride the entry), an optional deadline (monotonic seconds;
    expired entries are shed at drain time, never dispatched), and the
    rendezvous — a threading.Event for blocking callers plus an optional
    ``on_done`` callback for event-loop callers (the asyncio front end
    bridges it to a Future instead of parking a thread)."""

    __slots__ = (
        "pred", "x", "event", "result", "error", "t_enqueued", "trace_id",
        "deadline", "on_done",
    )

    def __init__(self, pred, x, deadline: float | None = None, on_done=None):
        from tpuflow.obs import current_trace_id

        self.pred = pred
        self.x = x
        self.event = threading.Event()
        self.result = None
        self.error: BaseException | None = None
        self.t_enqueued = time.monotonic()
        self.trace_id = current_trace_id()
        self.deadline = deadline
        self.on_done = on_done

    def expired(self, now: float) -> bool:
        return self.deadline is not None and now > self.deadline

    def signal(self) -> None:
        """Publish result/error: wake the blocking waiter and fire the
        event-loop callback (guarded — a dead loop must not kill the
        dispatcher)."""
        self.event.set()
        if self.on_done is not None:
            try:
                self.on_done(self)
            except Exception:
                pass

    def wait(self, timeout: float):
        """Block until signalled; returns the result or raises the
        dispatch group's error (the blocking-caller half of the
        rendezvous, shared by both batchers' ``submit``)."""
        if not self.event.wait(timeout=timeout):
            raise RuntimeError(
                f"predict batch dispatch timed out after "
                f"{timeout:g}s (dispatcher wedged?)"
            )
        if self.error is not None:
            raise self.error
        return self.result


class _BatcherBase:
    """Shared substrate of the two batchers: the obs surface (counters,
    depth gauges, batch-size histogram — one family-name set, so either
    batcher renders identically into /metrics), the bounded-queue
    bookkeeping, and the instance-grouped dispatch+scatter. Subclasses
    own the draining policy — WHEN a dispatch happens and what it
    takes."""

    def __init__(self, run_batch, max_batch_rows, max_queue_rows,
                 submit_timeout, registry):
        from tpuflow.obs import DEFAULT_COUNT_BUCKETS, Registry

        if max_batch_rows < 1:
            raise ValueError(f"max_batch_rows must be >= 1, got {max_batch_rows}")
        self._run_batch = run_batch
        self.max_batch_rows = max_batch_rows
        self.max_queue_rows = max_queue_rows
        self.submit_timeout = submit_timeout
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._queued_rows = 0
        self._stop = False
        # Registry-backed counters (tpuflow/obs): dispatches = device
        # calls made; coalesced_dispatches = those carrying > 1 request;
        # the batch-size histogram is the observable proof coalescing
        # actually happens under load. Increments happen under
        # self._cond's lock exactly where the old dict writes did, so
        # metrics() keeps its one-consistent-view discipline; the same
        # registry renders straight into /metrics?format=prometheus.
        # Default: a private run-scoped Registry, so parallel batchers
        # (tests, benchmarks) never bleed counts into each other.
        self.registry = registry if registry is not None else Registry()
        self._counters = {
            name: self.registry.counter(
                f"predict_batch_{name}_total", help
            )
            for name, help in (
                ("requests", "requests entering the batch queue"),
                ("rejected", "submissions refused on a full queue"),
                ("dispatches", "device dispatches made"),
                ("coalesced_dispatches", "dispatches carrying > 1 request"),
                ("rows_dispatched", "total rows sent to the device"),
                ("expired", "requests shed at drain time on a passed "
                            "deadline (never dispatched)"),
            )
        }
        # Pull-gauge callbacks run on the SCRAPE thread (registry
        # collect), so they must take the batcher lock like any other
        # cross-thread reader — the TPF016 discipline. Safe: collect()
        # holds no metric-family lock while evaluating a callback, and
        # the batcher's own lock→counter-lock order is one-directional.
        self._depth_gauge = self.registry.gauge(
            "predict_batch_queue_depth_rows",
            "rows currently waiting to be coalesced",
            fn=self._read_queued_rows,
        )
        self._max_depth_gauge = self.registry.gauge(
            "predict_batch_max_queue_depth_rows",
            "high-water mark of rows waiting to be coalesced",
        )
        self._max_depth = 0
        self._inflight = 0
        self._inflight_gauge = self.registry.gauge(
            "predict_batch_inflight_dispatches",
            "device dispatches currently executing",
            fn=self._read_inflight,
        )
        self._size_hist = self.registry.histogram(
            "predict_batch_size",
            "requests coalesced per dispatch",
            buckets=DEFAULT_COUNT_BUCKETS,
        )
        # Exact requests-per-dispatch tallies for the JSON view (the
        # fixed-bucket registry histogram backs the Prometheus one).
        self._hist: dict[int, int] = {}

    def _read_queued_rows(self) -> int:
        """Scrape-thread read of the queue depth, under the lock (the
        dispatcher mutates ``_queued_rows`` under ``self._cond``, which
        wraps this same mutex)."""
        with self._lock:
            return self._queued_rows

    def _read_inflight(self) -> int:
        with self._lock:
            return self._inflight

    def _admit_locked(self, entry: _Pending, what: str) -> None:
        """Bounded-queue admission under ``self._cond`` (caller holds
        it): raises on a closed batcher or a full queue, else counts the
        entry in."""
        if self._stop:
            raise RuntimeError(f"predict {what} is closed")
        if self._queued_rows + len(entry.x) > self.max_queue_rows:
            self._counters["rejected"].inc()
            raise QueueFull(
                f"predict batch queue full "
                f"({self._queued_rows} rows pending, max "
                f"{self.max_queue_rows}); retry shortly"
            )
        self._counters["requests"].inc()
        self._queued_rows += len(entry.x)
        if self._queued_rows > self._max_depth:
            self._max_depth = self._queued_rows
            self._max_depth_gauge.set(self._max_depth)

    def _metrics_locked(self) -> dict:
        return {
            "enabled": True,
            **{
                name: int(c.value())
                for name, c in self._counters.items()
            },
            "max_queue_depth_rows": self._max_depth,
            "queue_depth_rows": self._queued_rows,
            "inflight_dispatches": self._inflight,
            "batch_size_hist": dict(sorted(self._hist.items())),
            "max_batch_rows": self.max_batch_rows,
        }

    def _shed_expired(self, expired: list[_Pending]) -> None:
        """Fail deadline-expired entries to their callers (outside the
        lock — signal() may run an event-loop callback). Their rows were
        already uncounted by the drain; the device never sees them."""
        for e in expired:
            waited = time.monotonic() - e.t_enqueued
            e.error = DeadlineExpired(
                f"request deadline expired after {waited * 1000:.1f}ms "
                "in the batch queue (never dispatched)"
            )
            e.signal()

    def _dispatch(self, taken: list[_Pending]) -> None:
        # Group by predictor INSTANCE: entries at one key can straddle a
        # cache invalidation (retrain mid-flight), and a single forward
        # mixing old and new params would scatter stale predictions to
        # whichever side didn't match the batch. One dispatch per
        # distinct instance, in arrival order.
        from tpuflow.obs import record_span

        groups: dict[int, list[_Pending]] = {}
        for e in taken:
            groups.setdefault(id(e.pred), []).append(e)
        for group in groups.values():
            rows = sum(len(e.x) for e in group)
            t0 = time.perf_counter()
            failed = False
            try:
                # Concatenate inside the try: even a pathological shape
                # mismatch must fail THIS group, never kill the
                # dispatcher thread and wedge every later caller.
                xs = [e.x for e in group]
                x = np.concatenate(xs, axis=0) if len(xs) > 1 else xs[0]
                y = np.asarray(self._run_batch(group[0].pred, x))
                if len(y) != len(x):
                    raise RuntimeError(
                        f"batched forward returned {len(y)} rows "
                        f"for {len(x)} inputs"
                    )
                offset = 0
                for e in group:
                    n = len(e.x)
                    e.result = y[offset : offset + n]
                    offset += n
            except BaseException as exc:  # scatter the failure, stay alive
                failed = True
                for e in group:
                    e.error = exc
            finally:
                with self._cond:
                    self._counters["dispatches"].inc()
                    self._counters["rows_dispatched"].inc(rows)
                    if len(group) > 1:
                        self._counters["coalesced_dispatches"].inc()
                    self._size_hist.observe(len(group))
                    self._hist[len(group)] = self._hist.get(len(group), 0) + 1
                # The coalesced-dispatch span: every trace ID this device
                # call answered, so one caller's request is linkable to
                # the shared dispatch that served it (forensics ring +
                # any test reading obs.recent_events()).
                record_span(
                    "predict.dispatch",
                    time.perf_counter() - t0,
                    hot=True,  # per-dispatch rate: the forensics hot ring
                    requests=len(group),
                    rows=rows,
                    ok=not failed,
                    trace_ids=[
                        e.trace_id for e in group if e.trace_id
                    ],
                )
                for e in group:
                    e.signal()


class MicroBatcher(_BatcherBase):
    """Coalesces concurrent ``submit`` calls per artifact key into shared
    forward dispatches on a ``max_wait_ms`` timer. ``run_batch(pred, x)``
    is the one hook: it must return one output row per input row (the
    service passes the predictor's denormalizing forward)."""

    def __init__(
        self,
        run_batch,
        max_batch_rows: int = 128,
        max_wait_ms: float = 2.0,
        max_queue_rows: int = 8192,
        submit_timeout: float = 60.0,
        registry=None,
    ):
        if max_wait_ms < 0:
            raise ValueError(f"max_wait_ms must be >= 0, got {max_wait_ms}")
        super().__init__(
            run_batch, max_batch_rows, max_queue_rows, submit_timeout,
            registry,
        )
        self.max_wait_ms = max_wait_ms
        self._pending: dict[tuple, list[_Pending]] = {}
        self._thread = threading.Thread(
            target=self._loop, name="tpuflow-microbatch", daemon=True
        )
        self._thread.start()

    # ---- caller side ----

    def submit(self, key: tuple, pred, x) -> np.ndarray:
        """Enqueue ``x`` (rows already feature-transformed for ``pred``)
        and block until the dispatcher scatters this request's slice
        back. Raises the dispatch group's exception if the forward
        failed, and RuntimeError on a full queue or a closed batcher."""
        entry = _Pending(pred, x)
        with self._cond:
            self._admit_locked(entry, "micro-batcher")
            self._pending.setdefault(key, []).append(entry)
            self._cond.notify_all()
        return entry.wait(self.submit_timeout)

    def metrics(self) -> dict:
        """Counter snapshot under the lock — one consistent view, built
        from the registry counters (the JSON keys are unchanged; the
        Prometheus view renders the same registry)."""
        with self._cond:
            return {
                **self._metrics_locked(),
                "mode": "micro",
                "max_wait_ms": self.max_wait_ms,
            }

    def close(self) -> None:
        """Stop the dispatcher; pending entries are drained first so no
        in-flight caller is abandoned mid-wait."""
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        self._thread.join(timeout=10)

    # ---- dispatcher side ----

    def _due_key_locked(self, now: float):
        """(key, seconds-until-next-deadline): among keys whose oldest
        entry has aged past max_wait_ms or whose rows hit max_batch_rows,
        the one whose oldest entry has waited LONGEST — dict order would
        starve every other artifact behind one hot key that is always
        due (it never fully drains, so it never loses its slot). If none
        is due: how long the dispatcher may sleep before one will."""
        due_key, due_age, next_due = None, -1.0, None
        for key, entries in self._pending.items():
            rows = sum(len(e.x) for e in entries)
            age = now - entries[0].t_enqueued
            if rows >= self.max_batch_rows or age * 1000.0 >= self.max_wait_ms:
                if age > due_age:
                    due_key, due_age = key, age
                continue
            remaining = self.max_wait_ms / 1000.0 - age
            if next_due is None or remaining < next_due:
                next_due = remaining
        if due_key is not None:
            return due_key, 0.0
        return None, next_due

    def _drain_locked(self, key: tuple) -> list[_Pending]:
        """Take entries for ``key`` up to max_batch_rows (leaving the
        rest queued with their original enqueue times)."""
        entries = self._pending[key]
        taken, rows = [], 0
        while entries and rows < self.max_batch_rows:
            taken.append(entries.pop(0))
            rows += len(taken[-1].x)
        if not entries:
            del self._pending[key]
        self._queued_rows -= rows
        return taken

    def _loop(self) -> None:
        while True:
            with self._cond:
                while not self._pending:
                    if self._stop:
                        return
                    self._cond.wait()
                key, wait_s = self._due_key_locked(time.monotonic())
                if key is None and self._stop:
                    # Closing: drain promptly, don't sit out max_wait_ms.
                    key = next(iter(self._pending))
                if key is None:
                    # Nothing due yet: sleep until the earliest deadline
                    # (or an arrival/notify), then re-scan.
                    self._cond.wait(timeout=wait_s)
                    continue
                taken = self._drain_locked(key)
                self._inflight += 1
            try:
                self._dispatch(taken)
            finally:
                with self._cond:
                    self._inflight -= 1


class _Lane:
    """One artifact's dispatch lane: its queue of pending entries, the
    thread that drives its double-buffered dispatch loop, and a
    per-lane condition (sharing the batcher's one lock, so every
    invariant still holds under it) — an enqueue wakes exactly the
    lane it fed, not every resident lane.

    ``inflight_rows``/``dispatches`` are the per-lane load accounting
    the replica data plane selects on (``lane_outstanding``): rows a
    lane has taken but not yet answered count against it exactly like
    rows still queued, so join-shortest-queue sees the dispatch a lane
    is busy running, not just its backlog."""

    __slots__ = (
        "entries", "thread", "closing", "cond", "inflight_rows",
        "dispatches",
    )

    def __init__(self, lock: threading.Lock):
        self.entries: list[_Pending] = []
        self.thread: threading.Thread | None = None
        self.closing = False
        self.cond = threading.Condition(lock)
        self.inflight_rows = 0
        self.dispatches = 0


class ContinuousBatcher(_BatcherBase):
    """Continuous (double-buffered) batching: one dispatch lane per
    artifact key. A lane dispatches the moment it is free and its queue
    is non-empty — no ``max_wait_ms`` timer — so rows that arrive while
    a device dispatch is in flight are admitted into the NEXT dispatch
    the instant the previous one returns. Lone requests on an idle lane
    ship immediately (no deliberate latency floor); coalescing emerges
    exactly when the device is the bottleneck, which is the only time it
    helps.

    Deadlines: ``submit``/``enqueue`` accept a monotonic ``deadline``;
    entries whose deadline passed while queued are failed with
    :class:`DeadlineExpired` at drain time and never occupy a dispatch
    slot (counted by ``predict_batch_expired_total``).

    Lanes are bounded (``max_lanes``): past that many distinct artifact
    keys, submissions for NEW keys are refused — the thread-count
    analogue of the bounded row queue. ``close_lane(key)`` retires one
    lane (the LRU-spill hook: the service closes an artifact's lane when
    it evicts the artifact); its queued entries still drain first. A
    lane idle for ``lane_idle_s`` with an empty queue retires ITSELF —
    the table self-heals without an eviction policy upstream, so a
    long-tail of once-touched artifacts can never pin all ``max_lanes``
    slots (and their parked threads) forever.
    """

    def __init__(
        self,
        run_batch,
        max_batch_rows: int = 256,
        max_queue_rows: int = 8192,
        max_lanes: int = 32,
        lane_idle_s: float = 60.0,
        submit_timeout: float = 60.0,
        registry=None,
    ):
        super().__init__(
            run_batch, max_batch_rows, max_queue_rows, submit_timeout,
            registry,
        )
        if max_lanes < 1:
            raise ValueError(f"max_lanes must be >= 1, got {max_lanes}")
        if lane_idle_s <= 0:
            raise ValueError(f"lane_idle_s must be > 0, got {lane_idle_s}")
        self.max_lanes = max_lanes
        self.lane_idle_s = lane_idle_s
        self._lanes: dict[tuple, _Lane] = {}
        self._lanes_gauge = self.registry.gauge(
            "predict_batch_lanes",
            "artifact dispatch lanes currently resident",
            fn=self._read_lanes,
        )
        # Optional per-lane dispatch hook: called AFTER each lane
        # dispatch completes with (key, requests, rows). The serving
        # replica plane hangs its replica-labeled dispatch counters
        # here; the batcher itself stays replica-agnostic.
        self.on_lane_dispatch = None

    def _read_lanes(self) -> int:
        """Scrape-thread read of the resident-lane count, under the
        lock (lanes open/retire under ``self._cond``)."""
        with self._lock:
            return len(self._lanes)

    # ---- caller side ----

    def enqueue(
        self, key: tuple, pred, x, deadline: float | None = None,
        on_done=None,
    ) -> _Pending:
        """Admit one request into ``key``'s lane without blocking on the
        result: returns the entry, whose ``event`` fires (and ``on_done``
        runs) when the dispatch scatters back. The asyncio front end's
        seam — it bridges ``on_done`` to a Future instead of parking an
        event-loop thread. Raises RuntimeError when the row queue or the
        lane table is full (load shedding, not backlog)."""
        entry = _Pending(pred, x, deadline=deadline, on_done=on_done)
        with self._cond:
            lane = self._lanes.get(key)
            # A closing lane's key reuses its table slot, so only a
            # genuinely NEW key can overflow the table. "retry shortly"
            # is honest: idle lanes retire after lane_idle_s.
            if lane is None and len(self._lanes) >= self.max_lanes:
                self._counters["rejected"].inc()
                raise QueueFull(
                    f"no free dispatch lane ({len(self._lanes)} "
                    f"artifact lanes resident, max {self.max_lanes}); "
                    "retry shortly"
                )
            # Admit BEFORE opening a lane: a full-queue rejection must
            # not leak an empty lane (+ its parked thread) that counts
            # against max_lanes forever.
            self._admit_locked(entry, "continuous batcher")
            if lane is None or lane.closing:
                lane = self._open_lane_locked(key)
            lane.entries.append(entry)
            # Wake only THIS lane's thread: notify_all on the shared
            # condition is O(resident lanes) context switches per
            # request — on the exact path whose p99 this module exists
            # to protect.
            lane.cond.notify()
        return entry

    def submit(
        self, key: tuple, pred, x, deadline: float | None = None
    ) -> np.ndarray:
        """Blocking enqueue-and-wait (the MicroBatcher-compatible shape
        PredictService calls). Raises the dispatch group's exception,
        :class:`DeadlineExpired` on a shed deadline, and RuntimeError on
        a full queue or a closed batcher."""
        return self.enqueue(key, pred, x, deadline=deadline).wait(
            self.submit_timeout
        )

    def metrics(self) -> dict:
        with self._cond:
            return {
                **self._metrics_locked(),
                "mode": "continuous",
                "lanes": len(self._lanes),
            }

    def lane_outstanding(self, key: tuple) -> int:
        """Rows this lane owes answers for: queued + currently
        dispatching. THE join-shortest-queue load signal (an absent
        lane reads as 0 — an idle replica is maximally attractive)."""
        with self._cond:
            lane = self._lanes.get(key)
            if lane is None:
                return 0
            return sum(len(e.x) for e in lane.entries) + lane.inflight_rows

    def lane_keys(self, prefix: tuple = ()) -> list[tuple]:
        """Resident lane keys, optionally filtered to those extending
        ``prefix`` (an artifact's replica lanes share its key as their
        prefix — the replica-aware observability/teardown seam)."""
        with self._cond:
            return [
                k for k in self._lanes if k[: len(prefix)] == prefix
            ]

    def lane_stats(self, prefix: tuple = ()) -> dict[tuple, dict]:
        """Per-lane load snapshot under one lock acquisition: queued
        rows, in-flight rows, lifetime dispatches — the JSON /metrics
        view of what ``lane_outstanding`` selects on."""
        with self._cond:
            return {
                k: {
                    "queued_rows": sum(len(e.x) for e in lane.entries),
                    "inflight_rows": lane.inflight_rows,
                    "dispatches": lane.dispatches,
                }
                for k, lane in self._lanes.items()
                if k[: len(prefix)] == prefix
            }

    def close_lane(self, key: tuple) -> None:
        """Retire one artifact's lane (after the service evicts the
        artifact): queued entries still drain, then the thread exits.
        A later submit for the same key opens a fresh lane."""
        with self._cond:
            lane = self._lanes.get(key)
            if lane is not None:
                lane.closing = True
                lane.cond.notify_all()

    def retire_lane(self, key: tuple, timeout: float = 5.0) -> bool:
        """``close_lane`` plus a bounded wait for the lane thread to
        actually drain and drop its table entry — the synchronous seam
        replica downscaling needs (a retired replica's lane must finish
        its queued work before the replica object is released). Returns
        True once the entry is gone (or was never there), False if the
        drain outlived ``timeout``."""
        deadline = time.monotonic() + timeout
        with self._cond:
            lane = self._lanes.get(key)
            if lane is None:
                return True
            lane.closing = True
            lane.cond.notify_all()
            while self._lanes.get(key) is lane:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cond.wait(remaining)
        return True

    def close_lanes_for(self, prefix: tuple) -> int:
        """Retire EVERY lane whose key extends ``prefix`` — the
        replica-aware spill/reload hook: an artifact's eviction must
        drain all of its replica lanes (keys ``prefix + (replica,)``)
        as well as a plain ``prefix`` lane, with zero dropped entries
        (each lane's queue drains before its thread exits). Returns how
        many lanes were told to close."""
        with self._cond:
            matched = [
                lane for k, lane in self._lanes.items()
                if k[: len(prefix)] == prefix
            ]
            for lane in matched:
                lane.closing = True
                lane.cond.notify_all()
        return len(matched)

    def close(self) -> None:
        """Stop every lane; queued entries are drained first so no
        in-flight caller is abandoned mid-wait."""
        with self._cond:
            self._stop = True
            threads = []
            for lane in self._lanes.values():
                lane.cond.notify_all()
                if lane.thread is not None:
                    threads.append(lane.thread)
        for t in threads:
            t.join(timeout=10)

    # ---- lane side ----

    def _open_lane_locked(self, key: tuple) -> _Lane:
        lane = _Lane(self._lock)
        lane.thread = threading.Thread(
            target=self._lane_loop, args=(key, lane),
            name=f"tpuflow-lane-{'/'.join(str(k) for k in key)}"[:48],
            daemon=True,
        )
        self._lanes[key] = lane
        lane.thread.start()
        return lane

    def _drain_lane_locked(
        self, lane: _Lane, now: float
    ) -> tuple[list[_Pending], list[_Pending]]:
        """Take up to ``max_batch_rows`` live rows (leaving the rest
        queued, original enqueue order) plus EVERY expired entry seen on
        the way — expired entries are uncounted here and never reach a
        dispatch."""
        taken: list[_Pending] = []
        expired: list[_Pending] = []
        rows = 0
        while lane.entries and rows < self.max_batch_rows:
            e = lane.entries[0]
            if e.expired(now):
                lane.entries.pop(0)
                self._queued_rows -= len(e.x)
                self._counters["expired"].inc()
                expired.append(e)
                continue
            if taken and rows + len(e.x) > self.max_batch_rows:
                break  # keep the lone-oversize-request case dispatchable
            lane.entries.pop(0)
            self._queued_rows -= len(e.x)
            taken.append(e)
            rows += len(e.x)
        return taken, expired

    def _lane_loop(self, key: tuple, lane: _Lane) -> None:
        while True:
            with self._cond:
                while not lane.entries and not (lane.closing or self._stop):
                    notified = lane.cond.wait(timeout=self.lane_idle_s)
                    if not notified and not lane.entries and not (
                        lane.closing or self._stop
                    ):
                        # Idle past lane_idle_s with nothing queued:
                        # retire (under the lock, so no enqueue can be
                        # appending concurrently). The next submit for
                        # this key opens a fresh lane. notify_all wakes
                        # any retire_lane() waiter watching for the
                        # table entry to go.
                        if self._lanes.get(key) is lane:
                            del self._lanes[key]
                            self._cond.notify_all()
                        return
                if not lane.entries and (lane.closing or self._stop):
                    # Drained and retiring: drop the table entry only if
                    # it is still OURS (a fresh lane may have replaced a
                    # closing one under the same key).
                    if self._lanes.get(key) is lane:
                        del self._lanes[key]
                        self._cond.notify_all()
                    return
                taken, expired = self._drain_lane_locked(
                    lane, time.monotonic()
                )
                if taken:
                    self._inflight += 1
                    lane.inflight_rows = sum(len(e.x) for e in taken)
            self._shed_expired(expired)
            if taken:
                try:
                    self._dispatch(taken)
                finally:
                    with self._cond:
                        self._inflight -= 1
                        lane.inflight_rows = 0
                        lane.dispatches += 1
                        hook = self.on_lane_dispatch
                if hook is not None:
                    # Outside the lock (the hook records metrics, which
                    # take their own locks); guarded — a broken hook
                    # must not kill the lane thread.
                    try:
                        hook(
                            key, len(taken),
                            sum(len(e.x) for e in taken),
                        )
                    except Exception:
                        pass
