"""SLO-driven serving autoscaler: close the observe-decide-act loop.

PR 3 gave the daemons an SLO engine (burn rate + error budget scored at
scrape time); the history plane (``tpuflow/obs/history.py``) now keeps
those scores over time. This module is the actuator that reads them
back: an :class:`ObservingController` in the
:class:`~tpuflow.train.autotune.OccupancyAutotuner` mold — a
hill-climbing control loop with hysteresis, judged moves, and a freeze
escape hatch — pointed at the serving control plane instead of the
training step.

The control surface is four runtime seams on
:class:`~tpuflow.serve_async.AsyncServer` (each a single GIL-atomic
store, effective on the next request):

- ``set_replicas``        — the replica data plane width
  (``serve_replica.ReplicaSet.resize`` under the hood; retired lanes
  drain before their params release).
- ``set_max_inflight``    — the admission bound.
- ``set_hedge_ms``        — hedged re-dispatch (dropped under
  pressure: hedging multiplies load exactly when load is the problem).
- ``set_drift_threshold`` — drift-admission strictness (tightened
  under pressure: far-out-of-distribution requests are shed earlier,
  protecting the budget for in-distribution traffic).

Decision policy (one move per tick, never a flap):

- **Hot** — windowed-mean ``slo_burn_rate`` (worst objective) at or
  past ``burn_high``, or error budget at/under ``budget_floor`` — for
  ``hold_ticks`` consecutive ticks: climb the up ladder (replicas →
  admission → drop hedge → tighten drift), first rung with headroom.
- **Calm** — burn at/under ``burn_low`` with budget healthy — for
  ``hold_ticks`` ticks and not frozen: climb down in reverse (relax
  drift → restore hedge → lower admission → retire a replica).
- A replica **down**-move is *judged*: it must survive
  ``judge_ticks`` ticks without the system going hot. Going hot
  mid-judgment **reverts** the move and freezes further down-moves for
  ``freeze_s`` — at most one direction reversal per load regime.
- **Hard availability floor**: ``min_replicas`` / ``min_inflight`` are
  clamps on every move; a budget at/under ``budget_floor`` is treated
  as hot (the controller adds capacity, never trims it).

Every decision is an ``autoscale.step`` span (trail + forensics via
``record_span``) and a ``serve_autoscale_steps_total{action=}``
increment; :meth:`ObservingController.summary` is the ``autoscale``
slice of the daemon's /metrics JSON. The loop waits on its stop event
— never a bare ``time.sleep`` (TPF022) — so tests drive :meth:`step`
with a fake clock and shutdown is drillable.

Knobs resolve defaults <- ``TPUFLOW_SERVE_AUTOSCALE_<KEY>`` env <-
explicit block (the autotune precedent); malformed env values raise
naming the variable and the expected form (tpuflow/utils/env.py).
"""

from __future__ import annotations

import threading
import time

# Every key has a TPUFLOW_SERVE_AUTOSCALE_<KEY> env spelling that
# supplies the default when the block leaves it unset; an explicit
# block value always wins (the TPUFLOW_AUTOTUNE_* precedent).
AUTOSCALE_DEFAULTS: dict = {
    "interval_s": 5.0,     # control-loop cadence (stop-event wait)
    "window_s": 30.0,      # burn-rate window scored each tick
    "warmup_ticks": 2,     # ticks observed before the first move
    "hold_ticks": 2,       # consecutive hot/calm ticks a move needs
    "judge_ticks": 2,      # ticks a replica down-move must survive
    "burn_high": 1.0,      # sustained burn >= this reads as hot
    "burn_low": 0.25,      # sustained burn <= this reads as calm
    "budget_floor": 0.1,   # budget fraction <= this reads as hot
    "freeze_s": 60.0,      # down-moves frozen after a revert
    "min_replicas": 1,     # hard availability floor (never crossed)
    "max_replicas": 8,
    "min_inflight": 8,     # hard admission floor (never crossed)
    "max_inflight": 1024,
    "max_moves": 0,        # total moves before freezing (0 = unbounded)
}

_AUTOSCALE_INT_KEYS = {
    # key -> minimum
    "warmup_ticks": 0,
    "hold_ticks": 1,
    "judge_ticks": 1,
    "min_replicas": 1,
    "max_replicas": 1,
    "min_inflight": 1,
    "max_inflight": 1,
    "max_moves": 0,
}
_AUTOSCALE_FLOAT_KEYS = {
    # key -> (minimum, form)
    "interval_s": (0.05, "a control cadence in seconds >= 0.05"),
    "window_s": (1.0, "a scoring window in seconds >= 1"),
    "burn_high": (1e-9, "a positive burn-rate threshold"),
    "burn_low": (0.0, "a non-negative burn-rate threshold"),
    "budget_floor": (0.0, "a budget fraction in [0, 1)"),
    "freeze_s": (0.0, "a non-negative freeze window in seconds"),
}


def validate_autoscale_block(block) -> list[str]:
    """Every problem with an ``autoscale`` config block, as messages
    (empty = valid). Never raises — preflight passes report all
    findings at once; :func:`resolve_autoscale` turns them into the
    fail-loud raise for runtime callers."""
    if not isinstance(block, dict):
        return [
            f"autoscale must be a dict config block (or {{}} for "
            f"defaults), got {type(block).__name__}"
        ]
    out = []
    unknown = sorted(set(block) - set(AUTOSCALE_DEFAULTS))
    if unknown:
        out.append(
            f"unknown autoscale key(s) {unknown}; known: "
            f"{sorted(AUTOSCALE_DEFAULTS)}"
        )
    for key, minimum in _AUTOSCALE_INT_KEYS.items():
        if key not in block:
            continue
        value = block[key]
        if isinstance(value, bool) or not isinstance(value, int):
            out.append(
                f"autoscale.{key} must be an integer >= {minimum}, "
                f"got {value!r}"
            )
        elif value < minimum:
            out.append(
                f"autoscale.{key} must be >= {minimum}, got {value}"
            )
    for key, (minimum, form) in _AUTOSCALE_FLOAT_KEYS.items():
        if key not in block:
            continue
        value = block[key]
        if isinstance(value, bool) or not isinstance(value, (int, float)):
            out.append(f"autoscale.{key} must be {form}, got {value!r}")
        elif float(value) < minimum:
            out.append(f"autoscale.{key} must be {form}, got {value}")
    floor = block.get("budget_floor")
    if isinstance(floor, (int, float)) and not isinstance(floor, bool):
        if not (0 <= float(floor) < 1):
            out.append(
                f"autoscale.budget_floor must be in [0, 1), got {floor}"
            )

    def _pair(lo_key, hi_key):
        lo = block.get(lo_key, AUTOSCALE_DEFAULTS[lo_key])
        hi = block.get(hi_key, AUTOSCALE_DEFAULTS[hi_key])
        if (
            isinstance(lo, (int, float)) and isinstance(hi, (int, float))
            and not isinstance(lo, bool) and not isinstance(hi, bool)
            and lo > hi
        ):
            out.append(
                f"autoscale.{lo_key} {lo} exceeds autoscale.{hi_key} {hi}"
            )

    _pair("min_replicas", "max_replicas")
    _pair("min_inflight", "max_inflight")
    _pair("burn_low", "burn_high")
    return out


def _env_knobs() -> dict:
    """The ``TPUFLOW_SERVE_AUTOSCALE_*`` env family, validated at read
    time through tpuflow/utils/env.py (a malformed value raises naming
    the variable and the expected form). Returns only the keys the
    environment actually sets — block values win over these."""
    from tpuflow.utils.env import env_num

    out: dict = {}
    for key, minimum in _AUTOSCALE_INT_KEYS.items():
        var = f"TPUFLOW_SERVE_AUTOSCALE_{key.upper()}"
        value = env_num(
            var, None, int, minimum=minimum,
            form=f"an integer >= {minimum}",
        )
        if value is not None:
            out[key] = int(value)
    for key, (minimum, form) in _AUTOSCALE_FLOAT_KEYS.items():
        var = f"TPUFLOW_SERVE_AUTOSCALE_{key.upper()}"
        value = env_num(var, None, float, minimum=minimum, form=form)
        if value is not None:
            if key == "budget_floor" and value >= 1:
                raise ValueError(
                    f"invalid TPUFLOW_SERVE_AUTOSCALE_BUDGET_FLOOR="
                    f"{value!r}: expected a budget fraction in [0, 1)"
                )
            out[key] = float(value)
    return out


def resolve_autoscale(block: dict | None) -> dict:
    """One resolved knob dict: defaults <- env knobs <- explicit block.
    Raises ValueError naming every problem (the runtime spelling of
    :func:`validate_autoscale_block`)."""
    block = {} if block is None else block
    problems = validate_autoscale_block(block)
    if problems:
        raise ValueError(
            "invalid autoscale config: " + "; ".join(problems)
        )
    resolved = {**AUTOSCALE_DEFAULTS, **_env_knobs(), **block}
    for lo_key, hi_key in (
        ("min_replicas", "max_replicas"),
        ("min_inflight", "max_inflight"),
        ("burn_low", "burn_high"),
    ):
        if resolved[lo_key] > resolved[hi_key]:
            raise ValueError(
                f"invalid autoscale config: {lo_key} "
                f"{resolved[lo_key]} exceeds {hi_key} "
                f"{resolved[hi_key]}"
            )
    return resolved


class ObservingController:
    """The SLO-driven hill climber over a server's control seams.

    ``server`` needs the four ``set_*`` seams plus ``service.replicas``
    / ``admission.max_inflight`` / ``hedge_ms`` / ``drift_threshold``
    reads (:class:`~tpuflow.serve_async.AsyncServer`, or any adapter —
    the benchmark drives a simulated one). ``history`` is the
    :class:`~tpuflow.obs.history.MetricsHistory` whose ``slo_burn_rate``
    / ``slo_error_budget_remaining`` lanes the decisions read.
    """

    SCHEMA_ID = "tpuflow.serve_autoscale/v1"

    def __init__(
        self, server, history, *, registry=None, block=None,
        logger=None, clock=time.monotonic, max_trail=256,
    ):
        self.server = server
        self.history = history
        self.cfg = resolve_autoscale(block)
        self.clock = clock
        self.logger = logger
        self._lock = threading.Lock()
        self._steps = None
        if registry is not None:
            self._steps = registry.counter(
                "serve_autoscale_steps_total",
                "autoscaler control-loop decisions, by action "
                "(hold/warmup/no_signal and every ladder move)",
            )
        # Baselines: the down ladder relaxes each knob back toward what
        # the operator configured, never past it.
        self._hedge0 = float(getattr(server, "hedge_ms", 0.0))
        self._drift0 = float(getattr(server, "drift_threshold", 6.0))
        self._inflight0 = int(
            getattr(getattr(server, "admission", None), "max_inflight", 0)
            or self.cfg["min_inflight"]
        )
        self._ticks = 0
        self._hot_ticks = 0
        self._calm_ticks = 0
        self._moves = 0
        self._reversals = 0
        self._pending = None  # judged replica down-move awaiting verdict
        self._frozen_until = 0.0
        self.trail: list[dict] = []
        self._max_trail = int(max_trail)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # ---- current control state (reads the server's documented
    # cross-thread-tolerant attributes; no lock needed) ----

    def _replicas(self) -> int:
        return int(getattr(self.server.service, "replicas", 1))

    def _max_inflight(self) -> int:
        return int(self.server.admission.max_inflight)

    # ---- signals ----

    def _signals(self, now: float):
        """(worst windowed-mean burn, worst budget remaining, p99 ms)
        across every SLO objective's history lane; None where the lane
        has no points yet (warmup, or SLO gauges not publishing)."""
        h = self.history
        w = self.cfg["window_s"]
        burn = budget = None
        for labels in h.labelsets("slo_burn_rate"):
            m = h.mean("slo_burn_rate", w, now, **labels)
            if m is not None:
                burn = m if burn is None else max(burn, m)
        for labels in h.labelsets("slo_error_budget_remaining"):
            v = h.latest("slo_error_budget_remaining", **labels)
            if v is not None:
                budget = v if budget is None else min(budget, v)
        p99 = h.latest("predict_latency_ms", quantile="0.99")
        return burn, budget, p99

    # ---- the ladders (one rung per call; "" = no headroom) ----

    def _scale_up(self) -> tuple[str, dict]:
        cfg = self.cfg
        if self._replicas() < cfg["max_replicas"]:
            return "scale_up_replicas", {"replicas": self._replicas() + 1}
        cur = self._max_inflight()
        if cur < cfg["max_inflight"]:
            return "raise_inflight", {
                "max_inflight": min(cfg["max_inflight"], cur * 2),
            }
        if float(self.server.hedge_ms) > 0:
            # Hedging duplicates dispatches — exactly the wrong
            # multiplier while the SLO is burning.
            return "drop_hedge", {"hedge_ms": 0.0}
        if float(self.server.drift_threshold) > 1.0:
            return "tighten_drift", {
                "drift_threshold": max(
                    1.0, float(self.server.drift_threshold) / 2.0
                ),
            }
        return "saturated", {}

    def _scale_down(self) -> tuple[str, dict]:
        cfg = self.cfg
        if float(self.server.drift_threshold) < self._drift0:
            return "relax_drift", {
                "drift_threshold": min(
                    self._drift0, float(self.server.drift_threshold) * 2.0
                ),
            }
        if float(self.server.hedge_ms) < self._hedge0:
            return "restore_hedge", {"hedge_ms": self._hedge0}
        cur = self._max_inflight()
        lo = max(cfg["min_inflight"], self._inflight0)
        if cur > lo:
            return "lower_inflight", {"max_inflight": max(lo, cur // 2)}
        if self._replicas() > cfg["min_replicas"]:
            return "scale_down_replicas", {
                "replicas": self._replicas() - 1,
            }
        return "floor", {}

    def _apply(self, changes: dict) -> str | None:
        """Push one move's knob changes through the server seams.
        Returns an error string (and clamps the ceiling so the rung is
        not retried forever) when the data plane refuses — a replica
        count the devices cannot place is a ceiling, not a crash."""
        try:
            if "replicas" in changes:
                self.server.set_replicas(int(changes["replicas"]))
            if "max_inflight" in changes:
                self.server.set_max_inflight(int(changes["max_inflight"]))
            if "hedge_ms" in changes:
                self.server.set_hedge_ms(float(changes["hedge_ms"]))
            if "drift_threshold" in changes:
                self.server.set_drift_threshold(
                    float(changes["drift_threshold"])
                )
        except ValueError as e:
            if "replicas" in changes:
                self.cfg["max_replicas"] = self._replicas()
            return str(e)
        return None

    # ---- the control step ----

    def step(self, now: float | None = None) -> dict:
        """One decision. Tests and the benchmark call this directly
        with a fake clock; :meth:`run` calls it on the cadence."""
        now = self.clock() if now is None else float(now)
        t0 = time.perf_counter()
        with self._lock:
            row = self._step_locked(now)
        self._record(row, time.perf_counter() - t0)
        return row

    def _step_locked(self, now: float) -> dict:
        cfg = self.cfg
        self._ticks += 1
        burn, budget, p99 = self._signals(now)
        hot = burn is not None and (
            burn >= cfg["burn_high"]
            or (budget is not None and budget <= cfg["budget_floor"])
        )
        calm = (
            burn is not None
            and burn <= cfg["burn_low"]
            and (budget is None or budget > cfg["budget_floor"])
        )
        action, detail = "hold", {}
        if self._pending is not None:
            # A judged down-move is on trial: going hot reverts it and
            # freezes the down ladder; surviving the window adopts it.
            if hot:
                err = self._apply(self._pending["undo"])
                self._frozen_until = now + cfg["freeze_s"]
                self._reversals += 1
                action = "revert"
                detail = {
                    "undone": self._pending["action"],
                    "frozen_until": round(self._frozen_until, 3),
                }
                if err:
                    detail["error"] = err
                self._pending = None
            else:
                self._pending["judge_left"] -= 1
                if self._pending["judge_left"] <= 0:
                    action = "adopt"
                    detail = {"adopted": self._pending["action"]}
                    self._pending = None
                else:
                    action = "judging"
                    detail = {"judge_left": self._pending["judge_left"]}
        elif self._ticks <= cfg["warmup_ticks"]:
            action = "warmup"
        elif burn is None:
            action = "no_signal"
        elif hot:
            self._hot_ticks += 1
            self._calm_ticks = 0
            if self._hot_ticks >= cfg["hold_ticks"]:
                action, detail = self._bounded_move(self._scale_up())
                if action not in ("saturated", "frozen"):
                    self._hot_ticks = 0
        elif calm:
            self._calm_ticks += 1
            self._hot_ticks = 0
            if (
                self._calm_ticks >= cfg["hold_ticks"]
                and now >= self._frozen_until
            ):
                action, detail = self._bounded_move(self._scale_down())
                if action == "scale_down_replicas":
                    self._pending = {
                        "action": action,
                        "undo": {"replicas": self._replicas() + 1},
                        "judge_left": cfg["judge_ticks"],
                    }
                if action not in ("floor", "frozen"):
                    self._calm_ticks = 0
        else:
            self._hot_ticks = 0
            self._calm_ticks = 0
        return {
            "t": round(now, 6),
            "action": action,
            "burn": burn,
            "budget": budget,
            "p99_ms": p99,
            "replicas": self._replicas(),
            "max_inflight": self._max_inflight(),
            "hedge_ms": float(self.server.hedge_ms),
            "drift_threshold": float(self.server.drift_threshold),
            **detail,
        }

    def _bounded_move(self, move: tuple[str, dict]) -> tuple[str, dict]:
        """Apply one ladder rung, honoring the total-move budget."""
        action, changes = move
        if not changes:
            return action, {}
        if 0 < self.cfg["max_moves"] <= self._moves:
            return "frozen", {"reason": "max_moves"}
        err = self._apply(changes)
        if err is not None:
            return "blocked", {"attempted": action, "error": err}
        self._moves += 1
        return action, dict(changes)

    def _record(self, row: dict, duration_s: float) -> None:
        self.trail.append(row)
        if len(self.trail) > self._max_trail:
            del self.trail[: len(self.trail) - self._max_trail]
        if self._steps is not None:
            self._steps.inc(action=row["action"])
        from tpuflow.obs.tracing import record_span

        record_span(
            "autoscale.step", duration_s, logger=self.logger,
            **{k: v for k, v in row.items() if k != "t"},
        )

    # ---- lifecycle ----

    def run(self, stop_event: threading.Event) -> dict:
        """The control loop body — also the ``runtime/`` service shape
        (``thread_service(..., run=controller.run)``). Waits on the
        stop event (TPF022); a broken step never kills the loop."""
        while not stop_event.is_set():
            try:
                self.step()
            except Exception:
                pass
            stop_event.wait(self.cfg["interval_s"])
        return self.summary()

    def start(self) -> "ObservingController":
        """Start the control thread (idempotent)."""
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self.run, args=(self._stop,),
            name="tpuflow-serve-autoscale", daemon=True,
        )
        self._thread.start()
        return self

    def stop(self, timeout: float = 5.0) -> None:
        """Stop and join the control thread. Idempotent."""
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout)
        self._thread = None

    def summary(self) -> dict:
        """The ``autoscale`` slice of the daemon's /metrics JSON."""
        with self._lock:
            return {
                "schema": self.SCHEMA_ID,
                "ticks": self._ticks,
                "moves": self._moves,
                "reversals": self._reversals,
                "pending_judgment": self._pending is not None,
                "frozen_until": round(self._frozen_until, 3),
                "replicas": self._replicas(),
                "max_inflight": self._max_inflight(),
                "hedge_ms": float(self.server.hedge_ms),
                "drift_threshold": float(self.server.drift_threshold),
                "floors": {
                    "min_replicas": self.cfg["min_replicas"],
                    "min_inflight": self.cfg["min_inflight"],
                },
                "recent": [dict(r) for r in self.trail[-10:]],
            }
