"""ctypes bindings for the native data plane (native/csv.cc).

The C++ library is the TPU-native stand-in for the reference's delegated
native data layer (Spark/JVM via PySpark — SURVEY.md §5.8): multithreaded
headerless-CSV parsing under the dynamic schema, plus window extraction.
Every entry point returns ``None`` when the shared library isn't built, and
the pure-NumPy fallbacks in ``tpuflow.data`` take over with identical
results — the native path is an accelerator, never a requirement.

Build: ``make -C native`` (or it is attempted automatically once per
process; set TPUFLOW_BUILD_NATIVE=0 to disable).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import sys
from typing import TYPE_CHECKING

import numpy as np

if TYPE_CHECKING:
    from tpuflow.data.schema import Schema

_LIB_PATH = os.path.join(os.path.dirname(__file__), "libtpuflow_native.so")
_lib = None
_build_attempted = False


def _load():
    global _lib, _build_attempted
    if _lib is not None:
        return _lib
    if not os.path.exists(_LIB_PATH) and not _build_attempted:
        _build_attempted = True
        if os.environ.get("TPUFLOW_BUILD_NATIVE", "1") != "0":
            native_dir = os.path.join(
                os.path.dirname(os.path.dirname(os.path.dirname(__file__))),
                "native",
            )
            try:
                subprocess.run(
                    ["make", "-C", native_dir],
                    capture_output=True,
                    timeout=120,
                    check=True,
                )
            except Exception as e:  # toolchain absent → fallback path
                print(
                    f"tpuflow._native: build skipped ({type(e).__name__})",
                    file=sys.stderr,
                )
    if not os.path.exists(_LIB_PATH):
        return None
    try:
        lib = ctypes.CDLL(_LIB_PATH)
    except OSError as e:  # corrupt/incompatible .so → NumPy fallback
        print(f"tpuflow._native: load failed ({e}); using fallbacks",
              file=sys.stderr)
        return None
    lib.tf_csv_read.restype = ctypes.c_void_p
    lib.tf_csv_read.argtypes = [
        ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_int),
        ctypes.c_int,
        ctypes.c_char_p,
        ctypes.c_int,
    ]
    lib.tf_csv_nrows.restype = ctypes.c_long
    lib.tf_csv_nrows.argtypes = [ctypes.c_void_p]
    lib.tf_csv_get_int.argtypes = [ctypes.c_void_p, ctypes.c_int, ctypes.c_void_p]
    lib.tf_csv_get_float.argtypes = [ctypes.c_void_p, ctypes.c_int, ctypes.c_void_p]
    lib.tf_csv_str_maxlen.restype = ctypes.c_int
    lib.tf_csv_str_maxlen.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.tf_csv_get_str.argtypes = [
        ctypes.c_void_p,
        ctypes.c_int,
        ctypes.c_void_p,
        ctypes.c_int,
    ]
    lib.tf_csv_free.argtypes = [ctypes.c_void_p]
    # Streaming buffer parse (newer builds; absent in stale .so files —
    # callers hasattr-check so an old library degrades to the fallback).
    if hasattr(lib, "tf_csv_parse"):
        lib.tf_csv_parse.restype = ctypes.c_void_p
        lib.tf_csv_parse.argtypes = [
            ctypes.c_char_p,
            ctypes.c_long,
            ctypes.POINTER(ctypes.c_int),
            ctypes.c_int,
            ctypes.c_char_p,
            ctypes.c_int,
        ]
    lib.tf_window_count.restype = ctypes.c_long
    lib.tf_window_count.argtypes = [ctypes.c_long] * 3
    lib.tf_sliding_windows.argtypes = [
        ctypes.c_void_p,
        ctypes.c_void_p,
        ctypes.c_long,
        ctypes.c_long,
        ctypes.c_long,
        ctypes.c_long,
        ctypes.c_int,
        ctypes.c_void_p,
        ctypes.c_void_p,
    ]
    _lib = lib
    return lib


def native_available() -> bool:
    return _load() is not None


_KIND_CODES = {"int": 0, "float": 1}


def _drain_table(lib, handle, schema: "Schema", kinds) -> dict[str, np.ndarray]:
    """Copy a CsvTable handle's columns into numpy arrays and free it."""
    try:
        n = lib.tf_csv_nrows(handle)
        out: dict[str, np.ndarray] = {}
        for i, spec in enumerate(schema.columns):
            if kinds[i] == 0:
                a = np.empty(n, dtype=np.int32)
                lib.tf_csv_get_int(handle, i, a.ctypes.data_as(ctypes.c_void_p))
            elif kinds[i] == 1:
                a = np.empty(n, dtype=np.float32)
                lib.tf_csv_get_float(handle, i, a.ctypes.data_as(ctypes.c_void_p))
            else:
                width = max(lib.tf_csv_str_maxlen(handle, i), 1)
                buf = np.zeros(n, dtype=f"S{width}")
                lib.tf_csv_get_str(
                    handle, i, buf.ctypes.data_as(ctypes.c_void_p), width
                )
                # Bytes are UTF-8 (astype would decode latin-1).
                a = np.char.decode(buf, "utf-8")
            out[spec.name] = a
        return out
    finally:
        lib.tf_csv_free(handle)


def read_csv_native(path: str, schema: "Schema") -> dict[str, np.ndarray] | None:
    """Parse a headerless CSV with the C++ library; None if unavailable."""
    lib = _load()
    if lib is None:
        return None
    kinds = [_KIND_CODES.get(c.kind, 2) for c in schema.columns]
    ckinds = (ctypes.c_int * len(kinds))(*kinds)
    err = ctypes.create_string_buffer(512)
    handle = lib.tf_csv_read(
        path.encode(), ckinds, len(kinds), err, len(err)
    )
    if not handle:
        raise ValueError(
            f"{path}: {err.value.decode(errors='replace')}"
        )
    return _drain_table(lib, handle, schema, kinds)


def parse_csv_native(
    data: bytes, schema: "Schema", source: str = "<buffer>"
) -> dict[str, np.ndarray] | None:
    """Parse one in-memory CSV chunk with the C++ library — the streaming
    reader's fast path. None if the library (or the tf_csv_parse symbol,
    on stale builds) is unavailable; raises ValueError on malformed rows
    like the file reader."""
    lib = _load()
    if lib is None or not hasattr(lib, "tf_csv_parse"):
        return None
    kinds = [_KIND_CODES.get(c.kind, 2) for c in schema.columns]
    ckinds = (ctypes.c_int * len(kinds))(*kinds)
    err = ctypes.create_string_buffer(512)
    handle = lib.tf_csv_parse(
        data, len(data), ckinds, len(kinds), err, len(err)
    )
    if not handle:
        raise ValueError(
            f"{source}: {err.value.decode(errors='replace')}"
        )
    return _drain_table(lib, handle, schema, kinds)


def sliding_windows_native(
    series: np.ndarray,
    targets: np.ndarray,
    length: int,
    stride: int = 1,
    teacher_forcing: bool = False,
) -> tuple[np.ndarray, np.ndarray] | None:
    """Window extraction via the C++ library; None if unavailable."""
    lib = _load()
    if lib is None:
        return None
    series = np.ascontiguousarray(series, dtype=np.float32)
    targets = np.ascontiguousarray(targets, dtype=np.float32)
    T, F = series.shape
    # Validate BEFORE crossing into C: stride=0 is a SIGFPE (integer
    # divide) in tf_window_count, and short targets an out-of-bounds read
    # in tf_sliding_windows — mirror the NumPy fallback's exceptions.
    if stride < 1:
        raise ValueError(f"stride must be >= 1, got {stride}")
    if length < 1:
        raise ValueError(f"window length must be >= 1, got {length}")
    if targets.shape[0] != T:
        raise ValueError(
            f"targets length {targets.shape[0]} != series length {T}"
        )
    n = lib.tf_window_count(T, length, stride)
    x = np.empty((n, length, F), dtype=np.float32)
    y = np.empty((n, length) if teacher_forcing else (n,), dtype=np.float32)
    if n:
        lib.tf_sliding_windows(
            series.ctypes.data_as(ctypes.c_void_p),
            targets.ctypes.data_as(ctypes.c_void_p),
            T,
            F,
            length,
            stride,
            int(teacher_forcing),
            x.ctypes.data_as(ctypes.c_void_p),
            y.ctypes.data_as(ctypes.c_void_p),
        )
    return x, y
