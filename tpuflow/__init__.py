"""tpuflow — a TPU-native deep-learning framework for well-flow prediction.

A ground-up JAX/XLA re-design of the capability surface of
OmarZOS/deep-learning-at-scale (see SURVEY.md): a Gilbert's-equation physical
baseline, a family of learned regressors (static ANN, dynamic windowed ANN,
1-D CNN, single- and multi-well LSTMs), a dynamic-schema tabular data
pipeline, and data-parallel training over a TPU device mesh.

Layers (bottom-to-top, mirroring SURVEY.md §1's L0-L6 map, TPU-natively):

- ``tpuflow.parallel``  — device mesh + collectives over ICI/DCN (replaces the
  reference's Spark/Hadoop cluster runtime, SURVEY §5.8).
- ``tpuflow.data``      — dynamic-schema ingest + feature ETL (replaces Spark
  DataFrames / Spark ML pipelines, reference cnn.py:48-107).
- ``tpuflow.core``      — pure functions: Gilbert equation, losses, metrics.
- ``tpuflow.models``    — Flax modules (replaces Keras Sequential models).
- ``tpuflow.train``     — jitted train/eval steps, early stopping, save-best
  checkpointing (replaces Keras callbacks, reference cnn.py:110-134).
- ``tpuflow.api``       — ``train(config)`` entrypoint + CLI preserving the
  reference's dynamic-schema contract (reference cnn.py:2,41-44).
- ``tpuflow.kernels``   — Pallas TPU kernels for the hot ops.
"""

__version__ = "0.1.0"


def _honor_jax_platforms_env() -> None:
    """Make ``JAX_PLATFORMS=cpu python -m tpuflow.cli ...`` actually work.

    A force-registered out-of-tree platform plugin (e.g. the axon TPU
    tunnel) can override the documented JAX_PLATFORMS env contract; when
    its backend is unreachable, every jax init then hangs. Pinning the
    config from the env var restores the contract. No-op when the var is
    unset or jax is already initialized.
    """
    import os

    value = os.environ.get("JAX_PLATFORMS")
    if not value:
        return
    try:
        import jax

        current = jax.config.jax_platforms
        if current and not current.startswith("axon"):
            # A script already pinned the config explicitly (e.g. a
            # virtual-CPU-mesh demo that ran jax.config.update("cpu")
            # before importing tpuflow) — its choice outranks the
            # inherited env var. The force-registering plugin's own
            # "axon,cpu" preset is NOT a user pin (it is exactly what
            # this function exists to override), hence the startswith.
            return
        jax.config.update("jax_platforms", value)
    except Exception:
        pass  # jax absent or already initialized — leave as-is


_honor_jax_platforms_env()
