"""Sequence windowing for the dynamic-ANN and LSTM model families.

The reference family's sequence models operate on 24-step well-log windows
(BASELINE.json configs; reference Readme.md:19-21 — the scripts themselves
are absent from the snapshot, so this implements the documented intent).
Windows are materialized host-side as static-shape arrays; the time axis is
consumed on-chip by ``lax.scan`` (SURVEY.md §5.7).
"""

from __future__ import annotations

import numpy as np

DEFAULT_WINDOW = 24


def _validate(series, targets, length, stride):
    if stride < 1:
        raise ValueError(f"stride must be >= 1, got {stride}")
    if length < 1:
        raise ValueError(f"window length must be >= 1, got {length}")
    if targets.shape[0] != series.shape[0]:
        raise ValueError(
            f"targets length {targets.shape[0]} != series length "
            f"{series.shape[0]}"
        )


def _native_windows(series, targets, length, stride, teacher_forcing):
    """C++ fast path (native/csv.cc); None → use the NumPy fallback."""
    try:
        from tpuflow._native import sliding_windows_native

        return sliding_windows_native(
            series, targets, length, stride, teacher_forcing
        )
    except ImportError:
        return None


def _strided_view(arr: np.ndarray, length: int, stride: int) -> np.ndarray:
    """All length-windows of ``arr`` along axis 0 at ``stride`` — a
    zero-copy stride-trick view indexed once, no per-window Python loop
    (~8x faster than stacking slices at real chunk sizes)."""
    view = np.lib.stride_tricks.sliding_window_view(arr, length, axis=0)
    idx = np.arange(0, arr.shape[0] - length + 1, stride)
    out = view[idx]  # [N, ..., length]
    # sliding_window_view puts the window axis LAST; callers want time
    # as the second axis ([N, length, F] / [N, length]).
    return np.ascontiguousarray(np.moveaxis(out, -1, 1))


def sliding_windows(
    series: np.ndarray,
    targets: np.ndarray,
    length: int = DEFAULT_WINDOW,
    stride: int = 1,
) -> tuple[np.ndarray, np.ndarray]:
    """Windows over a single well's log.

    Args:
      series: [T, F] per-timestep features.
      targets: [T] per-timestep target (e.g. flow rate).
      length: window length (24 per BASELINE configs).
      stride: hop between window starts.

    Returns:
      (windows [N, length, F], y [N]) where ``y[i]`` is the target at the
      window's **last** step — the "predict current flow from the trailing
      window" task of the dynamic models.
    """
    _validate(series, targets, length, stride)
    T = series.shape[0]
    if T < length:
        return (
            np.zeros((0, length, series.shape[1]), dtype=np.float32),
            np.zeros((0,), dtype=np.float32),
        )
    native = _native_windows(series, targets, length, stride, False)
    if native is not None:
        return native
    starts = np.arange(0, T - length + 1, stride)
    windows = _strided_view(series, length, stride)
    y = targets[starts + length - 1]
    # copy=False: already-float32 inputs (the whole pipeline) skip a full
    # re-materialization of the window block.
    return windows.astype(np.float32, copy=False), y.astype(np.float32, copy=False)


def teacher_forcing_pairs(
    series: np.ndarray,
    targets: np.ndarray,
    length: int = DEFAULT_WINDOW,
    stride: int = 1,
) -> tuple[np.ndarray, np.ndarray]:
    """Teacher-forced sequence pairs for LSTM training (BASELINE "LSTM-64
    single-well sequence model (teacher-forced)").

    Returns (windows [N, length, F], y [N, length]) — a target for *every*
    step, so the LSTM is supervised along the whole sequence.
    """
    _validate(series, targets, length, stride)
    T = series.shape[0]
    if T < length:
        return (
            np.zeros((0, length, series.shape[1]), dtype=np.float32),
            np.zeros((0, length), dtype=np.float32),
        )
    native = _native_windows(series, targets, length, stride, True)
    if native is not None:
        return native
    windows = _strided_view(series, length, stride)
    y = _strided_view(targets, length, stride)
    return windows.astype(np.float32, copy=False), y.astype(np.float32, copy=False)
