"""Headerless-CSV ingest against a dynamic schema.

Equivalent of the reference's ``spark.read.csv(path, header=False,
schema=schema)`` (reference cnn.py:65) — minus its [BUG] of reading the
columnTypes argv slot as the path (SURVEY.md C4): here the data path is an
explicit, separate argument.

A native C++ fast path (``tpuflow._native``) is used when built; the NumPy
implementation is the always-available fallback with identical results.
"""

from __future__ import annotations

import numpy as np

from tpuflow.data.schema import Schema
from tpuflow.resilience import fault_point, io_policy, retry_call


def read_csv(path: str, schema: Schema) -> dict[str, np.ndarray]:
    """Read a headerless CSV into per-column arrays, typed by the schema.

    Returns a dict: column name -> 1-D array (int32 / float32 / unicode).
    Transient I/O errors (EIO, timeouts, stale-handle OSErrors) retry
    under the shared policy; the read is idempotent so a retry re-reads
    from scratch. Deterministic failures propagate immediately: a
    malformed CSV's ValueError, and the ENOENT/EACCES-shaped OSErrors a
    typo'd path produces (see ``retry.NON_TRANSIENT_OSERRORS`` — the
    cost is that an outage which manifests as ENOENT also fails fast).
    ``csv.read`` is a registered fault site.
    """

    def _read():
        fault_point("csv.read")
        try:
            from tpuflow._native import read_csv_native  # built lazily

            out = read_csv_native(path, schema)
            if out is not None:
                return out
        except ImportError:
            pass
        return _read_csv_numpy(path, schema)

    return retry_call(io_policy(), _read)


def iter_csv_lines(path: str):
    """Yield ``(lineno, text)`` for every non-blank line — the single
    line-reading loop shared by the whole-file and streaming readers.
    The open retries transient OSErrors (idempotent; the streaming
    reader may be hours into a file when the next pass's open hits an
    EIO/ESTALE blip — absorbed instead of killing the epoch). ENOENT/
    EACCES-shaped errors fail fast as deterministic (a typo'd path
    replays identically; see ``retry.NON_TRANSIENT_OSERRORS``)."""
    with retry_call(io_policy(), open, path, "r", encoding="utf-8") as f:
        for lineno, raw in enumerate(f, 1):
            line = raw.rstrip("\n").rstrip("\r")
            if line:
                yield lineno, line


def parse_rows(
    rows, schema: Schema, source: str = "<csv>"
) -> dict[str, np.ndarray]:
    """Parse an iterable of ``(lineno, text)`` rows into typed per-column
    arrays.

    The single Python-side row parser — used by the whole-file fallback
    below and by the streaming reader (tpuflow.data.stream), so field
    validation and dtype semantics live in exactly one place (the native
    parser in native/csv.cc mirrors them and is tested for parity).
    Consumes the iterable lazily: only the split fields are retained.
    """
    ncols = len(schema.columns)
    cells: list[list[str]] = [[] for _ in range(ncols)]
    for lineno, line in rows:
        parts = line.split(",")
        if len(parts) != ncols:
            raise ValueError(
                f"{source}:{lineno}: expected {ncols} fields, got {len(parts)}"
            )
        for i, p in enumerate(parts):
            cells[i].append(p)
    out: dict[str, np.ndarray] = {}
    for spec, col in zip(schema.columns, cells):
        if spec.kind == "int":
            out[spec.name] = np.asarray(col, dtype=np.int32)
        elif spec.kind == "float":
            out[spec.name] = np.asarray(col, dtype=np.float32)
        else:
            out[spec.name] = np.asarray(col, dtype=np.str_)
    return out


def _read_csv_numpy(path: str, schema: Schema) -> dict[str, np.ndarray]:
    return parse_rows(iter_csv_lines(path), schema, source=path)
