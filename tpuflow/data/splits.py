"""Seeded train/val/test splitting.

The reference splits 64% / 16% / 20% via Spark's ``randomSplit`` (reference
cnn.py:68) with no seed. Here the split is deterministic given a seed, so
runs are reproducible and resumable.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

DEFAULT_FRACTIONS = (0.64, 0.16, 0.20)


def random_split(
    n: int,
    fractions: Sequence[float] = DEFAULT_FRACTIONS,
    seed: int = 0,
) -> tuple[np.ndarray, ...]:
    """Partition ``range(n)`` into len(fractions) disjoint index arrays.

    Fractions must sum to 1 (within tolerance). The last part absorbs
    rounding remainder, so every index lands in exactly one part.
    """
    total = float(sum(fractions))
    if abs(total - 1.0) > 1e-6:
        raise ValueError(f"fractions must sum to 1, got {total}")
    rng = np.random.default_rng(seed)
    perm = rng.permutation(n)
    out = []
    start = 0
    for frac in fractions[:-1]:
        size = int(round(n * frac))
        out.append(np.sort(perm[start : start + size]))
        start += size
    out.append(np.sort(perm[start:]))
    return tuple(out)
