"""Synthetic oil-well data generator.

The reference trains on local, uncommitted well-production data whose schema
changes per submission (reference Readme.md:23-25; SURVEY.md C21 "ABSENT by
design"). This module generates physically-plausible stand-in data so the
framework's models, benchmarks, and the Gilbert-baseline comparison are
runnable end-to-end.

The generative story mirrors the reference's problem: per-well logs of
wellhead pressure / choke size / GLR (plus auxiliary channels and a
categorical well-completion type), with true gross flow = Gilbert prediction
× a *well-state-dependent correction* + noise. The correction depends on
channels Gilbert's equation ignores (water cut, temperature, completion
type), so learned regressors can beat the physical baseline — exactly the
reference system's reason to exist (reference Readme.md:7-21).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from tpuflow.core.gilbert import GILBERT, ChokeCoefficients

COMPLETION_TYPES = ("openhole", "cased", "gravelpack")


@dataclass(frozen=True)
class WellLog:
    """One well's time series. All arrays are [T]."""

    pressure: np.ndarray  # wellhead pressure [psig]
    choke: np.ndarray  # choke size [64ths inch]
    glr: np.ndarray  # gas-liquid ratio [Mscf/stb]
    temperature: np.ndarray  # wellhead temperature [degF]
    water_cut: np.ndarray  # fraction [0,1]
    completion: str  # categorical well property
    flow: np.ndarray  # TRUE gross liquid rate [stb/day] (the target)

    @property
    def gilbert_flow(self) -> np.ndarray:
        """The physical-baseline prediction for this log."""
        import jax.numpy as jnp

        return np.asarray(
            jnp.asarray(self.pressure)
            * jnp.power(jnp.asarray(self.choke), GILBERT.c)
            / (GILBERT.a * jnp.power(jnp.maximum(jnp.asarray(self.glr), 1e-6), GILBERT.b))
        )


def generate_wells(
    n_wells: int = 8,
    steps: int = 512,
    seed: int = 0,
    coeffs: ChokeCoefficients = GILBERT,
) -> list[WellLog]:
    """Generate ``n_wells`` independent well logs of ``steps`` timesteps."""
    rng = np.random.default_rng(seed)
    wells = []
    t = np.arange(steps, dtype=np.float32)
    for w in range(n_wells):
        # Static well character.
        p0 = rng.uniform(150.0, 400.0)
        decline = rng.uniform(1e-4, 6e-4)
        glr0 = rng.uniform(0.4, 2.5)
        choke0 = rng.choice([16.0, 24.0, 32.0, 40.0, 48.0])
        completion = COMPLETION_TYPES[int(rng.integers(len(COMPLETION_TYPES)))]

        # Slow exponential pressure decline + operational noise.
        pressure = p0 * np.exp(-decline * t) * (
            1.0 + 0.02 * rng.standard_normal(steps)
        )
        # Choke changes occasionally (operator interventions).
        choke = np.full(steps, choke0, dtype=np.float32)
        for step in np.sort(rng.integers(0, steps, size=max(1, steps // 128))):
            choke[step:] = rng.choice([16.0, 24.0, 32.0, 40.0, 48.0])
        # GLR drifts upward as the reservoir depletes.
        glr = glr0 * (1.0 + 0.3 * t / steps) * (
            1.0 + 0.05 * rng.standard_normal(steps)
        )
        glr = np.maximum(glr, 0.05)
        temperature = rng.uniform(90.0, 180.0) + 2.0 * rng.standard_normal(steps)
        water_cut = np.clip(
            rng.uniform(0.05, 0.4)
            + 0.3 * t / steps
            + 0.02 * rng.standard_normal(steps),
            0.0,
            0.95,
        )

        # True flow: Gilbert × learnable correction + noise. The correction
        # uses channels Gilbert ignores, plus a completion-type efficiency.
        gilbert_q = (
            pressure
            * np.power(choke, coeffs.c)
            / (coeffs.a * np.power(np.maximum(glr, 1e-6), coeffs.b))
        )
        completion_eff = {
            "openhole": 1.0,
            "cased": 0.92,
            "gravelpack": 0.85,
        }[completion]
        correction = (
            completion_eff
            * (1.0 - 0.45 * water_cut)
            * (1.0 + 0.001 * (temperature - 120.0))
        )
        noise = 1.0 + 0.03 * rng.standard_normal(steps)
        flow = gilbert_q * correction * noise

        wells.append(
            WellLog(
                pressure=pressure.astype(np.float32),
                choke=choke.astype(np.float32),
                glr=glr.astype(np.float32),
                temperature=temperature.astype(np.float32),
                water_cut=water_cut.astype(np.float32),
                completion=completion,
                flow=flow.astype(np.float32),
            )
        )
    return wells


# The canonical dynamic-schema strings for the synthetic table — what a
# job submission would pass on the CLI (reference cnn.py:2 contract).
SYNTHETIC_COLUMN_NAMES = (
    "pressure,choke,glr,temperature,water_cut,completion,flow"
)
SYNTHETIC_COLUMN_TYPES = "float,float,float,float,float,string,float"
SYNTHETIC_TARGET = "flow"


def wells_to_table(wells: list[WellLog]) -> dict[str, np.ndarray]:
    """Flatten well logs into one tabular column dict (static-model view)."""
    return {
        "pressure": np.concatenate([w.pressure for w in wells]),
        "choke": np.concatenate([w.choke for w in wells]),
        "glr": np.concatenate([w.glr for w in wells]),
        "temperature": np.concatenate([w.temperature for w in wells]),
        "water_cut": np.concatenate([w.water_cut for w in wells]),
        "completion": np.concatenate(
            [np.full(len(w.pressure), w.completion) for w in wells]
        ),
        "flow": np.concatenate([w.flow for w in wells]),
    }


def write_csv(path: str, table: dict[str, np.ndarray], names: list[str]) -> None:
    """Write a headerless CSV in the given column order (reference format,
    cnn.py:65 reads header=False)."""
    cols = [table[n] for n in names]
    n = len(cols[0])
    with open(path, "w", encoding="utf-8") as f:
        for i in range(n):
            f.write(",".join(str(c[i]) for c in cols) + "\n")
