"""Host-side data plane: dynamic-schema ingest, feature ETL, batching.

Replaces the reference's Spark data layers (L1/L2, reference cnn.py:48-107)
with a NumPy host pipeline that resolves per-submission dynamic schemas
(reference Readme.md:25) into the *static* shapes XLA requires, then feeds
device-resident batches — closing the Spark-DataFrame→Keras seam the
reference never bridged (reference cnn.py:127; SURVEY.md §3.1).
"""

from tpuflow.data.schema import ColumnSpec, Schema  # noqa: F401
from tpuflow.data.splits import random_split  # noqa: F401
from tpuflow.data.features import FeaturePipeline  # noqa: F401
from tpuflow.data.windows import sliding_windows, teacher_forcing_pairs  # noqa: F401
from tpuflow.data.synthetic import generate_wells, wells_to_table, write_csv  # noqa: F401
from tpuflow.data.csv_io import read_csv  # noqa: F401
from tpuflow.data.pipeline import (  # noqa: F401
    ArrayDataset,
    batches,
    prepare_tabular,
    prepare_windowed,
    prepare_windowed_table,
)
from tpuflow.data.prefetch import device_prefetch, prefetch  # noqa: F401
