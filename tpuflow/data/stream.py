"""Streaming CSV ingest: larger-than-memory tables → fixed-shape batches.

The reference's scale story is cluster-resident HDFS data read by Spark
executors (reference Readme.md:3, cnn.py:65). The TPU-host equivalent for
tables that don't fit in RAM: stream the headerless CSV in bounded row
chunks, transform each chunk with an ALREADY-FITTED feature pipeline (fit
on a training sample — never refit mid-stream, preserving the
fit-once-on-train discipline of SURVEY.md C6), and emit fixed-size device
batches. Composes with ``tpuflow.data.prefetch`` for host→device overlap.
"""

from __future__ import annotations

from typing import Iterator

import numpy as np

from tpuflow.data.csv_io import iter_csv_lines, parse_rows
from tpuflow.data.features import FeaturePipeline
from tpuflow.data.schema import Schema
from tpuflow.resilience import fault_point, io_policy, retry_call


def stream_csv_columns(
    path: str, schema: Schema, chunk_rows: int = 65536
) -> Iterator[dict[str, np.ndarray]]:
    """Yield the CSV as a sequence of column-dict chunks of ≤ chunk_rows.

    Memory is bounded by ``chunk_rows``, not the file size. Each chunk is
    parsed by the multithreaded C++ parser when built (tf_csv_parse —
    the per-cell conversion is the streaming path's hot loop), falling
    back to the shared Python parser (csv_io.parse_rows). Row-to-chunk
    assignment is identical in both backends, so everything downstream
    (hash splits, window carries, shuffles) is backend-invariant.
    """
    rows: list[tuple[int, str]] = []
    for lineno, line in iter_csv_lines(path):
        rows.append((lineno, line))
        if len(rows) >= chunk_rows:
            yield _chunk_with_retry(rows, schema, path)
            rows = []
    if rows:
        yield _chunk_with_retry(rows, schema, path)


def _chunk_with_retry(
    rows: list[tuple[int, str]], schema: Schema, path: str
) -> dict[str, np.ndarray]:
    """One chunk parse under the transient-I/O retry policy: the rows are
    already in memory, so a retry is pure recompute — which is exactly
    what absorbs an injected transient at the ``stream.read`` site (the
    flaky-storage drill) without losing the epoch. Real parse errors
    (ValueError) propagate immediately."""

    def _one():
        fault_point("stream.read")
        return _parse_chunk(rows, schema, path)

    return retry_call(io_policy(), _one)


def _parse_chunk(
    rows: list[tuple[int, str]], schema: Schema, path: str
) -> dict[str, np.ndarray]:
    from tpuflow._native import parse_csv_native

    first, last = rows[0][0], rows[-1][0]
    try:
        native = parse_csv_native(
            "\n".join(line for _, line in rows).encode(),
            schema,
            source=f"{path}:{first}-{last}",
        )
    except ValueError as native_err:
        # The C++ error names the chunk, not the row; re-parse the one
        # bad chunk with the Python parser so the raised error carries
        # the TRUE file line (error path only — no hot-loop cost). If
        # the Python parser ACCEPTS what the native parser rejected, the
        # two backends disagree on row validity — surface that loudly
        # instead of silently accepting data that a whole-file native
        # read (tf_csv_read) would reject, which would quietly break the
        # documented backend invariance.
        out = parse_rows(rows, schema, source=path)
        import warnings

        warnings.warn(
            f"CSV parser divergence at {path}:{first}-{last}: the native "
            f"parser rejected this chunk ({native_err}) but the Python "
            "parser accepted it; proceeding with the Python result — "
            "report this, the two backends should agree",
            RuntimeWarning,
            stacklevel=2,
        )
        return out
    if native is not None:
        return native
    return parse_rows(rows, schema, source=path)


SPLIT_FRACTIONS = (0.64, 0.16, 0.20)  # train/val/test — reference cnn.py:68
_SPLITS = ("train", "val", "test")
_HASH_MULT = np.uint64(0x9E3779B97F4A7C15)  # Fibonacci hashing constant


def split_assignments(
    start: int, n: int, seed: int, fractions=SPLIT_FRACTIONS
) -> np.ndarray:
    """Deterministic per-row split ids (0=train, 1=val, 2=test) for global
    rows [start, start+n).

    The streaming analog of the seeded 64/16/20 permutation split
    (``tpuflow.data.splits``): each row's assignment is a pure hash of
    (global row index, seed), so it is identical on every pass over the
    file and independent of chunking — a row never migrates between splits
    across epochs or between the train stream and the eval materializer.
    """
    idx = np.arange(start, start + n, dtype=np.uint64)
    # Mix the seed in Python ints (explicit 64-bit wrap): numpy SCALAR
    # uint64 ops reject negative seeds and warn on overflow, while the
    # array ops below wrap silently as intended.
    seed_mix = np.uint64((seed * 0x517CC1B727220A95) % (1 << 64))
    h = (idx + seed_mix) * _HASH_MULT
    h ^= h >> np.uint64(31)
    h *= _HASH_MULT
    h ^= h >> np.uint64(29)
    u = (h >> np.uint64(11)).astype(np.float64) / float(1 << 53)
    bounds = np.cumsum(fractions)
    return np.digitize(u, bounds[:-1]).astype(np.int8)


def stream_split_columns(
    path: str,
    schema: Schema,
    which: str,
    seed: int,
    chunk_rows: int = 65536,
) -> Iterator[dict[str, np.ndarray]]:
    """Stream one split's rows as column-dict chunks (possibly ragged).

    Filters each chunk to the rows ``split_assignments`` maps to ``which``
    — bounded memory, deterministic across passes.
    """
    want = _SPLITS.index(which)
    start = 0
    for columns in stream_csv_columns(path, schema, chunk_rows):
        n = len(next(iter(columns.values())))
        keep = split_assignments(start, n, seed) == want
        start += n
        if keep.any():
            yield {k: v[keep] for k, v in columns.items()}


def materialize_splits(
    path: str,
    pipeline: FeaturePipeline,
    whichs: tuple[str, ...],
    seed: int,
    max_rows: int = 100_000,
    chunk_rows: int = 65536,
) -> dict[str, tuple[np.ndarray, np.ndarray, dict[str, np.ndarray]]]:
    """Materialize up to ``max_rows`` of each requested split in ONE pass:
    ``{which: (x, y, raw_columns)}``.

    Bounded-memory eval samples for streaming training: val/test metrics
    come from these capped samples instead of the full (possibly
    unbounded) splits. One file scan serves all requested splits — the
    chunk's hash assignments are computed once and routed. Stops early
    once every split hit its cap. Raw columns ride along for the
    physical-baseline (Gilbert) MAE.
    """
    ids = {w: _SPLITS.index(w) for w in whichs}
    acc = {w: {"xs": [], "ys": [], "raws": [], "got": 0} for w in whichs}
    start = 0
    for columns in stream_csv_columns(path, pipeline.schema, chunk_rows):
        n = len(next(iter(columns.values())))
        assigned = split_assignments(start, n, seed)
        start += n
        for w, a in acc.items():
            if a["got"] >= max_rows:
                continue
            keep = assigned == ids[w]
            if not keep.any():
                continue
            part = {k: v[keep] for k, v in columns.items()}
            take = min(int(keep.sum()), max_rows - a["got"])
            part = {k: v[:take] for k, v in part.items()}
            a["xs"].append(pipeline.transform(part))
            a["ys"].append(pipeline.transform_target(part))
            a["raws"].append(part)
            a["got"] += take
        if all(a["got"] >= max_rows for a in acc.values()):
            break
    out = {}
    for w, a in acc.items():
        if not a["xs"]:
            raise ValueError(f"{path}: split {w!r} has no rows")
        raw = {k: np.concatenate([r[k] for r in a["raws"]]) for k in a["raws"][0]}
        out[w] = (np.concatenate(a["xs"]), np.concatenate(a["ys"]), raw)
    return out


def materialize_split(
    path: str,
    pipeline: FeaturePipeline,
    which: str,
    seed: int,
    max_rows: int = 100_000,
    chunk_rows: int = 65536,
) -> tuple[np.ndarray, np.ndarray, dict[str, np.ndarray]]:
    """One-split convenience wrapper around ``materialize_splits``."""
    return materialize_splits(
        path, pipeline, (which,), seed, max_rows, chunk_rows
    )[which]


def stream_batches(
    path: str,
    pipeline: FeaturePipeline,
    batch_size: int,
    chunk_rows: int = 65536,
    drop_remainder: bool = True,
    shuffle_buffer: int = 0,
    seed: int = 0,
    split: str | None = None,
    split_seed: int = 0,
) -> Iterator[tuple[np.ndarray, np.ndarray]]:
    """Stream fixed-size (x, y) training batches from a large CSV.

    ``pipeline`` must already be fitted (on a train sample). Rows carry
    over between chunks so every batch has exactly ``batch_size`` rows;
    with ``drop_remainder`` the ragged tail is dropped (one XLA shape for
    the whole stream — SURVEY.md §7's no-recompilation discipline).

    ``shuffle_buffer > 0`` decorrelates the stream for SGD without
    materializing it: rows pass through a ``shuffle_buffer``-row windowed
    shuffle (the bounded-memory analog of a full-epoch permutation; memory
    stays O(shuffle_buffer) regardless of file size).

    ``split`` ("train"/"val"/"test") restricts the stream to one side of
    the deterministic hash split keyed by ``split_seed`` (see
    ``split_assignments``) — the out-of-core 64/16/20 contract.
    """
    if not pipeline.fitted:
        raise RuntimeError("stream_batches requires a fitted pipeline")
    rng = np.random.default_rng(seed) if shuffle_buffer else None
    if split is None:
        source = stream_csv_columns(path, pipeline.schema, chunk_rows)
    else:
        source = stream_split_columns(
            path, pipeline.schema, split, split_seed, chunk_rows
        )
    x_rem: np.ndarray | None = None
    y_rem: np.ndarray | None = None
    for columns in source:
        x = pipeline.transform(columns)
        y = pipeline.transform_target(columns)
        if x_rem is not None:
            x = np.concatenate([x_rem, x])
            y = np.concatenate([y_rem, y])
        if rng is not None:
            # Windowed shuffle: permute the whole buffer, emit its head,
            # hold back up to shuffle_buffer rows to mix with later
            # chunks. Until the buffer exceeds shuffle_buffer nothing is
            # emitted — rows accumulate so the window is always full.
            perm = rng.permutation(len(x))
            x, y = x[perm], y[perm]
            hold = min(len(x), shuffle_buffer)
        else:
            hold = 0
        n_avail = max(len(x) - hold, 0)
        n_full = n_avail // batch_size * batch_size
        for s in range(0, n_full, batch_size):
            yield x[s : s + batch_size], y[s : s + batch_size]
        x_rem, y_rem = x[n_full:], y[n_full:]
    # Drain the tail; rows held back by the shuffle are already the tail
    # of a uniform permutation, so no extra shuffle is needed here.
    if x_rem is not None and len(x_rem):
        n_full = len(x_rem) // batch_size * batch_size
        for s in range(0, n_full, batch_size):
            yield x_rem[s : s + batch_size], y_rem[s : s + batch_size]
        if not drop_remainder and n_full < len(x_rem):
            yield x_rem[n_full:], y_rem[n_full:]


def fit_pipeline_on_sample(
    path: str,
    schema: Schema,
    sample_rows: int = 100_000,
    split: str | None = None,
    split_seed: int = 0,
) -> FeaturePipeline:
    """Fit the feature pipeline on the stream's head.

    The streaming analog of fit-on-train: stats and vocabularies come from
    a bounded sample instead of a full materialized split. With
    ``split="train"`` the sample is further restricted to train-assigned
    rows, preserving the fit-once-on-train discipline (SURVEY.md C6) even
    out of core.
    """
    if split is None:
        source = stream_csv_columns(path, schema, chunk_rows=sample_rows)
    else:
        source = stream_split_columns(
            path, schema, split, split_seed, chunk_rows=sample_rows
        )
    # Accumulate until the sample is full — with a split filter each raw
    # chunk only contributes that split's share (~64% for train), so one
    # chunk would silently under-fill the requested sample.
    parts: list[dict[str, np.ndarray]] = []
    got = 0
    for columns in source:
        parts.append(columns)
        got += len(next(iter(columns.values())))
        if got >= sample_rows:
            break
    if not parts:
        raise ValueError(
            f"{path}: empty CSV" + (f" (split {split!r})" if split else "")
        )
    merged = {
        k: np.concatenate([p[k] for p in parts])[:sample_rows] for k in parts[0]
    }
    return FeaturePipeline(schema).fit(merged)
