"""Streaming CSV ingest: larger-than-memory tables → fixed-shape batches.

The reference's scale story is cluster-resident HDFS data read by Spark
executors (reference Readme.md:3, cnn.py:65). The TPU-host equivalent for
tables that don't fit in RAM: stream the headerless CSV in bounded row
chunks, transform each chunk with an ALREADY-FITTED feature pipeline (fit
on a training sample — never refit mid-stream, preserving the
fit-once-on-train discipline of SURVEY.md C6), and emit fixed-size device
batches. Composes with ``tpuflow.data.prefetch`` for host→device overlap.
"""

from __future__ import annotations

from typing import Iterator

import numpy as np

from tpuflow.data.csv_io import iter_csv_lines, parse_rows
from tpuflow.data.features import FeaturePipeline
from tpuflow.data.schema import Schema


def stream_csv_columns(
    path: str, schema: Schema, chunk_rows: int = 65536
) -> Iterator[dict[str, np.ndarray]]:
    """Yield the CSV as a sequence of column-dict chunks of ≤ chunk_rows.

    Memory is bounded by ``chunk_rows``, not the file size. Parsing and
    validation are shared with the whole-file reader (csv_io.parse_rows),
    with true file line numbers in every error.
    """
    rows: list[tuple[int, str]] = []
    for lineno, line in iter_csv_lines(path):
        rows.append((lineno, line))
        if len(rows) >= chunk_rows:
            yield parse_rows(rows, schema, source=path)
            rows = []
    if rows:
        yield parse_rows(rows, schema, source=path)


def stream_batches(
    path: str,
    pipeline: FeaturePipeline,
    batch_size: int,
    chunk_rows: int = 65536,
    drop_remainder: bool = True,
    shuffle_buffer: int = 0,
    seed: int = 0,
) -> Iterator[tuple[np.ndarray, np.ndarray]]:
    """Stream fixed-size (x, y) training batches from a large CSV.

    ``pipeline`` must already be fitted (on a train sample). Rows carry
    over between chunks so every batch has exactly ``batch_size`` rows;
    with ``drop_remainder`` the ragged tail is dropped (one XLA shape for
    the whole stream — SURVEY.md §7's no-recompilation discipline).

    ``shuffle_buffer > 0`` decorrelates the stream for SGD without
    materializing it: rows pass through a ``shuffle_buffer``-row windowed
    shuffle (the bounded-memory analog of a full-epoch permutation; memory
    stays O(shuffle_buffer) regardless of file size).
    """
    if not pipeline.fitted:
        raise RuntimeError("stream_batches requires a fitted pipeline")
    rng = np.random.default_rng(seed) if shuffle_buffer else None
    x_rem: np.ndarray | None = None
    y_rem: np.ndarray | None = None
    for columns in stream_csv_columns(path, pipeline.schema, chunk_rows):
        x = pipeline.transform(columns)
        y = pipeline.transform_target(columns)
        if x_rem is not None:
            x = np.concatenate([x_rem, x])
            y = np.concatenate([y_rem, y])
        if rng is not None:
            # Windowed shuffle: permute the whole buffer, emit its head,
            # hold back up to shuffle_buffer rows to mix with later
            # chunks. Until the buffer exceeds shuffle_buffer nothing is
            # emitted — rows accumulate so the window is always full.
            perm = rng.permutation(len(x))
            x, y = x[perm], y[perm]
            hold = min(len(x), shuffle_buffer)
        else:
            hold = 0
        n_avail = max(len(x) - hold, 0)
        n_full = n_avail // batch_size * batch_size
        for s in range(0, n_full, batch_size):
            yield x[s : s + batch_size], y[s : s + batch_size]
        x_rem, y_rem = x[n_full:], y[n_full:]
    # Drain the tail; rows held back by the shuffle are already the tail
    # of a uniform permutation, so no extra shuffle is needed here.
    if x_rem is not None and len(x_rem):
        n_full = len(x_rem) // batch_size * batch_size
        for s in range(0, n_full, batch_size):
            yield x_rem[s : s + batch_size], y_rem[s : s + batch_size]
        if not drop_remainder and n_full < len(x_rem):
            yield x_rem[n_full:], y_rem[n_full:]


def fit_pipeline_on_sample(
    path: str, schema: Schema, sample_rows: int = 100_000
) -> FeaturePipeline:
    """Fit the feature pipeline on the stream's head.

    The streaming analog of fit-on-train: stats and vocabularies come from
    a bounded sample instead of a full materialized split.
    """
    for columns in stream_csv_columns(path, schema, chunk_rows=sample_rows):
        return FeaturePipeline(schema).fit(columns)
    raise ValueError(f"{path}: empty CSV")
