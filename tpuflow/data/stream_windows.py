"""Out-of-core ingest for the SEQUENCE family: stream windows, not rows.

The tabular streaming path (``tpuflow.data.stream``) splits by ROW; a
sequence model cannot — a window must come from one well's contiguous
log, and train/val/test must not share a well (windows from the same well
are heavily correlated). This module streams multi-well CSVs at bounded
memory with the right invariants:

- **split by WELL**: each well id hashes to train/val/test with the
  64/16/20 fractions (deterministic, chunking-invariant) — no window ever
  straddles a split, no well leaks across splits;
- **per-well carry**: rows are grouped by the well column per chunk; each
  well's trailing ``window-1`` rows carry over to the next chunk, so
  windows crossing chunk boundaries are emitted exactly once. Memory is
  O(active wells × window), not file size;
- **stats from a head sample**: channel mean/std and target mean/std come
  from the first ``sample_rows`` train-split rows (the streaming analog of
  fit-on-train), held in a ``WindowNormalizer`` that also serves as the
  serving-sidecar state.

Rows must be time-ordered within each well (the same contract as the
materialized ``prepare_windowed_table``); wells may interleave freely.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass
from typing import Iterator

import numpy as np

from tpuflow.data.schema import Schema
from tpuflow.data.stream import SPLIT_FRACTIONS, stream_csv_columns

_SPLITS = ("train", "val", "test")


def well_split(well_id, seed: int, fractions=SPLIT_FRACTIONS) -> int:
    """Deterministic split id (0=train, 1=val, 2=test) for one well.

    Hash of (str(well_id), seed) — stable across runs, processes, and
    chunk sizes (Python's builtin hash is salted per process; blake2b is
    not).
    """
    digest = hashlib.blake2b(
        f"{well_id}\x00{seed}".encode(), digest_size=8
    ).digest()
    u = int.from_bytes(digest, "big") / float(1 << 64)
    bounds = np.cumsum(fractions)
    return int(np.digitize(u, bounds[:-1]))


@dataclass
class WindowNormalizer:
    """Per-channel and target standardization stats for windowed streams —
    fit on a head sample of train wells; doubles as the serving sidecar
    state (same fields the materialized ``WindowedSplits`` carries)."""

    feature_names: tuple
    mean: np.ndarray
    std: np.ndarray
    target_mean: float
    target_std: float

    # WindowedSplits-compatible aliases: the serving-sidecar writer reads
    # .norm_mean/.norm_std, so a normalizer can stand in for the
    # materialized splits object directly.
    @property
    def norm_mean(self) -> np.ndarray:
        return self.mean

    @property
    def norm_std(self) -> np.ndarray:
        return self.std

    def normalize(self, windows: np.ndarray) -> np.ndarray:
        return ((windows - self.mean) / self.std).astype(np.float32)

    def normalize_target(self, y: np.ndarray) -> np.ndarray:
        return ((y - self.target_mean) / self.target_std).astype(np.float32)


class _WellWindower:
    """Per-well carry buffers → teacher-forced windows, across chunks."""

    def __init__(self, window: int, stride: int):
        self.window = window
        self.stride = stride
        # well id -> (feature rows carry, target rows carry, next emit offset)
        self._carry: dict = {}

    def feed(self, well, series: np.ndarray, target: np.ndarray):
        """Append one well's new rows; return the newly-complete windows."""
        prev_s, prev_t, offset = self._carry.get(
            well, (np.zeros((0, series.shape[1]), np.float32),
                   np.zeros((0,), np.float32), 0)
        )
        s = np.concatenate([prev_s, series])
        t = np.concatenate([prev_t, target])
        if len(s) < self.window:
            # Preserve the emit offset (can be > 0 with stride > 1).
            self._carry[well] = (s, t, offset)
            return None
        # Windows starting at offset, offset+stride, ... within this
        # buffer — extracted by the shared engine (tpuflow.data.windows:
        # C++ fast path, vectorized stride-trick fallback).
        starts = np.arange(offset, len(s) - self.window + 1, self.stride)
        if len(starts):
            from tpuflow.data.windows import teacher_forcing_pairs

            x, y = teacher_forcing_pairs(
                s[offset:], t[offset:], self.window, self.stride
            )
            next_start = starts[-1] + self.stride
        else:
            x = y = None
            next_start = offset
        # Keep only the tail that future windows can still reach.
        keep_from = min(next_start, len(s) - self.window + 1)
        keep_from = max(keep_from, 0)
        self._carry[well] = (s[keep_from:], t[keep_from:], next_start - keep_from)
        return (x, y) if x is not None else None


def _iter_split_windows(
    path: str,
    schema: Schema,
    well_column: str,
    feature_names: tuple,
    seed: int,
    window: int,
    stride: int = 1,
    chunk_rows: int = 65536,
    wanted: frozenset | None = None,
) -> Iterator[tuple[int, int, np.ndarray, np.ndarray]]:
    """Yield (split_id, n_source_rows, x, y) for every well's windows in
    ONE file scan — the single engine under ``iter_windows`` and the
    multi-split materializer. ``wanted`` restricts which splits are even
    windowed (others are skipped without buffering).
    """
    windower = _WellWindower(window, stride)
    target_col = schema.target
    split_cache: dict = {}
    for columns in stream_csv_columns(path, schema, chunk_rows):
        ids = np.asarray(columns[well_column])
        uniq, first_idx, inverse, counts = np.unique(
            ids, return_index=True, return_inverse=True, return_counts=True
        )
        kept_wells = []
        for i in np.argsort(first_idx):  # first-appearance order
            well = uniq[i]
            sid = split_cache.get(well)
            if sid is None:
                sid = split_cache[well] = well_split(well, seed)
            if wanted is None or sid in wanted:
                kept_wells.append((i, well, sid))
        if not kept_wells:
            continue
        # Convert only the kept wells' rows to float32 — a train-only scan
        # would otherwise stack and convert the ~36% val/test rows it is
        # about to discard (and an eval scan, the 64% train rows).
        clustered = np.argsort(inverse, kind="stable")
        slices = np.split(clustered, np.cumsum(counts)[:-1])
        for i, well, sid in kept_wells:
            rows = slices[i]
            # Slice only what the windower consumes — feature channels and
            # the target — not the well ids / bookkeeping columns.
            out = windower.feed(
                well,
                np.stack(
                    [np.asarray(columns[n][rows], np.float32)
                     for n in feature_names],
                    axis=1,
                ),
                np.asarray(columns[target_col][rows], np.float32),
            )
            if out is not None:
                yield sid, len(rows), out[0], out[1]


def iter_windows(
    path: str,
    schema: Schema,
    well_column: str,
    feature_names: tuple,
    split: str,
    seed: int,
    window: int,
    stride: int = 1,
    chunk_rows: int = 65536,
) -> Iterator[tuple[np.ndarray, np.ndarray]]:
    """Yield RAW (un-normalized) teacher-forced windows of one split.

    Streams the CSV once; memory is bounded by chunk size plus the
    per-well carry buffers.
    """
    want = _SPLITS.index(split)
    for sid, _, x, y in _iter_split_windows(
        path, schema, well_column, feature_names, seed, window, stride,
        chunk_rows, wanted=frozenset((want,)),
    ):
        yield x, y


def fit_window_normalizer(
    path: str,
    schema: Schema,
    well_column: str,
    seed: int,
    window: int,
    stride: int = 1,
    sample_rows: int = 100_000,
    chunk_rows: int = 65536,
) -> WindowNormalizer:
    """Fit channel/target stats on the head sample's TRAIN-well windows."""
    from tpuflow.data.pipeline import sequence_feature_names

    feature_names = sequence_feature_names(schema, well_column)
    xs, ys, got = [], [], 0
    for _, n_rows, x, y in _iter_split_windows(
        path, schema, well_column, feature_names, seed, window, stride,
        chunk_rows, wanted=frozenset((0,)),  # train wells only
    ):
        xs.append(x)
        ys.append(y)
        # Count SOURCE rows consumed, not overlapping window elements, so
        # sample_rows means the same thing here as on the tabular path.
        got += n_rows
        if got >= sample_rows:
            break
    if not xs:
        raise ValueError(
            f"{path}: no full {window}-step train-well windows in the sample"
        )
    x = np.concatenate(xs)
    y = np.concatenate(ys)
    flat = x.reshape(-1, x.shape[-1])
    mean = flat.mean(axis=0)
    std = flat.std(axis=0)
    std = np.where(std < 1e-8, 1.0, std).astype(np.float32)
    t_mean = float(y.mean())
    t_std = float(y.std()) or 1.0
    return WindowNormalizer(
        feature_names, mean.astype(np.float32), std, t_mean, t_std
    )


def stream_window_batches(
    path: str,
    schema: Schema,
    well_column: str,
    norm: WindowNormalizer,
    batch_size: int,
    seed: int,
    window: int,
    stride: int = 1,
    chunk_rows: int = 65536,
    shuffle_buffer: int = 0,
    shuffle_seed: int = 0,
    split: str = "train",
) -> Iterator[tuple[np.ndarray, np.ndarray]]:
    """Fixed-size NORMALIZED (x, y) window batches of one split.

    ``shuffle_buffer > 0`` decorrelates windows through a bounded windowed
    shuffle (same scheme as the tabular stream); batches always have
    exactly ``batch_size`` windows (drop-remainder — one XLA shape).
    """
    rng = np.random.default_rng(shuffle_seed) if shuffle_buffer else None
    x_rem = y_rem = None
    for x, y in iter_windows(
        path, schema, well_column, norm.feature_names, split, seed, window,
        stride, chunk_rows,
    ):
        x = norm.normalize(x)
        y = norm.normalize_target(y)
        if x_rem is not None:
            x = np.concatenate([x_rem, x])
            y = np.concatenate([y_rem, y])
        if rng is not None:
            perm = rng.permutation(len(x))
            x, y = x[perm], y[perm]
            hold = min(len(x), shuffle_buffer)
        else:
            hold = 0
        n_full = max(len(x) - hold, 0) // batch_size * batch_size
        for s in range(0, n_full, batch_size):
            yield x[s : s + batch_size], y[s : s + batch_size]
        x_rem, y_rem = x[n_full:], y[n_full:]
    if x_rem is not None and len(x_rem):
        n_full = len(x_rem) // batch_size * batch_size
        for s in range(0, n_full, batch_size):
            yield x_rem[s : s + batch_size], y_rem[s : s + batch_size]


def materialize_window_splits(
    path: str,
    schema: Schema,
    well_column: str,
    norm: WindowNormalizer,
    whichs: tuple[str, ...],
    seed: int,
    window: int,
    stride: int = 1,
    max_windows: int = 50_000,
    chunk_rows: int = 65536,
    raw_for: tuple[str, ...] = (),
) -> dict[str, tuple[np.ndarray, np.ndarray, np.ndarray | None, np.ndarray | None]]:
    """Up to ``max_windows`` windows of EACH requested split in one file
    scan: ``{which: (x_norm, y_norm, x_raw | None, y_raw | None)}``.

    Bounded eval samples. Raw copies (for the Gilbert-baseline MAE) are
    kept only for the splits in ``raw_for`` — don't retain hundreds of MB
    of un-normalized windows on the bounded-memory path. Stops scanning
    once every split hit its cap.
    """
    ids = {w: _SPLITS.index(w) for w in whichs}
    by_id = {v: k for k, v in ids.items()}
    acc = {w: {"xs": [], "ys": [], "got": 0} for w in whichs}
    for sid, _, x, y in _iter_split_windows(
        path, schema, well_column, norm.feature_names, seed, window, stride,
        chunk_rows, wanted=frozenset(ids.values()),
    ):
        a = acc[by_id[sid]]
        if a["got"] >= max_windows:
            if all(v["got"] >= max_windows for v in acc.values()):
                break
            continue
        take = min(len(x), max_windows - a["got"])
        a["xs"].append(x[:take])
        a["ys"].append(y[:take])
        a["got"] += take
    out = {}
    for which, a in acc.items():
        if not a["xs"]:
            raise ValueError(f"{path}: split {which!r} has no full windows")
        x_raw = np.concatenate(a["xs"])
        y_raw = np.concatenate(a["ys"])
        keep_raw = which in raw_for
        out[which] = (
            norm.normalize(x_raw),
            norm.normalize_target(y_raw),
            x_raw if keep_raw else None,
            y_raw if keep_raw else None,
        )
    return out


def materialize_window_split(
    path: str,
    schema: Schema,
    well_column: str,
    norm: WindowNormalizer,
    split: str,
    seed: int,
    window: int,
    stride: int = 1,
    max_windows: int = 50_000,
    chunk_rows: int = 65536,
) -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """One-split convenience wrapper (raw copies included)."""
    return materialize_window_splits(
        path, schema, well_column, norm, (split,), seed, window, stride,
        max_windows, chunk_rows, raw_for=(split,),
    )[split]
