"""Dynamic schema: per-submission column names/types as runtime config.

The reference's load-bearing design constraint: "the features were changing
at each learning job submission" (reference Readme.md:25), so the schema is
a *runtime input*, not code. Its contract is positional CLI strings —
comma-separated names and types, plus a target column (reference
cnn.py:2,41-44,59-60) — with the type mapping int→IntegerType,
float→FloatType, anything else→StringType (reference cnn.py:53-58).

This module keeps that exact contract (``Schema.from_cli``) but resolves it
eagerly into a typed, validated object. Column kinds drive feature handling
exactly as the reference intended: int/float columns are continuous
features (reference cnn.py:93), everything else is categorical (reference
cnn.py:72).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

# Reference type-string mapping (cnn.py:53-58): int | float | <anything else>.
_NUMPY_DTYPES = {"int": np.int32, "float": np.float32}
CONTINUOUS_KINDS = ("int", "float")


@dataclass(frozen=True)
class ColumnSpec:
    """One column: its name and reference-style type string."""

    name: str
    kind: str  # "int" | "float" | anything-else == categorical string

    @property
    def is_continuous(self) -> bool:
        return self.kind in CONTINUOUS_KINDS

    @property
    def numpy_dtype(self) -> np.dtype:
        return np.dtype(_NUMPY_DTYPES.get(self.kind, np.str_))


@dataclass(frozen=True)
class Schema:
    """A full per-submission schema: ordered columns plus the target.

    ``target=None`` denotes a features-only schema — unlabeled data at
    serving time (tpuflow.api.predict), where the target column the model
    was trained on does not exist yet.
    """

    columns: tuple[ColumnSpec, ...]
    target: str | None
    _by_name: dict = field(init=False, repr=False, compare=False, hash=False)

    def __post_init__(self):
        names = [c.name for c in self.columns]
        if len(set(names)) != len(names):
            dupes = sorted({n for n in names if names.count(n) > 1})
            raise ValueError(f"duplicate column names: {dupes}")
        if self.target is not None and self.target not in names:
            raise ValueError(
                f"target column {self.target!r} not in schema columns {names}"
            )
        object.__setattr__(self, "_by_name", {c.name: c for c in self.columns})

    @classmethod
    def from_cli(cls, names_csv: str, types_csv: str, target: str) -> "Schema":
        """Parse the reference's positional CLI contract.

        ``names_csv`` and ``types_csv`` are comma-separated (reference
        cnn.py:59-60); ``target`` is the target column name (cnn.py:43).
        """
        names = [n.strip() for n in names_csv.split(",") if n.strip()]
        kinds = [t.strip() for t in types_csv.split(",") if t.strip()]
        if len(names) != len(kinds):
            raise ValueError(
                f"{len(names)} column names but {len(kinds)} types"
            )
        return cls(
            columns=tuple(ColumnSpec(n, k) for n, k in zip(names, kinds)),
            target=target,
        )

    def __getitem__(self, name: str) -> ColumnSpec:
        return self._by_name[name]

    @property
    def names(self) -> tuple[str, ...]:
        return tuple(c.name for c in self.columns)

    @property
    def feature_columns(self) -> tuple[ColumnSpec, ...]:
        """All non-target columns, in schema order."""
        return tuple(c for c in self.columns if c.name != self.target)

    @property
    def continuous_features(self) -> tuple[ColumnSpec, ...]:
        """int/float feature columns (reference cnn.py:93 selection)."""
        return tuple(c for c in self.feature_columns if c.is_continuous)

    @property
    def categorical_features(self) -> tuple[ColumnSpec, ...]:
        """Non-numeric feature columns (reference cnn.py:72 selection)."""
        return tuple(c for c in self.feature_columns if not c.is_continuous)

    @property
    def target_spec(self) -> ColumnSpec:
        return self._by_name[self.target]
