"""End-to-end host pipeline: columns -> splits -> features -> device batches.

The composition layer that makes SURVEY.md §3.1's broken seam real: raw
dynamic-schema columns become static-shape float32 arrays, split 64/16/20
(reference cnn.py:68), featurized by a pipeline fit once on train, and
served as fixed-size minibatches ready for a jitted train step.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Iterator, NamedTuple, Sequence

import numpy as np

from tpuflow.data.features import FeaturePipeline
from tpuflow.data.schema import Schema
from tpuflow.data.splits import DEFAULT_FRACTIONS, random_split
from tpuflow.data.synthetic import WellLog
from tpuflow.data.windows import sliding_windows, teacher_forcing_pairs


class ArrayDataset(NamedTuple):
    """Device-ready arrays: x [N, ...] float32, y [N] or [N, T] float32."""

    x: np.ndarray
    y: np.ndarray

    @property
    def n(self) -> int:
        return len(self.x)


@dataclass
class TabularSplits:
    train: ArrayDataset
    val: ArrayDataset
    test: ArrayDataset
    pipeline: FeaturePipeline


def _take(columns: dict[str, np.ndarray], idx: np.ndarray) -> dict[str, np.ndarray]:
    return {k: v[idx] for k, v in columns.items()}


def prepare_tabular(
    schema: Schema,
    columns: dict[str, np.ndarray],
    seed: int = 0,
    fractions: Sequence[float] = DEFAULT_FRACTIONS,
    standardize: bool = True,
    standardize_target: bool = True,
    append_gilbert: bool = False,
) -> TabularSplits:
    """Static-model path: split, fit features on train ONLY, transform all.

    ``append_gilbert`` adds the RAW (un-standardized) Gilbert-equation
    prediction as the last feature column — the input contract of the
    physics-informed ``GilbertResidualMLP``, which multiplies that column
    by a learned correction and standardizes its own output with the
    train-split target stats (so targets stay standardized here, keeping
    the clip=6 loss meaningful). Requires pressure/choke/glr columns.
    """
    n = len(next(iter(columns.values())))
    tr, va, te = (
        _take(columns, idx) for idx in random_split(n, fractions, seed)
    )
    pipe = FeaturePipeline(
        schema, standardize=standardize, standardize_target=standardize_target
    ).fit(tr)

    def mk(c):
        x = pipe.transform(c)
        if append_gilbert:
            from tpuflow.core.gilbert import append_gilbert_column

            x = append_gilbert_column(x, c)
        return ArrayDataset(x, pipe.transform_target(c))

    return TabularSplits(mk(tr), mk(va), mk(te), pipe)


@dataclass
class WindowedSplits:
    train: ArrayDataset
    val: ArrayDataset
    test: ArrayDataset
    feature_names: tuple[str, ...]
    norm_mean: np.ndarray
    norm_std: np.ndarray
    # Target standardization (train stats): training runs in scaled units so
    # the clip=6 loss is meaningful; invert with y*target_std + target_mean.
    target_mean: float = 0.0
    target_std: float = 1.0

    def inverse_target(self, y: np.ndarray) -> np.ndarray:
        return np.asarray(y) * self.target_std + self.target_mean


_SEQ_CHANNELS = ("pressure", "choke", "glr", "temperature", "water_cut")


def sequence_feature_names(schema: Schema, well_column: str | None) -> tuple[str, ...]:
    """The sequence-model feature channels: the schema's continuous feature
    columns minus the well-grouping column, in schema order (the analog of
    the reference's continuous selection, cnn.py:93). Single source for
    the materialized and streaming windowed paths — their channel ORDER
    must agree or a stream-trained sidecar would serve scrambled inputs.
    """
    names = tuple(
        c.name for c in schema.continuous_features if c.name != well_column
    )
    if not names:
        raise ValueError("no continuous feature columns for sequence model")
    return names


def prepare_windowed(
    wells: Sequence[WellLog],
    window: int = 24,
    stride: int = 1,
    seed: int = 0,
    fractions: Sequence[float] = DEFAULT_FRACTIONS,
    teacher_forcing: bool = False,
    append_gilbert: bool = False,
) -> WindowedSplits:
    """Sequence-model path: window each well's log, then split by window.

    Splitting happens at the *window* level across all wells (the
    multi-well training population), with normalization stats computed from
    the training windows only. ``append_gilbert`` adds the RAW per-timestep
    Gilbert prediction as the last channel (see ``_windowed_from_pairs``).
    """
    pairs = [
        (
            np.stack([getattr(w, ch) for ch in _SEQ_CHANNELS], axis=1).astype(
                np.float32
            ),
            w.flow,
        )
        for w in wells
    ]
    return _windowed_from_pairs(
        pairs, _SEQ_CHANNELS, window, stride, seed, fractions, teacher_forcing,
        append_gilbert,
    )


def prepare_windowed_table(
    schema: Schema,
    columns: dict[str, np.ndarray],
    well_column: str | None = None,
    window: int = 24,
    stride: int = 1,
    seed: int = 0,
    fractions: Sequence[float] = DEFAULT_FRACTIONS,
    teacher_forcing: bool = False,
    append_gilbert: bool = False,
) -> WindowedSplits:
    """Sequence-model path from a dynamic-schema table (CSV ingest).

    Rows are assumed time-ordered within each well. ``well_column`` groups
    rows into per-well logs (the multi-well population); ``None`` treats
    the whole table as a single well's log. Features are the schema's
    continuous feature columns (minus the grouping column), in schema
    order — the sequence-model analog of the reference's continuous
    selection (reference cnn.py:93).
    """
    feature_names = sequence_feature_names(schema, well_column)
    target = columns[schema.target].astype(np.float32)
    series_all = np.stack(
        [columns[n].astype(np.float32) for n in feature_names], axis=1
    )
    if well_column is None:
        pairs = [(series_all, target)]
    else:
        # One-pass grouping: stable argsort of the inverse codes clusters
        # each well's rows while preserving their original (time) order.
        ids = np.asarray(columns[well_column])
        _, inverse, counts = np.unique(
            ids, return_inverse=True, return_counts=True
        )
        grouped = np.argsort(inverse, kind="stable")
        pairs = [
            (series_all[rows], target[rows])
            for rows in np.split(grouped, np.cumsum(counts)[:-1])
        ]
    return _windowed_from_pairs(
        pairs, feature_names, window, stride, seed, fractions, teacher_forcing,
        append_gilbert,
    )


def _windowed_from_pairs(
    pairs: Sequence[tuple[np.ndarray, np.ndarray]],
    feature_names: tuple[str, ...],
    window: int,
    stride: int,
    seed: int,
    fractions: Sequence[float],
    teacher_forcing: bool,
    append_gilbert: bool = False,
) -> WindowedSplits:
    if append_gilbert:
        # Per-timestep RAW Gilbert prediction as the LAST channel — the
        # input contract of GilbertResidualLSTM: computed from the raw
        # series BEFORE normalization, and excluded from it below
        # (mean 0 / std 1) so the model receives raw physical flow. Shared
        # helper with the serving path (append_gilbert_channel) so the two
        # can never drift.
        from tpuflow.core.gilbert import append_gilbert_channel

        pairs = [
            (append_gilbert_channel(series, feature_names), target)
            for series, target in pairs
        ]
    xs, ys = [], []
    for series, target in pairs:
        fn = teacher_forcing_pairs if teacher_forcing else sliding_windows
        x, y = fn(series, target, length=window, stride=stride)
        if len(x):
            xs.append(x)
            ys.append(y)
    if not xs:
        raise ValueError(
            f"no windows: every series is shorter than window={window}"
        )
    x = np.concatenate(xs, axis=0)
    y = np.concatenate(ys, axis=0)
    tr_i, va_i, te_i = random_split(len(x), fractions, seed)

    mean = x[tr_i].reshape(-1, x.shape[-1]).mean(axis=0)
    std = x[tr_i].reshape(-1, x.shape[-1]).std(axis=0)
    std = np.where(std < 1e-8, 1.0, std).astype(np.float32)
    if append_gilbert:
        # The appended physical channel stays RAW (the model multiplies it
        # by a learned correction); identity stats keep the stored
        # mean/std aligned with the serving path's normalization.
        mean = mean.copy()
        mean[-1] = 0.0
        std[-1] = 1.0
    norm = lambda a: ((a - mean) / std).astype(np.float32)

    t_mean = float(y[tr_i].mean())
    t_std = float(y[tr_i].std())
    t_std = t_std if t_std > 1e-8 else 1.0
    norm_y = lambda a: ((a - t_mean) / t_std).astype(np.float32)

    mk = lambda idx: ArrayDataset(norm(x[idx]), norm_y(y[idx]))
    return WindowedSplits(
        mk(tr_i), mk(va_i), mk(te_i), tuple(feature_names), mean, std, t_mean, t_std
    )


def batches(
    dataset: ArrayDataset,
    batch_size: int,
    seed: int | None = None,
    drop_remainder: bool = True,
) -> Iterator[tuple[np.ndarray, np.ndarray]]:
    """Minibatch iterator with optional shuffling.

    ``drop_remainder=True`` keeps every batch the same shape — one XLA
    compilation for the whole epoch (SURVEY.md §7: no per-schema/shape
    recompilation blowups).
    """
    n = dataset.n
    order = (
        np.random.default_rng(seed).permutation(n)
        if seed is not None
        else np.arange(n)
    )
    stop = n - (n % batch_size) if drop_remainder else n
    for s in range(0, stop, batch_size):
        idx = order[s : s + batch_size]
        yield dataset.x[idx], dataset.y[idx]
