"""Background batch prefetching: overlap host ETL with device compute.

The host pipeline must never bound samples/sec (SURVEY.md §7 "hard parts":
"careful host-pipeline overlap so input feed doesn't bound samples/sec").
``prefetch`` runs the upstream batch iterator in a daemon thread and keeps
a small bounded queue of ready batches; ``device_prefetch`` additionally
moves them onto the device (optionally sharded over a mesh) ahead of use,
so the accelerator never waits on a host→device copy.
"""

from __future__ import annotations

import queue
import threading
from typing import Iterable, Iterator

_SENTINEL = object()


def prefetch(iterator: Iterable, buffer_size: int = 2) -> Iterator:
    """Run ``iterator`` in a background thread, ``buffer_size`` items ahead.

    If the consumer abandons the generator early (``close()``, GC, or an
    exception mid-epoch), the worker observes ``stop`` at its next bounded
    ``put`` and exits instead of blocking forever on the full queue.
    """
    q: queue.Queue = queue.Queue(maxsize=buffer_size)
    err: list[BaseException] = []
    stop = threading.Event()

    def worker():
        try:
            for item in iterator:
                while not stop.is_set():
                    try:
                        q.put(item, timeout=0.1)
                        break
                    except queue.Full:
                        continue
                if stop.is_set():
                    return
        except BaseException as e:  # re-raised on the consumer side
            err.append(e)
        finally:
            while not stop.is_set():
                try:
                    q.put(_SENTINEL, timeout=0.1)
                    break
                except queue.Full:
                    continue

    # Registry-backed feed accounting (process-wide): total batches fed
    # and how often the CONSUMER found the buffer empty — the signal
    # that the host pipeline, not the device, is the bottleneck.
    # Recorded outside any jitted code (TPF005).
    from tpuflow.obs import default_registry

    reg = default_registry()
    fed = reg.counter(
        "data_prefetch_batches_total", "batches handed to the consumer"
    )
    starved = reg.counter(
        "data_prefetch_starvation_total",
        "consumer arrivals that found the prefetch buffer empty",
    )

    t = threading.Thread(target=worker, name="tpuflow-data-prefetch", daemon=True)
    t.start()
    try:
        yielded = False
        while True:
            empty_on_arrival = q.empty()
            item = q.get()
            if item is _SENTINEL:
                if err:
                    raise err[0]
                return
            # Starvation = the consumer found the buffer empty MID-epoch
            # and the wait produced a real batch. The first get (worker
            # just started) and the end-of-stream sentinel are inherent
            # empties, not a host-pipeline bottleneck — counting them
            # would flag every healthy epoch.
            if empty_on_arrival and yielded:
                starved.inc()
            fed.inc()
            yielded = True
            yield item
    finally:
        stop.set()


def device_prefetch(
    iterator: Iterable,
    buffer_size: int = 2,
    sharding=None,
) -> Iterator:
    """Prefetch AND device_put batches ahead of consumption.

    Each yielded item is a tuple of device arrays. With ``sharding`` (e.g.
    ``tpuflow.parallel.data_sharding(mesh)``) the batch lands pre-sharded
    over the mesh; otherwise it goes to the default device. The transfer of
    batch k+1 overlaps the compute of batch k.
    """
    import jax

    from tpuflow.parallel.placement import device_put

    def put(item):
        if sharding is None:
            return jax.tree_util.tree_map(device_put, item)
        return jax.tree_util.tree_map(
            lambda a: device_put(a, sharding), item
        )

    return prefetch((put(item) for item in iterator), buffer_size)
