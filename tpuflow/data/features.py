"""Feature ETL: categorical indexing, one-hot, assembly, standardization.

TPU-native rebuild of the reference's Spark ML pipeline (reference
cnn.py:71-107): ``StringIndexer`` per categorical column → ``OneHotEncoder``
→ ``VectorAssembler`` merging one-hots with the continuous columns into a
single ``features`` matrix, plus the target label indexer the reference
created but never wired in (reference cnn.py:106-107, SURVEY.md C8).

Two reference bugs are deliberately fixed (SURVEY.md C6):
- The pipeline is **fit exactly once on the training split** and then
  applied to val/test, so category indices are consistent across splits
  (the reference re-fit per split, reference cnn.py:89-91).
- Unknown categories at transform time map to an all-zeros one-hot instead
  of crashing.

Vocabularies are ordered by descending training frequency (ties broken
lexically), matching Spark ``StringIndexer``'s default ``frequencyDesc``.
Output is a dense float32 ``[N, F]`` matrix with a *static* feature width —
the shape contract XLA compilation needs (SURVEY.md §7 "hard parts").
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from tpuflow.data.schema import Schema


def _vocab_frequency_desc(values: np.ndarray) -> list[str]:
    uniq, counts = np.unique(values, return_counts=True)
    order = np.lexsort((uniq, -counts))  # freq desc, then lexical
    return [str(u) for u in uniq[order]]


@dataclass
class FeaturePipeline:
    """Fit-once / transform-many feature pipeline for a dynamic schema."""

    schema: Schema
    standardize: bool = True
    standardize_target: bool = True
    vocabs: dict[str, list[str]] = field(default_factory=dict)
    target_vocab: list[str] | None = None
    mean_: np.ndarray | None = None
    std_: np.ndarray | None = None
    target_mean_: float = 0.0
    target_std_: float = 1.0
    fitted: bool = False

    def fit(self, train_columns: dict[str, np.ndarray]) -> "FeaturePipeline":
        """Learn vocabularies and standardization stats from TRAIN only."""
        for spec in self.schema.categorical_features:
            self.vocabs[spec.name] = _vocab_frequency_desc(
                train_columns[spec.name]
            )
        tspec = self.schema.target_spec
        if not tspec.is_continuous:
            # The reference's intended target StringIndexer (cnn.py:106-107).
            self.target_vocab = _vocab_frequency_desc(train_columns[tspec.name])
        elif self.standardize_target:
            # Targets TRAIN in standardized units: with raw flow targets
            # (O(10^3) stb/day) every residual would saturate the clip=6
            # loss and its gradient is exactly zero — the loss only makes
            # sense on O(1)-scale targets (SURVEY.md §7 "accuracy parity
            # discipline"). Metrics are reported back in raw units via
            # ``target_std`` / ``inverse_target``.
            tv = np.asarray(train_columns[tspec.name], dtype=np.float64)
            self.target_mean_ = float(tv.mean())
            std = float(tv.std())
            self.target_std_ = std if std > 1e-8 else 1.0
        raw = self._assemble(train_columns)
        if self.standardize:
            self.mean_ = raw.mean(axis=0)
            std = raw.std(axis=0)
            self.std_ = np.where(std < 1e-8, 1.0, std).astype(np.float32)
        self.fitted = True
        return self

    def inverse_target(self, y: np.ndarray) -> np.ndarray:
        """Scaled-unit predictions/targets back to raw units."""
        return np.asarray(y) * self.target_std_ + self.target_mean_

    def to_dict(self) -> dict:
        """JSON-serializable state for serving (tpuflow.api.predict)."""
        return {
            "names": [c.name for c in self.schema.columns],
            "kinds": [c.kind for c in self.schema.columns],
            "target": self.schema.target,
            "standardize": self.standardize,
            "standardize_target": self.standardize_target,
            "vocabs": self.vocabs,
            "target_vocab": self.target_vocab,
            "mean": None if self.mean_ is None else self.mean_.tolist(),
            "std": None if self.std_ is None else self.std_.tolist(),
            "target_mean": self.target_mean_,
            "target_std": self.target_std_,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "FeaturePipeline":
        from tpuflow.data.schema import ColumnSpec

        schema = Schema(
            columns=tuple(
                ColumnSpec(n, k) for n, k in zip(d["names"], d["kinds"])
            ),
            target=d["target"],
        )
        pipe = cls(
            schema,
            standardize=d["standardize"],
            standardize_target=d["standardize_target"],
        )
        pipe.vocabs = {k: list(v) for k, v in d["vocabs"].items()}
        pipe.target_vocab = d["target_vocab"]
        pipe.mean_ = None if d["mean"] is None else np.asarray(d["mean"], np.float32)
        pipe.std_ = None if d["std"] is None else np.asarray(d["std"], np.float32)
        pipe.target_mean_ = float(d["target_mean"])
        pipe.target_std_ = float(d["target_std"])
        pipe.fitted = True
        return pipe

    @property
    def feature_dim(self) -> int:
        """Static width of the assembled feature vector."""
        dim = len(self.schema.continuous_features)
        for spec in self.schema.categorical_features:
            dim += len(self.vocabs[spec.name])
        return dim

    def _one_hot(self, name: str, values: np.ndarray) -> np.ndarray:
        vocab = self.vocabs[name]
        index = {v: i for i, v in enumerate(vocab)}
        out = np.zeros((len(values), len(vocab)), dtype=np.float32)
        for row, v in enumerate(values):
            j = index.get(str(v))
            if j is not None:  # unknown category -> all-zeros row
                out[row, j] = 1.0
        return out

    def _assemble(self, columns: dict[str, np.ndarray]) -> np.ndarray:
        """One-hot categoricals + continuous columns -> [N, F] float32.

        Column order: categorical one-hot blocks (schema order) first, then
        continuous columns (schema order) — the reference's assembler order
        (`categorical-features` vector then continuous cols, cnn.py:96-99).
        """
        blocks = [
            self._one_hot(spec.name, columns[spec.name])
            for spec in self.schema.categorical_features
        ]
        for spec in self.schema.continuous_features:
            blocks.append(
                np.asarray(columns[spec.name], dtype=np.float32)[:, None]
            )
        if not blocks:
            raise ValueError("schema has no feature columns")
        return np.concatenate(blocks, axis=1)

    def transform(self, columns: dict[str, np.ndarray]) -> np.ndarray:
        if not self.fitted:
            raise RuntimeError("FeaturePipeline.transform before fit")
        out = self._assemble(columns)
        if self.standardize:
            out = (out - self.mean_) / self.std_
        return out.astype(np.float32)

    def transform_target(self, columns: dict[str, np.ndarray]) -> np.ndarray:
        """Target column -> float32 vector (label-indexed if categorical)."""
        if not self.fitted:
            raise RuntimeError("FeaturePipeline.transform_target before fit")
        tspec = self.schema.target_spec
        values = columns[tspec.name]
        if tspec.is_continuous:
            y = np.asarray(values, dtype=np.float32)
            if self.standardize_target:
                y = (y - self.target_mean_) / self.target_std_
            return y.astype(np.float32)
        index = {v: i for i, v in enumerate(self.target_vocab)}
        return np.asarray(
            [index.get(str(v), -1) for v in values], dtype=np.float32
        )
