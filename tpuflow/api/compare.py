"""Multi-model comparison runs — the reference's experiment workflow.

"We had to make tests on our computing services using multiple model
types" (reference Readme.md:13): the reference system's test strategy WAS
comparative model experiments (SURVEY.md §4). This module makes that
workflow one call: train each model family on the same data/seed, collect
test MAE (raw units), throughput, and the Gilbert-baseline comparison into
one ranked report.
"""

from __future__ import annotations

import dataclasses
import math
from dataclasses import dataclass, field

import jax

from tpuflow.api.config import TrainJobConfig
from tpuflow.api.train_api import train

DEFAULT_MODELS = (
    "static_mlp",
    "dynamic_mlp",
    "cnn1d",
    "lstm",
    "stacked_lstm",
    "attention",  # after the LSTMs: shares their cached preparation
    "gilbert_residual",
    "lstm_residual",
)


class RankedByMAE:
    """Shared ranking machinery for experiment reports (compare/sweep).

    Ranks successful results by held-out MAE ascending. A result whose MAE
    is NaN (a diverged run that didn't raise) is excluded like a failure —
    NaN keys would make the sort order arbitrary and could crown a
    diverged run ``best``.
    """

    @property
    def ranked(self):
        ok = [
            r
            for r in self.results
            if r.error is None and not math.isnan(r.test_mae)
        ]
        return sorted(ok, key=lambda r: r.test_mae)

    @property
    def failed(self):
        """(result, reason) for every run the ranking excludes — the one
        source of truth for the failure predicate, shared by the tables
        and the job-server JSON reports so they can't disagree."""
        out = []
        for r in self.results:
            if r.error is not None:
                out.append((r, r.error))
            elif math.isnan(r.test_mae):
                out.append((r, "diverged (NaN MAE)"))
        return out

    @property
    def best(self):
        ranked = self.ranked
        if not ranked:
            raise RuntimeError("nothing trained successfully")
        return ranked[0]


@dataclass
class ModelResult:
    model: str
    test_mae: float
    test_loss: float
    gilbert_mae: float | None
    samples_per_sec: float
    epochs_ran: int
    time_elapsed: float
    param_count: int = 0
    error: str | None = None


@dataclass
class ComparisonReport(RankedByMAE):
    results: list[ModelResult] = field(default_factory=list)

    def table(self) -> str:
        """The per-model report the reference printed ad hoc, as one table."""
        lines = [
            f"{'model':<16} {'params':>9} {'test MAE':>12} {'vs Gilbert':>11} "
            f"{'samples/s':>12} {'epochs':>7} {'time':>8}"
        ]
        for r in self.ranked:
            vs = (
                f"{r.test_mae / r.gilbert_mae:.3f}x"
                if r.gilbert_mae
                else "n/a"
            )
            lines.append(
                f"{r.model:<16} {r.param_count:>9} {r.test_mae:>12.2f} {vs:>11} "
                f"{r.samples_per_sec:>12.0f} {r.epochs_ran:>7} "
                f"{r.time_elapsed:>7.1f}s"
            )
        # Excluded from the ranking but must not vanish silently.
        for r, reason in self.failed:
            lines.append(f"{r.model:<16} FAILED: {reason}")
        return "\n".join(lines)


def compare(
    models: tuple[str, ...] = DEFAULT_MODELS,
    base_config: TrainJobConfig | None = None,
    stop_fn=None,
) -> ComparisonReport:
    """Train every model family on the same data and seed; rank by MAE.

    ``base_config`` carries the shared data/training settings; its
    ``model`` field is overridden per run. A failing model is recorded,
    not fatal — the comparison is the deliverable. ``stop_fn`` (see
    ``train``) aborts the whole comparison: a cancellation/timeout must
    not be swallowed as one FAILED row while the remaining models train
    anyway.
    """
    from tpuflow.train.loop import TrainingInterrupted

    base = base_config or TrainJobConfig(max_epochs=40, batch_size=256)
    report = ComparisonReport()
    # One ingest+feature pass per distinct preparation, not per model:
    # families that prepare identical data (e.g. every teacher-forced
    # sequence model) share one _Prepared through this dict, which dies
    # with the comparison.
    data_cache: dict = {}
    for name in models:
        config = dataclasses.replace(base, model=name)
        try:
            r = train(config, _data_cache=data_cache, stop_fn=stop_fn)
        except TrainingInterrupted:
            raise
        except Exception as e:  # record and keep comparing
            report.results.append(
                ModelResult(
                    model=name, test_mae=float("inf"), test_loss=float("inf"),
                    gilbert_mae=None, samples_per_sec=0.0, epochs_ran=0,
                    time_elapsed=0.0, error=f"{type(e).__name__}: {e}",
                )
            )
            continue
        n_params = sum(
            int(leaf.size)
            for leaf in jax.tree_util.tree_leaves(r.result.state.params)
        )
        report.results.append(
            ModelResult(
                model=name,
                test_mae=r.test_mae,
                test_loss=r.test_loss,
                gilbert_mae=r.gilbert_mae,
                samples_per_sec=r.samples_per_sec,
                epochs_ran=r.result.epochs_ran,
                time_elapsed=r.time_elapsed,
                param_count=n_params,
            )
        )
    return report
