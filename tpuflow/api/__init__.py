"""Job-level API: config + web-callable train entrypoint.

The L6/L5 layers of the reference (SURVEY.md §1): where its web component
shelled out ``spark-submit <script> <argv>`` (reference Readme.md:4,
cnn.py:2), a service here calls ``tpuflow.api.train(TrainJobConfig(...))``
in-process, and the CLI (``python -m tpuflow.cli``) preserves the
positional dynamic-schema contract for drop-in job submission.
"""

from tpuflow.api.config import TrainJobConfig  # noqa: F401
from tpuflow.api.train_api import TrainReport, train  # noqa: F401
from tpuflow.api.predict_api import Predictor, predict  # noqa: F401
from tpuflow.api.compare import ComparisonReport, compare  # noqa: F401
from tpuflow.api.sweep import SweepReport, sweep  # noqa: F401
