"""Hyperparameter sweeps — the other half of the experiment workflow.

The reference's test strategy WAS comparative experiments ("We had to make
tests on our computing services using multiple model types",
reference Readme.md:13). ``compare()`` covers the across-families half;
this module sweeps configurations WITHIN a family: a grid over any
``TrainJobConfig`` fields (or ``model_kwargs``/``optimizer_kwargs``
entries via dotted names), each combination trained on the same data and
seed, ranked by held-out MAE.
"""

from __future__ import annotations

import dataclasses
import itertools
from dataclasses import dataclass, field
from typing import Any, Mapping, Sequence

from tpuflow.api.compare import RankedByMAE
from tpuflow.api.config import TrainJobConfig
from tpuflow.api.train_api import train

_CONFIG_FIELDS = {f.name for f in dataclasses.fields(TrainJobConfig)}
_NESTED = ("model_kwargs", "optimizer_kwargs")


def _validate_name(name: str) -> None:
    if "." in name:
        outer = name.split(".", 1)[0]
        if outer not in _NESTED:
            raise ValueError(f"unknown sweep field {name!r}")
    elif name not in _CONFIG_FIELDS:
        raise ValueError(f"unknown sweep field {name!r}")


def _apply(base: TrainJobConfig, assignment: Mapping[str, Any]) -> TrainJobConfig:
    """One grid point -> a concrete config.

    Plain names set TrainJobConfig fields; dotted ``model_kwargs.X`` /
    ``optimizer_kwargs.X`` names set entries inside those dicts (merged
    over a plain assignment of the same dict, if both are present).
    Unknown names are rejected loudly (a typo'd axis would sweep nothing).
    """
    plain: dict[str, Any] = {}
    nested: dict[str, dict[str, Any]] = {}
    for name, value in assignment.items():
        _validate_name(name)
        if "." in name:
            outer, inner = name.split(".", 1)
            nested.setdefault(outer, {})[inner] = value
        else:
            plain[name] = value
    for outer, extra in nested.items():
        # Start from the plain-assigned dict when the grid also sets the
        # whole dict, else from the base config's.
        plain[outer] = {**plain.get(outer, getattr(base, outer)), **extra}
    return dataclasses.replace(base, **plain)


@dataclass
class SweepResult:
    assignment: dict
    test_mae: float
    test_loss: float
    gilbert_mae: float | None
    epochs_ran: int
    time_elapsed: float
    error: str | None = None


@dataclass
class SweepReport(RankedByMAE):
    results: list[SweepResult] = field(default_factory=list)

    def table(self) -> str:
        lines = [f"{'assignment':<48} {'test MAE':>12} {'epochs':>7} {'time':>8}"]
        for r in self.ranked:
            desc = ", ".join(f"{k}={v}" for k, v in r.assignment.items())
            lines.append(
                f"{desc:<48} {r.test_mae:>12.2f} {r.epochs_ran:>7} "
                f"{r.time_elapsed:>7.1f}s"
            )
        for r, reason in self.failed:
            desc = ", ".join(f"{k}={v}" for k, v in r.assignment.items())
            lines.append(f"{desc:<48} FAILED: {reason}")
        return "\n".join(lines)


def sweep(
    grid: Mapping[str, Sequence[Any]],
    base_config: TrainJobConfig | None = None,
    stop_fn=None,
) -> SweepReport:
    """Train every combination of ``grid`` and rank by held-out MAE.

    ``grid`` maps field names (see ``_apply``) to candidate values; the
    cartesian product is trained with the base config's data and seed. A
    failing point is recorded, not fatal — the ranking is the deliverable.
    ``stop_fn`` (see ``train``) aborts the whole sweep: a cancellation/
    timeout must not be swallowed as FAILED rows while the rest of the
    grid trains anyway.

    Example::

        sweep({"model_kwargs.hidden": [32, 64], "batch_size": [64, 256]},
              TrainJobConfig(model="lstm", max_epochs=20))
    """
    from tpuflow.train.loop import TrainingInterrupted

    base = base_config or TrainJobConfig(max_epochs=40, batch_size=256)
    names = list(grid)
    # Typos fail HERE, before any training: inside the per-point
    # try/except they would surface only as a report full of FAILED rows.
    for name in names:
        _validate_name(name)
    report = SweepReport()
    # Grid points that don't vary the data axes (most sweeps: model
    # width, optimizer settings) share one ingest+feature pass.
    data_cache: dict = {}
    for values in itertools.product(*(grid[n] for n in names)):
        assignment = dict(zip(names, values))
        try:
            config = _apply(base, assignment)
            r = train(config, _data_cache=data_cache, stop_fn=stop_fn)
        except TrainingInterrupted:
            raise
        except Exception as e:  # record and keep sweeping
            report.results.append(
                SweepResult(
                    assignment=assignment,
                    test_mae=float("inf"),
                    test_loss=float("inf"),
                    gilbert_mae=None,
                    epochs_ran=0,
                    time_elapsed=0.0,
                    error=f"{type(e).__name__}: {e}",
                )
            )
            continue
        report.results.append(
            SweepResult(
                assignment=assignment,
                test_mae=r.test_mae,
                test_loss=r.test_loss,
                gilbert_mae=r.gilbert_mae,
                epochs_ran=r.result.epochs_ran,
                time_elapsed=r.time_elapsed,
            )
        )
    return report
