"""Job configuration.

One dataclass carries everything a submission needs. The first four fields
are the reference's exact positional CLI contract (reference cnn.py:2,
41-44): comma-separated column names, comma-separated type strings, target
column, artifact storage path. ``data_path`` is the explicit data location
the reference intended but lost to its argv bug (SURVEY.md C4).
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass
class TrainJobConfig:
    # --- the reference's dynamic-schema contract (runtime inputs) ---
    column_names: str = ""  # "pressure,choke,...", comma-separated
    column_types: str = ""  # "float,float,...,string", comma-separated
    target: str = "flow"
    storage_path: str | None = None  # checkpoint root ({storage}/models/...)

    # --- data source (C4 fixed: explicit path; synthetic fallback) ---
    data_path: str | None = None  # headerless CSV; None -> synthetic wells
    well_column: str | None = None  # groups CSV rows into per-well logs
    synthetic_wells: int = 8
    synthetic_steps: int = 512
    # Out-of-core ingest (tabular models): never materialize the CSV —
    # fit the pipeline on a head sample of train-assigned rows, re-stream
    # train batches each epoch through a windowed shuffle, and evaluate on
    # bounded val/test samples. Memory stays O(chunk + buffers) regardless
    # of file size (the reference's cluster-resident-data story, Readme.md:3).
    stream: bool = False
    stream_chunk_rows: int = 65536  # CSV rows parsed per chunk
    stream_shuffle_buffer: int = 8192  # windowed-shuffle rows (0 = in order)
    stream_sample_rows: int = 100_000  # pipeline-fit head sample size
    stream_eval_rows: int = 100_000  # val/test materialization cap

    # --- model ---
    model: str = "lstm"  # key into tpuflow.models.MODELS
    model_kwargs: dict = field(default_factory=dict)
    window: int = 24  # sequence window (BASELINE configs)
    stride: int = 1

    # --- training (reference defaults: cnn.py:121,128) ---
    max_epochs: int = 1000
    batch_size: int = 20
    patience: int = 10
    loss: str = "mae_clip"
    optimizer: str = "keras_sgd"
    optimizer_kwargs: dict = field(default_factory=dict)
    clip_norm: float = 0.0  # 0 = off; optax.clip_by_global_norm otherwise
    # Mixed-precision policy (tpuflow/train/precision.py): "f32" (default)
    # or "bf16". Under bf16 the models compute in bfloat16 (params and
    # activations cast per layer, batch cast at step entry) while master
    # params, optimizer state, loss/grad reduction, checkpoints, and
    # serving artifacts all stay float32 — roughly halving HBM
    # bytes/sample on the HBM-bound train path with no change to any
    # artifact consumer. Spec-validated; the roofline gauges and the
    # epoch-program autotuner both key on it.
    precision: str = "f32"
    # >1: average k micro-batch grads per optimizer update (MultiSteps) —
    # effective batch k*batch_size without k-times the activation memory.
    # Size epochs to a multiple of k: a trailing partial window's grads
    # wait in the accumulator (discarded if training ends there).
    accumulate_steps: int = 1
    seed: int = 0
    verbose: bool = True
    # Epoch program: True compiles each epoch into one XLA program
    # (removes per-step dispatch, the big lever at the reference's batch
    # size of 20); False steps per-batch (measured faster at bench-scale
    # batches). None = AUTO: resolved from the measured program sweep
    # for the running device (tpuflow/train/autotune.py), so production
    # jobs ride whichever program measured faster.
    jit_epoch: bool | None = None

    # --- fault tolerance (SURVEY §5.3; requires storage_path) ---
    save_every: int = 0  # epochs between full-state run checkpoints
    resume: bool = False  # continue from the latest run checkpoint
    # Warm start: storage_path of an EXISTING artifact whose best params
    # are overlaid onto the freshly-built state via
    # train/resume.py::apply_params before fitting — the online loop's
    # retrain resumes from the SERVING artifact this way (not from a run
    # checkpoint: the serving artifact is the state the fleet actually
    # answers with). The artifact must be the same model/model_kwargs;
    # a mismatch fails loudly naming the first mismatching leaf paths.
    warm_start: str | None = None
    fault_epoch: int | None = None  # inject a simulated preemption (tests)
    fault_hard: bool = False  # preempt WITHOUT committing async ckpt writes
    ckpt_async: bool = True  # False: synchronous checkpoint writes
    # --- resilience drills (tpuflow/resilience; docs/resilience.md) ---
    # Fault specs armed for THIS run only ("site,at=3,mode=exit", ...);
    # the registry grammar of resilience/faults.py. The supervisor drops
    # them on restart attempts (a drill is one-shot; the recovery runs
    # clean) — use TPUFLOW_FAULTS for faults that must survive restarts.
    faults: list = field(default_factory=list)
    # Liveness file overwritten after every completed epoch ({"epoch": N,
    # "time": ...}); the supervisor injects its own path here so its
    # stall watchdog can tell hung from slow-but-alive.
    progress_path: str | None = None
    # --- elastic data-parallel membership (tpuflow/elastic) ---
    # When set, this run is ONE worker of an elastic gang: it trains on
    # its disjoint row shard and syncs params with the coordinator every
    # sync_every epochs — blocking per round, or barrier-free when
    # async_push is set (staleness-bounded adoption of the freshest
    # average). The exchange rides transport="file" (shared gang dir)
    # or "socket" (TCP to the coordinator-hosted exchange server at
    # addr — no shared filesystem). Required keys: dir, worker_id,
    # n_workers; knobs, defaults, and the TPUFLOW_ELASTIC_* env
    # fallbacks in tpuflow/elastic/__init__.py (ELASTIC_DEFAULTS).
    # Spec-validated by the preflight spec pass; normally assembled by
    # tpuflow.elastic.runner.worker_spec, not by hand.
    elastic: dict | None = None
    # --- online continuous training (tpuflow/online) ---
    # When set, `python -m tpuflow.online` / `cli --online` runs this
    # job as a continuous loop: streaming windows of data_path are
    # scored against the serving artifact's reference stats (drift
    # watchdog), drift (or a scheduled cadence) triggers a warm-start
    # retrain on a bounded replay of recent windows, and a
    # non-regressing candidate is hot-swapped into the serving artifact
    # path with rollback on post-swap regression. Knobs and defaults in
    # tpuflow/online/__init__.py (ONLINE_DEFAULTS); every knob also has
    # a TPUFLOW_ONLINE_* env spelling. Spec-validated by the preflight
    # spec pass. {} enables the loop with defaults.
    online: dict | None = None

    # --- online occupancy autotuning (tpuflow/train/autotune.py) ---
    # When set (a dict; {} enables defaults — CLI --autotune, env flag
    # TPUFLOW_AUTOTUNE), a post-epoch controller hill-climbs the
    # microbatch size (pow-2 ladder), remat on/off, and the
    # scan-vs-per-batch epoch program from each epoch's measured
    # throughput and the live MFU/HBM gauges, charging every move
    # against an explicit recompile budget (RecompileDetector) and
    # FREEZING on the best-seen config when the budget is spent. The
    # winning point is persisted next to the serving sidecar (keyed by
    # device@precision) so restarted/warm-started runs resume tuned.
    # Knobs and defaults in tpuflow/train/autotune.py
    # (AUTOTUNE_DEFAULTS); every knob has a TPUFLOW_AUTOTUNE_* env
    # spelling. Spec-validated; single-chip default-step runs only
    # (stream/tp/pp/ep/elastic/multi-device are rejected at
    # submission).
    autotune: dict | None = None

    # --- observability ---
    trace_dir: str | None = None  # jax.profiler trace of the first epoch
    metrics_path: str | None = None  # per-epoch JSONL metrics file
    # Numerics-watchdog policy (tpuflow/obs/health.py): each epoch the
    # loss/grad_norm aux is checked host-side for NaN/Inf and EWMA
    # spikes; anomalies count into train_numerics_anomalies_total and
    # dump a forensics trail. "warn" (default) logs and continues;
    # "halve_lr" scales the optimizer LR by 0.5 per anomalous epoch;
    # "abort" raises the typed NumericsDivergence, which the supervisor
    # classifies as terminal (no restart-backoff churn — a diverged run
    # replays deterministically). "off"/None disables the watchdog.
    health: str | None = "warn"

    # --- parallelism ---
    n_devices: int | None = None  # None -> all visible devices; 1 -> no DP
    # Tensor parallelism: size of the model axis of the (data, model)
    # mesh. n_devices/tp devices do DP; each replica's params are sharded
    # megatron-style across tp devices (GSPMD; MLP families only — see
    # parallel/tp_train.py). 1 = off.
    tp: int = 1
    # Pipeline parallelism: stage count of the GPipe microbatch pipeline
    # over the model axis (pipeline_mlp family only — see
    # parallel/pp_train.py). n_devices/pp device columns do DP in the
    # same program. 1 = off; mutually exclusive with tp.
    pp: int = 1
    # Microbatches per pipelined step (GPipe M; bubble fraction
    # (pp-1)/(M+pp-1), raise M to amortize). 0 = auto (= pp).
    pp_microbatches: int = 0
    # Expert parallelism: device count of the expert axis (moe_mlp
    # family only — see parallel/ep_train.py). The stacked expert bank
    # shards experts-per-device; n_devices/ep device columns do DP in
    # the same program. 1 = off; mutually exclusive with tp/pp.
    ep: int = 1

    @property
    def is_sequence_model(self) -> bool:
        return self.model in (
            "dynamic_mlp", "cnn1d", "lstm", "stacked_lstm", "lstm_residual",
            "attention",
        )

    @property
    def teacher_forcing(self) -> bool:
        """Sequence-target training for the recurrent/causal families
        (BASELINE config 4; the attention model is causal, so per-step
        targets are legitimate the same way)."""
        return self.model in (
            "lstm", "stacked_lstm", "lstm_residual", "attention",
        )
