"""The train(config) entrypoint — the whole reference pipeline, working.

Executes the intended trace of the reference's one entry point (SURVEY.md
§3.1: argv→schema→ingest→split→features→model→fit→report) as a callable
function: ingest (CSV or synthetic) under a dynamic schema, split 64/16/20,
fit features on train only, build the model, train with early stopping +
save-best, evaluate on the held-out test split, and report elapsed time,
test loss, throughput, and MAE-vs-Gilbert — single-chip or data-parallel
over a device mesh.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field

import jax
import numpy as np

from tpuflow.core.gilbert import gilbert_flow
from tpuflow.core.losses import LOSSES
from tpuflow.data import (
    Schema,
    generate_wells,
    prepare_tabular,
    prepare_windowed,
    prepare_windowed_table,
    read_csv,
    wells_to_table,
)
from tpuflow.data.synthetic import (
    SYNTHETIC_COLUMN_NAMES,
    SYNTHETIC_COLUMN_TYPES,
    SYNTHETIC_TARGET,
    WellLog,
)
from tpuflow.api.config import TrainJobConfig
from tpuflow.models import build_model
from tpuflow.parallel import (
    data_sharding,
    init_distributed,
    local_devices,
    make_dp_epoch_step,
    make_dp_eval_step,
    make_dp_train_step,
    make_mesh,
    make_process_fed_steps,
    process_batch_bounds,
    shard_epoch,
)
from tpuflow.parallel.dp import replicate
from tpuflow.train import FitConfig, FitResult, create_state, evaluate, fit
from tpuflow.train.optim import build_optimizer, wrap_optimizer


@dataclass
class TrainReport:
    result: FitResult
    test_loss: float
    test_mae: float
    gilbert_mae: float | None  # physical-baseline MAE on the same test rows
    time_elapsed: float
    samples_per_sec: float
    # Which epoch program the run used and why — "jit_epoch"/"per_batch",
    # resolved by tpuflow.train.autotune when config.jit_epoch is None.
    epoch_program: str = ""
    epoch_program_reason: str = ""
    # Health monitor outcomes (tpuflow/obs/health.py): the numerics
    # watchdog's anomaly trail and the recompile detector's summary —
    # both surfaced in summary() as preflight-style diagnostics.
    anomalies: list = field(default_factory=list)
    recompiles: dict | None = None
    # Occupancy-autotuner summary (train/autotune.py; None = not tuned).
    autotune: dict | None = None

    def summary(self) -> str:
        lines = [
            f"Time elapsed: {self.time_elapsed:.2f}s",
            f"Testing set loss: {self.test_loss:.4f}",
            f"Testing set MAE: {self.test_mae:.4f}",
            f"Throughput: {self.samples_per_sec:.0f} samples/sec/chip",
        ]
        if self.epoch_program:
            lines.append(f"Epoch program: {self.epoch_program}")
        if self.autotune:
            at = self.autotune
            state = "frozen" if at.get("frozen") else "tuning"
            lines.append(
                f"Autotune: {at.get('best_config')} ({state}; "
                f"{at.get('recompiles_charged')} recompile(s) of budget "
                f"{at.get('recompile_budget')}, {at.get('reverts')} "
                "revert(s))"
            )
        if self.gilbert_mae is not None:
            beat = "beats" if self.test_mae <= self.gilbert_mae else "trails"
            lines.append(
                f"Gilbert-baseline MAE: {self.gilbert_mae:.4f} (model {beat} baseline)"
            )
        if self.anomalies:
            kinds: dict[str, int] = {}
            for a in self.anomalies:
                kinds[a["kind"]] = kinds.get(a["kind"], 0) + 1
            lines.append(
                "Numerics anomalies: "
                + ", ".join(f"{k}={v}" for k, v in sorted(kinds.items()))
                + " (train_numerics_anomalies_total; see forensics.jsonl)"
            )
        if self.recompiles and self.recompiles.get("diagnostic"):
            lines.append(f"Recompile churn: {self.recompiles['diagnostic']}")
        return "\n".join(lines)


def _gilbert_mae(pressure, choke, glr, y_raw) -> float:
    """MAE of the closed-form Gilbert baseline against RAW-unit targets —
    the accuracy yardstick every learned model is judged by (SURVEY.md §3.3)."""
    return float(
        np.mean(np.abs(y_raw - np.asarray(gilbert_flow(pressure, choke, glr))))
    )


def _gilbert_mae_last_step(names, raw_last, y_raw) -> float | None:
    """Sequence-family baseline: Gilbert on each window's FINAL step.

    ``raw_last [N, F]`` are the un-standardized final-step channels named
    by ``names``; returns None when the physical channels are absent.
    Shared by the materialized and streaming sequence branches.
    """
    if not {"pressure", "choke", "glr"} <= set(names):
        return None
    ip, ic, ig = (
        names.index("pressure"),
        names.index("choke"),
        names.index("glr"),
    )
    return _gilbert_mae(raw_last[:, ip], raw_last[:, ic], raw_last[:, ig], y_raw)


def _load_wells(config: TrainJobConfig) -> list[WellLog]:
    return generate_wells(
        n_wells=config.synthetic_wells,
        steps=config.synthetic_steps,
        seed=config.seed,
    )


@dataclass
class _Prepared:
    """Everything the ingest+feature phase hands to the training phase."""

    train_ds: object
    val_ds: object
    test_ds: object
    splits: object
    target_std: float
    gilbert_test: float | None
    seq_physics: bool


def _sidecar_kwargs(model_kwargs: dict) -> dict:
    """model_kwargs as the serving sidecar records them.

    Ring-CP attention trains against a live Mesh, which neither
    serializes nor exists at serving time; the artifact's checkpoints are
    backend-interchangeable, so the sidecar swaps in the on-chip "full"
    backend and drops the mesh — a ring-trained run still produces a
    servable artifact. The compute ``dtype`` is dropped for the same
    reason: checkpoints hold f32 MASTER params whatever the training
    precision (tpuflow/train/precision.py), so artifacts serve f32 and
    a bf16-trained run's artifact is byte-compatible with every f32
    consumer. Everything else passes through (and must be
    JSON-serializable; train() checks before fitting).
    """
    kwargs = dict(model_kwargs)
    if kwargs.get("backend") == "ring":
        kwargs["backend"] = "full"
    kwargs.pop("mesh", None)
    kwargs.pop("dtype", None)
    return kwargs


def _prep_key(config: TrainJobConfig) -> tuple:
    """Cache key over every config field ``_prepare_data`` reads.

    The model name enters only through its three derived flags — all
    teacher-forced sequence families, for instance, prepare identical
    data — which is what lets ``compare()``/``sweep()`` share one
    ``_Prepared`` across runs via ``train(_data_cache=...)``. The
    streaming knobs (incl. batch_size, which only the stream sources
    bake into their batch iterators) enter the key only when streaming,
    so e.g. a batch-size sweep over materialized data is one prep.

    MAINTENANCE CONTRACT: any new config field read inside
    ``_prepare_data`` (or a new model-specific branch there) MUST be
    added to this tuple, or cache hits will silently hand one model
    another model's data preparation. The guard is executable:
    ``TPUFLOW_CHECK_PREP_CACHE=1`` makes every cache hit recompute the
    preparation and compare (``_assert_prep_equivalent``) — the
    experiment tests run with it on, so a missed field fails CI instead
    of corrupting sweeps.
    """
    stream_fields = (
        (
            config.batch_size, config.stream_chunk_rows,
            config.stream_shuffle_buffer, config.stream_sample_rows,
            config.stream_eval_rows,
        )
        if config.stream
        else None
    )
    return (
        config.data_path, config.well_column,
        config.synthetic_wells, config.synthetic_steps, config.seed,
        config.window, config.stride,
        config.stream, stream_fields,
        config.column_names, config.column_types, config.target,
        config.is_sequence_model, config.teacher_forcing,
        config.model in ("gilbert_residual", "lstm_residual"),
    )


def _assert_prep_equivalent(cached: _Prepared, fresh: _Prepared, config) -> None:
    """Raise if a ``_data_cache`` hit differs from a fresh preparation.

    Only run under ``TPUFLOW_CHECK_PREP_CACHE=1`` (it recomputes the whole
    ingest+feature phase per hit). A mismatch means ``_prepare_data`` now
    reads a config field ``_prep_key`` doesn't cover — the silent-aliasing
    failure mode where one model trains on another model's preparation.
    """

    def _fail(what: str):
        raise AssertionError(
            f"_prep_key aliasing for model {config.model!r}: cached {what} "
            "differs from a fresh preparation — _prepare_data reads a "
            "config field _prep_key doesn't cover (see _prep_key's "
            "maintenance contract)"
        )

    for name in ("target_std", "gilbert_test", "seq_physics"):
        if getattr(cached, name) != getattr(fresh, name):
            _fail(name)
    for name in ("train_ds", "val_ds", "test_ds"):
        c, f = getattr(cached, name), getattr(fresh, name)
        if not (hasattr(c, "x") and hasattr(f, "x")):
            continue  # streaming sources: per-epoch iterators, no arrays
        cx, fx = np.asarray(c.x), np.asarray(f.x)
        cy, fy = np.asarray(c.y), np.asarray(f.y)
        if cx.shape != fx.shape or not np.array_equal(cx, fx):
            _fail(f"{name}.x")
        if cy.shape != fy.shape or not np.array_equal(cy, fy):
            _fail(f"{name}.y")


def _prepared_with_span(
    config: TrainJobConfig, schema: Schema, target: str
) -> _Prepared:
    """``_prepare_data`` wrapped in the run's "ingest" span: the whole
    ingest+feature phase lands in the run's metrics JSONL (when
    ``metrics_path`` is set) and the forensics ring with a duration —
    for CSV jobs this phase can dominate wall-clock, and without a span
    it is invisible time."""
    from tpuflow.obs import span

    mlog = None
    if config.metrics_path:
        from tpuflow.utils.logging import MetricsLogger

        mlog = MetricsLogger(config.metrics_path)
    try:
        with span("ingest", logger=mlog, model=config.model):
            return _prepare_data(config, schema, target)
    finally:
        if mlog is not None:
            mlog.close()


def _prepare_data(
    config: TrainJobConfig, schema: Schema, target: str
) -> _Prepared:
    """The ingest + feature phase (L1/L2): everything between the dynamic
    schema and the model. Pure in (config, schema, target) — extracted so
    experiment drivers can reuse one preparation across model runs."""
    gilbert_test = None
    seq_physics = False
    if config.stream and config.is_sequence_model:
        if config.data_path is None:
            raise ValueError("stream=True needs data_path (nothing to stream)")
        if config.well_column is None:
            raise ValueError(
                "streaming sequence ingest splits train/val/test by WELL "
                "(windows must not straddle splits); pass well_column"
            )
        if config.model == "lstm_residual":
            raise ValueError(
                "stream=True does not support lstm_residual (the Gilbert "
                "channel is appended by the materialized windowed pipeline)"
            )
    if config.is_sequence_model and config.stream:
        # Out-of-core WINDOWED ingest: split by well, window per well with
        # chunk carry-over, stats from a head sample (stream_windows.py).

        from tpuflow.data.pipeline import ArrayDataset
        from tpuflow.data.stream_windows import (
            fit_window_normalizer,
            materialize_window_splits,
            stream_window_batches,
        )
        from tpuflow.train import StreamingSource

        norm = fit_window_normalizer(
            config.data_path,
            schema,
            config.well_column,
            seed=config.seed,
            window=config.window,
            stride=config.stride,
            sample_rows=config.stream_sample_rows,
            chunk_rows=config.stream_chunk_rows,
        )

        def _tf(y):  # teacher-forced [N, T] vs last-step [N] targets
            return y if config.teacher_forcing else y[:, -1]

        # One file scan serves both eval splits; raw copies (for the
        # physical baseline) are kept for test only and dropped below —
        # nothing un-normalized survives into the training phase.
        evals = materialize_window_splits(
            config.data_path, schema, config.well_column, norm,
            ("val", "test"), seed=config.seed, window=config.window,
            stride=config.stride, max_windows=config.stream_eval_rows,
            chunk_rows=config.stream_chunk_rows, raw_for=("test",),
        )
        val_ds = ArrayDataset(evals["val"][0], _tf(evals["val"][1]))
        test_ds = ArrayDataset(evals["test"][0], _tf(evals["test"][1]))
        _, _, tex_raw, tey_raw = evals["test"]
        del evals
        gilbert_test = _gilbert_mae_last_step(
            norm.feature_names, tex_raw[:, -1, :], tey_raw[:, -1]
        )
        del tex_raw, tey_raw

        def _train_stream(epoch):
            for x, y in stream_window_batches(
                config.data_path,
                schema,
                config.well_column,
                norm,
                config.batch_size,
                seed=config.seed,
                window=config.window,
                stride=config.stride,
                chunk_rows=config.stream_chunk_rows,
                shuffle_buffer=config.stream_shuffle_buffer,
                shuffle_seed=config.seed + epoch,
                split="train",
            ):
                yield x, _tf(y)

        train_ds = StreamingSource(_train_stream)
        target_std = norm.target_std
        seq_physics = False  # lstm_residual rejected for streams above
        splits = norm  # WindowNormalizer carries the sidecar fields
    elif config.is_sequence_model:
        seq_physics = config.model == "lstm_residual"
        if config.data_path is not None:
            columns = read_csv(config.data_path, schema)
            splits = prepare_windowed_table(
                schema,
                columns,
                well_column=config.well_column,
                window=config.window,
                stride=config.stride,
                seed=config.seed,
                teacher_forcing=config.teacher_forcing,
                append_gilbert=seq_physics,
            )
        else:
            splits = prepare_windowed(
                _load_wells(config),
                window=config.window,
                stride=config.stride,
                seed=config.seed,
                teacher_forcing=config.teacher_forcing,
                append_gilbert=seq_physics,
            )
        train_ds, val_ds, test_ds = splits.train, splits.val, splits.test
        target_std = splits.target_std
        # Physical baseline on the test windows' final step, from the
        # UN-standardized channels against RAW-unit targets.
        raw_last = test_ds.x[:, -1, :] * splits.norm_std + splits.norm_mean
        y_ref = splits.inverse_target(
            test_ds.y[:, -1] if config.teacher_forcing else test_ds.y
        )
        gilbert_test = _gilbert_mae_last_step(
            splits.feature_names, raw_last, y_ref
        )
    elif config.stream:
        # Out-of-core tabular ingest: the CSV is never materialized.
        if config.data_path is None:
            raise ValueError("stream=True needs data_path (nothing to stream)")
        if config.model == "gilbert_residual":
            raise ValueError(
                "stream=True does not support gilbert_residual (the Gilbert "
                "feature channel is appended by the in-memory pipeline); "
                "use the materialized path"
            )
        from tpuflow.data.pipeline import ArrayDataset
        from tpuflow.data.stream import (
            fit_pipeline_on_sample,
            materialize_splits,
            stream_batches,
        )
        from tpuflow.train import StreamingSource

        pipeline = fit_pipeline_on_sample(
            config.data_path,
            schema,
            sample_rows=config.stream_sample_rows,
            split="train",
            split_seed=config.seed,
        )
        evals = materialize_splits(
            config.data_path, pipeline, ("val", "test"), config.seed,
            max_rows=config.stream_eval_rows,
            chunk_rows=config.stream_chunk_rows,
        )
        vx, vy, _ = evals["val"]
        tex, tey, raw_test = evals["test"]
        val_ds, test_ds = ArrayDataset(vx, vy), ArrayDataset(tex, tey)
        train_ds = StreamingSource(
            lambda epoch: stream_batches(
                config.data_path,
                pipeline,
                config.batch_size,
                chunk_rows=config.stream_chunk_rows,
                shuffle_buffer=config.stream_shuffle_buffer,
                seed=config.seed + epoch,
                split="train",
                split_seed=config.seed,
            )
        )

        from types import SimpleNamespace

        splits = SimpleNamespace(pipeline=pipeline)  # sidecar reads .pipeline
        target_std = pipeline.target_std_
        if {"pressure", "choke", "glr", target} <= set(raw_test):
            gilbert_test = _gilbert_mae(
                raw_test["pressure"],
                raw_test["choke"],
                raw_test["glr"],
                raw_test[target],
            )
    else:
        if config.data_path is not None:
            columns = read_csv(config.data_path, schema)
        else:
            columns = wells_to_table(_load_wells(config))
        cols = {c.name for c in schema.columns}
        physics = config.model == "gilbert_residual"
        if physics and not {"pressure", "choke", "glr"} <= cols:
            raise ValueError(
                "gilbert_residual needs pressure/choke/glr columns"
            )
        splits = prepare_tabular(
            schema,
            columns,
            seed=config.seed,
            append_gilbert=physics,
        )
        train_ds, val_ds, test_ds = splits.train, splits.val, splits.test
        target_std = splits.pipeline.target_std_
        if {"pressure", "choke", "glr"} <= cols:
            # Recover raw test columns for the physical baseline.
            from tpuflow.data.splits import random_split

            n = len(next(iter(columns.values())))
            _, _, te_idx = random_split(n, seed=config.seed)
            gilbert_test = _gilbert_mae(
                columns["pressure"][te_idx],
                columns["choke"][te_idx],
                columns["glr"][te_idx],
                columns[target][te_idx],
            )
    return _Prepared(
        train_ds=train_ds, val_ds=val_ds, test_ds=test_ds, splits=splits,
        target_std=target_std, gilbert_test=gilbert_test,
        seq_physics=seq_physics,
    )



def _validate_model_axis(config, jit_epoch: bool, n_dev: int) -> None:
    """Config-only model-axis validation, run BEFORE data preparation:
    a misconfigured tp/pp/ep job must fail in milliseconds, not after a
    possibly hours-long ingest+feature phase (the same early-rejection
    discipline as the stream+jit_epoch check). The rule set itself lives
    in ``tpuflow.analysis.plan`` — one ruleset shared with preflight, so
    a plan rejected at submission and a plan rejected here are the same
    rule with the same message."""
    import dataclasses

    from tpuflow.analysis.plan import check_plan

    diags = check_plan(
        # n_dev is already resolved (config.n_devices or device_count);
        # pin it so the checker sees exactly the mesh this run would use.
        dataclasses.replace(config, n_devices=n_dev),
        device_count=jax.device_count(),
        local_device_count=jax.local_device_count(),
        process_count=jax.process_count(),
        jit_epoch=jit_epoch,
    )
    errors = [d for d in diags if d.severity == "error"]
    if errors:
        raise ValueError("; ".join(d.message for d in errors))


def _worker_identity(config) -> str | None:
    """This run's fleet identity ("w{N}" for an elastic worker, None
    for a plain run): the suffix that keeps forensics dumps from
    sibling processes sharing one storage root from clobbering each
    other (tpuflow/obs/forensics.py::forensics_path)."""
    block = getattr(config, "elastic", None)
    if isinstance(block, dict) and "worker_id" in block:
        try:
            return f"w{int(block['worker_id'])}"
        except (TypeError, ValueError):
            return None
    return None


def train(
    config: TrainJobConfig,
    *,
    _data_cache: dict | None = None,
    stop_fn=None,
) -> TrainReport:
    """Run the whole pipeline for one job config; see the module docstring.

    ``_data_cache`` (private; used by ``compare()``/``sweep()``) memoizes
    the ingest+feature phase across runs that prepare identical data —
    keyed by ``_prep_key``, scoped to the dict the caller passes, so
    nothing outlives the experiment that created it.

    ``stop_fn`` (optional ``() -> str | None``) is polled before data
    preparation and between epochs; a non-None string aborts the run with
    ``TrainingInterrupted(reason)`` — the job-runner's cancellation and
    per-job-timeout hook.

    ``config.faults`` arms resilience-registry fault specs for exactly
    this run (armed before ingest so data-path sites are covered,
    disarmed on the way out so nothing leaks into a later run in the
    same process).
    """
    # Fail-fast on submission: the spec pass of the preflight analyzer
    # (registry keys, schema, windowing, stream knobs, fault grammar)
    # rejects a malformed job in milliseconds, before ANY ingest — and
    # reports every problem at once, not the first one hit. Plan/mesh
    # arithmetic runs just below via _validate_model_axis (which shares
    # the analyzer's rule set); the shape dry-run is preflight-only.
    from tpuflow.analysis import ensure_preflight

    ensure_preflight(config, passes=("spec",))
    fault_handles = []
    if config.faults:
        from tpuflow.resilience import arm, parse_fault_spec

        # Parse EVERY entry before arming ANY: a typo in the second spec
        # must not leave the first one armed process-wide (the finally
        # below can only disarm handles that were recorded).
        specs = [parse_fault_spec(s) for s in config.faults]
        fault_handles = [arm(s) for s in specs]
    from tpuflow.obs import (
        current_trace_id,
        dump_forensics,
        trace_from_env,
        use_trace,
    )
    from tpuflow.train.loop import TrainingInterrupted

    try:
        # One run-scoped trace ID for the whole job: the fit loop's
        # ingest/step/eval/checkpoint spans all carry it, so a run's
        # JSONL (and a crash dump) is filterable to this run. An
        # already-bound trace (the online loop's drift lifecycle) or a
        # validated TPUFLOW_TRACE_ID (a supervised child attempt — all
        # attempts of one job share the parent's trace) is INHERITED,
        # never replaced: cross-process propagation is the whole point.
        with use_trace(current_trace_id() or trace_from_env()):
            return _train_impl(
                config, _data_cache=_data_cache, stop_fn=stop_fn
            )
    except TrainingInterrupted:
        raise  # a cooperative stop is an outcome, not a failure
    except BaseException:
        # Crash forensics: the recent-event ring (spans, fault firings,
        # retries) dumped next to the artifacts — the "what was it doing
        # just before?" trail. Best-effort; never masks the original
        # failure.
        if config.storage_path:
            from tpuflow.obs.forensics import forensics_path

            # Elastic workers sharing one storage root must not clobber
            # each other's last-moments trail: the dump is suffixed with
            # the worker identity (forensics-w{N}.jsonl); plain runs
            # keep the bare forensics.jsonl name.
            dump_forensics(
                forensics_path(
                    config.storage_path, identity=_worker_identity(config)
                ),
                reason=f"train({config.model}) failed",
            )
        raise
    finally:
        if fault_handles:
            from tpuflow.resilience import disarm

            for spec in fault_handles:
                disarm(spec)


def _train_impl(
    config: TrainJobConfig,
    *,
    _data_cache: dict | None = None,
    stop_fn=None,
) -> TrainReport:
    init_distributed()
    if stop_fn is not None:
        reason = stop_fn()
        if reason:
            from tpuflow.train.loop import TrainingInterrupted

            raise TrainingInterrupted(reason)
    t0 = time.monotonic()  # duration clock (TPF015): NTP-step-proof

    names = config.column_names or SYNTHETIC_COLUMN_NAMES
    types = config.column_types or SYNTHETIC_COLUMN_TYPES
    target = config.target or SYNTHETIC_TARGET
    schema = Schema.from_cli(names, types, target)
    loss_fn = LOSSES[config.loss]

    # Epoch-program resolution: explicit True/False is respected (and
    # validated); None = AUTO picks per-batch vs jit_epoch from the
    # measured sweep for this device (tpuflow/train/autotune.py) — the
    # reference's batch-20 jobs (cnn.py:128) ride the measured-fastest
    # program without the submitter knowing the knob exists.
    from tpuflow.train.autotune import ProgramChoice, choose_epoch_program

    if config.jit_epoch is None:
        program = choose_epoch_program(
            config.batch_size,
            stream=config.stream,
            tp=config.tp,
            pp=config.pp,
            ep=config.ep,
            multi_host=jax.process_count() > 1,
            # A crossover measured under one compute dtype must not
            # silently decide runs under another (the HBM working-set
            # halves under bf16, which is exactly what moves the knee).
            compute_dtype=config.precision,
        )
    else:
        program = ProgramChoice(
            bool(config.jit_epoch), "explicitly set in config", "explicit"
        )
    jit_epoch = program.jit_epoch

    if config.stream and jit_epoch:
        # Rejected before any file scans (fit() would also raise, but only
        # after the possibly hours-long eval materialization) and OUTSIDE
        # _prepare_data, which must read only _prep_key-covered fields.
        raise ValueError(
            "jit_epoch stacks the whole epoch into device arrays and would "
            "defeat the bounded-memory stream; use per-batch stepping for "
            "streaming runs"
        )
    n_dev = config.n_devices or jax.device_count()
    _validate_model_axis(config, jit_epoch, n_dev)

    # --- online occupancy autotuner (tpuflow/train/autotune.py) ---
    # The block (or the TPUFLOW_AUTOTUNE env flag) is resolved BEFORE
    # data preparation so a malformed knob or an unsupported
    # combination dies in milliseconds, not after an hours-long ingest
    # (the _validate_model_axis discipline); the controller itself is
    # built after prep — its batch ladder is bounded by the
    # training-row count.
    autotune_block = config.autotune
    if autotune_block is None:
        from tpuflow.utils.env import env_flag

        if env_flag("TPUFLOW_AUTOTUNE", False):
            autotune_block = {}
    autotune_cfg = None
    if autotune_block is not None:
        from tpuflow.train.autotune import resolve_autotune

        autotune_cfg = resolve_autotune(autotune_block)
        conflict = None
        if config.stream:
            conflict = (
                "stream=True (the stream bakes the microbatch into its "
                "per-epoch iterators)"
            )
        elif config.tp > 1 or config.pp > 1 or config.ep > 1:
            conflict = (
                "a model axis (tp/pp/ep inject their own step programs)"
            )
        elif config.elastic is not None:
            conflict = (
                "elastic membership (gang workers must keep one shard "
                "shape for averaging)"
            )
        elif jax.process_count() > 1:
            conflict = "a multi-host runtime"
        elif n_dev > 1:
            conflict = (
                f"n_devices={n_dev} (the tuner drives the single-chip "
                "default steps; set n_devices=1)"
            )
        if conflict:
            raise ValueError(
                f"autotune is not supported with {conflict}; the online "
                "occupancy tuner drives the default single-chip train "
                "path (docs/performance.md)"
            )
        if config.jit_epoch is not None:
            # An explicitly pinned epoch program is a user decision,
            # not a knob: the tuner honors it and tunes the rest.
            autotune_cfg = {**autotune_cfg, "tune_program": False}
    # (model_kwargs JSON-serializability under storage_path is enforced
    # by train()'s preflight spec pass — tpuflow/analysis/spec.py
    # _check_storage, which reuses _sidecar_kwargs — before we get here.)

    if _data_cache is not None:
        key = _prep_key(config)
        prep = _data_cache.get(key)
        if prep is None:
            # Most-recent-only: consecutive experiment runs of the same
            # family are the sharing win; holding every distinct
            # preparation of a data-axis sweep alive at once could
            # multiply peak host memory.
            _data_cache.clear()
            prep = _data_cache[key] = _prepared_with_span(
                config, schema, target
            )
        elif os.environ.get("TPUFLOW_CHECK_PREP_CACHE"):
            # Executable _prep_key contract (see its docstring): a hit
            # must equal a fresh preparation, or the key is missing a
            # field _prepare_data has started reading.
            _assert_prep_equivalent(
                prep, _prepare_data(config, schema, target), config
            )
    else:
        prep = _prepared_with_span(config, schema, target)
    train_ds, val_ds, test_ds = prep.train_ds, prep.val_ds, prep.test_ds
    splits, target_std = prep.splits, prep.target_std
    gilbert_test, seq_physics = prep.gilbert_test, prep.seq_physics

    # --- elastic gang membership (tpuflow/elastic) ---
    # This run is one worker of an elastic data-parallel gang: train on
    # a disjoint row shard; the sync hook below pushes params and adopts
    # the coordinator's average every sync round. Sharding happens AFTER
    # the (cacheable) preparation — every worker prepares identical
    # data, shards differ only by slice, and _prep_key stays untouched.
    elastic_client = None
    if config.elastic is not None:
        from tpuflow.elastic.worker import ElasticWorkerClient, shard_rows

        elastic_client = ElasticWorkerClient(
            config.elastic,
            resuming=bool(config.resume),
            progress_path=config.progress_path,
        )
        train_ds = shard_rows(
            train_ds, elastic_client.worker_id, elastic_client.n_workers
        )

    # --- model + state (L3/L4) ---
    # Mixed-precision policy (tpuflow/train/precision.py): the model
    # leg (per-layer dtype cast inside the differentiated graph — grads
    # stay f32 against f32 masters) is installed by the shared
    # injection rule, the step leg (batch cast at step entry, f32 loss
    # reduction and aux) rides FitConfig.compute_dtype below. The model
    # leg is the one that reaches EVERY path — the injected dp/tp/pp/ep
    # steps build their own programs without FitConfig.compute_dtype,
    # and compute there goes bf16 because the model casts at its own
    # entry (all registry families do). Explicit user model_kwargs
    # dtype wins — the knob is a default, not a clamp.
    from tpuflow.train.precision import (
        compute_dtype as resolve_compute_dtype,
        inject_model_dtype,
        precision_itemsize,
    )

    step_dtype = None
    if config.precision != "f32":
        step_dtype = resolve_compute_dtype(config.precision)
    model_kwargs = inject_model_dtype(
        config.model, config.model_kwargs, config.precision
    )
    if config.model == "gilbert_residual":
        # The physics-informed model standardizes its raw physical output
        # with the train-split stats (see GilbertResidualMLP docstring).
        # Unconditional: user-supplied stats would desynchronize from the
        # pipeline's target standardization and silently break the loss.
        model_kwargs["target_mean"] = splits.pipeline.target_mean_
        model_kwargs["target_std"] = splits.pipeline.target_std_
    elif config.model == "lstm_residual":
        # Same discipline for the sequence variant (windowed-split stats).
        model_kwargs["target_mean"] = splits.target_mean
        model_kwargs["target_std"] = splits.target_std
    model = build_model(config.model, **model_kwargs)
    tx = wrap_optimizer(
        build_optimizer(config.optimizer, **config.optimizer_kwargs),
        clip_norm=config.clip_norm,
        accumulate_steps=config.accumulate_steps,
    )
    # Streaming sources have no .x; the val sample provides the init shape.
    sample_x = val_ds.x[:2] if config.stream else train_ds.x[:2]
    state = create_state(model, jax.random.PRNGKey(config.seed), sample_x, tx)

    if config.warm_start:
        # Warm start from an ARTIFACT's best params (the online loop's
        # retrain-from-the-serving-artifact path): overlay via
        # apply_params so a model/config mismatch fails loudly naming
        # the first mismatching leaf paths, before any epoch runs.
        # Optimizer state stays fresh — the warm start transfers the
        # weights, not a previous run's trajectory bookkeeping.
        from tpuflow.train.checkpoint import make_checkpointer
        from tpuflow.train.resume import apply_params, check_params_match

        from tpuflow.storage import is_store_uri

        ws = make_checkpointer(config.warm_start, config.model)
        try:
            # Compatibility first, against the checkpoint's METADATA: a
            # structurally-different artifact fails here with the first
            # mismatching leaf paths named (check_params_match), not
            # inside Orbax's template matching as an opaque pytree
            # error. Only a compatible artifact pays for the restore.
            # Store-resident artifacts carry flat leaf metadata instead
            # of a tree; their restore path runs the same leaf-count and
            # shape checks inside ``unflatten_like``.
            if not is_store_uri(config.warm_start):
                check_params_match(state.params, ws.best_structure())
            warm = ws.restore_best(state.params)
        finally:
            ws.close()
        state = apply_params(state, warm)

    # --- the occupancy-autotuner controller (single-chip path only;
    # the conflicts above already rejected everything else) ---
    tuner = None
    if autotune_cfg is not None:
        from tpuflow.parallel.placement import (
            device_kind as _placed_kind,
        )
        from tpuflow.train.autotune import (
            OccupancyAutotuner,
            TuningPoint,
            load_tuned,
        )

        _kind = _placed_kind(default=jax.default_backend())
        start = None
        if autotune_cfg["persist"] and config.storage_path:
            start = load_tuned(
                config.storage_path, config.model, _kind,
                config.precision,
            )
        if start is not None:
            # Resume tuned: a supervised restart or warm-started run
            # begins at the persisted winner instead of re-exploring
            # (dtype-keyed — a bf16 winner never seeds an f32 run).
            if config.jit_epoch is not None:
                start = TuningPoint(
                    start.batch_size, start.remat, bool(config.jit_epoch)
                )
            program = ProgramChoice(
                start.jit_epoch,
                f"resumed persisted tuned config {start.key} for "
                f"{_kind!r}@{config.precision}",
                "autotuned",
            )
            jit_epoch = program.jit_epoch
        else:
            start = TuningPoint(config.batch_size, False, jit_epoch)
        tuner = OccupancyAutotuner(
            autotune_cfg,
            start,
            n_train_rows=int(train_ds.n),
            n_devices=1,
            device_kind=_kind,
            compute_dtype=config.precision,
            storage_path=config.storage_path,
            model_name=config.model,
            # The offline measured crossover decides the STARTING
            # program — the prior the tuner climbs from, not a verdict.
            prior=f"{program.source}: {program.reason}",
            verbose=config.verbose,
        )

    # --- parallelism: DP over the mesh when >1 device; DP x TP when
    # config.tp > 1 (GSPMD megatron layout, parallel/tp_train.py) ---
    # (model-axis configs were validated by _validate_model_axis before
    # data preparation; the branches below only build the sharded state)
    train_step = eval_step = epoch_step = None
    batch_shard = None

    def _wire_axis_steps(mesh, train_fn, eval_fn):
        """The one multi-host-vs-single-host wiring for every model-axis
        strategy (tp/pp/ep): on a multi-process runtime wrap the step fns
        with THE shared per-process feeding recipe
        (parallel.dp.make_process_fed_steps); single-host, pass them
        through and let prefetch land batches pre-sharded over the data
        axis. Returns (train_step, eval_step, batch_shard)."""
        if jax.process_count() > 1:
            fed_train, fed_eval = make_process_fed_steps(
                mesh, train_fn, eval_fn
            )
            return fed_train, fed_eval, None
        return train_fn, eval_fn, data_sharding(mesh)

    if config.tp > 1:
        from tpuflow.parallel.tp_train import (
            make_tp_eval_step,
            make_tp_mesh,
            make_tp_train_step,
            mlp_tp_shardings,
            shard_state,
        )

        mesh = make_tp_mesh(
            n_data=n_dev // config.tp,
            n_model=config.tp,
            devices=local_devices()[:n_dev],
        )
        # Fails loudly for non-Dense-stack families (mlp_tp_shardings).
        state = shard_state(mesh, state, mlp_tp_shardings(mesh, state.params))
        train_step, eval_step, batch_shard = _wire_axis_steps(
            mesh, make_tp_train_step(state, loss_fn),
            make_tp_eval_step(loss_fn),
        )
    elif config.pp > 1:
        n_micro = config.pp_microbatches or config.pp
        from tpuflow.parallel.pp_train import (
            make_pp_eval_step,
            make_pp_mesh,
            make_pp_train_step,
            pp_shardings,
            shard_state,
        )

        mesh = make_pp_mesh(
            n_data=n_dev // config.pp,
            n_model=config.pp,
            devices=local_devices()[:n_dev],
        )
        # Fails loudly for non-pipeline families (pp_shardings).
        state = shard_state(mesh, state, pp_shardings(mesh, state.params))
        train_step, eval_step, batch_shard = _wire_axis_steps(
            mesh, make_pp_train_step(state, loss_fn, n_micro),
            make_pp_eval_step(mesh, loss_fn, n_micro),
        )
    elif config.ep > 1:
        from tpuflow.parallel.ep_train import (
            ep_shardings,
            make_ep_eval_step,
            make_ep_mesh,
            make_ep_train_step,
            shard_state,
        )

        mesh = make_ep_mesh(
            n_data=n_dev // config.ep,
            n_model=config.ep,
            devices=local_devices()[:n_dev],
        )
        # Fails loudly for non-MoE families (ep_shardings).
        state = shard_state(mesh, state, ep_shardings(mesh, state.params))
        train_step, eval_step, batch_shard = _wire_axis_steps(
            mesh, make_ep_train_step(state, loss_fn),
            make_ep_eval_step(mesh, loss_fn),
        )
    elif n_dev > 1:
        if config.batch_size % n_dev:
            raise ValueError(
                f"batch_size {config.batch_size} not divisible by {n_dev} devices"
            )
        mesh = make_mesh(n_data=n_dev, devices=local_devices()[:n_dev])
        state = replicate(mesh, state)
        dp_train = make_dp_train_step(mesh, loss_fn)
        dp_eval = make_dp_eval_step(mesh, loss_fn)
        # Multi-host pods: every host materializes the same seeded batch
        # order and feeds only its slice — THE shared recipe
        # (parallel.dp.make_process_fed_steps).
        multi_host = jax.process_count() > 1
        train_step, eval_step = make_process_fed_steps(
            mesh, dp_train, dp_eval
        )

        if jit_epoch:
            # The scanned DP program: K train steps (each with its ICI
            # all-reduce) per dispatch — same dispatch-amortization as
            # single-chip jit_epoch.
            dp_epoch = make_dp_epoch_step(mesh, loss_fn)

            def _put_epoch(a):
                # _stacked_epoch materializes the full global batches on
                # every host; keep only this process's dim-1 slice before
                # the shared per-process assembly.
                if multi_host and not isinstance(a, jax.Array):
                    lo, hi = process_batch_bounds(a.shape[1])
                    a = a[:, lo:hi]
                return shard_epoch(mesh, a)

            def epoch_step(state, xs, ys, rng):  # noqa: F811
                return dp_epoch(state, _put_epoch(xs), _put_epoch(ys), rng)

        # DP runs: land prefetched batches pre-sharded over the mesh so
        # the step's shard_batch is a no-op instead of a device0
        # re-transfer. Single-host only — a pod-global device_put from one
        # host would fail; multi-host feeding goes through _local above.
        if jax.process_count() == 1:
            batch_shard = data_sharding(mesh)

    # --- live roofline context (tpuflow/obs/health.py publish leg) ---
    # The sequence families have a FLOPs/bytes cost model; the fit loop
    # publishes train_mfu / train_bound from it each epoch. Families
    # without a model get no MFU gauge — honest absence over noise.
    from tpuflow.utils.roofline import model_cost_per_sample

    roofline_cfg = None
    if config.is_sequence_model:
        feat_dim = (
            val_ds.x.shape[-1] if config.stream else train_ds.x.shape[-1]
        )
        cost = model_cost_per_sample(
            config.model,
            window=config.window,
            features=int(feat_dim),
            model_kwargs=model_kwargs,
            # Honest bytes: activation traffic travels in the COMPUTE
            # dtype, so bf16 halves hbm_bytes_per_sample — the live
            # train_hbm_util/train_bound gauges must reflect it or the
            # policy's whole win is invisible to the roofline.
            itemsize=precision_itemsize(config.precision),
        )
        if cost is not None:
            roofline_cfg = {
                "flops_per_sample": cost[0],
                "bytes_per_sample": cost[1],
                "n_chips": n_dev,
                "compute_dtype": config.precision,
            }

    # --- fit (the reference's hot loop, cnn.py:126-129) ---
    fit_cfg = FitConfig(
        max_epochs=config.max_epochs,
        # A resumed tuned point starts the run at the persisted winner;
        # the tuner keeps climbing (or holds) from there.
        batch_size=(
            tuner.current.batch_size if tuner is not None
            else config.batch_size
        ),
        patience=config.patience,
        seed=config.seed,
        loss=loss_fn,
        storage_path=config.storage_path,
        model_name=config.model,
        verbose=config.verbose,
        jit_epoch=jit_epoch,
        save_every=config.save_every,
        resume=config.resume,
        fault_epoch=config.fault_epoch,
        fault_hard=config.fault_hard,
        ckpt_async=config.ckpt_async,
        progress_path=config.progress_path,
        trace_dir=config.trace_dir,
        metrics_path=config.metrics_path,
        stop_fn=stop_fn,
        health=config.health,
        roofline=roofline_cfg,
        compute_dtype=step_dtype,
        sync_fn=elastic_client.sync if elastic_client is not None else None,
        autotune=tuner,
        run_identity=_worker_identity(config),
    )
    if elastic_client is not None:
        # Register with the gang: heartbeat thread + (for a fresh late
        # joiner) warm-start from the latest published average; a
        # RESUMING worker skips the warm start — its own checkpoint,
        # restored inside fit(), is the right starting point. Adjacent
        # to the try below so any failure after the heartbeat thread
        # starts reaches the finish(failed=True) goodbye.
        state = elastic_client.join(state)
    try:
        result = fit(
            state,
            train_ds,
            val_ds,
            fit_cfg,
            train_step,
            eval_step,
            batch_sharding=batch_shard,
            epoch_step=epoch_step,
        )
    except BaseException:
        if elastic_client is not None:
            # Say goodbye so the coordinator stops waiting on this
            # worker immediately (the eviction deadline would get there
            # anyway; a terminal heartbeat is just faster and labeled).
            elastic_client.finish(failed=True)
        raise
    if elastic_client is not None:
        # Final push: the runner averages every worker's last params
        # into the gang's deliverable after all workers return.
        elastic_client.finish(result.state)

    # --- final evaluation (cnn.py:132-134, working) ---
    # Batch sizing: reuse the fit loop's eval shape (config.batch_size)
    # whenever the test split fits in a few such batches — the eval step
    # is already compiled at that shape, and a new 256-wide program would
    # cost a fresh XLA compile to save microseconds. Only single-chip
    # runs over genuinely large test splits get the wider batch.
    eval_bs = config.batch_size
    if n_dev == 1 and test_ds.n > 4 * config.batch_size:
        eval_bs = max(config.batch_size, 256)
    test = evaluate(
        result.state,
        test_ds,
        batch_size=eval_bs,
        eval_step=eval_step,
        loss=loss_fn,
    )
    # --- serving sidecar (SURVEY.md §3.2: the artifact the web layer reads) ---
    if config.storage_path:
        from tpuflow.api.predict_api import save_artifact_meta

        if config.is_sequence_model:
            pre = {
                "feature_names": list(splits.feature_names),
                "window": config.window,
                "stride": config.stride,
                "well_column": config.well_column,
                "append_gilbert": seq_physics,
                "mean": splits.norm_mean.tolist(),
                "std": splits.norm_std.tolist(),
                "target_mean": splits.target_mean,
                "target_std": splits.target_std,
                "schema_columns": [
                    {"name": c.name, "kind": c.kind} for c in schema.columns
                ],
                "target": schema.target,
            }
            kind = "windowed"
        else:
            pre = splits.pipeline.to_dict()
            pre["append_gilbert"] = config.model == "gilbert_residual"
            kind = "tabular"
        save_artifact_meta(
            config.storage_path,
            config.model,
            config.model,
            # Resolved kwargs (incl. injected target stats), sanitized
            # for serving: a ring-CP training run still writes a
            # checkpoint-compatible artifact.
            _sidecar_kwargs(model_kwargs),
            kind,
            pre,
            tuple(val_ds.x.shape if config.stream else train_ds.x.shape),
        )

    report = TrainReport(
        result=result,
        test_loss=test["loss"],
        # Training runs in standardized target units (clip=6 discipline);
        # MAE is reported in RAW flow units for the Gilbert comparison.
        test_mae=test["mae"] * target_std,
        gilbert_mae=gilbert_test,
        time_elapsed=time.monotonic() - t0,
        samples_per_sec=result.samples_per_sec / max(n_dev, 1),
        epoch_program=program.name,
        epoch_program_reason=f"{program.source}: {program.reason}",
        anomalies=result.anomalies,
        recompiles=result.recompiles,
        autotune=result.autotune,
    )
    if config.verbose:
        print(report.summary())
    return report
